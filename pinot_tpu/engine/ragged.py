"""Cross-query micro-batching: one fused device dispatch for N queries.

PR 8 tentpole. Pinot serves thousands of small concurrent queries per
node and the engine paid one device dispatch per query — the plan cache
amortized compiles but not launches. This module sits between the
serving layer (engine/batch.execute_plans_batched) and the kernel
engine: a short-window admission queue (engine/scheduler.MicroBatchQueue
— the scheduler grown beyond FCFS/priority) collects in-flight queries
that share the exact plan structure the plan cache already keys
(ops/plan_cache: KernelPlan + bucket + param signature) plus
segment-stack compatibility from engine/batch, and fuses each group
into ONE ragged launch.

The fusion core borrows the variable-length packing idiom of *Ragged
Paged Attention* and the one-tensor-program-per-plan framing of *Query
Processing on Tensor Computation Runtimes* (PAPERS.md): queries sharing
a KernelPlan differ only in hoisted literal params, so

- ONE unmasked group-by over the union of predicate + group dimensions
  builds a literal-free **cube** per segment (cached device-resident in
  ops/plan_cache.global_cube_cache, keyed by segment uid);
- per-query literal params stack as a leading batch axis and each
  query's predicate is evaluated over the cube's id grid — a few
  thousand cells instead of millions of rows;
- per-query variable-length segment lists pack into a padded
  segment-id layout (items = (query, segment) pairs, pow2 ladder so
  shapes stay jit-cache-stable and zero-retrace after warmup);
- one contraction launch reduces masked cells per item, results unpack
  and extract per query through the ordinary extract_partial path, so
  fused digests are byte-identical to solo (exact integer sums only —
  float sums would reassociate and are never fused).

Fairness and admission: a query near its accountant deadline, or a
plan the cube cost model rejects, dispatches solo immediately — never
queue-blocked. The per-key ``estimate_ms()`` EWMA (the engine-side
analog of the adaptive instance selector's latency estimator) feeds
the deadline check. Every query wraps its wait + dispatch in a
``ragged_dispatch`` span on its own thread (queue_wait_ms annotated)
so per-query wall attribution survives the fusion, and the accountant
carries batched/batch_size per query for the query_stats ledger.

ENABLED by default since round 16 (PINOT_MICROBATCH=0,
Broker(micro_batch=False) or configure() turn it off). Batching was
opt-in through rounds 13-15 because fused compositions depend on
arrival timing and the fault registry's process-global per-site hit
counters made chaos decisions composition-sensitive; utils/faults.py
now keys decision streams by (owning query id, site key), so a query's
same-seed fault stream is identical whether its peers fused, ran solo,
or interleaved arbitrarily — chaos soaks run with batching armed.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import phases as ph
from ..utils.metrics import global_metrics
from ..utils.spans import annotate, device_fence, span
from .scheduler import MicroBatchQueue

# cost-model caps: the cube must stay small relative to the data it
# collapses, the per-item masked-cell work must stay bounded, and raw
# (no-dictionary) predicate columns only join as dims over a small
# metadata-bounded value span
CUBE_SPACE_LIMIT = 1 << 20
RAW_DIM_SPAN_CAP = 1 << 12
ITEM_CELL_BUDGET = 1 << 23          # pow2-padded items x cube_space
DEFAULT_WINDOW_MS = 4.0
DEFAULT_MAX_BATCH = 32

# why a submission dispatched solo instead of fusing (counted as
# solo_fallback_<reason>; a globally disabled batcher never reaches the
# admission path, so it is deliberately NOT a reason here)
_SOLO_REASONS = ("incompatible", "no_peers", "deadline",
                 "window_expired", "timeout", "leader_error")


@dataclass(frozen=True)
class CubeSpec:
    """Literal-free fusion recipe for one plan structure on one
    segment shape. Hashable — it keys the cube cache, the jitted
    builders, and the admission queue."""
    kp: Any                       # ops.ir.KernelPlan
    bucket: int
    n_cols: int
    # (col_idx, card, base, is_dict) in cube-key order: group dims
    # first (the plan's own arithmetic), then predicate-only dims
    dims: Tuple[Tuple[int, int, int, bool], ...]
    group_space: int              # G (1 for scalar aggregations)
    pred_space: int               # P
    cube_space: int               # G * P


def _value_param_indices(ve) -> Tuple[set, set]:
    """(dict-value param indices, other param indices) referenced by an
    aggregation value expression. Literal params inside agg values make
    the cube literal-DEPENDENT and therefore unshareable."""
    from ..ops.ir import Bin, Case, Col, Func, Lit, MvReduce
    dicts: set = set()
    other: set = set()

    def walk(e):
        if isinstance(e, Col):
            if e.dict_param is not None:
                dicts.add(e.dict_param)
        elif isinstance(e, MvReduce):
            if e.dict_param is not None:
                dicts.add(e.dict_param)
        elif isinstance(e, Lit):
            other.add(e.param)
        elif isinstance(e, Bin):
            walk(e.lhs)
            walk(e.rhs)
        elif isinstance(e, Func):
            for a in e.args:
                walk(a)
        elif isinstance(e, Case):
            other.add(-1)  # CASE may hide predicate params: ineligible
    walk(ve)
    return dicts, other


def _pred_fusable(p) -> bool:
    """Allowlist walk of the predicate IR: only node shapes the cube's
    grid evaluator has been vetted for may fuse. Anything else —
    MaskParam (per-row index-predicate masks), MvReduce/Case value
    shapes, or any FUTURE Pred/ValueExpr subclass — fails CLOSED, so
    new IR can never silently evaluate over a zero placeholder grid
    (the fail-open shape the Func/Case column-discovery fix patched)."""
    from ..ops.ir import (And, Cmp, EqId, FalseP, IdRange, InBitmap,
                          InSet, Not, Or, TrueP)

    def value_ok(ve) -> bool:
        from ..ops.ir import Bin, Col, Func, Lit
        if isinstance(ve, (Col, Lit)):
            return True
        if isinstance(ve, Bin):
            return value_ok(ve.lhs) and value_ok(ve.rhs)
        if isinstance(ve, Func):
            return all(value_ok(a) for a in ve.args)
        return False            # MvReduce needs (N, M) cols; Case and
        # unknown shapes are unvetted on the 1-D grid

    if isinstance(p, (TrueP, FalseP, EqId, IdRange, InSet, InBitmap)):
        return True
    if isinstance(p, Cmp):
        return value_ok(p.lhs)
    if isinstance(p, (And, Or)):
        return all(_pred_fusable(c) for c in p.children)
    if isinstance(p, Not):
        return _pred_fusable(p.child)
    return False


# (kernel plan, segment uid, x64 flag) -> derived (spec, reason): the
# derivation walks the plan IR + per-column segment metadata and runs
# on every submission, but both inputs are immutable per load uid (the
# cube cache's own invariant), so peers microseconds apart share it
_SPEC_MEMO: "OrderedDict[Tuple, Tuple[Optional[CubeSpec], str]]" = \
    OrderedDict()
_SPEC_MEMO_MAX = 512
_spec_lock = threading.Lock()


def cube_spec_for(plan) -> Tuple[Optional[CubeSpec], str]:
    """Derive the fusion recipe for a compiled kernel plan, or
    (None, reason) when the plan is ineligible. Eligibility is the
    cube cost model: every predicate column must be a bounded
    single-value dimension, aggregations must be exact under cell
    re-association (COUNT / integral SUM / AVG), and the cube must be
    small relative to the segment. Memoized by (plan, segment uid)."""
    kp = plan.kernel_plan
    uid = getattr(plan.segment, "uid", None)
    key = None
    if kp is not None and uid is not None:
        key = (kp, uid, bool(jax.config.jax_enable_x64))
        with _spec_lock:
            hit = _SPEC_MEMO.get(key)
            if hit is not None:
                _SPEC_MEMO.move_to_end(key)
                return hit
    out = _derive_cube_spec(plan)
    if key is not None:
        with _spec_lock:
            _SPEC_MEMO[key] = out
            _SPEC_MEMO.move_to_end(key)
            while len(_SPEC_MEMO) > _SPEC_MEMO_MAX:
                _SPEC_MEMO.popitem(last=False)
    return out


def _derive_cube_spec(plan) -> Tuple[Optional[CubeSpec], str]:
    from ..ops.kernels import _pred_col_indices
    kp = plan.kernel_plan
    if kp is None:
        return None, "incompatible"
    if kp.key_exprs:
        return None, "incompatible"          # expression group keys
    from ..ops.kernels import int_acc_dtype
    if int_acc_dtype() != jnp.int64:
        # cube cells accumulate int64 subtotals; with jax_enable_x64
        # off they would silently canonicalize to int32 and wrap —
        # the solo compact path errors LOUDLY on the same condition
        # (sum_carrier_dtype), so fusion must never mask it
        return None, "incompatible"
    for spec in kp.aggs:
        if spec.kind not in ("count", "sum", "avg"):
            return None, "incompatible"      # sketches / min-max / distinct
        if spec.kind in ("sum", "avg") and not spec.integral:
            return None, "incompatible"      # float sums reassociate
        if spec.null_param is not None:
            return None, "incompatible"      # null handling masks per agg
        if spec.value is not None:
            _dicts, other = _value_param_indices(spec.value)
            if other:
                return None, "incompatible"  # literal inside agg value
    if not _pred_fusable(kp.pred):
        return None, "incompatible"          # per-row mask semantics or
        # a node shape the grid evaluator was never vetted for — the
        # eligibility walk is allowlist-shaped so new IR fails CLOSED
    for p in plan.params:
        if isinstance(p, tuple) and len(p) == 2 and \
                p[0] in ("nullmask", "validdocs", "docmask", "hash64"):
            return None, "incompatible"      # per-row masks can't cube
    seg = plan.segment
    if getattr(seg, "uid", None) is None:
        return None, "incompatible"          # cache key contract
    group_cols = {ci for ci, _ in kp.group_keys}
    dims: List[Tuple[int, int, int, bool]] = [
        (ci, card, 0, True) for ci, card in kp.group_keys]
    pred_only = sorted(_pred_col_indices(kp.pred) - group_cols)
    pred_space = 1
    for ci in pred_only:
        if ci >= len(plan.col_names):
            return None, "incompatible"
        name = plan.col_names[ci]
        meta = seg.columns.get(name)
        if meta is None or not getattr(meta, "single_value", True):
            return None, "incompatible"      # MV predicate semantics
        if seg.dictionary(name) is not None:
            card, base, is_dict = int(meta.cardinality), 0, True
        else:
            lo, hi = getattr(meta, "min", None), getattr(meta, "max", None)
            if not isinstance(lo, int) or not isinstance(hi, int):
                return None, "incompatible"
            span = hi - lo + 1
            if span <= 0 or span > RAW_DIM_SPAN_CAP:
                return None, "incompatible"
            card, base, is_dict = span, lo, False
        if card <= 0:
            return None, "incompatible"
        dims.append((ci, card, base, is_dict))
        pred_space *= card
    from ..ops.kernels import GROUP_XFER_SPACE
    group_space = kp.group_space if kp.is_group_by else 1
    if group_space >= GROUP_XFER_SPACE:
        # the fused kernel emits dense [items, group_space] outputs;
        # at or past the engine's own sparse-transfer threshold the
        # solo path's (group_idx, value) contract moves orders of
        # magnitude fewer bytes than a fused dense transfer would
        return None, "incompatible"
    cube_space = group_space * pred_space
    if cube_space > CUBE_SPACE_LIMIT or cube_space > seg.bucket:
        return None, "incompatible"          # cube beats the scan only
        # when it is (much) smaller than the data it collapses
    return CubeSpec(kp=kp, bucket=seg.bucket, n_cols=len(plan.col_names),
                    dims=tuple(dims), group_space=group_space,
                    pred_space=pred_space, cube_space=cube_space), ""


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _dim_digits(spec: CubeSpec, cols) -> Tuple[jax.Array, jax.Array]:
    """(cube key [bucket], in-domain mask): the plan's own group-key
    Horner arithmetic extended by the predicate-only dims."""
    key = jnp.zeros((spec.bucket,), dtype=jnp.int32)
    ok = jnp.ones((spec.bucket,), dtype=jnp.bool_)
    for ci, card, base, _is_dict in spec.dims:
        digit = cols[ci].astype(jnp.int32) - jnp.int32(base)
        ok &= (digit >= 0) & (digit < card)
        key = key * jnp.int32(card) + digit
    return key, ok


def _grid_cols(spec: CubeSpec) -> Tuple[jax.Array, ...]:
    """Per-dim id/value arrays over the cube cells — the domain the
    per-query predicate masks evaluate on (pure iota arithmetic, traced
    inside the jitted combine kernel)."""
    idx = jnp.arange(spec.cube_space, dtype=jnp.int32)
    cols: List[Optional[jax.Array]] = [None] * spec.n_cols
    div = spec.cube_space
    for ci, card, base, _is_dict in spec.dims:
        div //= card
        cols[ci] = (idx // jnp.int32(div)) % jnp.int32(card) \
            + jnp.int32(base)
    zero = jnp.zeros((spec.cube_space,), dtype=jnp.int32)
    return tuple(zero if c is None else c for c in cols)


def _cube_jobs(spec: CubeSpec):
    """The deduped integral sum payload slots (ops/kernels
    _payload_columns contract, restricted to the cube-eligible kinds)."""
    jobs = []
    slots: Dict[Tuple, int] = {}
    for i, agg in enumerate(spec.kp.aggs):
        if agg.kind == "count":
            jobs.append((i, agg, None))
            continue
        key = (agg.value, agg.integral)
        slot = slots.setdefault(key, len(slots))
        jobs.append((i, agg, slot))
    return jobs, len(slots)


def build_cube_kernel(spec: CubeSpec):
    """fn(cols, n_docs, params) -> {"cnt": [cube] i64, "s<k>": [cube]
    i64}: the literal-free cube — one unmasked pass over the segment."""
    from ..ops.kernels import _eval_value

    jobs, n_slots = _cube_jobs(spec)
    slot_values = {}
    for _i, agg, slot in jobs:
        if slot is not None and slot not in slot_values:
            slot_values[slot] = agg.value

    def kernel(cols, n_docs, params):
        valid = jnp.arange(spec.bucket, dtype=jnp.int32) < n_docs
        key, ok = _dim_digits(spec, cols)
        keys_s = jnp.where(valid & ok, key, jnp.int32(spec.cube_space))
        nseg = spec.cube_space + 1
        out = {"cnt": jax.ops.segment_sum(
            (valid & ok).astype(jnp.int64), keys_s,
            num_segments=nseg)[: spec.cube_space]}
        for slot, ve in slot_values.items():
            v = _eval_value(ve, cols, params, promote=True)
            v = jnp.where(valid & ok, v.astype(jnp.int64), 0)
            out[f"s{slot}"] = jax.ops.segment_sum(
                v, keys_s, num_segments=nseg)[: spec.cube_space]
        return out

    return kernel


def build_cube_combine_kernel(spec: CubeSpec):
    """fn(cubes, seg_idx [N], params [N-stacked]) -> per-item outputs
    named exactly like the solo kernel's (matched / group_count /
    agg<i>_*), so extract_partial is oblivious to the fusion."""
    from ..ops.kernels import _agg_name, _eval_pred

    jobs, _n_slots = _cube_jobs(spec)
    G, P = spec.group_space, spec.pred_space
    grouped = spec.kp.is_group_by

    def kernel(cubes, seg_idx, params):
        grid = _grid_cols(spec)

        def mask_one(ps):
            return _eval_pred(spec.kp.pred, grid, ps, spec.cube_space)

        masks = jax.vmap(mask_one)(params)            # [N, cube] bool
        n = masks.shape[0]

        def reduce_cells(cells):
            sel = jnp.where(masks, cells[seg_idx], 0)  # [N, cube] i64
            if grouped:
                return sel.reshape(n, G, P).sum(-1)    # [N, G]
            return sel.sum(-1)                         # [N]

        counts = reduce_cells(cubes["cnt"])
        out: Dict[str, jax.Array] = {}
        if grouped:
            out["group_count"] = counts
            out["matched"] = counts.sum(-1)
        else:
            out["matched"] = counts
        slot_sums: Dict[int, jax.Array] = {}
        for i, agg, slot in jobs:
            name = _agg_name(i, agg)
            if agg.kind == "count":
                if not grouped:
                    out[name] = counts
                continue  # grouped COUNT rides group_count
            s = slot_sums.get(slot)
            if s is None:
                s = reduce_cells(cubes[f"s{slot}"])
                slot_sums[slot] = s
            if agg.kind == "avg":
                out[name + "_sum"] = s
                out[name + "_cnt"] = counts
            else:
                out[name] = s
        return out

    return kernel


class _KernelRegistry:
    """Bounded jit cache for the cube builders/combiners. Every compile
    registers with the plan cache's RetraceDetector under the full
    shape key (spec, segment count, pow2 pad, param shapes): a
    RE-compile of a key already seen in an earlier query generation —
    an LRU eviction rebuild, a flipped knob — is flagged exactly like
    a plan-cache retrace. A key's FIRST-ever compile is warmup by the
    detector's own rule, so benches that want compile-free measured
    windows must visit their pow2 rungs during warmup (bench.py's
    --concurrency mode does)."""

    def __init__(self, maxsize: int = 256):
        self._lock = threading.Lock()
        self._fns: "OrderedDict[Tuple, Any]" = OrderedDict()
        # keys the LRU dropped: their rebuild classifies as
        # lru_evict_rebuild in the compile-event taxonomy
        self._evicted: "OrderedDict[Tuple, bool]" = OrderedDict()
        self._maxsize = maxsize

    def get(self, key: Tuple, make):
        # the whole miss path stays under the lock so concurrent
        # leaders of one key can't double-build the wrapper; the
        # compile itself classifies + lands its compile_event at first
        # call (utils/compileplane.StagedFn, single-flight under the
        # wrapper's own lock). Cheap to hold: jax.jit() is lazy.
        from ..utils.compileplane import staged
        with self._lock:
            fn = self._fns.get(key)
            if fn is not None:
                self._fns.move_to_end(key)
                return fn
            hints = None
            if key in self._evicted:
                del self._evicted[key]
                hints = {"evicted": True}
            fn = staged(jax.jit(make()), "ragged", key, hints=hints)
            self._fns[key] = fn
            while len(self._fns) > self._maxsize:
                old_key, _old = self._fns.popitem(last=False)
                self._evicted[old_key] = True
                while len(self._evicted) > 4 * self._maxsize:
                    self._evicted.popitem(last=False)
            return fn

    def clear(self):
        with self._lock:
            self._fns.clear()
            self._evicted.clear()


_kernels = _KernelRegistry()


def _pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length()


# ---------------------------------------------------------------------------
# the batcher
# ---------------------------------------------------------------------------

def default_enabled() -> bool:
    """The process-default batching switch: ON unless PINOT_MICROBATCH=0
    (flipped from opt-in in round 16 — module docstring)."""
    return os.environ.get("PINOT_MICROBATCH") != "0"


class _Submission:
    __slots__ = ("plans", "resolved", "future", "query_id", "t0",
                 "n_items", "abandoned")

    def __init__(self, plans, resolved, query_id):
        self.plans = plans
        self.resolved = resolved
        self.future: "Future[Any]" = Future()
        self.query_id = query_id
        self.t0 = time.perf_counter()
        self.n_items = len(plans)
        # set by a follower that gave up waiting (deadline margin) and
        # re-dispatched solo: the leader must not report this query as
        # batched — its fused results were discarded
        self.abandoned = False


class RaggedBatcher:
    """The cross-query micro-batching dispatcher (module docstring)."""

    def __init__(self, window_ms: float = DEFAULT_WINDOW_MS,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 enabled: Optional[bool] = None):
        self.window_ms = window_ms
        # overload degradation (broker/workload.OverloadGovernor): at
        # rung >= 1 the governor widens the admission window by this
        # factor — fewer, fuller fused launches while the cluster sheds
        # speculative work (reset to 1.0 when pressure clears)
        self.window_scale = 1.0
        self.max_batch = max_batch
        self.enabled = (default_enabled()
                        if enabled is None else bool(enabled))
        self.queue = MicroBatchQueue()
        self._lock = threading.Lock()
        self._est_ms: Dict[Any, float] = {}

    def configure(self, enabled: Optional[bool] = None,
                  window_ms: Optional[float] = None,
                  max_batch: Optional[int] = None) -> "RaggedBatcher":
        if enabled is not None:
            self.enabled = bool(enabled)
        if window_ms is not None:
            self.window_ms = float(window_ms)
        if max_batch is not None:
            self.max_batch = int(max_batch)
        return self

    # -- admission ---------------------------------------------------------
    def estimate_ms(self, key: Any) -> Optional[float]:
        """EWMA of fused-dispatch wall ms for a compatibility key (the
        adaptive selector's estimate_ms analog, keyed by plan shape)."""
        with self._lock:
            return self._est_ms.get(key)

    def _record_ms(self, key: Any, ms: float) -> None:
        with self._lock:
            prev = self._est_ms.get(key)
            self._est_ms[key] = ms if prev is None \
                else 0.7 * prev + 0.3 * ms
            if len(self._est_ms) > 512:
                self._est_ms.pop(next(iter(self._est_ms)))

    @staticmethod
    def _solo(reason: str) -> None:
        global_metrics.count(f"solo_fallback_{reason}")
        annotate(batched=False, solo_reason=reason)
        return None

    def submit(self, plans: List[Any], resolved: List[Tuple],
               bucket: int, group_sig: Tuple) -> Optional[List[Any]]:
        """Try to fuse one query's compatible kernel-plan group with
        concurrent peers. Returns per-plan partials, or None — the
        caller then runs the ordinary solo dispatch (reason counted in
        solo_fallback_* and annotated on the span). Never queue-blocks
        a query that should dispatch solo: ineligible, peer-less and
        deadline-pressured queries bail before enqueueing."""
        if not self.enabled:
            return None
        from .accounting import global_accountant
        # a lone query never waits the window: admission only batches
        # when there is concurrent demand — checked FIRST because it is
        # the common low-concurrency hot path and costs one lock, while
        # spec derivation below walks the plan IR and segment metadata
        if len(global_accountant.running()) < 2:
            return self._solo("no_peers")
        spec, _why = cube_spec_for(plans[0])
        if spec is None:
            return self._solo("incompatible")
        # the budget bounds what the kernel EXECUTES — the pow2-padded
        # item count, not the raw one (pad rows do real work)
        if _pow2(len(plans)) * spec.cube_space > ITEM_CELL_BUDGET:
            return self._solo("incompatible")
        # dim cardinalities are segment state (dictionaries differ per
        # segment): every segment in this group must derive the same
        # spec or the shared grid would mis-decode its ids
        seen_uids = {plans[0].segment.uid}
        for plan in plans[1:]:
            if plan.segment.uid in seen_uids:
                continue
            seen_uids.add(plan.segment.uid)
            other, _w = cube_spec_for(plan)
            if other != spec:
                return self._solo("incompatible")
        qid = global_accountant.current_query_id()
        key = (spec, bucket, group_sig)
        window_ms = self.window_ms * self.window_scale
        usage = global_accountant.usage(qid) if qid else None
        if usage is not None and usage.deadline is not None:
            rem_ms = (usage.deadline - time.perf_counter()) * 1e3
            est = self.estimate_ms(key) or window_ms
            if rem_ms < window_ms + 2.0 * est:
                return self._solo("deadline")
        sub = _Submission(plans, resolved, qid)
        # weight cap = largest pow2 <= the budgeted item count, so the
        # PADDED batch still fits ITEM_CELL_BUDGET on device
        budget_items = max(ITEM_CELL_BUDGET // max(spec.cube_space, 1), 1)
        max_weight = 1 << max(budget_items.bit_length() - 1, 0)
        with span(ph.RAGGED_DISPATCH, bucket=bucket,
                  strategy=spec.kp.strategy):
            global_metrics.gauge("batch_queue_depth", self.queue.depth())
            batch = self.queue.offer(
                key, sub, window_ms / 1e3, self.max_batch,
                max_weight=max_weight, weight=sub.n_items)
            # re-read after the offer resolves so a drained queue
            # reports 0 instead of freezing at the last pre-offer value
            global_metrics.gauge("batch_queue_depth", self.queue.depth())
            if batch is None:
                return self._await_follower(sub, usage)
            if len(batch) == 1:
                # the window expired with no peers for this key
                annotate(queue_wait_ms=round(
                    (time.perf_counter() - sub.t0) * 1e3, 3))
                return self._solo("window_expired")
            return self._lead(key, spec, batch, sub)

    def _await_follower(self, sub: _Submission, usage) -> Optional[List]:
        from concurrent.futures import TimeoutError as FutTimeout
        timeout = 60.0
        if usage is not None and usage.deadline is not None:
            # reserve half the remaining budget for the solo fallback:
            # a stalled leader must not convert a servable query into
            # a guaranteed deadline kill after the wait
            rem = usage.deadline - time.perf_counter()
            timeout = max(min(rem * 0.5, 60.0), 0.05)
        reason = "leader_error"
        try:
            result = sub.future.result(timeout=timeout)
        except FutTimeout:
            # abandon BEFORE the last-chance re-check: either the
            # leader already set the result (use it — nothing was
            # wasted) or it sees the flag and skips this query's
            # batched accounting. A leader reading the flag in the same
            # instant may still count one abandoned query as batched —
            # an accepted, annotated-in-review race, not a hang.
            sub.abandoned = True
            result = sub.future.result(0) if sub.future.done() else None
            reason = "timeout"
        except Exception:
            result = None
        wait_ms = (time.perf_counter() - sub.t0) * 1e3
        if result is None:
            return self._solo(reason)
        partials, batch_size, exec_ms = result
        annotate(batched=True, batch_size=batch_size,
                 queue_wait_ms=round(wait_ms - exec_ms, 3),
                 fused_share_ms=round(
                     exec_ms * sub.n_items / max(batch_size, 1), 3))
        return partials

    # -- fused execution (leader thread) -----------------------------------
    def _lead(self, key, spec: CubeSpec, batch: List[_Submission],
              own: _Submission) -> Optional[List]:
        t_exec = time.perf_counter()
        try:
            results = self._execute_fused(key, spec, batch)
        except BaseException as e:  # noqa: BLE001 — followers must not hang
            for sub in batch:
                if sub is not own and not sub.future.done():
                    sub.future.set_result(None)
            global_metrics.count("fused_dispatch_errors")
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            return self._solo("leader_error")
        exec_ms = (time.perf_counter() - t_exec) * 1e3
        self._record_ms(key, exec_ms)
        n_queries = len(batch)
        global_metrics.count("batched_dispatches")
        global_metrics.count("batched_queries", n_queries)
        global_metrics.count(_size_bucket(n_queries))
        from .accounting import global_accountant
        for sub in batch:
            # a follower that abandoned the wait (deadline margin) is
            # answering solo: its fused results are discarded, so it
            # must not be reported as batched
            if sub.query_id and not sub.abandoned:
                global_accountant.note_batched(sub.query_id, n_queries)
            if sub is not own:
                sub.future.set_result(
                    (results[id(sub)], n_queries, exec_ms))
        annotate(batched=True, batch_size=n_queries, leader=True,
                 fused_items=sum(s.n_items for s in batch),
                 queue_wait_ms=round(
                     (t_exec - own.t0) * 1e3, 3),
                 fused_ms=round(exec_ms, 3))
        return results[id(own)]

    def _execute_fused(self, key, spec: CubeSpec,
                       batch: List[_Submission]) -> Dict[int, List]:
        from ..ops.plan_cache import global_cube_cache
        from .executor import extract_partial

        items: List[Tuple[_Submission, Any, Tuple]] = []
        for sub in batch:
            for plan, params in zip(sub.plans, sub.resolved):
                items.append((sub, plan, params))

        # per-unique-segment cubes (cached device-resident; one unmasked
        # scan each on a cold cache, zero scans when warm)
        seg_order: Dict[int, int] = {}
        seg_plans: List[Any] = []
        for _sub, plan, _p in items:
            uid = plan.segment.uid
            if uid not in seg_order:
                seg_order[uid] = len(seg_plans)
                seg_plans.append(plan)
        cubes: List[Dict[str, jax.Array]] = []
        for plan in seg_plans:
            cubes.append(global_cube_cache.entry(
                spec, plan.segment,
                lambda p=plan: self._build_cube(spec, p)))
        stacked = global_cube_cache.stacked(
            spec, [p.segment for p in seg_plans], cubes)

        # ragged pack: pow2-padded item axis (pads repeat item 0 and are
        # sliced off at unpack, so shapes stay cache-stable)
        n_items = len(items)
        npad = _pow2(n_items)
        seg_idx = np.zeros(npad, dtype=np.int32)
        for k, (_s, plan, _p) in enumerate(items):
            seg_idx[k] = seg_order[plan.segment.uid]
        params0 = items[0][2]
        stacked_params = tuple(
            jnp.stack([items[k][2][j] if k < n_items else params0[j]
                       for k in range(npad)])
            for j in range(len(params0)))
        fn = _kernels.get(
            ("combine", spec, len(cubes), npad,
             tuple((tuple(p.shape), str(p.dtype)) for p in params0)),
            lambda: build_cube_combine_kernel(spec))
        with span(ph.FUSED_EXECUTE, queries=len(batch), items=n_items,
                  padded=npad, segments=len(cubes),
                  cube_space=spec.cube_space):
            dev = fn(stacked, jnp.asarray(seg_idx), stacked_params)
            device_fence(dev)
            host = jax.device_get(dev)  # jaxlint: ok host-sync
        from .accounting import global_accountant
        # memory accounting is apportioned per participant (outputs are
        # [npad, ...] so every item owns an equal slice): piling the
        # whole batch onto the leader's query would make the heap
        # watcher kill it for the followers' footprint
        total_bytes = sum(np.asarray(v).nbytes  # jaxlint: ok host-sync
                          for v in host.values())
        per_item = total_bytes // max(npad, 1)
        for sub in batch:
            if sub.query_id:
                global_accountant.track_memory_for(
                    sub.query_id, per_item * sub.n_items)
        # unpack + extract per item on host numpy behind the single
        # fence above — host-sync [jaxlint baseline]
        results: Dict[int, List[Any]] = {id(s): [] for s in batch}
        for k, (sub, plan, _p) in enumerate(items):
            per_item = {name: v[k] for name, v in host.items()}
            results[id(sub)].append(extract_partial(plan, per_item))
        return results

    def _build_cube(self, spec: CubeSpec, plan) -> Dict[str, jax.Array]:
        from .executor import resolve_params
        seg = plan.segment
        fn = _kernels.get(("cube", spec),
                          lambda: build_cube_kernel(spec))
        with span(ph.CUBE_BUILD, segment=seg.name, bucket=seg.bucket,
                  cube_space=spec.cube_space):
            cols = seg.device_cols(plan.col_names)
            params = resolve_params(plan)
            out = fn(cols, jnp.int32(seg.n_docs), params)
            device_fence(out)
            return out

    def clear(self) -> None:
        """Test hook: drop kernel caches and estimates (the cube cache
        is cleared through ops/plan_cache.global_cube_cache)."""
        _kernels.clear()
        with self._lock:
            self._est_ms.clear()


def _size_bucket(n: int) -> str:
    for b in (2, 4, 8, 16, 32):
        if n <= b:
            return f"fused_batch_size_le_{b}"
    return "fused_batch_size_gt_32"


global_batcher = RaggedBatcher()


def batching_health(snapshot: Dict[str, Any]) -> Dict[str, Any]:
    """The micro-batching block the broker /metrics endpoint and /ui
    console render next to the scatter counters."""
    c = snapshot["counters"]
    out = {k: c.get(k, 0) for k in (
        "batched_dispatches", "batched_queries", "fused_dispatch_errors",
        "cube_cache_hits", "cube_cache_misses")}
    out["solo_fallbacks"] = {r: c.get(f"solo_fallback_{r}", 0)
                             for r in _SOLO_REASONS}
    out["batch_size_histogram"] = {
        f"le_{b}": c.get(f"fused_batch_size_le_{b}", 0)
        for b in (2, 4, 8, 16, 32)}
    out["batch_size_histogram"]["gt_32"] = c.get(
        "fused_batch_size_gt_32", 0)
    out["batch_queue_depth"] = snapshot["gauges"].get(
        "batch_queue_depth", 0)
    # live device bytes the fusion plane holds resident (utils/devmem
    # gauges mirrored by the cube cache) — rendered on /ui next to the
    # hit counters so cache pressure is visible where batching is tuned
    g = snapshot["gauges"]
    out["cube_cache_bytes"] = int(g.get("device_bytes_cube_cache", 0)
                                  + g.get("device_bytes_cube_stacked", 0))
    out["enabled"] = global_batcher.enabled
    return out
