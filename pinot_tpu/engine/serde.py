"""Partial-result wire format: the DataTable analog.

Reference parity: pinot-common/.../datatable/ (versioned server->broker
result serialization) + common/datablock/. Pinot ships row-wise binary
DataTables over Netty; here partials are the mergeable aggregation states
(engine/executor.py), JSON-encoded with type tags for the few non-JSON
state shapes (sets for DISTINCTCOUNT, tuples for AVG and group keys).
JSON keeps the wire debuggable; a packed binary codec can swap in behind
the same two functions without touching the broker or servers.
"""
from __future__ import annotations

from typing import Any, Dict, List

from .executor import AggPartial, GroupByPartial, SelectionPartial


def _enc_state(s: Any) -> Any:
    if isinstance(s, set):
        return {"__set__": sorted(s, key=lambda v: (str(type(v)), str(v)))}
    if isinstance(s, tuple):
        return {"__tuple__": [_enc_state(x) for x in s]}
    if isinstance(s, dict):
        # MODE value->count maps: JSON stringifies object keys, so ship as
        # pairs to keep numeric keys numeric
        return {"__dict__": [[_enc_state(k), _enc_state(v)]
                             for k, v in s.items()]}
    return s


def _dec_state(s: Any) -> Any:
    if isinstance(s, dict) and "__set__" in s:
        return set(s["__set__"])
    if isinstance(s, dict) and "__tuple__" in s:
        return tuple(_dec_state(x) for x in s["__tuple__"])
    if isinstance(s, dict) and "__dict__" in s:
        return {_dec_state(k): _dec_state(v) for k, v in s["__dict__"]}
    return s


def partial_to_wire(p: Any) -> Dict[str, Any]:
    if isinstance(p, AggPartial):
        return {"type": "agg", "states": [_enc_state(s) for s in p.states]}
    if isinstance(p, GroupByPartial):
        return {"type": "groupby",
                "groups": [[list(k), [_enc_state(s) for s in v]]
                           for k, v in p.groups.items()]}
    if isinstance(p, SelectionPartial):
        return {"type": "selection", "labels": p.labels,
                "rows": [list(r) for r in p.rows],
                "orderKeys": [list(k) for k in p.order_keys]}
    raise TypeError(f"unknown partial {type(p)}")


def partial_from_wire(d: Dict[str, Any]) -> Any:
    t = d["type"]
    if t == "agg":
        return AggPartial([_dec_state(s) for s in d["states"]])
    if t == "groupby":
        return GroupByPartial({tuple(k): [_dec_state(s) for s in v]
                               for k, v in d["groups"]})
    if t == "selection":
        return SelectionPartial(d["labels"],
                                [tuple(r) for r in d["rows"]],
                                [tuple(k) for k in d["orderKeys"]])
    raise ValueError(f"unknown partial type {t!r}")
