"""Set-operation combine: UNION / INTERSECT / EXCEPT [ALL].

Reference parity: pinot-query-runtime/.../runtime/operator/set/
{UnionOperator,IntersectOperator,MinusOperator}.java — the v2 engine's
set operators over transferable blocks. Here both sides are fully
reduced ResultTables (each side ran the normal scatter-gather/reduce
path), so the combine is a counter-based multiset merge on the broker:
UNION dedupes, INTERSECT keeps min multiplicity, EXCEPT subtracts, ALL
variants keep multiplicities. Column count must match; names come from
the left side, as in the reference.
"""
from __future__ import annotations

from collections import Counter
from typing import Any, List, Optional, Tuple

from ..query.sql import Identifier, Literal, SqlError
from .reduce import ResultTable, _OrderKey


def _key(row: tuple) -> tuple:
    # np scalars and python scalars of equal value must collide
    out = []
    for v in row:
        if hasattr(v, "item"):
            v = v.item()
        out.append(v)
    return tuple(out)


def combine_setop(op: str, all_: bool, left: ResultTable,
                  right: ResultTable) -> ResultTable:
    if len(left.columns) != len(right.columns):
        raise SqlError(
            f"set operation arms have {len(left.columns)} vs "
            f"{len(right.columns)} columns")
    lrows = [tuple(r) for r in left.rows]
    rrows = [tuple(r) for r in right.rows]
    if op == "union":
        if all_:
            rows = lrows + rrows
        else:
            seen = set()
            rows = []
            for r in lrows + rrows:
                k = _key(r)
                if k not in seen:
                    seen.add(k)
                    rows.append(r)
    elif op == "intersect":
        rc = Counter(_key(r) for r in rrows)
        rows = []
        if all_:
            for r in lrows:
                k = _key(r)
                if rc.get(k, 0) > 0:
                    rc[k] -= 1
                    rows.append(r)
        else:
            emitted = set()
            for r in lrows:
                k = _key(r)
                if k in rc and k not in emitted:
                    emitted.add(k)
                    rows.append(r)
    elif op == "except":
        rc = Counter(_key(r) for r in rrows)
        rows = []
        if all_:
            for r in lrows:
                k = _key(r)
                if rc.get(k, 0) > 0:
                    rc[k] -= 1
                else:
                    rows.append(r)
        else:
            rset = set(rc)
            emitted = set()
            for r in lrows:
                k = _key(r)
                if k not in rset and k not in emitted:
                    emitted.add(k)
                    rows.append(r)
    else:
        raise SqlError(f"unknown set operation {op!r}")
    out = ResultTable(list(left.columns), rows)
    out.num_segments = left.num_segments + right.num_segments
    out.num_docs_scanned = left.num_docs_scanned + right.num_docs_scanned
    return out


def order_limit_rows(result: ResultTable, order_by, limit: Optional[int],
                     offset: int) -> ResultTable:
    """Compound-level ORDER BY (output columns by name or 1-based
    position) + LIMIT/OFFSET."""
    rows = result.rows
    if order_by:
        idxs: List[Tuple[int, bool]] = []
        for o in order_by:
            if isinstance(o.expr, Identifier):
                name = o.expr.name
                if name not in result.columns:
                    raise SqlError(
                        f"ORDER BY column {name!r} not in output "
                        f"{result.columns}")
                idxs.append((result.columns.index(name), o.ascending))
            elif isinstance(o.expr, Literal) and \
                    isinstance(o.expr.value, int):
                pos = o.expr.value
                if not 1 <= pos <= len(result.columns):
                    raise SqlError(f"ORDER BY position {pos} out of range")
                idxs.append((pos - 1, o.ascending))
            else:
                raise SqlError(
                    "compound ORDER BY supports output columns and "
                    "1-based positions")
        rows = sorted(rows, key=lambda r: tuple(
            _OrderKey(r[i], asc) for i, asc in idxs))
    if offset:
        rows = rows[offset:]
    if limit is not None:
        rows = rows[:limit]
    result.rows = rows
    return result
