"""Query scheduler: admission control + ordered execution of query jobs.

Reference parity: pinot-core/.../query/scheduler/QueryScheduler.java:93
(submit -> ListenableFuture of serialized response), QuerySchedulerFactory
.java:45-47 (fcfs | prioritized by config key `query.scheduler.name`), and
the multi-level PriorityScheduler with per-group resource accounting
(scheduler/resources/). TPU-native shape: one query = a few large XLA
launches, so the scheduler's job is admission (bound concurrent queries so
device/HBM pressure stays sane) and ordering (priority queues per
workload), not thread juggling; execution itself stays in the caller's
callable.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional

from ..query.sql import SqlError
from ..utils.metrics import global_metrics
from .accounting import ResourceAccountant, global_accountant


class SchedulerRejectedError(SqlError):
    """Queue full — the 'server busy, scheduler rejected' analog
    (Pinot's SERVER_OUT_OF_CAPACITY, error code 211). A ``SqlError``
    (not a bare RuntimeError) so it can never escape the HTTP plane as
    a 500: cluster/http_util.JsonHandler renders any exception carrying
    ``error_code``/``retry_after_ms`` as structured retryable JSON, and
    the broker/server query handlers surface it the same way the
    overload sheds are surfaced (broker/workload.OverloadShedError)."""

    error_code = 211  # broker/workload.ERR_SERVER_OUT_OF_CAPACITY

    def __init__(self, msg: str, retry_after_ms: int = 200):
        super().__init__(msg)
        self.retry_after_ms = int(retry_after_ms)

    def payload(self):
        return {"error": str(self), "errorCode": self.error_code,
                "retryAfterMs": self.retry_after_ms}


class _Job:
    __slots__ = ("fn", "future", "query_id", "priority", "seq")

    def __init__(self, fn, future, query_id, priority, seq):
        self.fn = fn
        self.future = future
        self.query_id = query_id
        self.priority = priority
        self.seq = seq


class QueryScheduler:
    """Base: worker pool draining an ordered queue.

    FCFS = single priority level (arrival order); PriorityScheduler orders
    by (priority, arrival). Both bound the queue (admission control).
    """

    name = "fcfs"

    def __init__(self, num_workers: int = 4, max_pending: int = 64,
                 accountant: Optional[ResourceAccountant] = None):
        self.accountant = accountant or global_accountant
        self.max_pending = max_pending
        self._heap: list = []
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._stopped = False
        self._workers = [threading.Thread(target=self._run, daemon=True)
                         for _ in range(num_workers)]
        for w in self._workers:
            w.start()
        self.started = time.time()

    # -- submission --------------------------------------------------------
    def _priority_of(self, priority: int) -> int:
        return 0  # FCFS: arrival order only

    def submit(self, fn: Callable[[], Any], query_id: str,
               priority: int = 0) -> "Future[Any]":
        """Enqueue a query callable; returns a Future (QueryScheduler.submit
        ListenableFuture analog). Raises SchedulerRejectedError when the
        pending queue is full."""
        future: Future = Future()
        job = _Job(fn, future, query_id, self._priority_of(priority),
                   next(self._seq))
        with self._lock:
            if self._stopped:
                raise SchedulerRejectedError("scheduler stopped")
            if len(self._heap) >= self.max_pending:
                global_metrics.count("scheduler_rejected")
                # retryAfterMs scales with the backlog: a full queue of
                # short queries drains in tens of ms per entry
                raise SchedulerRejectedError(
                    f"{len(self._heap)} queries pending >= "
                    f"{self.max_pending}",
                    retry_after_ms=50 + 10 * len(self._heap))
            heapq.heappush(self._heap, (job.priority, job.seq, job))
            self._work.notify()
        return future

    def execute(self, fn: Callable[[], Any], query_id: str,
                priority: int = 0, timeout_s: Optional[float] = None) -> Any:
        return self.submit(fn, query_id, priority).result(timeout=timeout_s)

    # -- workers -----------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._heap and not self._stopped:
                    self._work.wait()
                if self._stopped and not self._heap:
                    return
                _, _, job = heapq.heappop(self._heap)
            if not job.future.set_running_or_notify_cancel():
                continue
            self.accountant.attach_thread(job.query_id)
            try:
                job.future.set_result(job.fn())
            except BaseException as e:  # noqa: BLE001 — future carries it
                job.future.set_exception(e)

    def pending(self) -> int:
        with self._lock:
            return len(self._heap)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            self._work.notify_all()


class _Bucket:
    __slots__ = ("items", "weight", "cond", "closed")

    def __init__(self, cond):
        self.items: list = []
        self.weight = 0
        self.cond = cond
        self.closed = False


class MicroBatchQueue:
    """Cross-query micro-batch admission window (PR 8 tentpole) — the
    scheduler grown beyond FCFS/priority ordering: instead of ordering
    independent jobs, it COLLECTS compatible in-flight submissions.

    The first ``offer`` for a compatibility key becomes the *leader*:
    it holds the admission window open (``window_s``) and returns every
    submission that arrived for the key — its own included — once the
    window expires or the batch fills (``max_items`` submissions or
    ``max_weight`` total weight). Later offers for an open key are
    *followers*: ``offer`` returns None immediately and the follower
    waits on whatever completion handle it attached to its item (the
    RaggedBatcher uses a Future). A key whose leader is already
    executing starts a fresh bucket, so submissions are never blocked
    behind a dispatch in flight.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: Dict[Any, _Bucket] = {}

    def depth(self) -> int:
        with self._lock:
            return sum(len(b.items) for b in self._buckets.values())

    def offer(self, key: Any, item: Any, window_s: float,
              max_items: int, max_weight: Optional[int] = None,
              weight: int = 1) -> Optional[list]:
        """-> the closed batch (leader) or None (follower)."""
        with self._lock:
            b = self._buckets.get(key)
            if b is not None and not b.closed:
                if max_weight is not None and b.items \
                        and b.weight + weight > max_weight:
                    # admitting this item would blow the weight budget
                    # (a hard resource bound, not a target): close the
                    # bucket for its leader and lead a fresh one below
                    b.closed = True
                    b.cond.notify_all()
                    if self._buckets.get(key) is b:
                        del self._buckets[key]
                else:
                    b.items.append(item)
                    b.weight += weight
                    if len(b.items) >= max_items or (
                            max_weight is not None
                            and b.weight >= max_weight):
                        b.closed = True
                        b.cond.notify_all()
                    return None
            b = _Bucket(threading.Condition(self._lock))
            b.items.append(item)
            b.weight += weight
            self._buckets[key] = b
            deadline = time.monotonic() + window_s
            while not b.closed:
                rem = deadline - time.monotonic()
                if rem <= 0 or len(b.items) >= max_items or (
                        max_weight is not None and b.weight >= max_weight):
                    break
                b.cond.wait(rem)
            b.closed = True
            if self._buckets.get(key) is b:
                del self._buckets[key]
            return list(b.items)


class FcfsScheduler(QueryScheduler):
    name = "fcfs"


class PriorityScheduler(QueryScheduler):
    """Lower priority value runs first; queries of equal priority are FCFS
    (multi-level queue analog of scheduler/PriorityScheduler.java)."""

    name = "priority"

    def _priority_of(self, priority: int) -> int:
        return priority


def make_scheduler(config: Optional[Dict[str, Any]] = None) -> QueryScheduler:
    """QuerySchedulerFactory.java:45-47 analog: pick by
    `query.scheduler.name`."""
    cfg = config or {}
    name = str(cfg.get("query.scheduler.name", "fcfs")).lower()
    workers = int(cfg.get("query.scheduler.workers", 4))
    pending = int(cfg.get("query.scheduler.max_pending", 64))
    cls = {"fcfs": FcfsScheduler, "priority": PriorityScheduler}.get(name)
    if cls is None:
        raise ValueError(f"unknown scheduler {name!r}; use fcfs|priority")
    return cls(num_workers=workers, max_pending=pending)
