"""Shared per-table execution: the one rollup-or-plan-then-batch loop.

Both the in-process broker (broker/broker.py) and the HTTP server node
(cluster/server_node.py) serve a query over a list of segments; this is
that loop in one place so fixes (rollup gating, tracing, upsert handling)
cannot drift between the two paths.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..query.context import QueryContext
from ..query.planner import CompiledPlan, SegmentPlanner
from ..startree.query import try_rollup_execute
from ..utils import phases as ph
from ..utils.spans import annotate, span
from ..utils.trace import Tracing
from .batch import execute_plans_batched


@dataclass
class TableExecution:
    plans: List[Optional[CompiledPlan]]         # None where rollup answered
    real_plans: List[CompiledPlan]
    partials: List[Any] = field(default_factory=list)
    rollup_segments: int = 0

    @property
    def pruned(self) -> int:
        return sum(1 for p in self.real_plans if p.kind == "pruned")

    @property
    def docs_scanned(self) -> int:
        return sum(p.segment.n_docs for p in self.real_plans
                   if p.kind in ("kernel", "host"))


def plan_segments(ctx: QueryContext, segments: List[Any],
                  use_rollups: bool = True) -> TableExecution:
    # one query = one plan-cache generation: the retrace detector flags
    # any kernel compile of a plan structure already warm from an
    # EARLIER query (ops/plan_cache.RetraceDetector). The accountant's
    # query id dedupes multi-table executions of one query (hybrid
    # offline+realtime) into a single warmup generation.
    from ..ops.plan_cache import global_plan_cache
    from .accounting import global_accountant
    global_plan_cache.detector.begin_query(
        global_accountant.current_query_id())
    plans: List[Optional[CompiledPlan]] = []
    precomputed: Dict[int, Any] = {}
    with Tracing.phase(ph.PLANNING), span(ph.PLANNING,
                                        segments=len(segments)):
        for i, seg in enumerate(segments):
            partial = (try_rollup_execute(ctx, seg)
                       if use_rollups and hasattr(seg, "metadata") else None)
            if partial is not None:
                precomputed[i] = partial
                plans.append(None)
            else:
                plans.append(SegmentPlanner(ctx, seg).plan())
        ex = TableExecution(plans, [p for p in plans if p is not None],
                            rollup_segments=len(precomputed))
        ex._precomputed = precomputed  # type: ignore[attr-defined]
        # segment-heat telemetry (utils/heat): one touch per executed
        # segment — the access signal the fleet rollup ranks hot
        # segments by and the future HBM tier admits on
        from ..utils.heat import global_segment_heat
        for p in ex.real_plans:
            if p.kind in ("kernel", "host"):
                global_segment_heat.touch(p.segment, ctx.table,
                                          p.segment.n_docs)
        if ex.real_plans:
            p0 = ex.real_plans[0]
            annotate(kinds=sorted({p.kind for p in ex.real_plans}),
                     rollup_segments=len(precomputed), pruned=ex.pruned)
            if p0.kind == "kernel":
                annotate(strategy=p0.kernel_plan.strategy,
                         est_sel=p0.est_selectivity,
                         slots_cap=p0.slots_cap,
                         cost_trace=p0.strategy_trace)
    return ex


def execute_planned(ex: TableExecution) -> List[Any]:
    """Run the batched device dispatch and interleave rollup partials back
    into input order."""
    with Tracing.phase(ph.EXECUTION), span(ph.EXECUTION,
                                          segments=len(ex.real_plans)):
        executed = list(execute_plans_batched(ex.real_plans))
    precomputed = getattr(ex, "_precomputed", {})
    executed = iter(executed)
    ex.partials = [precomputed[i] if p is None else next(executed)
                   for i, p in enumerate(ex.plans)]
    return ex.partials


def execute_segments(ctx: QueryContext, segments: List[Any],
                     use_rollups: bool = True) -> TableExecution:
    ex = plan_segments(ctx, segments, use_rollups)
    execute_planned(ex)
    return ex
