"""Batched segment execution: one device dispatch for many segments.

Reference parity: pinot-core/.../operator/combine/BaseCombineOperator
.java:83,99-117 — Pinot runs one task per segment on a thread pool and
merges. TPU-native: segments sharing a plan structure, bucket, and param
signature jit ONE vmapped kernel and launch ONCE — jax.vmap over the
stacked (n_segments, bucket) columns replaces the thread pool, and the
fixed per-execution dispatch cost (~65ms RPC floor on tunneled TPUs) is
paid once per query instead of once per segment. Per-segment partials are
sliced out of the stacked outputs host-side, so per-segment dictionaries
stay correct (unlike parallel/distributed.py, which requires shared
dictionaries in exchange for on-device psum combine).
"""
from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.kernels import build_kernel
from ..query.planner import CompiledPlan
from ..utils.devmem import global_device_memory
from ..utils.spans import annotate, device_fence, span
from .executor import execute_plan, extract_partial, resolve_params

# stacked-column cache: ((segment uid, name) pairs, cols, bucket) -> tuple
# of stacked device arrays; bounded LRU since segment sets change under
# realtime. Keyed by the segments' process-unique LOAD uid, not the name:
# segment names recur across tables and across reloads at the same bucket,
# and a name-only key served the PREVIOUS table's device data to exact-
# looking queries (round-9 chaos-soak find). The name rides along only for
# evict_stacks_containing.
_STACK_CACHE: "OrderedDict[Tuple, Tuple[jax.Array, ...]]" = OrderedDict()
_STACK_CACHE_MAX = 32
# _stacked_cols runs on broker pool / scheduler worker threads and
# evict_stacks_containing on the reload path: OrderedDict LRU mutation
# (move_to_end/popitem) is a multi-step linked-list relink that is NOT
# GIL-atomic (the segdir._CACHE_LOCK lesson; surfaced by concur CC201).
# The device-side stack BUILD stays outside the lock — a rare double
# build is benign (last insert wins), a corrupted LRU is not. The
# eviction epoch closes the build window: a stack built while an
# eviction ran may contain a just-evicted segment, and inserting it
# would resurrect device buffers the eviction claimed to free — such a
# build is returned uncached instead.
_STACK_LOCK = threading.Lock()
_EVICT_EPOCH = 0


def _seg_key(seg) -> Tuple[int, str]:
    # the uid is REQUIRED: an id() fallback would reintroduce the same
    # stale-data class via recycled addresses, because _STACK_CACHE
    # outlives the segment object (only ImmutableSegment reaches the
    # batched kernel path today — give any new segment type a uid)
    return (seg.uid, seg.name)


@functools.lru_cache(maxsize=512)
def _vmapped_kernel_cached(plan_struct, bucket: int, scatter: bool):
    from ..utils.compileplane import staged
    return staged(jax.jit(jax.vmap(build_kernel(plan_struct, bucket,
                                                scatter=scatter))),
                  "vmap_kernel", ("vmap", plan_struct, bucket, scatter))


def _vmapped_kernel(plan_struct, bucket: int):
    from ..ops.kernels import cpu_scatter_default

    return _vmapped_kernel_cached(plan_struct, bucket,
                                  cpu_scatter_default())


def _param_sig(params: Tuple[jax.Array, ...]) -> Tuple:
    return tuple((tuple(p.shape), str(p.dtype)) for p in params)


def _stacked_cols(plans: List[CompiledPlan], bucket: int
                  ) -> Tuple[jax.Array, ...]:
    key = (tuple(_seg_key(p.segment) for p in plans),
           tuple(plans[0].col_names), bucket)
    with _STACK_LOCK:
        hit = _STACK_CACHE.get(key)
        if hit is not None:
            _STACK_CACHE.move_to_end(key)
            return hit
        epoch = _EVICT_EPOCH
    cols = tuple(
        jnp.stack([p.segment.device_col(c, bucket) for p in plans])
        for c in plans[0].col_names)
    # a reload's superseded entry (same names, older uids) is left to
    # the 32-entry LRU: proactively deleting same-name entries would
    # make two LIVE tables with generic segment names evict each other's
    # stacks on every alternation
    with _STACK_LOCK:
        if _EVICT_EPOCH != epoch:
            # an eviction ran mid-build: this stack may include the
            # evicted segment — serve it to THIS query but never cache
            return cols
        _STACK_CACHE[key] = cols
        # device-memory telemetry: the stack cache is an HBM resident
        # the tiered store manages (utils/devmem, /debug/memory)
        global_device_memory.add("stack_cache", key,
                                 sum(int(c.nbytes) for c in cols))
        while len(_STACK_CACHE) > _STACK_CACHE_MAX:
            old_key, _old = _STACK_CACHE.popitem(last=False)
            global_device_memory.remove("stack_cache", old_key)
    # shared-budget admission (engine/tier.py), OUTSIDE _STACK_LOCK
    # (the demotion path re-enters evict_stacks_containing): a stack
    # insert can push HBM over budget — demote the coldest segments
    # outside this group's working set
    from .tier import global_tier
    global_tier.enforce(protect={u for u, _n in key[0]})
    return cols


def evict_stacks_containing(segment_name: str) -> None:
    """Drop stacked copies that include a segment (called from
    ImmutableSegment.evict_device so eviction actually frees HBM)."""
    global _EVICT_EPOCH
    with _STACK_LOCK:
        _EVICT_EPOCH += 1
        for key in [k for k in _STACK_CACHE
                    if any(n == segment_name for _, n in k[0])]:
            del _STACK_CACHE[key]
            global_device_memory.remove("stack_cache", key)


def clear_stack_cache() -> None:
    """Drop every stacked entry AND its device-memory accounting in
    one locked step (test isolation; not an eviction — no counters)."""
    global _EVICT_EPOCH
    with _STACK_LOCK:
        _EVICT_EPOCH += 1
        _STACK_CACHE.clear()
        global_device_memory.drop_pool("stack_cache")


def execute_plans_batched(plans: List[CompiledPlan]) -> List[Any]:
    """Execute all plans; kernel plans with matching structure run in one
    vmapped dispatch. Returns partials in input order."""
    results: List[Any] = [None] * len(plans)
    groups: Dict[Tuple, List[int]] = {}
    resolved: Dict[int, Tuple[jax.Array, ...]] = {}

    from ..ops.kernels import COMPACT_GROUP_LIMIT, segmented_compact_ok
    from .accounting import global_accountant
    for i, plan in enumerate(plans):
        # preemption point between per-segment launches (the hot-loop
        # ThreadAccountantOps.sample analog): raises on kill/timeout
        global_accountant.sample()
        if plan.kind != "kernel":
            results[i] = execute_plan(plan)
            continue
        kp = plan.kernel_plan
        # column shapes join the group key: same-plan segments can differ
        # in MV padded width (maxValues), and jnp.stack needs equal shapes
        shape_sig = tuple(
            getattr(plan.segment.columns[c], "max_values", None) or 0
            for c in plan.col_names)
        if kp.strategy == "compact":
            sv_only = all(getattr(plan.segment.columns[c],
                                  "single_value", True)
                          for c in plan.col_names)
            if segmented_compact_ok(kp) and sv_only:
                # compact group-bys batch via the segmented kernel: the
                # segment index becomes the leading group-key factor
                # (ops/kernels.build_segmented_compact_kernel), replacing
                # the per-segment launches the Pallas compaction forced
                params = resolve_params(plan)
                resolved[i] = params
                key = ("segc", kp, plan.segment.bucket,
                       _param_sig(params) + shape_sig)
                groups.setdefault(key, []).append(i)
            else:
                results[i] = execute_plan(plan)
            continue
        params = resolve_params(plan)
        resolved[i] = params
        key = ("dense", kp, plan.segment.bucket,
               _param_sig(params) + shape_sig)
        groups.setdefault(key, []).append(i)

    from .ragged import global_batcher
    for (kind, plan_struct, bucket, sig), idxs in groups.items():
        global_accountant.sample()
        if global_batcher.enabled:
            # cross-query micro-batching (PR 8): offer this group to the
            # ragged admission queue — concurrent queries sharing the
            # plan structure fuse into one cube-contraction launch.
            # None means dispatch solo (reason counted/annotated).
            fused = global_batcher.submit(
                [plans[i] for i in idxs], [resolved[i] for i in idxs],
                bucket, (kind,) + sig)
            if fused is not None:
                for k, i in enumerate(idxs):
                    results[i] = fused[k]
                continue
        n_seg = len(idxs)
        if n_seg == 1 or (kind == "segc" and n_seg * plan_struct.group_space
                          > COMPACT_GROUP_LIMIT):
            for i in idxs:
                results[i] = execute_plan(plans[i])
            continue
        group_plans = [plans[i] for i in idxs]
        if kind == "dense":
            from .pipeline import (execute_kernel_plans_pipelined,
                                   group_stack_bytes, hbm_budget_bytes)
            if group_stack_bytes(group_plans, bucket) > hbm_budget_bytes():
                # working set exceeds the HBM budget: stream segments
                # through the double-buffered pipeline instead of
                # staking everything resident (engine/pipeline.py)
                partials = execute_kernel_plans_pipelined(
                    plans, plan_struct, bucket, resolved, idxs)
                for k, i in enumerate(idxs):
                    results[i] = partials[k]
                continue
        # tier access hook BEFORE the stack build: a warm stack hit
        # never reaches device_col, so this is where the tier.evict
        # chaos point can force a mid-query demotion of a segment this
        # group is using (the build below then re-promotes it)
        from .tier import global_tier
        for p in group_plans:
            global_tier.on_access(p.segment)
        # pin the group's working set for the WHOLE dispatch (stack
        # build through extraction): a budget demotion triggered from
        # THIS thread — the group's own admissions, or a nested plan-
        # cache accumulator registration — must pick victims outside it
        # (engine/tier.py, anti-thrash)
        with global_tier.pinned({p.segment.uid for p in group_plans}):
            cols = _stacked_cols(group_plans, bucket)
            n_docs = jnp.asarray([p.segment.n_docs for p in group_plans],
                                 dtype=jnp.int32)
            params = tuple(
                jnp.stack([resolved[i][j] for i in idxs])
                for j in range(len(resolved[idxs[0]])))
            if kind == "segc":
                _run_segmented_compact(plans, idxs, plan_struct, bucket,
                                       cols, n_docs, params, results)
                continue
            with span("vmap_dispatch", segments=n_seg, bucket=bucket,
                      strategy=plan_struct.strategy):
                _maybe_profile_phases(group_plans[0])
                fn = _vmapped_kernel(plan_struct, bucket)
                with span("device_execute"):
                    dev = fn(cols, n_docs, params)
                    device_fence(dev)
                with span("device_transfer"):
                    out = jax.device_get(dev)  # jaxlint: ok host-sync
                global_accountant.track_result(out)
                # per-segment slicing below runs on host numpy behind
                # the single fence above — host-sync [jaxlint baseline]
                for k, i in enumerate(idxs):
                    per_seg = {name: v[k] for name, v in out.items()}
                    if int(per_seg.pop("group_overflow", 0)):
                        # this segment alone exceeded the transfer-
                        # compaction cap; rerun it solo, straight to
                        # dense outputs
                        from .executor import run_kernel
                        dense = run_kernel(plans[i], xfer_compact=False)
                        results[i] = extract_partial(plans[i], dense)
                    else:
                        results[i] = extract_partial(plans[i], per_seg)
    return results


def _maybe_profile_phases(plan: CompiledPlan) -> None:
    """EXPLAIN ANALYZE OPTION(profilePhases=true) on a batched dispatch:
    attach the phase ladder of ONE representative segment (the group
    shares plan structure and bucket, so phases scale uniformly) as
    child spans — the fused paths bypass run_kernel's attach point."""
    from ..query.planner import _truthy
    from ..utils.spans import tracing_active
    if not (tracing_active()
            and _truthy(plan.ctx.options.get("profilePhases"))):
        return
    from ..ops.phase_profile import attach_phase_spans, profile_plan
    with span("phase_profile", segment=plan.segment.name,
              representative=True):
        attach_phase_spans(profile_plan(plan, iters=2))


def _run_segmented_compact(plans, idxs, plan_struct, bucket, cols, n_docs,
                           params, results) -> None:
    """One device program for S same-plan compact group-by segments;
    slices the (S*space,) dense outputs apart and extracts per segment."""
    from ..ops.compact import full_slots_cap
    from ..ops.kernels import jitted_segmented_compact
    from .accounting import global_accountant

    n_seg = len(idxs)
    # cost-model capacity scaled to the combined live rows of the fused
    # dispatch (ROADMAP: no heuristic default caps on segmented paths)
    from ..multistage.costs import scaled_compact_cap
    cap = scaled_compact_cap(plans[idxs[0]],
                             sum(plans[i].segment.n_docs for i in idxs))
    with span("segmented_compact_dispatch", segments=n_seg, bucket=bucket,
              slots_cap=cap, est_sel=plans[idxs[0]].est_selectivity):
        _maybe_profile_phases(plans[idxs[0]])
        fn = jitted_segmented_compact(plan_struct, bucket, n_seg, cap)
        with span("device_execute"):
            dev = fn(cols, n_docs, params)
            device_fence(dev)
        out = jax.device_get(dev)  # jaxlint: ok host-sync
        # retry-ladder checks + slicing below read host numpy behind the
        # fence above — host-sync [jaxlint baseline]
        from ..ops.plan_cache import global_plan_cache
        if int(out.pop("overflow", 0)):
            cap = full_slots_cap(n_seg * bucket)
            # expected() bracket: the full-capacity recompile is a
            # deliberate retry, counted overflow_retry in the
            # compile-event taxonomy — never a retrace
            with span("overflow_retry", slots_cap=cap), \
                    global_plan_cache.detector.expected():
                fn = jitted_segmented_compact(plan_struct, bucket, n_seg,
                                              cap)
                out = jax.device_get(fn(cols, n_docs, params))
            out.pop("overflow", None)
            annotate(overflow_retry=True, slots_cap=cap)
        if int(out.pop("group_overflow", 0)):
            with span("group_overflow_retry"), \
                    global_plan_cache.detector.expected():
                fn = jitted_segmented_compact(plan_struct, bucket, n_seg,
                                              cap, xfer_compact=False)
                out = jax.device_get(fn(cols, n_docs, params))
            out.pop("overflow", None)
            annotate(group_overflow_retry=True)
        global_accountant.track_result(out)
    space = plan_struct.group_space
    matched = out.pop("matched")
    gi = out.pop("group_idx", None)
    for k, i in enumerate(idxs):
        per_seg = {"matched": matched[k]}
        if gi is not None:
            # transfer-compacted: rows are live groups of the combined
            # S*space; this segment owns flat ids [k*space, (k+1)*space)
            rows = np.nonzero((gi >= k * space) & (gi < (k + 1) * space)
                              & (np.asarray(out["group_count"]) > 0))[0]
            per_seg["group_idx"] = np.asarray(gi)[rows] - k * space
            for name, v in out.items():
                per_seg[name] = np.asarray(v)[rows]
        else:
            for name, v in out.items():
                v = np.asarray(v)
                if v.ndim >= 1 and v.shape[0] == n_seg * space:
                    per_seg[name] = v.reshape(
                        (n_seg, space) + v.shape[1:])[k]
                else:
                    per_seg[name] = v
        results[i] = extract_partial(plans[i], per_seg)
