"""HBM-tiered segment store: hot / warm / cold under ONE device budget.

Pinot's entire performance layer is off-heap mmap (PAPER.md §2.9); the
TPU analog is HBM residency. Before this module every device cache —
segment columns (segment/immutable), the stack cache (engine/batch),
the cube caches (ops/plan_cache.CubeCache) and the donated plan-cache
accumulators — grew unboundedly and independently, so a node serving
more table-bytes than fit in HBM either OOMed or re-uploaded per query.
This is the managed memory hierarchy ROADMAP direction 1 called for:

- **hot**: a segment's padded columns resident in HBM (uid-keyed, the
  ``ImmutableSegment._device`` cache);
- **warm**: the padded host arrays kept after a demotion, ready to
  ``jax.device_put`` without re-reading/re-padding the mmap;
- **cold**: mmap on disk only (the load state every segment starts in).

Admission is driven by use: any ``device_col`` upload promotes the
segment hot and charges the shared budget. The budget is ONE number —
``PINOT_HBM_BUDGET_BYTES`` (also the resident-vs-streamed group router
knob in engine/pipeline.py) or ``configure(budget_bytes=...)`` — summed
across ALL devmem pools (utils/devmem.POOLS), and an over-budget
admission demotes the **coldest** hot segments first, ranked by
``utils/heat.SegmentHeat``'s time-decayed scores with the uid as the
deterministic tiebreak: the same heat sequence always produces the
same promote/demote decisions (``decisions`` is the replayable log the
state-machine test pins). Demoting a segment drops its device columns
AND every stacked/cube copy that contains it (the round-9 eviction
discipline), so the accounting in utils/devmem reconciles exactly
across demotions. A query touching a demoted segment transparently
re-promotes through the normal ``device_col`` path — warm arrays skip
the host-side re-pad — with digests byte-identical regardless of tier
placement (same arrays, same kernels; the plan cache keeps the
compiled executables, so re-promotion never retraces).

Enforcement is edge-triggered and slightly soft: the budget is checked
at every admission, with the admitting working set protected (demoting
the segment a query is mid-upload on would thrash), so one admission
whose group IS the whole hot set can overshoot transiently and is
reconciled at the next admission. The default budget is **unbounded**
(env var absent): tier-1 and the env-pinned baselines run exactly the
round-14 behavior, and warm host copies are only kept while a budget
is armed.

Chaos: the ``tier.evict`` fault point (utils/faults.py, per-(query id,
site key) stream discipline) fires in ``on_access`` and force-demotes
the touched segment MID-QUERY; the query must re-promote and finish
byte-exact (tools/chaos_smoke.py ``--tier``).

Counters/gauges: ``tier_promotions`` / ``tier_demotions`` (+ broker-
side ``tier_affinity_hits`` / ``tier_affinity_misses``) in
global_metrics, per-query in ``query_stats``, fleet-aggregated by
cluster/rollup.py; occupancy gauges (``tier_hot_bytes`` etc.) feed
/debug/memory, broker /metrics + /ui and the controller Fleet view.
"""
from __future__ import annotations

import os
import threading
import weakref
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Set, Tuple

from ..utils.devmem import POOLS, global_device_memory
from ..utils.heat import global_segment_heat
from ..utils.metrics import global_metrics

TIER_HOT, TIER_WARM, TIER_COLD = "hot", "warm", "cold"
MAX_DECISIONS = 4096
_UNSET = object()


def env_budget_bytes() -> Optional[int]:
    """The tier budget from PINOT_HBM_BUDGET_BYTES — only when the
    operator set it explicitly (None = unbounded, the tier-1 default;
    engine/pipeline.py's group router keeps its own 8 GB default for
    the resident-vs-streamed decision)."""
    raw = os.environ.get("PINOT_HBM_BUDGET_BYTES")
    if not raw:
        return None
    try:
        return max(int(raw), 1)
    except ValueError:
        return None


def env_warm_budget_bytes() -> Optional[int]:
    """Optional host-side warm-tier bound (PINOT_WARM_BUDGET_BYTES):
    over it, the coldest warm segments drop to cold (mmap only)."""
    raw = os.environ.get("PINOT_WARM_BUDGET_BYTES")
    if not raw:
        return None
    try:
        return max(int(raw), 1)
    except ValueError:
        return None


class TierManager:
    """The hot/warm/cold segment state machine (module docstring).

    Thread discipline: ``_lock`` is a LEAF lock — it guards only the
    registry/state/log dicts and is NEVER held while calling into a
    segment's demotion path (which takes the stack/cube cache locks);
    victims are selected under the lock, the demotion executes outside
    it. A concurrent re-admission between selection and execution is
    benign: the state heals at the next transition and the data path
    re-promotes through device_col either way."""

    def __init__(self, devmem=None, heat=None,
                 budget_bytes: Optional[int] = None,
                 warm_budget_bytes: Optional[int] = None):
        self._devmem = devmem if devmem is not None else \
            global_device_memory
        self._heat = heat if heat is not None else global_segment_heat
        self._lock = threading.Lock()
        self._refs: Dict[int, Any] = {}            # uid -> weakref
        self._state: Dict[int, str] = {}           # uid -> tier
        self._names: Dict[int, str] = {}           # uid -> segment name
        self._warm_bytes: Dict[int, int] = {}      # uid -> host bytes
        # GC'd uids pending removal: fed by the weakref callbacks
        # WITHOUT the lock (GC can run the callback on a thread
        # already holding _lock), drained by _reap_locked
        self._dead: List[int] = []
        self._budget = budget_bytes                # None -> env
        self._warm_budget = warm_budget_bytes      # None -> env
        # thread-local pin set: the segments of the group THIS thread
        # is currently staking resident (engine/batch wraps the stack
        # build + dispatch) — never demotion victims, or an admission
        # mid-stack would evict its own working set (thrash)
        self._pins = threading.local()
        self.promotions = 0
        self.demotions = 0
        # replayable decision log: (action, segment, from, to, reason)
        # — the state-machine determinism contract (same heat sequence
        # => same decisions)
        self.decisions: List[Tuple[str, str, str, str, str]] = []

    # -- configuration -----------------------------------------------------
    @property
    def budget_bytes(self) -> Optional[int]:
        return self._budget if self._budget is not None \
            else env_budget_bytes()

    @property
    def warm_budget_bytes(self) -> Optional[int]:
        return self._warm_budget if self._warm_budget is not None \
            else env_warm_budget_bytes()

    @property
    def armed(self) -> bool:
        """True when an HBM budget is in force (warm host copies are
        only stashed while armed — unbounded runs stay byte-for-byte
        the pre-tier behavior)."""
        return self.budget_bytes is not None

    def configure(self, budget_bytes: Any = _UNSET,
                  warm_budget_bytes: Any = _UNSET) -> None:
        """Set/clear the budgets from code (None reverts to the env)."""
        if budget_bytes is not _UNSET:
            self._budget = budget_bytes
        if warm_budget_bytes is not _UNSET:
            self._warm_budget = warm_budget_bytes
        self.enforce()

    # -- bookkeeping ---------------------------------------------------------
    def _reap_locked(self) -> None:  # holds-lock: _lock
        # drain the GC'd-segment queue (the weakref callbacks feed
        # ``_dead`` lock-free — a callback can fire during GC on a
        # thread that ALREADY holds _lock, so taking the lock there
        # would self-deadlock)
        while self._dead:
            uid = self._dead.pop()  # jaxlint: ok unlocked-mutation
            self._refs.pop(uid, None)  # jaxlint: ok unlocked-mutation
            self._state.pop(uid, None)  # jaxlint: ok unlocked-mutation
            self._names.pop(uid, None)  # jaxlint: ok unlocked-mutation
            self._warm_bytes.pop(uid, None)  # jaxlint: ok unlocked-mutation

    def _register_locked(self, segment) -> None:  # holds-lock: _lock
        self._reap_locked()
        uid = segment.uid
        if uid not in self._refs:
            # the GC-time callback feeds _dead DELIBERATELY without
            # the lock: list.append is GIL-atomic, and GC can fire the
            # callback on a thread already holding _lock — taking it
            # there would self-deadlock (the CC203 this replaces)
            self._refs[uid] = weakref.ref(  # jaxlint: ok unlocked-mutation
                segment,
                lambda _r, u=uid: self._dead.append(u))  # jaxlint: ok unlocked-mutation # concur: ok CC201
            self._names[uid] = segment.name  # jaxlint: ok unlocked-mutation
            self._state[uid] = TIER_COLD  # jaxlint: ok unlocked-mutation

    def _log_locked(self, action: str, name: str, frm: str, to: str,
                    reason: str) -> None:  # holds-lock: _lock
        self.decisions.append((action, name, frm, to, reason))  # jaxlint: ok unlocked-mutation
        if len(self.decisions) > MAX_DECISIONS:
            del self.decisions[: MAX_DECISIONS // 2]  # jaxlint: ok unlocked-mutation

    def note_warm(self, uid: int, delta: int) -> None:
        """Warm host-array accounting (segment/immutable stashes/drops
        padded host copies through here)."""
        with self._lock:
            n = self._warm_bytes.get(uid, 0) + int(delta)
            if n > 0:
                self._warm_bytes[uid] = n
            else:
                self._warm_bytes.pop(uid, None)

    def _hbm_bytes(self) -> int:
        """Live HBM bytes across ALL accounted pools — the one number
        the shared budget compares against."""
        return sum(self._devmem.pool_bytes(p) for p in POOLS)

    # -- transitions ---------------------------------------------------------
    def admitted(self, segment) -> None:
        """A device-cache insert landed for ``segment`` (the ONE
        admission edge: segment/immutable._cache_device). Registers the
        segment, counts the cold/warm->hot promotion, then enforces the
        shared budget with this segment protected."""
        uid = segment.uid
        promoted = prev = None
        with self._lock:
            self._register_locked(segment)
            prev = self._state.get(uid, TIER_COLD)
            if prev != TIER_HOT:
                self._state[uid] = TIER_HOT
                self.promotions += 1
                self._log_locked("promote", segment.name, prev,
                                 TIER_HOT, "access")
                promoted = True
        if promoted:
            global_metrics.count("tier_promotions")
        self.enforce(protect={uid})

    def on_access(self, segment) -> None:
        """Per-column-read hook on the device_col path: one attribute
        read when no chaos plan is armed; under a plan the ``tier.evict``
        point can force a MID-QUERY demotion (the query then re-promotes
        and must finish byte-exact)."""
        from ..utils.faults import fault_fires
        if fault_fires("tier.evict", key=segment.name):
            self.demote(segment, TIER_WARM, reason="fault")

    def demote(self, segment, to: str = TIER_WARM,
               reason: str = "") -> bool:
        """HBM -> host: drop the segment's device residents (and every
        stacked/cube copy containing it); the padded host arrays stay
        warm unless ``to=TIER_COLD`` (host -> disk, mmap only).
        Returns True when a transition actually happened."""
        uid = segment.uid
        drop_warm = to == TIER_COLD
        with self._lock:
            self._register_locked(segment)
            prev = self._state.get(uid, TIER_COLD)
            if prev == TIER_COLD or (prev == TIER_WARM and not drop_warm):
                return False
            self._state[uid] = to
            self.demotions += 1
            self._log_locked("demote", segment.name, prev, to,
                             reason or "explicit")
        # the demotion body runs OUTSIDE _lock (it takes the stack and
        # cube cache locks; _lock stays a leaf)
        segment.demote_device(drop_warm=drop_warm)
        global_metrics.count("tier_demotions")
        self._export()
        return True

    def drain(self, name: str, reason: str = "drain",
              table: Optional[str] = None) -> int:
        """Rebalance drain entry point (cluster/rebalancer.py): warm-
        demote every live HOT copy of the named segment — device
        residents drop, the padded host arrays stay warm, so there is
        NO cold re-pad if the copy is touched again and in-flight
        queries finish on references they already acquired. In-process
        replicas register distinct segment objects under the same name;
        a drain demotes them all (a receiver that just pre-warmed
        re-promotes from its warm arrays on first touch — cheap
        device_put, digests unaffected). Segment names recur ACROSS
        tables too, so pass ``table`` to demote only copies whose
        schema carries that table — an unrelated table sharing the
        name must not pay a re-promotion. Returns demotions
        performed."""
        with self._lock:
            self._reap_locked()
            uids = sorted(uid for uid, n in self._names.items()
                          if n == name
                          and self._state.get(uid) == TIER_HOT)
            segs = []
            for uid in uids:
                ref = self._refs.get(uid)
                seg = ref() if ref is not None else None
                if seg is None:
                    continue
                if table is not None and \
                        getattr(getattr(seg, "schema", None),
                                "name", None) != table:
                    continue
                segs.append(seg)
        n = 0
        for seg in segs:  # demote takes _lock itself (leaf) — call outside
            if self.demote(seg, TIER_WARM, reason=reason):
                n += 1
        return n

    def on_evicted(self, segment) -> None:
        """ImmutableSegment.evict_device (unload/reload path): the
        segment left the hierarchy entirely — mark cold, no demotion
        counters (an unload is not a budget decision)."""
        with self._lock:
            if segment.uid in self._state:
                self._state[segment.uid] = TIER_COLD
            self._warm_bytes.pop(segment.uid, None)
        self._export()

    # -- budget enforcement --------------------------------------------------
    @contextmanager
    def pinned(self, uids):
        """Pin a working set for the enclosed dispatch on THIS thread
        (engine/batch group execution): pinned segments are never
        budget-demotion victims. Stacks nest; chaos demotions
        (tier.evict) ignore pins on purpose — they test correctness,
        not placement policy."""
        prev = getattr(self._pins, "uids", frozenset())
        self._pins.uids = prev | set(uids)
        try:
            yield
        finally:
            self._pins.uids = prev

    def enforce(self, protect: Optional[Set[int]] = None) -> int:
        """Demote coldest-first until HBM is back under budget; the
        ``protect`` uids plus this thread's pinned working set are
        never victims. Returns the number of demotions performed."""
        budget = self.budget_bytes
        n = 0
        if budget is not None:
            protect = (protect or frozenset()) \
                | getattr(self._pins, "uids", frozenset())
            total = self._hbm_bytes()
            if total > budget:
                scores = self._heat.scores()
                for _score, uid, seg in self._victims(scores, TIER_HOT,
                                                      protect):
                    if total <= budget:
                        break
                    if self.demote(seg, TIER_WARM, reason="budget"):
                        n += 1
                        total = self._hbm_bytes()
        n += self._enforce_warm()
        if n:
            self._export()
        return n

    def _victims(self, scores: Dict[Any, float], state: str,
                 protect: Set[int]) -> List[Tuple[float, int, Any]]:
        """Live candidate segments in ``state``, coldest-first with the
        uid as the deterministic tiebreak."""
        with self._lock:
            cands = sorted(
                (scores.get(uid, 0.0), uid)
                for uid, st in self._state.items()
                if st == state and uid not in protect
                and uid in self._refs)
        out = []
        for score, uid in cands:
            with self._lock:
                ref = self._refs.get(uid)
            seg = ref() if ref is not None else None
            if seg is not None:
                out.append((score, uid, seg))
        return out

    def _enforce_warm(self) -> int:
        budget = self.warm_budget_bytes
        if budget is None:
            return 0
        with self._lock:
            total = sum(self._warm_bytes.values())
        if total <= budget:
            return 0
        n = 0
        scores = self._heat.scores()
        for _score, uid, seg in self._victims(scores, TIER_WARM,
                                              frozenset()):
            if total <= budget:
                break
            if self.demote(seg, TIER_COLD, reason="warm_budget"):
                n += 1
            with self._lock:
                total = sum(self._warm_bytes.values())
        # HOT segments stash warm copies too (for their eventual
        # demotion) — when warm-state victims alone can't reach the
        # budget, trim the coldest hot segments' stashes WITHOUT
        # touching their device residents (the next demotion re-pads
        # from mmap instead)
        if total > budget:
            for _score, uid, seg in self._victims(scores, TIER_HOT,
                                                  frozenset()):
                if total <= budget:
                    break
                drop = getattr(seg, "drop_warm", None)
                if drop is not None and drop():
                    # logged only when a stash actually dropped — the
                    # decision log stays a faithful replay, not a visit
                    # trace
                    self._log_warm_trim(seg)
                with self._lock:
                    total = sum(self._warm_bytes.values())
        return n

    def _log_warm_trim(self, segment) -> None:
        with self._lock:
            self._log_locked("trim_warm", segment.name, TIER_HOT,
                             TIER_HOT, "warm_budget")

    # -- serving -------------------------------------------------------------
    def occupancy(self) -> Dict[str, Any]:
        """{tier: {segments, bytes}} occupancy. Hot bytes are the
        accounted segment-column pool (stack/cube copies are charged to
        their own pools); warm bytes are the stashed host arrays."""
        with self._lock:
            counts = {TIER_HOT: 0, TIER_WARM: 0, TIER_COLD: 0}
            for st in self._state.values():
                counts[st] = counts.get(st, 0) + 1
            warm_b = sum(self._warm_bytes.values())
        return {
            "hot": {"segments": counts[TIER_HOT],
                    "bytes": self._devmem.pool_bytes("segment_cols")},
            "warm": {"segments": counts[TIER_WARM], "bytes": warm_b},
            "cold": {"segments": counts[TIER_COLD]},
        }

    def snapshot(self) -> Dict[str, Any]:
        """The tier block /debug/memory, broker /metrics and the fleet
        rollup carry."""
        budget = self.budget_bytes
        out = {
            "armed": budget is not None,
            "budget_bytes": budget or 0,
            "hbm_used_bytes": self._hbm_bytes(),
            "promotions": self.promotions,
            "demotions": self.demotions,
            **self.occupancy(),
        }
        self._export(out)
        return out

    def _export(self, snap: Optional[Dict[str, Any]] = None) -> None:
        """Mirror occupancy into global_metrics gauges (consoles +
        Prometheus)."""
        s = snap if snap is not None else {
            "budget_bytes": self.budget_bytes or 0,
            "hbm_used_bytes": self._hbm_bytes(),
            **self.occupancy()}
        global_metrics.gauge("tier_budget_bytes", s["budget_bytes"])
        global_metrics.gauge("tier_hbm_used_bytes", s["hbm_used_bytes"])
        for t in (TIER_HOT, TIER_WARM):
            global_metrics.gauge(f"tier_{t}_bytes", s[t]["bytes"])
            global_metrics.gauge(f"tier_{t}_segments", s[t]["segments"])
        global_metrics.gauge("tier_cold_segments",
                             s["cold"]["segments"])

    def clear(self) -> None:
        """Test isolation: forget every registration and counter (the
        segments' own caches are untouched — the conftest fixture
        clears those through their devmem-synced paths)."""
        with self._lock:
            self._refs.clear()
            self._state.clear()
            self._names.clear()
            self._warm_bytes.clear()
            del self._dead[:]
            self.promotions = 0
            self.demotions = 0
            self.decisions = []
        self._budget = None
        self._warm_budget = None


def segment_tier(segment) -> str:
    """Observed tier of one segment object (the residency heartbeats
    report): hot = device residents, warm = stashed padded host arrays,
    else cold."""
    if getattr(segment, "_device", None):
        return TIER_HOT
    if getattr(segment, "_warm", None):
        return TIER_WARM
    return TIER_COLD


def tier_health(snapshot: Dict[str, Any]) -> Dict[str, Any]:
    """The tier block broker /metrics + /ui render: live occupancy plus
    the affinity-routing hit ratio derived from the counters."""
    c = snapshot.get("counters", {})
    hits = c.get("tier_affinity_hits", 0)
    misses = c.get("tier_affinity_misses", 0)
    out = dict(global_tier.snapshot())
    out["affinity_hits"] = hits
    out["affinity_misses"] = misses
    out["affinity_hit_ratio"] = round(hits / (hits + misses), 4) \
        if hits + misses else None
    return out


def reconcile_devmem(segments, pools=None) -> Dict[str, Dict[str, int]]:
    """tracked-vs-actual bytes per HBM pool — the bench/test check that
    NO promote/demote/evict path leaks accounting ("zero unaccounted
    devmem bytes"). ``segments`` is the full live segment set whose
    device caches back the segment_cols pool. Reads the caches'
    internals; verification-only, never on a serving path. Callers in
    long-lived/shared processes must start from devmem-synced caches
    (the pytest fixture resets accounting but keeps warm cube/plan
    entries — clear those first, or pass ``pools`` to restrict the
    check to the pools that ARE synced; e.g. chaos_smoke --tier skips
    plan_cache_acc, whose donated buffers are suite-wide compile
    warmth it must not wipe)."""
    from ..engine import batch as eb
    from ..index import vector as vix
    from ..ops.plan_cache import global_cube_cache, global_plan_cache
    from ..utils.devmem import nbytes_of
    actual = {
        "segment_cols": sum(
            int(a.nbytes) for s in segments
            for a in list(getattr(s, "_device", {}).values())),
        "vector": sum(r.device_bytes() for r in vix.live_readers()),
        "stack_cache": sum(nbytes_of(v)
                           for v in list(eb._STACK_CACHE.values())),
        "cube_cache": sum(
            nbytes_of(v)
            for v in list(global_cube_cache._entries.values())),
        "cube_stacked": sum(
            nbytes_of(v)
            for v in list(global_cube_cache._stacked.values())),
        "plan_cache_acc": sum(
            nbytes_of(e._acc)
            for e in list(global_plan_cache._entries.values())
            if e._acc is not None),
    }
    snap = global_device_memory.snapshot()
    return {p: {"tracked": snap.get(p, {}).get("bytes", 0),
                "actual": actual[p]}
            for p in (pools if pools is not None else actual)}


global_tier = TierManager()
