"""Per-segment execution: run the compiled plan, extract mergeable partials.

Reference parity: pinot-core/.../query/executor/ServerQueryExecutorV1Impl
.java:134 + operator/combine/BaseCombineOperator.java:99-117. Pinot runs one
task per segment on a thread pool and merges; here each segment is one XLA
program launch (the device's internal parallelism replaces the thread pool)
and partial states come back as host numpy to merge at reduce. vmap over
same-bucket segment batches and on-device psum combine live in
parallel/distributed.py.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..ops import aggregations
from ..query.context import QueryContext
from ..query.sql import Star
from ..query.planner import AggBinding, CompiledPlan, SegmentPlanner
from ..segment.immutable import ImmutableSegment
from ..utils.metrics import global_metrics
from ..utils.spans import annotate, span
from . import host_eval


@dataclass
class AggPartial:
    states: List[Any]  # aligned with ctx.aggregations


@dataclass
class GroupByPartial:
    groups: Dict[Tuple, List[Any]]  # key values -> states per aggregation


@dataclass
class SelectionPartial:
    labels: List[str]
    rows: List[tuple]
    order_keys: List[tuple] = field(default_factory=list)


def empty_partial(ctx: QueryContext):
    if ctx.is_group_by:
        return GroupByPartial({})
    if ctx.is_aggregation:
        na = host_eval.null_aware(ctx)
        # with null handling, SUM over zero rows is null, not 0 (the merge
        # is null-absorbing, so any segment with rows still wins)
        return AggPartial([None if na and a.kind == "sum"
                           else aggregations.empty_state(a)
                           for a in ctx.aggregations])
    return SelectionPartial([], [])


class SegmentExecutor:
    """Plans + executes one query over one segment."""

    def __init__(self, segment: ImmutableSegment):
        self.segment = segment

    def execute(self, ctx: QueryContext):
        plan = SegmentPlanner(ctx, self.segment).plan()
        return execute_plan(plan)


def execute_segment(ctx: QueryContext, segment: ImmutableSegment):
    return SegmentExecutor(segment).execute(ctx)


def execute_plan(plan: CompiledPlan):
    ctx, seg = plan.ctx, plan.segment
    if plan.kind == "pruned":
        if not ctx.is_aggregation and plan.select_names:
            return SelectionPartial(list(plan.select_names), [])
        return empty_partial(ctx)
    if plan.kind == "fast":
        return AggPartial(list(plan.fast_states))
    if plan.kind == "host":
        with span("segment_host", segment=seg.name):
            if host_eval.null_aware(ctx):
                mask, _ = host_eval.eval_filter_3vl(ctx.filter, seg)
            else:
                mask = host_eval.eval_filter(ctx.filter, seg)
            vd = getattr(seg, "valid_docs", None)
            if vd is not None:
                from ..query.planner import _truthy
                if not _truthy(ctx.options.get("skipUpsert")):
                    mask = mask & vd[: seg.n_docs]
            if ctx.is_group_by:
                return GroupByPartial(
                    host_eval.host_group_by(ctx, seg, mask))
            if ctx.is_aggregation:
                return AggPartial(host_eval.host_aggregate(ctx, seg, mask))
            labels, rows, okeys = host_eval.host_selection(ctx, seg, mask)
            return SelectionPartial(labels, rows, okeys)
    if plan.kind == "kselect":
        return extract_select(plan, run_select_kernel(plan))
    assert plan.kind == "kernel"
    out = run_kernel(plan)
    with span("extract_partial", segment=seg.name):
        return extract_partial(plan, out)


def run_select_kernel(plan: CompiledPlan) -> Dict[str, np.ndarray]:
    from ..ops.kernels import jitted_select_kernel
    from ..utils.spans import device_fence
    seg = plan.segment
    with span("segment_kselect", segment=seg.name, bucket=seg.bucket):
        cols = seg.device_cols(plan.col_names)
        params = resolve_params(plan)
        fn = jitted_select_kernel(plan.select_plan, seg.bucket)
        with span("device_execute"):
            out = fn(cols, np.int32(seg.n_docs), params)
            device_fence(out)
        with span("device_transfer"):
            host = jax.device_get(out)  # jaxlint: ok host-sync
        from .accounting import global_accountant
        global_accountant.track_result(host)
        return host


def extract_select(plan: CompiledPlan, out: Dict[str, np.ndarray]
                   ) -> "SelectionPartial":
    """Device top-k winners -> SelectionPartial (values resolved through
    the segment dictionaries; order keys resolved the same way so the
    broker's cross-segment merge compares values, not ids).

    host-sync [jaxlint baseline]: ``out`` is host numpy — the dispatch
    already fenced and device_got it; everything below is extraction."""
    seg, sp = plan.segment, plan.select_plan
    n = min(int(out["matched"]), sp.k)
    cols_vals: List[np.ndarray] = []
    for i, name in enumerate(plan.select_names):
        stored = np.asarray(out[f"sel_{i}"])[:n]
        d = seg.dictionary(name)
        cols_vals.append(d.values_for(stored) if d is not None else stored)
    rows = [tuple(_py(c[r]) for c in cols_vals) for r in range(n)]
    okeys_cols: List[np.ndarray] = []
    for j, (col, _d, card) in enumerate(sp.order):
        stored = np.asarray(out[f"ord_{j}"])[:n]
        name = plan.col_names[col]
        d = seg.dictionary(name)
        okeys_cols.append(d.values_for(stored) if d is not None else stored)
    okeys = [tuple(_py(c[r]) for c in okeys_cols) for r in range(n)]
    ctx = plan.ctx
    if any(isinstance(i, Star) for i in ctx.select_items):
        labels = list(plan.select_names)
    else:
        labels = list(ctx.labels)
    return SelectionPartial(labels, rows, okeys)


def resolve_params(plan: CompiledPlan, sharding=None) -> Tuple[jax.Array, ...]:
    """Materialize planner params: symbolic markers hit the segment device
    cache; literal scalars/arrays upload (tiny).

    `sharding` pins placement (e.g. a mesh-replicated NamedSharding for the
    distributed path) so params never land on the default backend — required
    when the process default is a real TPU but the query runs on a CPU mesh.
    """
    seg = plan.segment

    def put(x):
        return jax.device_put(x, sharding)  # sharding None = default

    out = []
    for p in plan.params:
        if isinstance(p, tuple) and len(p) == 2 and p[0] == "dictvals":
            out.append(seg.device_dict_values(p[1], sharding=sharding))
        elif isinstance(p, tuple) and len(p) == 2 and p[0] == "hash64":
            # per-dict-id 64-bit hash table for sketch aggregations
            # (host _hash64 — md5 for strings — so device and host
            # sketches agree bit-for-bit)
            from ..ops.aggregations import _hash64
            vals = np.asarray(seg.dictionary(p[1]).values)
            out.append(put(_hash64(vals)))
        elif isinstance(p, tuple) and len(p) == 2 and p[0] == "nullmask":
            out.append(seg.device_null_mask(p[1], sharding=sharding))
        elif isinstance(p, tuple) and len(p) == 2 and p[0] == "validdocs":
            out.append(seg.device_valid_mask(sharding=sharding))
        elif isinstance(p, tuple) and len(p) == 2 and p[0] == "docmask":
            # index-predicate doc mask (TEXT_MATCH/JSON_MATCH/
            # VECTOR_SIMILARITY): pad to the segment bucket
            mask = np.asarray(p[1], dtype=bool)
            padded = np.zeros(seg.bucket, dtype=bool)
            padded[: len(mask)] = mask
            out.append(put(padded))
        else:
            out.append(put(p))
    return tuple(out)


def run_kernel(plan: CompiledPlan,
               xfer_compact: bool = True) -> Dict[str, np.ndarray]:
    """Execute the compiled kernel through the keyed plan cache
    (ops/plan_cache.py): one compiled XLA program + donated accumulator
    buffers per (plan, bucket, slots_cap, platform, flags), so repeated
    iterations of the same query never re-trace or re-allocate.

    The compact strategy's compaction capacity comes from the planner's
    cost model (CompiledPlan.slots_cap — selectivity-estimate-derived and
    quantized, hence a stable cache key); an underestimate reports
    overflow and retries once at full_slots_cap. xfer_compact=False goes
    straight to dense (space,) group outputs — used when the caller
    already knows the transfer compaction spilled (engine/batch.py's
    vmapped path)."""
    from ..ops.plan_cache import global_plan_cache
    from .tier import global_tier
    seg = plan.segment
    with span("segment_kernel", segment=seg.name, bucket=seg.bucket,
              strategy=plan.kernel_plan.strategy,
              est_sel=plan.est_selectivity, slots_cap=plan.slots_cap), \
            global_tier.pinned({seg.uid}):
        # pinned for the WHOLE solo execution: the plan-cache entry's
        # first-run accumulator registration enforces the tier budget,
        # and without the pin it could demote the very segment whose
        # columns this query just uploaded (engine/tier anti-thrash)
        cols = seg.device_cols(plan.col_names)
        params = resolve_params(plan)
        n = np.int32(seg.n_docs)
        cap = plan.slots_cap
        # drift_requantized: the compile at the measured-selectivity
        # capacity is a deliberate, counted recompile — never a retrace.
        # The cache brackets only the actual miss, so the warm
        # re-plannings of a drifted shape (hits) stay outside expected()
        # and genuine retraces remain visible.
        entry = global_plan_cache.entry(
            plan.kernel_plan, seg.bucket, cap, xfer_compact=xfer_compact,
            expected_compile=plan.drift_requantized)
        if plan.drift_requantized:
            annotate(drift_requantized=True)
        if entry.overflowed:
            # this capacity already overflowed for this plan: go straight
            # to the (already compiled) full-capacity kernel instead of
            # paying the doomed tight kernel plus the retry on every
            # execution
            from ..ops.compact import full_slots_cap
            cap = full_slots_cap(seg.bucket)
            with global_plan_cache.detector.expected():
                entry = global_plan_cache.entry(
                    plan.kernel_plan, seg.bucket, cap,
                    xfer_compact=xfer_compact)
            annotate(slots_cap=cap, known_overflow=True)
        # everything below the entry.run fence is host numpy (entry.run
        # device_gets inside its lock) — host-sync [jaxlint baseline]
        host = entry.run(cols, n, params)
        if "matched" in host:
            matched = int(np.asarray(host["matched"]).sum())
            global_plan_cache.record_measured(
                plan.kernel_plan, seg.bucket, entry, matched, seg.n_docs,
                segment=seg, params=plan.params)
            annotate(matched=matched,
                     meas_sel=matched / max(seg.n_docs, 1))
        # chaos hook: force the overflow retry ladder on kernels that
        # report overflow (result-identical — the full-capacity rerun
        # recomputes the same answer; exercises the retry path + retrace
        # bracketing under test)
        from ..utils.faults import fault_fires
        forced_overflow = "overflow" in host and \
            fault_fires("device.overflow", key=seg.name)
        if int(host.pop("overflow", 0)) or forced_overflow:
            # compact-strategy capacity exceeded (the selectivity estimate
            # undershot): rerun with a capacity that cannot overflow
            from ..ops.compact import full_slots_cap
            entry.mark_overflowed()
            cap = full_slots_cap(seg.bucket)
            global_metrics.count("compact_overflow_retries")
            with span("overflow_retry", slots_cap=cap), \
                    global_plan_cache.detector.expected():
                entry = global_plan_cache.entry(
                    plan.kernel_plan, seg.bucket, cap,
                    xfer_compact=xfer_compact)
                host = entry.run(cols, n, params)
            host.pop("overflow", None)
            annotate(overflow_retry=True, slots_cap=cap)
        if int(host.pop("group_overflow", 0)):
            # more live groups than the transfer-compaction cap: rerun
            # with dense (space,) outputs
            global_metrics.count("group_xfer_overflow_retries")
            with span("group_overflow_retry"), \
                    global_plan_cache.detector.expected():
                entry = global_plan_cache.entry(
                    plan.kernel_plan, seg.bucket, cap,
                    xfer_compact=False)
                host = entry.run(cols, n, params)
            host.pop("overflow", None)
            annotate(group_overflow_retry=True)
        from ..query.planner import _truthy
        from ..utils.spans import tracing_active
        if tracing_active() and _truthy(
                plan.ctx.options.get("profilePhases")):
            # EXPLAIN ANALYZE deep mode: re-measure the kernel's internal
            # mask/fuse/compact/sort/aggregate/transfer ladder and attach
            # it as child spans (compiles profiling prefixes — opt-in)
            from ..ops.phase_profile import (attach_phase_spans,
                                             profile_plan)
            with span("phase_profile"):
                prof = profile_plan(plan, iters=2)
                attach_phase_spans(prof)
        from .accounting import global_accountant
        global_accountant.track_result(host)
        return host


def extract_partial(plan: CompiledPlan, out: Dict[str, np.ndarray]):
    # host-sync [jaxlint baseline]: ``out`` is host numpy (run_kernel /
    # the batched dispatch device_got it behind one fence); extraction
    # and the _scalar_state/_group_state helpers below never touch
    # device values.
    ctx, seg = plan.ctx, plan.segment
    matched = int(out["matched"])
    if not ctx.is_group_by:
        na = host_eval.null_aware(ctx)
        states: List[Any] = []
        for b in plan.agg_bindings:
            states.append(_scalar_state(b, out, matched, seg, na))
        return AggPartial(states)

    gi = out.get("group_idx")
    gc = out["group_count"]
    if gi is not None:
        # device-compacted outputs: arrays are gathered non-empty rows,
        # gi holds their dense space ids (sentinel rows have count 0)
        sel = np.nonzero(gc > 0)[0]
        idxs = np.asarray(gi)[sel]
    else:
        idxs = np.nonzero(gc > 0)[0]
        sel = idxs
    # decode dense cartesian keys -> per-key ids -> values
    key_cols: List[np.ndarray] = []
    rem = idxs.copy()
    decoders = plan.group_decoders or [
        ("dict", name, seg.columns[name].cardinality)
        for name in plan.group_cols]
    for dec in reversed(decoders):
        card = dec[-1]
        ids = rem % card
        rem = rem // card
        if dec[0] == "dict":
            key_cols.append(seg.dictionary(dec[1]).values_for(ids))
        else:  # ("int", lo, stride, card): expression keys (YEAR(ts)...)
            key_cols.append(dec[1] + ids.astype(np.int64) * dec[2])
    key_cols.reverse()
    keys = [tuple(_py(kc[i]) for kc in key_cols) for i in range(len(idxs))]

    groups: Dict[Tuple, List[Any]] = {k: [] for k in keys}
    for b in plan.agg_bindings:
        per_group = _group_state(b, out, sel, seg)
        for k_i, k in enumerate(keys):
            groups[k].append(per_group[k_i])
    return GroupByPartial(groups)


def _scalar_state(b: AggBinding, out: Dict[str, np.ndarray], matched: int,
                  seg: ImmutableSegment, na: bool = False) -> Any:
    name = f"agg{b.index}_{_kind(b)}"
    k = _kind(b)
    # null-aware plans emit the aggregation's own non-null row count
    # (AggSpec.null_param); all-null input finalizes SUM/MIN/MAX to null
    nnz = out.get(name + "_nnz")
    eff = int(nnz) if nnz is not None else matched
    if k == "count":
        return int(out[name])
    if k == "sum":
        # COUNTMV rides the sum state but keeps COUNT semantics: empty
        # input is 0, never null (round-4 fuzzer finding — the host path
        # and the SQL standard agree)
        if na and eff == 0 and b.agg.kind != "count_mv":
            return None
        v = out[name]
        return int(v) if b.integral else float(v)
    if k in ("min", "max"):
        if eff == 0:
            return None
        v = out[name]
        return int(v) if b.integral else float(v)
    if k == "avg":
        s = out[name + "_sum"]
        c = int(out[name + "_cnt"])
        return (int(s) if b.integral else float(s), c)
    if k == "distinct_count":
        present = out[name + "_present"]
        ids = np.nonzero(present)[0]
        vals = seg.dictionary(b.dict_col).values_for(ids)
        return set(_py(v) for v in vals)
    # device sketch partials -> host AggImpl state formats (the broker
    # reduce merges them through ops/aggregations like any host partial);
    # RAW forms share their base sketch's state (RawAgg delegates)
    k = {"raw_hll": "distinct_count_hll",
         "raw_theta": "distinct_count_theta",
         "percentile_raw_sketch": "percentile_sketch"}.get(k, k)
    if k == "distinct_count_hll":
        return _hll_registers(out[name + "_present"], b)[0]
    if k == "distinct_count_theta":
        h = np.asarray(out[name + "_hashes"]).astype(np.uint64)
        sent = np.uint64(0xFFFFFFFFFFFFFFFF)
        return [int(x) for x in h if x != sent]
    if k == "percentile_sketch":
        means = np.asarray(out[name + "_pc_mean"])
        ws = np.asarray(out[name + "_pc_w"])
        return [[float(m_), float(w_)]
                for m_, w_ in zip(means, ws) if w_ > 0]
    raise ValueError(k)


def _group_state(b: AggBinding, out: Dict[str, np.ndarray],
                 idxs: np.ndarray, seg: ImmutableSegment) -> List[Any]:
    name = f"agg{b.index}_{_kind(b)}"
    k = _kind(b)
    if k == "count":
        # group COUNT is served by the kernel's shared count row
        return [int(x) for x in out["group_count"][idxs]]
    if k == "sum":
        arr = out[name][idxs]
        return [int(x) for x in arr] if b.integral else [float(x) for x in arr]
    if k in ("min", "max"):
        arr = out[name][idxs]
        return [int(x) for x in arr] if b.integral else [float(x) for x in arr]
    if k == "avg":
        s = out[name + "_sum"][idxs]
        c = out[name + "_cnt"][idxs]
        if b.integral:
            return [(int(s[i]), int(c[i])) for i in range(len(idxs))]
        return [(float(s[i]), int(c[i])) for i in range(len(idxs))]
    if k == "distinct_count":
        present = out[name + "_present"][idxs]  # (n_groups, card)
        d = seg.dictionary(b.dict_col)
        res = []
        for row in present:
            ids = np.nonzero(row)[0]
            res.append(set(_py(v) for v in d.values_for(ids)))
        return res
    if k in ("distinct_count_hll", "raw_hll"):
        return _hll_registers(np.asarray(out[name + "_present"])[idxs], b)
    raise ValueError(k)


def _hll_registers(pm: np.ndarray, b: AggBinding) -> List[List[int]]:
    """(n?, m*R) presence bitmap(s) -> per-row HllAgg register lists,
    vectorized across groups (one reshape + two reductions)."""
    from ..ops.aggregations import HllAgg
    p = HllAgg(b.agg).log2m
    r_levels = 64 - p + 1
    pm = np.asarray(pm)
    if pm.ndim == 1:
        pm = pm[None, :]
    rr = pm.reshape(pm.shape[0], 1 << p, r_levels)
    ranks = np.arange(1, r_levels + 1, dtype=np.int64)
    regs = np.where(rr.any(axis=2), (rr * ranks).max(axis=2), 0)
    return [row.tolist() for row in regs]


def _kind(b: AggBinding) -> str:
    # MV kinds lower to their base kind's device states/names
    # (SUMMV -> agg<i>_sum etc.; ops/aggregations.MV_BASE_KIND)
    from ..ops.aggregations import base_kind
    return base_kind(b.agg.kind)


def _py(v: Any) -> Any:
    return v.item() if isinstance(v, np.generic) else v
