"""Broker reduce: merge per-segment partials into the final result table.

Reference parity: pinot-core/.../query/reduce/BrokerReduceService.java:61
(merges server DataTables; aggregation/groupby/selection reducers, HAVING,
ORDER BY, LIMIT trimming via IndexedTable). States arriving here are
value-space and mergeable (dict ids were resolved per segment at extract
time), so merging is pure arithmetic/set union regardless of which path
(device kernel, fast metadata, host numpy) produced each partial.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..query.context import AggExpr, QueryContext, _expr_label
from ..query import functions as F
from ..ops import aggregations
from ..query.sql import (Between, BinaryOp, BoolAnd, BoolNot, BoolOr,
                         CaseWhen, Cast, Comparison, FuncCall, Identifier,
                         InList, IsNull, Literal, SqlError, Star)
from .executor import AggPartial, GroupByPartial, SelectionPartial

DEFAULT_LIMIT = 10  # Pinot's default LIMIT for selection/group-by results


@dataclass
class ResultTable:
    columns: List[str]
    rows: List[tuple]
    num_docs_scanned: int = 0
    num_segments: int = 0
    num_segments_pruned: int = 0
    time_ms: float = 0.0
    trace: Optional[dict] = None
    # scatter-gather health (Pinot BrokerResponseNative metadata):
    # populated by the networked broker's gather; the in-process broker
    # leaves them zero and to_dict omits them (response shape unchanged)
    partial_result: bool = False
    num_servers_queried: int = 0
    num_servers_responded: int = 0
    exceptions: List[Dict[str, Any]] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "resultTable": {
                "dataSchema": {"columnNames": self.columns},
                "rows": [list(r) for r in self.rows],
            },
            "numSegmentsQueried": self.num_segments,
            "numSegmentsPruned": self.num_segments_pruned,
            "numDocsScanned": self.num_docs_scanned,
            "timeUsedMs": self.time_ms,
        }
        if self.num_servers_queried or self.exceptions \
                or self.partial_result:
            out["numServersQueried"] = self.num_servers_queried
            out["numServersResponded"] = self.num_servers_responded
            out["partialResult"] = self.partial_result
            out["exceptions"] = list(self.exceptions)
        return out

    def __repr__(self) -> str:
        return f"ResultTable({self.columns}, {len(self.rows)} rows)"


# ---------------------------------------------------------------------------
# state algebra
# ---------------------------------------------------------------------------

def merge_state(agg: AggExpr, a: Any, b: Any) -> Any:
    return aggregations.merge_states(agg, a, b)


def finalize_state(agg: AggExpr, s: Any) -> Any:
    return aggregations.finalize_state(agg, s)


# ---------------------------------------------------------------------------
# reduce
# ---------------------------------------------------------------------------

def reduce_partials(ctx: QueryContext, partials: List[Any]) -> ResultTable:
    if ctx.is_group_by:
        return _reduce_group_by(ctx, [p for p in partials
                                      if isinstance(p, GroupByPartial)])
    if ctx.is_aggregation:
        return _reduce_aggregation(ctx, [p for p in partials
                                         if isinstance(p, AggPartial)])
    return _reduce_selection(ctx, [p for p in partials
                                   if isinstance(p, SelectionPartial)])


def _reduce_aggregation(ctx: QueryContext, partials: List[AggPartial]
                        ) -> ResultTable:
    aggs = ctx.aggregations
    # seed from the first partial (not empty_state) so a null partial —
    # SUM over all-null input under enableNullHandling — stays null
    if partials:
        merged = list(partials[0].states)
    else:
        merged = [aggregations.empty_state(a) for a in aggs]
    for p in partials[1:]:
        for i, a in enumerate(aggs):
            merged[i] = merge_state(a, merged[i], p.states[i])
    env = {a.label: finalize_state(a, merged[i])
           for i, a in enumerate(aggs)}
    if ctx.having is not None and not _eval_scalar_bool(ctx.having, env):
        return ResultTable(list(ctx.labels), [])
    row = tuple(env[item.label] if isinstance(item, AggExpr)
                else _eval_scalar(item, env)
                for item in ctx.select_items)
    labels = [l for item, l in zip(ctx.select_items, ctx.labels)]
    return ResultTable(labels, [row])


def _reduce_group_by(ctx: QueryContext, partials: List[GroupByPartial]
                     ) -> ResultTable:
    aggs = ctx.aggregations
    merged: Dict[Tuple, List[Any]] = {}
    for p in partials:
        for key, states in p.groups.items():
            cur = merged.get(key)
            if cur is None:
                merged[key] = list(states)
            else:
                for i, a in enumerate(aggs):
                    cur[i] = merge_state(a, cur[i], states[i])

    group_labels = [_expr_label(g) for g in ctx.group_by]
    rows: List[tuple] = []
    for key, states in merged.items():
        env: Dict[str, Any] = dict(zip(group_labels, key))
        for i, agg in enumerate(ctx.aggregations):
            env[agg.label] = finalize_state(agg, states[i])
        if ctx.having is not None and not _eval_scalar_bool(ctx.having, env):
            continue
        rows.append((_build_row(ctx, env), env))  # env kept for ORDER BY

    if ctx.gapfill is not None:
        rows = _apply_gapfill(ctx, rows)

    if ctx.order_by:
        def sort_key(entry):
            _, env = entry
            parts = []
            for o in ctx.order_by:
                v = _eval_scalar(o.expr, env)
                parts.append(_OrderKey(v, o.ascending))
            return tuple(parts)
        rows.sort(key=sort_key)
    else:
        rows.sort(key=lambda e: _key_sortable(e[0]))

    limit = ctx.limit if ctx.limit is not None else DEFAULT_LIMIT
    rows = rows[ctx.offset: ctx.offset + limit]
    labels = list(ctx.labels)
    return ResultTable(labels, [r for r, _ in rows])


def _build_row(ctx: QueryContext, env: Dict[str, Any]) -> tuple:
    return tuple(env[item.label] if isinstance(item, AggExpr)
                 else env[_expr_label(item)]
                 if _expr_label(item) in env
                 else _eval_scalar(item, env)
                 for item in ctx.select_items)


def _apply_gapfill(ctx: QueryContext, entries: List[tuple]) -> List[tuple]:
    """Time-bucket gapfill over reduced group-by rows (GapfillProcessor
    analog). For every TIMESERIESON series observed in the result, emit
    one row per bucket in [start, end); missing buckets take
    FILL_PREVIOUS_VALUE (carry-forward along the series),
    FILL_DEFAULT_VALUE (zero-value of the column's observed type), or
    NULL for unfilled columns. Runs BEFORE order/limit, so LIMIT applies
    to the gapfilled output like the reference's outer query."""
    g = ctx.gapfill
    tl = g.time_label

    existing: Dict[tuple, Dict[int, Dict[str, Any]]] = {}
    series_order: List[tuple] = []
    other_labels: set = set()
    defaults: Dict[str, Any] = {}
    for _row, env in entries:
        t = env.get(tl)
        if not isinstance(t, (int, float)) or not g.start <= t < g.end:
            continue
        bucket = g.start + int((t - g.start) // g.interval) * g.interval
        sk = tuple(env.get(l) for l in g.series_labels)
        per = existing.get(sk)
        if per is None:
            per = existing[sk] = {}
            series_order.append(sk)
        per.setdefault(bucket, env)  # finer-than-interval rows: first wins
        for lbl, v in env.items():
            other_labels.add(lbl)
            if v is not None and lbl not in defaults:
                defaults[lbl] = type(v)()  # zero-value: 0 / 0.0 / ""
    other_labels -= {tl, *g.series_labels}

    out: List[tuple] = []
    for sk in series_order:
        per = existing[sk]
        prev_env: Optional[Dict[str, Any]] = None
        for bucket in range(g.start, g.end, g.interval):
            env = per.get(bucket)
            if env is None:
                env = {tl: bucket}
                env.update(zip(g.series_labels, sk))
                for lbl in other_labels:
                    mode = g.fills.get(lbl)
                    if mode == "previous" and prev_env is not None:
                        env[lbl] = prev_env.get(lbl)
                    elif mode == "default":
                        env[lbl] = defaults.get(lbl)
                    else:
                        env[lbl] = None
            else:
                env = dict(env)
                env[tl] = bucket
            out.append((_build_row(ctx, env), env))
            prev_env = env
    return out


def _key_sortable(row: tuple) -> tuple:
    return tuple((v is None, v) for v in row)


class _OrderKey:
    """Total-order wrapper handling DESC and None (nulls last)."""
    __slots__ = ("v", "asc")

    def __init__(self, v, asc: bool):
        self.v = v
        self.asc = asc

    def __lt__(self, other: "_OrderKey") -> bool:
        a, b = self.v, other.v
        if a is None:
            return False
        if b is None:
            return True
        return a < b if self.asc else b < a

    def __eq__(self, other) -> bool:
        return self.v == other.v


def _reduce_selection(ctx: QueryContext, partials: List[SelectionPartial]
                      ) -> ResultTable:
    labels: List[str] = []
    rows: List[tuple] = []
    okeys: List[tuple] = []
    for p in partials:
        if p.labels:
            labels = p.labels
        rows.extend(p.rows)
        okeys.extend(p.order_keys)
    if ctx.order_by and okeys:
        order = sorted(
            range(len(rows)),
            key=lambda i: tuple(
                _OrderKey(okeys[i][j], o.ascending)
                for j, o in enumerate(ctx.order_by)))
        rows = [rows[i] for i in order]
    limit = ctx.limit if ctx.limit is not None else DEFAULT_LIMIT
    rows = rows[ctx.offset: ctx.offset + limit]
    if not labels:
        labels = list(ctx.labels)
    return ResultTable(labels, rows)


# ---------------------------------------------------------------------------
# scalar (post-aggregation) expression evaluation for HAVING / ORDER BY
# ---------------------------------------------------------------------------

def _eval_scalar(e: Any, env: Dict[str, Any]) -> Any:
    if isinstance(e, AggExpr):
        return env[e.label]
    if isinstance(e, FuncCall):
        label = _expr_label(e)
        if label in env:
            return env[label]
        if F.lookup(e.name) is not None:
            args = [_eval_scalar(a, env) for a in e.args]
            out = F.call(e.name, *args)
            return out.item() if hasattr(out, "item") and \
                np.asarray(out).ndim == 0 else out
        raise SqlError(f"unknown function result {label!r}")
    if isinstance(e, Identifier):
        if e.name in env:
            return env[e.name]
        raise SqlError(f"unknown output column {e.name!r}")
    if isinstance(e, Literal):
        return e.value
    if isinstance(e, CaseWhen):
        for cond, res in e.whens:
            if _eval_scalar_bool(cond, env):
                return _eval_scalar(res, env)
        return None if e.else_ is None else _eval_scalar(e.else_, env)
    if isinstance(e, Cast):
        v = F.cast_value(_eval_scalar(e.expr, env), e.type_name)
        return v.item() if np.asarray(v).ndim == 0 else v
    if isinstance(e, BinaryOp):
        l = _eval_scalar(e.lhs, env)
        r = _eval_scalar(e.rhs, env)
        if l is None or r is None:
            return None
        if e.op == "+":
            return l + r
        if e.op == "-":
            return l - r
        if e.op == "*":
            return l * r
        if e.op == "/":
            return l / r
        if e.op == "%":
            return l % r
    raise SqlError(f"unsupported post-aggregation expression {e!r}")


def _eval_scalar_bool(e: Any, env: Dict[str, Any]) -> bool:
    """HAVING acceptance: only TRUE passes (SQL three-valued logic —
    a NULL aggregate, e.g. SUM over all-null inputs under
    enableNullHandling, makes the predicate NULL, which filters the
    group instead of raising; round-5 fuzz seed 777/166)."""
    return _bool3(e, env) is True


def _nullish(v: Any) -> bool:
    """NULL in either representation: None, or float NaN (what a null
    aggregate finalizes to on some paths — the same definition the
    IS NULL branch uses, so 3VL is consistent across predicates)."""
    return v is None or (isinstance(v, float) and v != v)


def _bool3(e: Any, env: Dict[str, Any]) -> Optional[bool]:
    """True / False / None (UNKNOWN), Kleene semantics."""
    if isinstance(e, BoolAnd):
        saw_null = False
        for c in e.children:          # short-circuits on False
            v = _bool3(c, env)
            if v is False:
                return False
            saw_null = saw_null or v is None
        return None if saw_null else True
    if isinstance(e, BoolOr):
        saw_null = False
        for c in e.children:          # short-circuits on True
            v = _bool3(c, env)
            if v is True:
                return True
            saw_null = saw_null or v is None
        return None if saw_null else False
    if isinstance(e, BoolNot):
        v = _bool3(e.child, env)
        return None if v is None else not v
    if isinstance(e, Comparison):
        l = _eval_scalar(e.lhs, env)
        r = _eval_scalar(e.rhs, env)
        if _nullish(l) or _nullish(r):
            return None
        try:                          # dispatch per op: == must never
            if e.op == "==":          # evaluate an ordering comparison
                return l == r
            if e.op == "!=":
                return l != r
            if e.op == "<":
                return l < r
            if e.op == "<=":
                return l <= r
            if e.op == ">":
                return l > r
            return l >= r
        except TypeError:
            raise SqlError(
                f"cannot compare {type(l).__name__} with "
                f"{type(r).__name__} in HAVING ({e.op})") from None
    if isinstance(e, Between):
        v = _eval_scalar(e.expr, env)
        lo = _eval_scalar(e.lo, env)
        hi = _eval_scalar(e.hi, env)
        if _nullish(v) or _nullish(lo) or _nullish(hi):
            return None
        ok = lo <= v <= hi
        return not ok if e.negated else ok
    if isinstance(e, InList):
        v = _eval_scalar(e.expr, env)
        if _nullish(v):
            return None
        ok = v in {x.value for x in e.values}
        return not ok if e.negated else ok
    if isinstance(e, IsNull):
        v = _eval_scalar(e.expr, env)
        isnull = _nullish(v)
        return not isnull if e.negated else isnull
    if isinstance(e, (FuncCall, Literal, CaseWhen, Cast)):
        v = _eval_scalar(e, env)
        return None if _nullish(v) else bool(v)
    raise SqlError(f"unsupported HAVING expression {e!r}")
