"""TPU-native vector search execution plane (ISSUE 14 tentpole).

The ``VECTOR_SIMILARITY(col, ARRAY[...], k[, nprobe])`` query family's
engine half, between the SQL surface (query/sql.py parses the ARRAY
literal, query/planner.py validates calls fail-fast) and the index
(index/vector.py: flat matmul + the IVF page layout). Everything here
is host orchestration; the search itself is one fused device pass per
launch.

Execution contract:

- **One search per (query, segment, call shape).** The filter
  predicate, the ORDER BY score key and a select-list score all reuse
  ONE memoized device search per query (keyed by (query id, reader,
  query vector, k, nprobe)) — the planner's mask request and
  host_eval's score request never double-launch.
- **Ragged micro-batching.** Concurrent queries against the same
  (segment, col, k, nprobe) shape meet in a MicroBatchQueue admission
  window (the round-13 leader/follower idiom): the leader stacks the
  query vectors on a pow2-padded batch axis and executes ONE device
  launch (``VectorIndexReader.search_batch`` — ``lax.map`` body, so
  batched results are EXACTLY equal to solo by construction); followers
  receive their row. Peer-less and disabled paths dispatch solo with
  the reason counted (``vector_solo_*``), honoring the process-wide
  ``PINOT_MICROBATCH`` switch.
- **Segment-parallel for free.** Per-segment top-k partials carry their
  host-recomputed score as the ORDER BY key, so the ordinary selection
  reduce (engine/reduce.py) and the broker scatter-gather
  (cluster/broker_node.py) merge vector partials like any other
  ordered selection — failover/hedging/partial-results and EXPLAIN
  ANALYZE spans apply unchanged.
- **Tier/chaos integration.** Every search touches the owning
  segment's tier hook first (``tier.evict`` can force-demote the
  vector pool mid-query; the search transparently re-uploads and must
  answer byte-identically), and every upload is accounted in the
  ``vector`` devmem pool under the shared HBM budget.

Structured user errors (SqlError -> HTTP 400, never a host-path
demotion): missing index, non-numeric/empty ARRAY, dim mismatch,
k <= 0, nprobe <= 0, malformed argument shapes.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutTimeout
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..query.sql import FuncCall, Identifier, Literal, SqlError
from ..utils import phases as ph
from ..utils.metrics import global_metrics
from ..utils.spans import annotate, span

FUNC_NAME = "vector_similarity"
DEFAULT_K = 10
DEFAULT_WINDOW_MS = 2.0
DEFAULT_MAX_BATCH = 16
_MEMO_CAP = 256


# ---------------------------------------------------------------------------
# SQL-surface validation (the structured 400s)
# ---------------------------------------------------------------------------

def is_vector_call(e: Any) -> bool:
    return isinstance(e, FuncCall) and e.name == FUNC_NAME


def parse_call(e: FuncCall) -> Tuple[str, Tuple[float, ...], int,
                                     Optional[int]]:
    """-> (col, query vector, k, nprobe|None); raises SqlError on every
    malformed shape (user errors — never host-fallback candidates)."""
    if not 2 <= len(e.args) <= 4:
        raise SqlError("VECTOR_SIMILARITY takes (col, ARRAY[...], "
                       "topK[, nprobe])")
    if not isinstance(e.args[0], Identifier):
        raise SqlError("VECTOR_SIMILARITY needs a column as its first "
                       "argument")
    col = e.args[0].name
    if not isinstance(e.args[1], Literal) \
            or not isinstance(e.args[1].value, (tuple, list)):
        raise SqlError("VECTOR_SIMILARITY query must be an ARRAY[...] "
                       "literal")
    qv = tuple(e.args[1].value)
    if not qv or not all(
            isinstance(v, (int, float)) and not isinstance(v, bool)
            for v in qv):
        raise SqlError("VECTOR_SIMILARITY query must be a non-empty "
                       "numeric ARRAY[...]")
    k = DEFAULT_K
    if len(e.args) > 2:
        k = _int_arg(e.args[2], "topK")
    nprobe = None
    if len(e.args) > 3:
        nprobe = _int_arg(e.args[3], "nprobe")
    return col, tuple(float(v) for v in qv), k, nprobe


def _int_arg(a: Any, what: str) -> int:
    if not isinstance(a, Literal) \
            or not isinstance(a.value, (int, float)) \
            or isinstance(a.value, bool) or int(a.value) != a.value \
            or int(a.value) <= 0:
        raise SqlError(f"VECTOR_SIMILARITY {what} must be a positive "
                       "integer")
    return int(a.value)


def reader_for(seg, col: str):
    """The segment's vector index reader, owner-attached (tier/devmem
    identity); SqlError when the column/index is missing."""
    meta = seg.columns.get(col)
    if meta is None:
        raise SqlError(f"unknown column {col!r}")
    reader = seg.index_reader(col, "vector")
    if reader is None:
        raise SqlError(f"VECTOR_SIMILARITY requires a vector index on "
                       f"{col!r} (tableConfig indexing."
                       "vectorIndexColumns)")
    return reader


def validate_call(seg, e: FuncCall):
    """Fail-fast plan-time validation (query/planner.py runs this over
    the filter, select list and ORDER BY): every structured 400 fires
    BEFORE any execution work, on the kernel and host paths alike.
    Returns (col, query vector, k, nprobe, reader) so execution-path
    callers consume ONE parse + reader lookup."""
    col, qv, k, nprobe = parse_call(e)
    reader = reader_for(seg, col)
    if len(qv) != reader.dim:
        raise SqlError(f"VECTOR_SIMILARITY dim mismatch: query has "
                       f"{len(qv)} components, index on {col!r} has "
                       f"{reader.dim}")
    return col, qv, k, nprobe, reader


# ---------------------------------------------------------------------------
# per-query search memo
# ---------------------------------------------------------------------------

_MEMO_LOCK = threading.Lock()
_MEMO: "OrderedDict[Tuple, Tuple[np.ndarray, np.ndarray]]" = OrderedDict()


def _memo_get(key: Tuple):
    with _MEMO_LOCK:
        got = _MEMO.get(key)
        if got is not None:
            _MEMO.move_to_end(key)
        return got


def _memo_put(key: Tuple, val) -> None:
    with _MEMO_LOCK:
        _MEMO[key] = val
        while len(_MEMO) > _MEMO_CAP:
            _MEMO.popitem(last=False)


def clear_memo() -> None:
    with _MEMO_LOCK:
        _MEMO.clear()


# ---------------------------------------------------------------------------
# the admission window (round-13 leader/follower idiom)
# ---------------------------------------------------------------------------

class _VSub:
    __slots__ = ("q", "future")

    def __init__(self, q: Tuple[float, ...]):
        self.q = q
        self.future: "Future[Any]" = Future()


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


class VectorBatcher:
    """Fuses concurrent same-shape vector searches into one padded
    device launch (module docstring). Results are exactly equal to solo
    — the kernel's per-query body is batch-size invariant — so the
    batcher is purely a throughput policy, never a semantics knob."""

    def __init__(self, window_ms: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 enabled: Optional[bool] = None):
        from ..engine.scheduler import MicroBatchQueue
        from .ragged import default_enabled
        self.window_ms = window_ms if window_ms is not None else \
            _env_float("PINOT_VECTOR_WINDOW_MS", DEFAULT_WINDOW_MS)
        self.max_batch = int(max_batch if max_batch is not None else
                             _env_float("PINOT_VECTOR_MAX_BATCH",
                                        DEFAULT_MAX_BATCH))
        self.enabled = default_enabled() if enabled is None \
            else bool(enabled)
        self.queue = MicroBatchQueue()

    def configure(self, enabled: Optional[bool] = None,
                  window_ms: Optional[float] = None,
                  max_batch: Optional[int] = None) -> "VectorBatcher":
        if enabled is not None:
            self.enabled = bool(enabled)
        if window_ms is not None:
            self.window_ms = float(window_ms)
        if max_batch is not None:
            self.max_batch = int(max_batch)
        return self

    @staticmethod
    def _solo(reader, q, k, nprobe, reason: str):
        global_metrics.count(f"vector_solo_{reason}")
        annotate(batched=False, solo_reason=reason)
        scores, docs = reader.search_batch((q,), k, nprobe)
        return scores[0], docs[0]

    def search(self, reader, q: Tuple[float, ...], k: int,
               nprobe: Optional[int]):
        """One query's (scores, docs) for one segment, fused with
        concurrent peers when the admission window catches any."""
        from .accounting import global_accountant
        if not self.enabled:
            return self._solo(reader, q, k, nprobe, "disabled")
        # a lone query never waits the window (round-13 discipline)
        if len(global_accountant.running()) < 2:
            return self._solo(reader, q, k, nprobe, "no_peers")
        # reader.token, never id(): a GC'd reader's reused address must
        # not alias another reader's compatibility bucket
        key = (reader.token, int(k), reader.effective_nprobe(nprobe))
        sub = _VSub(q)
        t0 = time.perf_counter()
        batch = self.queue.offer(key, sub, self.window_ms / 1e3,
                                 self.max_batch)
        if batch is None:
            return self._follow(reader, sub, k, nprobe)
        if len(batch) == 1:
            annotate(queue_wait_ms=round(
                (time.perf_counter() - t0) * 1e3, 3))
            return self._solo(reader, q, k, nprobe, "window_expired")
        return self._lead(reader, batch, sub, k, nprobe)

    @staticmethod
    def _follow_timeout() -> float:
        """Generous enough for a leader paying a first fused-kernel
        compile (the ragged-batcher discipline), but reserving half the
        query's remaining deadline for the solo fallback so a stalled
        leader can't convert a servable query into a deadline kill."""
        from .accounting import global_accountant
        timeout = 60.0
        qid = global_accountant.current_query_id()
        usage = global_accountant.usage(qid) if qid else None
        if usage is not None and usage.deadline is not None:
            rem = usage.deadline - time.perf_counter()
            timeout = max(min(rem * 0.5, 60.0), 0.05)
        return timeout

    def _follow(self, reader, sub: _VSub, k, nprobe):
        try:
            result = sub.future.result(timeout=self._follow_timeout())
        except _FutTimeout:
            result = None
            reason = "timeout"
        except Exception:
            result = None
            reason = "leader_error"
        else:
            reason = "leader_error"
        if result is None:
            return self._solo(reader, sub.q, k, nprobe, reason)
        row, batch_size = result
        annotate(batched=True, batch_size=batch_size)
        return row

    def _lead(self, reader, batch: List[_VSub], own: _VSub, k, nprobe):
        try:
            scores, docs = reader.search_batch(
                [s.q for s in batch], k, nprobe)
        except BaseException as e:  # noqa: BLE001 — followers must not hang
            for s in batch:
                if s is not own and not s.future.done():
                    s.future.set_result(None)
            global_metrics.count("vector_fused_errors")
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            return self._solo(reader, own.q, k, nprobe, "leader_error")
        n = len(batch)
        global_metrics.count("vector_batched_dispatches")
        global_metrics.count("vector_batched_queries", n)
        mine = None
        for i, s in enumerate(batch):
            if s is own:
                mine = (scores[i], docs[i])
            else:
                s.future.set_result(((scores[i], docs[i]), n))
        annotate(batched=True, batch_size=n, leader=True)
        return mine


global_vector_batcher = VectorBatcher()


# ---------------------------------------------------------------------------
# engine entry points
# ---------------------------------------------------------------------------

def segment_search(seg, e: FuncCall) -> Tuple[np.ndarray, np.ndarray]:
    """One (scores, docs) top-k search for one (query, segment, call) —
    memoized so the filter mask and the score key share one launch."""
    col, qv, k, nprobe, reader = validate_call(seg, e)
    from .accounting import global_accountant
    qid = global_accountant.current_query_id()
    # reader.token (process-unique, never reused) keys the memo: an
    # id() key could serve a dropped segment's top-k to a new reader
    # allocated at the same address
    key = (qid, reader.token, qv, k, nprobe)
    got = _memo_get(key)
    if got is not None:
        return got
    owner = reader.owner()
    if owner is not None:
        # tier chaos hook (tier.evict may force-demote mid-query; the
        # search below transparently re-uploads, byte-identically)
        from .tier import global_tier
        global_tier.on_access(owner)
    with span(ph.VECTOR_SEARCH, segment=getattr(seg, "name", ""),
              col=col, k=k):
        global_metrics.count("vector_searches")
        scores, docs = global_vector_batcher.search(reader, qv, k,
                                                    nprobe)
    _memo_put(key, (scores, docs))
    return scores, docs


def filter_mask(seg, e: FuncCall) -> np.ndarray:
    """The VECTOR_SIMILARITY filter predicate: top-k doc mask for one
    segment (VectorSimilarityFilterOperator analog, IVF-backed)."""
    _scores, docs = segment_search(seg, e)
    mask = np.zeros(seg.n_docs, dtype=bool)
    hits = docs[docs >= 0]
    mask[hits] = True
    return mask


def order_scores(seg, e: FuncCall, sel: Optional[np.ndarray] = None
                 ) -> np.ndarray:
    """VECTOR_SIMILARITY as a VALUE (ORDER BY key / select-list score):
    the exact host-side similarity of each (selected) doc to the query
    vector. Host-computed from the stored matrix, so the merge keys are
    deterministic and identical across solo/batched/cluster placements;
    with the idiomatic matching WHERE conjunct the heavy candidate
    SELECTION already happened on device via the filter's memoized
    search and ``sel`` holds at most k rows per segment. NOTE: without
    that filter (ORDER BY-only) this scores every selected doc on the
    host — a full-matrix numpy scan per segment; the device-side
    full-scoring formulation is a ROADMAP direction-5 follow-up."""
    _col, qv, _k, _nprobe, reader = validate_call(seg, e)
    return reader.host_scores(qv, sel)


def vector_calls(*exprs: Any) -> List[FuncCall]:
    """Every VECTOR_SIMILARITY call in the given expression trees (the
    planner's fail-fast validation walk)."""
    from ..query.sql import ast_children
    out: List[FuncCall] = []

    def walk(e: Any) -> None:
        if is_vector_call(e):
            out.append(e)
        for c in ast_children(e):
            walk(c)

    for e in exprs:
        if e is not None:
            walk(e)
    return out


def vector_health(snapshot: Dict[str, Any]) -> Dict[str, Any]:
    """The vector block for consoles: search/fuse counters plus the
    devmem pool occupancy."""
    c = snapshot.get("counters", {})
    from ..utils.devmem import global_device_memory
    return {
        "searches": c.get("vector_searches", 0),
        "batched_dispatches": c.get("vector_batched_dispatches", 0),
        "batched_queries": c.get("vector_batched_queries", 0),
        "kernel_compiles": c.get("vector_kernel_compiles", 0),
        "solo": {r: c[f"vector_solo_{r}"]
                 for r in ("disabled", "no_peers", "window_expired",
                           "timeout", "leader_error")
                 if f"vector_solo_{r}" in c},
        "pool_bytes": global_device_memory.pool_bytes("vector"),
    }
