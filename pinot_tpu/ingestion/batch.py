"""Batch ingestion job: read input files -> transform -> build segments
-> push.

Reference parity: pinot-spi/.../ingestion/batch/spec/
SegmentGenerationJobSpec + pinot-plugins/pinot-batch-ingestion/
pinot-batch-ingestion-standalone (the standalone runner) with the two
push modes: tar/metadata push to a controller (deep store) or plain
local segment output. The reference's Spark/Hadoop runners
(pinot-batch-ingestion-spark SparkSegmentGenerationJobRunner) map one
input file to one segment-generation task across executors; the
"parallel" execution framework here does the same over a local process
pool (executionFrameworkSpec: {"name": "parallel", "numWorkers": N}) —
per-file tasks, worker-disjoint segment names, pushes serialized in the
driver exactly like the reference's runner.

Job spec (dict; JSON/YAML-friendly, SegmentGenerationJobSpec analog):
    {
      "inputDirURI": "/data/in",            # or "inputFiles": [...]
      "includeFileNamePattern": "*.csv",    # fnmatch, default all
      "format": "csv",                # csv|json|jsonl|avro|parquet|orc|
                                      # protobuf|thrift|clp
      "formatArgs": {...},            # reader config (protobuf:
                                      # descriptor_file+message_type;
                                      # thrift: field_names; clp: fields)
      "outputDirURI": "/data/segments",
      "tableName": "mytable",
      "schema": {...},                      # Schema.to_dict()
      "tableConfig": {...},                 # TableConfig.to_dict()
      "segmentNamePrefix": "mytable",       # default tableName
      "rowsPerSegment": 1000000,
      "push": {                             # optional
        "controllerUrl": "http://...",
        "deepstoreURI": "file:///deepstore" # tar push when set,
      }                                     # location push otherwise
    }
"""
from __future__ import annotations

import fnmatch
import os
from typing import Any, Dict, List, Optional

from ..inputformat import read_records
from ..segment.builder import SegmentBuilder
from ..spi.config import TableConfig
from ..spi.schema import Schema
from .transformers import CompositeTransformer


class BatchIngestionJob:
    def __init__(self, spec: Dict[str, Any]):
        self.spec = spec
        self.schema = Schema.from_dict(spec["schema"])
        self.table_config = TableConfig.from_dict(
            spec.get("tableConfig")
            or {"tableName": spec["tableName"]})
        self.table = spec.get("tableName") or self.table_config.table_name

    # -- input discovery ---------------------------------------------------
    def input_files(self) -> List[str]:
        if self.spec.get("inputFiles"):
            return list(self.spec["inputFiles"])
        root = self.spec["inputDirURI"]
        pattern = self.spec.get("includeFileNamePattern", "*")
        out: List[str] = []
        for dirpath, _dirs, files in os.walk(root):
            for f in sorted(files):
                if fnmatch.fnmatch(f, pattern):
                    out.append(os.path.join(dirpath, f))
        if not out:
            raise FileNotFoundError(
                f"no input files under {root!r} matching {pattern!r}")
        return out

    # -- run ---------------------------------------------------------------
    def run(self) -> List[str]:
        fw = (self.spec.get("executionFrameworkSpec") or {})
        if fw.get("name") in ("parallel", "spark", "hadoop"):
            return self._run_parallel(int(fw.get("numWorkers") or 0))
        return self._run_standalone()

    def _run_parallel(self, workers: int) -> List[str]:
        """Per-file fan-out over WORKER PROCESSES the driver launches
        (Spark runner analog: one segment-generation task per input
        file; rowsPerSegment splits within a file). Plain subprocesses
        running ``python -m pinot_tpu.ingestion.batch --file-task``, not
        a multiprocessing pool: fork would deadlock a parent holding
        JAX runtime threads, and spawn/forkserver re-import the parent's
        __main__ (broken for REPL/stdin drivers). Segment names carry
        the file index so tasks never collide; pushes happen in the
        driver, in order."""
        import json as _json
        import shutil
        import subprocess
        import sys
        import tempfile
        import time as _time

        files = self.input_files()
        workers = workers or min(len(files), os.cpu_count() or 1)
        push = self.spec.get("push") or {}
        work_dir = tempfile.mkdtemp(prefix="pinot_ingest_")
        spec_path = os.path.join(work_dir, "spec.json")
        with open(spec_path, "w") as fh:
            _json.dump(self.spec, fh)
        # workers must import pinot_tpu in a FRESH interpreter: carry
        # the driver's sys.path (REPL drivers patch it rather than
        # installing the package)
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p]
            + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
        procs: List[tuple] = []
        pending = list(enumerate(files))
        results: Dict[int, List[str]] = {}
        try:
            while pending or procs:
                while pending and len(procs) < workers:
                    idx, path = pending.pop(0)
                    out_path = os.path.join(work_dir, f"task_{idx}.json")
                    log_path = os.path.join(work_dir, f"task_{idx}.log")
                    # results travel via --out FILES and worker output
                    # via a redirected log file, never pipes: a chatty
                    # worker can neither block on a full pipe nor
                    # corrupt the result protocol with stray prints
                    log_fh = open(log_path, "wb")
                    procs.append((idx, subprocess.Popen(
                        [sys.executable, "-m",
                         "pinot_tpu.ingestion.batch", "--file-task",
                         spec_path, path, str(idx), "--out", out_path],
                        stdout=log_fh, stderr=subprocess.STDOUT,
                        env=env), out_path, log_path, log_fh))
                # reap ANY finished worker (no head-of-line blocking: a
                # big file must not idle the other slots)
                done = [i for i, entry in enumerate(procs)
                        if entry[1].poll() is not None]
                if not done:
                    _time.sleep(0.05)
                    continue
                for i in reversed(done):
                    idx, p, out_path, log_path, log_fh = procs.pop(i)
                    p.wait()
                    log_fh.close()
                    if p.returncode != 0 or not os.path.exists(out_path):
                        with open(log_path, "rb") as lf:
                            lf.seek(max(0, os.path.getsize(log_path)
                                        - 2000))
                            tail = lf.read().decode(errors="replace")
                        raise RuntimeError(
                            f"ingestion task {idx} failed: {tail}")
                    with open(out_path) as rf:
                        results[idx] = _json.load(rf)
            seg_dirs = [d for idx in sorted(results)
                        for d in results[idx]]
        finally:
            # a failed task must not leave siblings running (they would
            # keep writing segments after the job reported failure)
            for entry in procs:
                entry[1].kill()
                entry[1].wait()
                entry[4].close()
            shutil.rmtree(work_dir, ignore_errors=True)
        if not push.get("controllerUrl"):
            return seg_dirs
        return [self._push(d, push) for d in seg_dirs]

    def job_params(self):
        """(fmt, pipeline, out_dir, prefix, per_seg, builder) — the ONE
        derivation of spec keys both runners share."""
        return (self.spec.get("format", ""),
                CompositeTransformer.from_table_config(
                    self.table_config, self.schema),
                self.spec["outputDirURI"],
                self.spec.get("segmentNamePrefix", self.table),
                int(self.spec.get("rowsPerSegment", 1_000_000)),
                SegmentBuilder(self.schema, self.table_config))

    def _run_standalone(self) -> List[str]:
        """Execute the job; returns the registered segment locations
        (deep-store URIs in tar-push mode, local dirs otherwise).

        Streaming: each input file is read + transformed on its own and
        segments flush as the buffer reaches rowsPerSegment, so peak
        memory is one file plus one segment of rows — never the whole
        dataset (the transform pipeline is row-independent, so chunking
        preserves semantics)."""
        fmt, pipeline, out_dir, prefix, per_seg, builder = \
            self.job_params()
        push = self.spec.get("push") or {}

        locations: List[str] = []
        buf: List[Dict[str, Any]] = []

        def flush(chunk: List[Dict[str, Any]]) -> None:
            name = f"{prefix}_{len(locations)}"
            seg_dir = builder.build(chunk, out_dir, name)
            locations.append(self._push(seg_dir, push)
                             if push.get("controllerUrl") else seg_dir)

        for path in self.input_files():
            buf.extend(pipeline.transform(read_records(
                path, fmt, **(self.spec.get("formatArgs") or {}))))
            while len(buf) >= per_seg:
                flush(buf[:per_seg])
                buf = buf[per_seg:]
        if buf:
            flush(buf)
        return locations

    def _push(self, seg_dir: str, push: Dict[str, Any]) -> str:
        """Metadata push: optional deep-store upload, then register the
        segment + pruning metadata with the controller."""
        from ..cluster.deepstore import pruning_metadata, upload_segment
        from ..cluster.http_util import http_json
        location = seg_dir
        if push.get("deepstoreURI"):
            location = upload_segment(
                seg_dir, push["deepstoreURI"].rstrip("/") + "/"
                + self.table)
        http_json("POST", f"{push['controllerUrl']}/segments", {
            "table": self.table,
            "segment": os.path.basename(seg_dir.rstrip("/")),
            "location": location,
            "metadata": pruning_metadata(seg_dir),
        })
        return location


def _build_file_segments(spec: Dict[str, Any], path: str,
                         file_idx: int) -> List[str]:
    """One parallel task: read + transform + build segments for ONE
    input file (the body of the ``--file-task`` worker subprocess)."""
    job = BatchIngestionJob(spec)
    fmt, pipeline, out_dir, prefix, per_seg, builder = job.job_params()
    rows = pipeline.transform(read_records(
        path, fmt, **(spec.get("formatArgs") or {})))
    out: List[str] = []
    for k in range(0, len(rows), per_seg):
        name = f"{prefix}_{file_idx}_{k // per_seg}"
        out.append(builder.build(rows[k:k + per_seg], out_dir, name))
    return out


def run_batch_ingestion(spec: Dict[str, Any]) -> List[str]:
    return BatchIngestionJob(spec).run()


if __name__ == "__main__":
    # worker entry: --file-task spec.json path idx --out result.json
    import json as _json
    import sys as _sys

    if len(_sys.argv) == 7 and _sys.argv[1] == "--file-task" \
            and _sys.argv[5] == "--out":
        with open(_sys.argv[2]) as _fh:
            _spec = _json.load(_fh)
        _dirs = _build_file_segments(_spec, _sys.argv[3],
                                     int(_sys.argv[4]))
        _tmp = _sys.argv[6] + ".tmp"
        with open(_tmp, "w") as _out:
            _json.dump(_dirs, _out)
        os.replace(_tmp, _sys.argv[6])  # exists == complete
    else:
        raise SystemExit(
            "usage: python -m pinot_tpu.ingestion.batch "
            "--file-task <spec.json> <input-file> <file-idx> "
            "--out <result.json>")
