"""Batch ingestion job: read input files -> transform -> build segments
-> push.

Reference parity: pinot-spi/.../ingestion/batch/spec/
SegmentGenerationJobSpec + pinot-plugins/pinot-batch-ingestion/
pinot-batch-ingestion-standalone (the standalone runner) with the two
push modes: tar/metadata push to a controller (deep store) or plain
local segment output. Spark/Hadoop runners in the reference parallelize
the same per-file work; here files chunk into segments serially (a
process pool can slot in behind run() without changing the spec).

Job spec (dict; JSON/YAML-friendly, SegmentGenerationJobSpec analog):
    {
      "inputDirURI": "/data/in",            # or "inputFiles": [...]
      "includeFileNamePattern": "*.csv",    # fnmatch, default all
      "format": "csv",                      # csv|json|jsonl|avro|parquet
      "outputDirURI": "/data/segments",
      "tableName": "mytable",
      "schema": {...},                      # Schema.to_dict()
      "tableConfig": {...},                 # TableConfig.to_dict()
      "segmentNamePrefix": "mytable",       # default tableName
      "rowsPerSegment": 1000000,
      "push": {                             # optional
        "controllerUrl": "http://...",
        "deepstoreURI": "file:///deepstore" # tar push when set,
      }                                     # location push otherwise
    }
"""
from __future__ import annotations

import fnmatch
import os
from typing import Any, Dict, List, Optional

from ..inputformat import read_records
from ..segment.builder import SegmentBuilder
from ..spi.config import TableConfig
from ..spi.schema import Schema
from .transformers import CompositeTransformer


class BatchIngestionJob:
    def __init__(self, spec: Dict[str, Any]):
        self.spec = spec
        self.schema = Schema.from_dict(spec["schema"])
        self.table_config = TableConfig.from_dict(
            spec.get("tableConfig")
            or {"tableName": spec["tableName"]})
        self.table = spec.get("tableName") or self.table_config.table_name

    # -- input discovery ---------------------------------------------------
    def input_files(self) -> List[str]:
        if self.spec.get("inputFiles"):
            return list(self.spec["inputFiles"])
        root = self.spec["inputDirURI"]
        pattern = self.spec.get("includeFileNamePattern", "*")
        out: List[str] = []
        for dirpath, _dirs, files in os.walk(root):
            for f in sorted(files):
                if fnmatch.fnmatch(f, pattern):
                    out.append(os.path.join(dirpath, f))
        if not out:
            raise FileNotFoundError(
                f"no input files under {root!r} matching {pattern!r}")
        return out

    # -- run ---------------------------------------------------------------
    def run(self) -> List[str]:
        """Execute the job; returns the registered segment locations
        (deep-store URIs in tar-push mode, local dirs otherwise).

        Streaming: each input file is read + transformed on its own and
        segments flush as the buffer reaches rowsPerSegment, so peak
        memory is one file plus one segment of rows — never the whole
        dataset (the transform pipeline is row-independent, so chunking
        preserves semantics)."""
        fmt = self.spec.get("format", "")
        pipeline = CompositeTransformer.from_table_config(
            self.table_config, self.schema)
        out_dir = self.spec["outputDirURI"]
        prefix = self.spec.get("segmentNamePrefix", self.table)
        per_seg = int(self.spec.get("rowsPerSegment", 1_000_000))
        builder = SegmentBuilder(self.schema, self.table_config)
        push = self.spec.get("push") or {}

        locations: List[str] = []
        buf: List[Dict[str, Any]] = []

        def flush(chunk: List[Dict[str, Any]]) -> None:
            name = f"{prefix}_{len(locations)}"
            seg_dir = builder.build(chunk, out_dir, name)
            locations.append(self._push(seg_dir, push)
                             if push.get("controllerUrl") else seg_dir)

        for path in self.input_files():
            buf.extend(pipeline.transform(read_records(path, fmt)))
            while len(buf) >= per_seg:
                flush(buf[:per_seg])
                buf = buf[per_seg:]
        if buf:
            flush(buf)
        return locations

    def _push(self, seg_dir: str, push: Dict[str, Any]) -> str:
        """Metadata push: optional deep-store upload, then register the
        segment + pruning metadata with the controller."""
        from ..cluster.deepstore import pruning_metadata, upload_segment
        from ..cluster.http_util import http_json
        location = seg_dir
        if push.get("deepstoreURI"):
            location = upload_segment(
                seg_dir, push["deepstoreURI"].rstrip("/") + "/"
                + self.table)
        http_json("POST", f"{push['controllerUrl']}/segments", {
            "table": self.table,
            "segment": os.path.basename(seg_dir.rstrip("/")),
            "location": location,
            "metadata": pruning_metadata(seg_dir),
        })
        return location


def run_batch_ingestion(spec: Dict[str, Any]) -> List[str]:
    return BatchIngestionJob(spec).run()
