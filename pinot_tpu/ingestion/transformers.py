"""Record transformer pipeline (pre-indexing row transforms).

Reference parity: pinot-segment-local/.../recordtransformer/ —
CompositeTransformer chaining ComplexTypeTransformer (nested-object
flattening), ExpressionTransformer (derived columns),
FilterTransformer (row drops), DataTypeTransformer (schema-conforming
type coercion), and SanitizationTransformer (string cleanup) in the
same order the reference applies them. Expression/filter evaluation is
vectorized: the row batch becomes a columnar Relation and runs through
the same host evaluators the query engine uses — no per-row expression
interpretation.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..query.sql import SqlError, parse_sql
from ..spi.schema import DataType, Schema

Rows = List[Dict[str, Any]]


def _parse_expr(text: str):
    """Parse a bare expression/predicate using the SELECT grammar."""
    stmt = parse_sql(f"SELECT 1 FROM t WHERE {text}")
    return stmt.where


def _rows_to_relation(rows: Rows):
    from ..multistage.relation import Relation
    cols: Dict[str, np.ndarray] = {}
    nulls: Dict[str, np.ndarray] = {}
    names = []
    seen = set()
    for r in rows:
        for k in r:
            if k not in seen:
                seen.add(k)
                names.append(k)
    for name in names:
        vals = [r.get(name) for r in rows]
        nm = np.array([v is None for v in vals], dtype=bool)
        if nm.any():
            nulls[name] = nm
        arr = np.array(vals, dtype=object)
        # numeric columns get real dtypes so arithmetic works
        if not nm.all():
            sample = next(v for v in vals if v is not None)
            if isinstance(sample, bool):
                pass
            elif isinstance(sample, int) and all(
                    v is None or isinstance(v, int) for v in vals):
                arr = np.array([0 if v is None else v for v in vals],
                               dtype=np.int64)
            elif isinstance(sample, (int, float)) and all(
                    v is None or isinstance(v, (int, float))
                    for v in vals):
                arr = np.array([np.nan if v is None else v for v in vals],
                               dtype=np.float64)
        cols[name] = arr
    return Relation(cols, nulls, "batch")


class RecordTransformer:
    def transform(self, rows: Rows) -> Rows:
        raise NotImplementedError


class ComplexTypeTransformer(RecordTransformer):
    """Flatten nested dicts into dotted columns; JSON-stringify residual
    collections (maps/lists) so they land in JSON/STRING columns."""

    def __init__(self, delimiter: str = "."):
        self.delimiter = delimiter

    def _flatten(self, prefix: str, value: Any, out: Dict[str, Any]) -> None:
        if isinstance(value, dict):
            for k, v in value.items():
                self._flatten(f"{prefix}{self.delimiter}{k}" if prefix
                              else str(k), v, out)
        else:
            out[prefix] = value

    def transform(self, rows: Rows) -> Rows:
        out: Rows = []
        for r in rows:
            flat: Dict[str, Any] = {}
            self._flatten("", r, flat)
            out.append(flat)
        return out


class ExpressionTransformer(RecordTransformer):
    """Derived columns: columnName <- transformFunction(expression over
    source columns), evaluated vectorized over the batch."""

    def __init__(self, transforms: Sequence[Dict[str, str]]):
        # [{"columnName": ..., "transformFunction": "..."}]
        self._specs = [(t["columnName"],
                        _parse_expr(t["transformFunction"]))
                       for t in transforms]

    def transform(self, rows: Rows) -> Rows:
        if not rows or not self._specs:
            return rows
        from ..engine import host_eval
        from ..query.sql import collect_identifiers
        rel = _rows_to_relation(rows)
        for name, expr in self._specs:
            try:
                vals = np.broadcast_to(
                    np.asarray(host_eval.eval_value(expr, rel)),
                    (len(rows),)).tolist()
            except (KeyError, SqlError, TypeError, ValueError):
                # e.g. a batch where no row carries the source column:
                # the derived column is null, not a dead consumer thread
                vals = [None] * len(rows)
            # null inputs yield null outputs (the placeholder 0/NaN the
            # relation builder substitutes must never escape as data)
            null_in = None
            for ref in collect_identifiers(expr):
                nm = rel.null_mask(ref)
                if nm is not None:
                    null_in = nm if null_in is None else (null_in | nm)
            for i, r in enumerate(rows):
                r[name] = None if (null_in is not None and null_in[i]) \
                    else vals[i]
        return rows


class FilterTransformer(RecordTransformer):
    """Drop rows matching filterFunction (FilterTransformer.java: the
    filter marks rows to SKIP)."""

    def __init__(self, filter_function: str):
        self._pred = _parse_expr(filter_function)

    def drop_mask(self, rows: Rows) -> np.ndarray:
        """True where the row matches the filter (to be dropped) —
        realtime uses this to invalidate instead of removing, keeping
        stream-offset == doc-id accounting exact."""
        if not rows:
            return np.zeros(0, dtype=bool)
        from ..engine import host_eval
        rel = _rows_to_relation(rows)
        return host_eval.eval_filter(self._pred, rel)

    def transform(self, rows: Rows) -> Rows:
        drop = self.drop_mask(rows)
        return [r for r, d in zip(rows, drop) if not d]


class DataTypeTransformer(RecordTransformer):
    """Coerce values to the schema's declared types; unknown columns are
    dropped (SchemaConformingTransformer + DataTypeTransformer)."""

    def __init__(self, schema: Schema):
        self.schema = schema

    @staticmethod
    def _coerce(dt: DataType, v: Any) -> Any:
        if v is None:
            return None
        if dt in (DataType.INT, DataType.LONG):
            return int(v)
        if dt in (DataType.FLOAT, DataType.DOUBLE):
            return float(v)
        if dt == DataType.BOOLEAN:
            if isinstance(v, str):
                return v.strip().lower() in ("1", "true", "yes")
            return bool(v)
        if dt == DataType.STRING:
            return v if isinstance(v, str) else str(v)
        if dt == DataType.JSON:
            return v if isinstance(v, str) else json.dumps(v)
        return v

    def transform(self, rows: Rows) -> Rows:
        fields = {f.name: f.data_type for f in self.schema.fields}
        out: Rows = []
        for r in rows:
            out.append({name: self._coerce(dt, r.get(name))
                        for name, dt in fields.items()})
        return out


class SanitizationTransformer(RecordTransformer):
    """String cleanup: strip NUL characters, enforce max length
    (SanitizationTransformer.java)."""

    def __init__(self, max_length: int = 512):
        self.max_length = max_length

    def transform(self, rows: Rows) -> Rows:
        for r in rows:
            for k, v in r.items():
                if isinstance(v, str):
                    v = v.replace("\x00", "")
                    if len(v) > self.max_length:
                        v = v[: self.max_length]
                    r[k] = v
        return rows


class CompositeTransformer(RecordTransformer):
    """The standard pipeline, in the reference's order: complex-type
    flatten -> expression transforms -> filter -> schema-conforming type
    coercion -> sanitization."""

    def __init__(self, transformers: Sequence[RecordTransformer]):
        self.transformers = list(transformers)

    @classmethod
    def from_table_config(cls, table_config, schema: Schema
                          ) -> "CompositeTransformer":
        ing = getattr(table_config, "ingestion", None)
        chain: List[RecordTransformer] = [ComplexTypeTransformer()]
        if ing is not None:
            if getattr(ing, "transforms", None):
                chain.append(ExpressionTransformer(ing.transforms))
            if getattr(ing, "filter_function", None):
                chain.append(FilterTransformer(ing.filter_function))
        chain.append(DataTypeTransformer(schema))
        chain.append(SanitizationTransformer())
        return cls(chain)

    def transform(self, rows: Rows) -> Rows:
        for t in self.transformers:
            rows = t.transform(rows)
            if not rows:
                break
        return rows
