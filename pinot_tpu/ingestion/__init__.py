"""Ingestion: record transformer pipeline + batch ingestion jobs.

Reference parity: pinot-segment-local/.../recordtransformer/ (the
CompositeTransformer row pipeline applied before indexing) and
pinot-spi/.../ingestion/batch/ + pinot-plugins/pinot-batch-ingestion/
(job spec + standalone runner building and pushing segments).
"""
from .batch import BatchIngestionJob, run_batch_ingestion  # noqa: F401
from .transformers import (ComplexTypeTransformer,  # noqa: F401
                           CompositeTransformer, DataTypeTransformer,
                           ExpressionTransformer, FilterTransformer,
                           SanitizationTransformer)
