"""JAX hazard linter: AST rules over the package source.

The runtime observability layer (PR 2's RetraceDetector, span tracer)
catches hot-path hazards only AFTER they burn a query. These rules catch
the same hazard classes at lint time:

- ``host-sync``: ``.item()`` / ``np.asarray`` / ``jax.device_get`` /
  ``int(...)``/``float(...)`` over call results in the device hot paths
  (ops/, engine/, multistage/, parallel/). Each forces a device→host
  round trip when applied to a device value; a stray one inside a
  dispatch loop serializes the pipeline. Most existing occurrences are
  legitimately host-side (post-``device_get`` extraction, host_eval) —
  those live in per-module allowlists, inline suppressions, or the
  checked-in ratchet baseline (tools/jaxlint_baseline.json).
- ``jit-in-loop``: ``jax.jit(...)`` constructed inside a ``for``/
  ``while`` body — a fresh jit wrapper per iteration defeats the trace
  cache and retraces per query/row.
- ``nonstatic-trace``: reads of non-static Python state (``os.environ``,
  ``time.*``, ``random``) inside functions that are jitted in the same
  module — the value bakes into the compiled program at trace time and
  silently goes stale.
- ``unlocked-mutation``: in classes that guard state with a lock
  attribute, a mutation of lock-guarded shared state (metrics counters,
  plan-cache registries, retrace counters) outside a ``with self.<lock>``
  block — increments race and observability counters drift.

Suppression: append ``# jaxlint: ok <rule>`` (comma-separated rules or
``all``) to the offending line. Grandfathered sites are counted per
``file::scope::rule`` in the baseline — new findings above the baseline
count fail ``tools/check_static.py``; counts that DROP fail too until
the baseline is ratcheted down with ``--update-baseline``.
"""
from __future__ import annotations

import ast
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .astutil import (Finding, compare_baseline, counts_of,
                      load_baseline, parse_suppressions)
from .astutil import write_baseline as _write_baseline

__all__ = [
    "LINT_RULES", "Finding", "lint_source", "lint_source_ex",
    "lint_tree", "lint_tree_ex", "counts_of", "load_baseline",
    "write_baseline", "compare_baseline",
]

LINT_RULES = {
    "host-sync": "device→host sync in a device hot path",
    "jit-in-loop": "jax.jit constructed inside a loop (retrace hazard)",
    "nonstatic-trace": "non-static Python state read under jit trace",
    "unlocked-mutation": "lock-guarded shared state mutated without "
                         "the lock",
    # never baselined (write_baseline drops it): a module that stops
    # parsing must fail the gate no matter what was grandfathered
    "parse-error": "module failed to parse",
}

# host-sync applies only inside the device hot paths
HOT_PACKAGES = ("ops", "engine", "multistage", "parallel")
# modules that ARE the host path by design: every value they touch is
# host numpy (oracle/merge/cost code), so the host-sync rule is noise
HOST_SYNC_ALLOW = (
    "pinot_tpu/engine/host_eval.py",     # host evaluation by definition
    "pinot_tpu/ops/aggregations.py",     # host partial-state registry
    "pinot_tpu/ops/sketches.py",         # host sketch implementations
    "pinot_tpu/multistage/costs.py",     # pure host cost model
)

_NUMPY_NAMES = ("np", "numpy", "_np")
_SYNC_ATTRS = {"asarray", "array", "device_get"}
_MUTATING_METHODS = {"append", "extend", "add", "update", "setdefault",
                     "pop", "popitem", "clear", "remove", "discard",
                     "insert", "move_to_end"}
_NONSTATIC_CALLS = {("os", "getenv"), ("time", "time"),
                    ("time", "perf_counter"), ("time", "thread_time"),
                    ("time", "monotonic")}


def _suppressions(src: str) -> Dict[int, set]:
    return parse_suppressions(src, "jaxlint")


def _call_name(func: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """('np', 'asarray') for np.asarray(...); (None, 'int') for int(...)."""
    from .astutil import call_parts
    return call_parts(func)


def _is_jax_jit(func: ast.AST) -> bool:
    base, attr = _call_name(func)
    return (base == "jax" and attr == "jit") or \
        (base is None and attr == "jit")


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, src: str, hot: bool):
        self.path = path
        self.hot = hot
        self.suppress = _suppressions(src)
        self.scope: List[str] = []
        self.loop_depth = 0
        self.jitted_fns: set = set()
        self.findings: List[Finding] = []
        self.suppressed: List[Finding] = []

    # -- plumbing ----------------------------------------------------------
    def emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        f = Finding(rule, self.path, line,
                    ".".join(self.scope) or "<module>", message)
        sup = self.suppress.get(line, ())
        if rule in sup or "all" in sup:
            self.suppressed.append(f)
            return
        self.findings.append(f)

    def _walk_scope(self, name: str, node: ast.AST) -> None:
        self.scope.append(name)
        outer_loops = self.loop_depth
        self.loop_depth = 0      # a new function resets loop context
        self.generic_visit(node)
        self.loop_depth = outer_loops
        self.scope.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._function(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._function(node)

    def _function(self, node: Any) -> None:
        for dec in node.decorator_list:
            if _is_jax_jit(dec) or (
                    isinstance(dec, ast.Call) and (
                        _is_jax_jit(dec.func)
                        or any(_is_jax_jit(a) for a in dec.args))):
                self.jitted_fns.add(node.name)
        self._walk_scope(node.name, node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._lint_lock_discipline(node)
        self._walk_scope(node.name, node)

    def visit_For(self, node: ast.For) -> None:
        self._loop(node)

    def visit_AsyncFor(self, node: ast.AsyncFor) -> None:
        self._loop(node)

    def visit_While(self, node: ast.While) -> None:
        self._loop(node)

    def _loop(self, node: Any) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    # -- host-sync + jit-in-loop ------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        base, attr = _call_name(node.func)
        if _is_jax_jit(node.func) and self.loop_depth > 0:
            self.emit("jit-in-loop", node,
                      "jax.jit constructed inside a loop body retraces "
                      "every iteration; hoist it (or functools.lru_cache "
                      "the builder) so the trace cache can hit")
        if self.hot:
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                self.emit("host-sync", node,
                          ".item() blocks on the device; fence once "
                          "after execute instead")
            elif attr in _SYNC_ATTRS and (
                    base in _NUMPY_NAMES or (base == "jax"
                                             and attr == "device_get")):
                self.emit("host-sync", node,
                          f"{base}.{attr}() on a device value forces a "
                          "transfer; do it once behind the post-execute "
                          "fence")
            elif base is None and attr in ("int", "float", "bool") \
                    and len(node.args) == 1 \
                    and isinstance(node.args[0], (ast.Call, ast.Subscript)):
                self.emit("host-sync", node,
                          f"{attr}() over a computed value syncs if the "
                          "value lives on device; hoist past the fence")
        self.generic_visit(node)

    # -- nonstatic-trace ---------------------------------------------------
    @staticmethod
    def _dotted(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            base = _Linter._dotted(node.value)
            return f"{base}.{node.attr}" if base else None
        return None

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self.scope and self.scope[-1] in self.jitted_fns:
            dotted = self._dotted(node)
            nonstatic = dotted is not None and (
                dotted == "os.environ"
                or dotted in {f"{m}.{a}" for m, a in _NONSTATIC_CALLS}
                # exact match on the submodule node so np.random.uniform
                # fires once (on the inner np.random attribute)
                or dotted in ("np.random", "numpy.random")
                or (isinstance(node.value, ast.Name)
                    and node.value.id == "random" and node.attr != "seed"))
            if nonstatic:
                self.emit("nonstatic-trace", node,
                          f"{dotted} read inside a jitted function "
                          "bakes into the compiled program at trace "
                          "time")
        self.generic_visit(node)

    # -- unlocked-mutation -------------------------------------------------
    @staticmethod
    def _self_attr(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        return None

    def _mutations(self, body: Iterable[ast.AST]):
        """Yield (attr, node) for every mutation of a self attribute in
        the statement list (assign/augassign/subscript/del/mutating
        method call)."""
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        a = self._self_attr(t)
                        if a is not None:
                            yield a, node
                        if isinstance(t, ast.Subscript):
                            a = self._self_attr(t.value)
                            if a is not None:
                                yield a, node
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        if isinstance(t, ast.Subscript):
                            a = self._self_attr(t.value)
                            if a is not None:
                                yield a, node
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _MUTATING_METHODS:
                    a = self._self_attr(node.func.value)
                    if a is not None:
                        yield a, node

    def _lint_lock_discipline(self, cls: ast.ClassDef) -> None:
        methods = [n for n in cls.body if isinstance(n, ast.FunctionDef)]
        lock_attrs: set = set()
        for m in methods:
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    _b, a = _call_name(node.value.func)
                    if a in ("Lock", "RLock"):
                        for t in node.targets:
                            la = self._self_attr(t)
                            if la is not None:
                                lock_attrs.add(la)
        if not lock_attrs:
            return

        def with_lock_bodies(m: ast.FunctionDef):
            for node in ast.walk(m):
                if isinstance(node, ast.With):
                    for item in node.items:
                        ctx = item.context_expr
                        a = self._self_attr(ctx)
                        if a is None and isinstance(ctx, ast.Call):
                            a = self._self_attr(ctx.func)  # lock() style
                        if a in lock_attrs:
                            yield node.body
                            break

        guarded: set = set()
        locked_nodes: set = set()
        for m in methods:
            for body in with_lock_bodies(m):
                for a, node in self._mutations(body):
                    if a not in lock_attrs:
                        guarded.add(a)
                    locked_nodes.add(id(node))
        if not guarded:
            return
        for m in methods:
            if m.name == "__init__":   # construction precedes sharing
                continue
            self.scope.append(f"{cls.name}.{m.name}")
            for a, node in self._mutations([m]):
                if a in guarded and id(node) not in locked_nodes:
                    self.emit("unlocked-mutation", node,
                              f"self.{a} is mutated under "
                              f"{'/'.join(sorted(lock_attrs))} elsewhere "
                              "but not here; concurrent increments race")
            self.scope.pop()


def lint_source_ex(src: str, path: str
                   ) -> Tuple[List[Finding], List[Finding]]:
    """Lint one module's source -> (findings, suppressed). ``path``
    must be repo-relative."""
    path = path.replace(os.sep, "/")
    hot = path.startswith(
        tuple(f"pinot_tpu/{p}/" for p in HOT_PACKAGES)) \
        and path not in HOST_SYNC_ALLOW
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("parse-error", path, e.lineno or 0, "<module>",
                        f"unparseable: {e.msg}")], []
    # pre-pass: names jitted at module level (jax.jit(f), jax.jit(vmap(f)))
    linter = _Linter(path, src, hot)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jax_jit(node.func):
            for arg in node.args:
                inner = arg
                while isinstance(inner, ast.Call) and inner.args:
                    inner = inner.args[0]
                if isinstance(inner, ast.Name):
                    linter.jitted_fns.add(inner.id)
    linter.visit(tree)
    return linter.findings, linter.suppressed


def lint_source(src: str, path: str) -> List[Finding]:
    """Lint one module's source. ``path`` must be repo-relative."""
    return lint_source_ex(src, path)[0]


def lint_tree_ex(root: str, package: str = "pinot_tpu"
                 ) -> Tuple[List[Finding], List[Finding]]:
    """Lint every .py file under <root>/<package> -> (findings,
    suppressed)."""
    from .astutil import iter_py_files
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    for full, rel in iter_py_files(root, package):
        with open(full, "r", encoding="utf-8") as fh:
            fs, sup = lint_source_ex(fh.read(), rel)
        findings.extend(fs)
        suppressed.extend(sup)
    return findings, suppressed


def lint_tree(root: str, package: str = "pinot_tpu") -> List[Finding]:
    """Lint every .py file under <root>/<package>."""
    return lint_tree_ex(root, package)[0]


# ---------------------------------------------------------------------------
# ratchet baseline (shared machinery: analysis/astutil.py)
# ---------------------------------------------------------------------------

def write_baseline(findings: Sequence[Finding], path: str,
                   comment: Optional[str] = None) -> None:
    _write_baseline(findings, path, comment=comment or (
        "jaxlint ratchet baseline — grandfathered findings "
        "per file::scope::rule. Regenerate with "
        "`python tools/check_static.py --update-baseline`; "
        "new findings above these counts fail check_static, "
        "and counts that drop must be ratcheted down here."))
