"""Whole-program determinism & replay-safety verifier: DT301–DT305
over the chaos/SLO/replay planes.

Every headline capability from rounds 16–22 — same-seed fault streams,
the deterministic shed plan in ``tools/traffic_replay.py``,
byte-deterministic SLO burn/alert streams, digest-exact chaos recovery
— rests on one invariant: decision and output paths are pure in (seed,
qid, site-key, record timestamps), never in wall clock, ambient RNG, or
iteration order. Tests catch violations after they ship (the round-22
``kind``/``slo_kind`` envelope collision turned a shed into a 500);
this pass makes the invariant a tier-1 gate, jaxlint/concur's sibling
(AST, stable rule ids, ratchet baseline at
``tools/detlint_baseline.json``) but *whole-program*: the shared call
resolver (analysis/astutil.py) propagates plane membership forward
from a registry of deterministic entry points, so a wall-clock read
three helpers deep under ``shed_decision`` is flagged at its site.

The entry-point registry (``ROOTS``) names the decision/output
functions whose transitive callees form the deterministic plane:
the fault plane's ``FaultPlan.decide``/``fault_fires``/
``corrupt_bytes``, the SLO plane's window/burn evaluation and status
emission, the alert latches and fire path, the overload ladder
(``shed_decision``/``retry_after_ms``/``OverloadGovernor.rung_for``),
span sampling, ledger record building, and the replay planner. A
function may also self-declare with ``# detlint: entrypoint`` on its
``def`` line (synthetic corpora in tests; future planes).

Rules:

- **DT301 wall-clock** — ``time.time``/``monotonic``/``perf_counter``/
  ``datetime.now`` reachable from a deterministic entry point without
  an injectable escape hatch. The escape-hatch idiom is recognized
  structurally: a clock read is EXEMPT when it is the ``is None``
  fallback of a None-default parameter (``t = now if now is not None
  else time.monotonic()``, ``if now is None: now = time.time()``,
  ``now or time.monotonic()`` — including one-step-derived locals like
  ``t = now if ... else event_time(rec)`` followed by ``if t is
  None:``). A clock read with no such hatch bakes wall time into a
  replayable decision.
- **DT302 ambient-randomness** — ``random.*`` module draws, global
  ``np.random.*`` (a seeded ``default_rng(seed)`` is exempt),
  ``uuid4``/``uuid1``, ``os.urandom``, ``secrets.*``, and builtin
  ``hash()`` (PYTHONHASHSEED-dependent for str/bytes) inside the
  plane. Deterministic draws go through hashlib over (seed, site, key)
  — the ``faults._unit`` / ``workload._unit`` idiom.
- **DT303 unordered-serialization** — iteration over a ``set``
  literal/comprehension/``set()`` call, or an unsorted
  ``os.listdir``/``glob.glob``, feeding a loop, ``join``, ``list`` or
  ``tuple`` inside the plane: iteration order leaks into output
  contracts (ledger records, digests, alert streams). Wrap in
  ``sorted(...)``.
- **DT304 query-time-environ** — ``os.environ``/``os.getenv`` read
  inside the plane instead of the startup-parsed-once idiom (the
  ``PINOT_DRIFT_RATIO`` drift-throttle precedent: env reads on the hot
  path also cost a dict probe per decision).
- **DT305 completion-order-float** — a float accumulated over
  ``as_completed(...)``/``imap_unordered(...)`` results (``total +=
  f.result()`` in the loop, or ``sum()`` over such a generator):
  thread-completion order re-associates floating-point addition, so
  two runs of the same work disagree in the last ulp — the
  re-association hazard the fusion cost model already guards
  on-device. Checked corpus-wide (integer counters like ``done += 1``
  are exempt).

Suppression: append ``# detlint: ok <rule>`` (comma-separated rules or
``all``) to the offending line. True-but-benign sites are
grandfathered in the ratchet baseline (``tools/detlint_baseline.json``)
with jaxlint semantics: new findings above a ``file::scope::rule``
count fail ``tools/check_static.py``, and counts that DROP fail too
until the baseline is ratcheted down with ``--update-baseline``.

Known approximations (deliberate): the resolver follows self-calls,
same-module bare calls, imported names/modules/classes, corpus-unique
singletons and corpus-unique method names — never inheritance or
duck-typed callables; escape-hatch analysis is structural (an ``is
None`` guard on ANY None-default parameter exempts the governed
branch); DT303 only sees syntactic set expressions and unsorted
listdir/glob at the iteration site (no type inference).
"""
from __future__ import annotations

import ast
import os
import re
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .astutil import (CallResolver, Finding, call_parts,
                      compare_baseline, counts_of, dotted_name,
                      iter_py_files, line_comments, load_baseline,
                      module_qual, parse_suppressions)

__all__ = [
    "DETLINT_RULES", "ROOTS", "EXTRA_FILES", "Program",
    "analyze_tree", "analyze_source", "compare_baseline", "counts_of",
    "load_baseline", "write_baseline",
]

DETLINT_RULES = {
    "DT301": "wall-clock read in a deterministic plane without an "
             "injectable escape hatch",
    "DT302": "ambient randomness in a deterministic plane",
    "DT303": "unordered-collection iteration serialized in a "
             "deterministic plane",
    "DT304": "query-time os.environ read in a deterministic plane",
    "DT305": "float accumulation in thread-completion order",
    # never baselined (write_baseline drops it): a module that stops
    # parsing must fail the gate no matter what was grandfathered
    "parse-error": "module failed to parse",
}

# The deterministic-plane entry registry: (repo-relative path,
# qualname). tests/test_static_analysis.py asserts every entry still
# resolves to a real function, so a rename cannot silently disarm the
# pass. Taint propagates transitively to everything these call.
ROOTS: Tuple[Tuple[str, str], ...] = (
    # chaos plane: same-seed fault streams (round 16)
    ("pinot_tpu/utils/faults.py", "FaultPlan.decide"),
    ("pinot_tpu/utils/faults.py", "fault_fires"),
    ("pinot_tpu/utils/faults.py", "corrupt_bytes"),
    # SLO plane: window/burn evaluation + status emission (ISSUE 17)
    ("pinot_tpu/utils/slo.py", "burn_rate"),
    ("pinot_tpu/utils/slo.py", "evaluate_objective"),
    ("pinot_tpu/utils/slo.py", "classify_query"),
    ("pinot_tpu/utils/slo.py", "event_time"),
    ("pinot_tpu/utils/slo.py", "plan_alert_stream"),
    ("pinot_tpu/utils/slo.py", "normalize_alerts"),
    ("pinot_tpu/utils/slo.py", "SloPlane.observe_query"),
    ("pinot_tpu/utils/slo.py", "SloPlane.observe_freshness"),
    ("pinot_tpu/utils/slo.py", "SloPlane._evaluate"),
    ("pinot_tpu/utils/slo.py", "SloPlane.status_block"),
    ("pinot_tpu/utils/slo.py", "SloPlane.emit_status"),
    # alert latches + the fire path (deterministic in the event stream)
    ("pinot_tpu/utils/alerts.py", "RateWindowRule.note"),
    ("pinot_tpu/utils/alerts.py", "LevelRule.check"),
    ("pinot_tpu/utils/alerts.py", "AlertManager.fire"),
    # overload ladder: the deterministic shed plane (ISSUE 12)
    ("pinot_tpu/broker/workload.py", "shed_decision"),
    ("pinot_tpu/broker/workload.py", "retry_after_ms"),
    ("pinot_tpu/broker/workload.py", "tier_shed_rank"),
    ("pinot_tpu/broker/workload.py", "OverloadGovernor.rung_for"),
    # span sampling: pure in (query_id, ratio)
    ("pinot_tpu/utils/spans.py", "sample_decision"),
    # ledger record building (the output contract)
    ("pinot_tpu/utils/ledger.py", "make_record"),
    # the pure replay planner (tools/ — outside the package walk)
    ("tools/traffic_replay.py", "load_records"),
    ("tools/traffic_replay.py", "plan_replay"),
    ("tools/traffic_replay.py", "plan_slo"),
    # closed-loop rebalance planning plane (round 24): the move plan
    # plus its freeze/burn/affinity/budget predicates — execution-side
    # impurity stays in ClosedLoopRebalanceTask, outside the registry
    ("pinot_tpu/cluster/rebalancer.py", "plan_moves"),
    ("pinot_tpu/cluster/rebalancer.py", "incident_frozen"),
    ("pinot_tpu/cluster/rebalancer.py", "burning_tables"),
    ("pinot_tpu/cluster/rebalancer.py", "receiver_affinity"),
    ("pinot_tpu/cluster/rebalancer.py", "churn_capped"),
    # incident autopsy plane (round 25): corpus loading, the window
    # assembler, every cause scorer and both verdict planners — the
    # byte-replayable attribution surface (traffic_replay --autopsy
    # computes each verdict twice and compares bytes). Ledger/ring
    # impurity stays in AutopsyPlane, outside the registry.
    ("pinot_tpu/cluster/autopsy.py", "load_corpus"),
    ("pinot_tpu/cluster/autopsy.py", "assemble_window"),
    ("pinot_tpu/cluster/autopsy.py", "score_compile_storm"),
    ("pinot_tpu/cluster/autopsy.py", "score_tier_thrash"),
    ("pinot_tpu/cluster/autopsy.py", "score_overload_shed"),
    ("pinot_tpu/cluster/autopsy.py", "score_rebalance_churn"),
    ("pinot_tpu/cluster/autopsy.py", "score_chaos_faults"),
    ("pinot_tpu/cluster/autopsy.py", "score_straggler"),
    ("pinot_tpu/cluster/autopsy.py", "score_drift_recompile"),
    ("pinot_tpu/cluster/autopsy.py", "score_ingest_stall"),
    ("pinot_tpu/cluster/autopsy.py", "plan_autopsy"),
    ("pinot_tpu/cluster/autopsy.py", "whydown"),
)

# tools/ modules named by the registry ride along with the package walk
EXTRA_FILES: Tuple[str, ...] = ("tools/traffic_replay.py",)

_ENTRY_RE = re.compile(r"detlint:\s*(entrypoint)")

# -- DT301 matchers ---------------------------------------------------------
_CLOCK_DOTTED = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.thread_time",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow", "datetime.date.today", "date.today",
}
# wall-clock only when called with NO args (with an arg they are pure
# epoch->struct_time conversions)
_CLOCK_NOARG = {"time.gmtime", "time.localtime"}

# -- DT302 matchers ---------------------------------------------------------
# seeded constructors: deterministic, never ambient
_RNG_SEEDED_CTORS = {"Random", "default_rng", "RandomState", "seed"}
_RNG_MODULES = ("random.", "np.random.", "numpy.random.", "secrets.")
_RNG_BARE = {"uuid4", "uuid1", "urandom", "getrandbits", "token_hex",
             "token_bytes"}
_RNG_DOTTED = {"uuid.uuid4", "uuid.uuid1", "os.urandom"}

# -- DT303 matchers ---------------------------------------------------------
_FS_UNORDERED = {"os.listdir", "glob.glob", "glob.iglob"}
_SERIALIZERS = {"list", "tuple"}   # list(set(...)), tuple(set(...))

# -- DT305 matchers ---------------------------------------------------------
_UNORDERED_POOLS_BARE = {"as_completed"}
_UNORDERED_POOLS_ATTR = {"as_completed", "imap_unordered"}


def _is_set_expr(node: ast.AST) -> Optional[str]:
    """Display name when ``node`` is a syntactic unordered collection."""
    if isinstance(node, ast.Set):
        return "set literal"
    if isinstance(node, ast.SetComp):
        return "set comprehension"
    if isinstance(node, ast.Call):
        _b, name = call_parts(node.func)
        if name in ("set", "frozenset"):
            return f"{name}()"
    return None


def _has_pool_iter(node: ast.AST) -> Optional[str]:
    """Display name when the subtree iterates an unordered pool."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            base, name = call_parts(n.func)
            if base is None and name in _UNORDERED_POOLS_BARE:
                return f"{name}()"
            if isinstance(n.func, ast.Attribute) and \
                    n.func.attr in _UNORDERED_POOLS_ATTR:
                return f".{n.func.attr}()"
    return None


def _int_constant(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, int) and \
            not isinstance(node.value, bool)
    if isinstance(node, ast.UnaryOp):
        return _int_constant(node.operand)
    return False


# ---------------------------------------------------------------------------
# per-module / per-function model
# ---------------------------------------------------------------------------

@dataclass
class _ModuleInfo:
    path: str                      # repo-relative, posix
    tree: ast.AST
    suppress: Dict[int, Set[str]]
    entry_lines: Set[int]          # detlint: entrypoint comment lines
    classes: Dict[str, ast.ClassDef] = field(default_factory=dict)
    functions: Dict[str, ast.AST] = field(default_factory=dict)
    singletons: Dict[str, str] = field(default_factory=dict)
    import_mods: Dict[str, str] = field(default_factory=dict)
    import_syms: Dict[str, Tuple[str, str]] = field(default_factory=dict)

    @property
    def qual(self) -> str:
        return module_qual(self.path)


@dataclass
class _FnInfo:
    fid: str
    qualname: str
    path: str
    module: _ModuleInfo
    cls_name: Optional[str]
    node: ast.AST
    is_entry: bool = False
    # (display, line, escaped)
    clocks: List[Tuple[str, int, bool]] = field(default_factory=list)
    rngs: List[Tuple[str, int]] = field(default_factory=list)
    unordered: List[Tuple[str, int]] = field(default_factory=list)
    envs: List[Tuple[str, int]] = field(default_factory=list)
    facc: List[Tuple[str, int]] = field(default_factory=list)
    calls: List[Tuple[str, Optional[str], str, int]] = \
        field(default_factory=list)   # (kind, base, name, line)


# ---------------------------------------------------------------------------
# the event walker
# ---------------------------------------------------------------------------

class _FnWalker:
    """Walks one function body collecting determinism events, tracking
    the escape-hatch context for clock reads (module docstring)."""

    def __init__(self, info: _FnInfo):
        self.info = info
        self.guards = self._guard_names(info.node)

    # -- escape-hatch analysis ---------------------------------------------
    @staticmethod
    def _none_default_params(fn: ast.AST) -> Set[str]:
        names: Set[str] = set()
        args = fn.args
        pos = list(getattr(args, "posonlyargs", [])) + list(args.args)
        for a, d in zip(pos[len(pos) - len(args.defaults):],
                        args.defaults):
            if isinstance(d, ast.Constant) and d.value is None:
                names.add(a.arg)
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None and isinstance(d, ast.Constant) \
                    and d.value is None:
                names.add(a.arg)
        return names

    def _guard_names(self, fn: ast.AST) -> Set[str]:
        """None-default parameters (of the function and its nested
        defs) plus locals derived from them: the names whose ``is
        None`` fallback branch is the injectable-clock idiom."""
        names: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                names |= self._none_default_params(n)
        # derived locals to a fixpoint: t = now if ... else event_time()
        changed = True
        while changed:
            changed = False
            for n in ast.walk(fn):
                if not isinstance(n, ast.Assign) or n.value is None:
                    continue
                refs = {x.id for x in ast.walk(n.value)
                        if isinstance(x, ast.Name)}
                if not (refs & names):
                    continue
                for t in n.targets:
                    if isinstance(t, ast.Name) and t.id not in names:
                        names.add(t.id)
                        changed = True
        return names

    def _test_guards(self, test: ast.AST) -> bool:
        """True when the test contains ``<guard> is None`` /
        ``is not None`` — the governed branches are the escape
        hatch."""
        for n in ast.walk(test):
            if isinstance(n, ast.Compare) and \
                    any(isinstance(op, (ast.Is, ast.IsNot))
                        for op in n.ops):
                sides = [n.left] + list(n.comparators)
                names = {s.id for s in sides if isinstance(s, ast.Name)}
                has_none = any(isinstance(s, ast.Constant)
                               and s.value is None for s in sides)
                if has_none and (names & self.guards):
                    return True
        return False

    @staticmethod
    def _refs_guard(node: ast.AST, guards: Set[str]) -> bool:
        return any(isinstance(x, ast.Name) and x.id in guards
                   for x in ast.walk(node))

    # -- walk --------------------------------------------------------------
    def walk(self) -> None:
        for stmt in getattr(self.info.node, "body", []):
            self._scan(stmt, esc=False, in_sorted=False)

    def _scan(self, node: ast.AST, esc: bool, in_sorted: bool) -> None:
        if isinstance(node, ast.If) and self._test_guards(node.test):
            self._scan(node.test, esc, in_sorted)
            for child in node.body + node.orelse:
                self._scan(child, True, in_sorted)
            return
        if isinstance(node, ast.IfExp) and self._test_guards(node.test):
            self._scan(node.test, esc, in_sorted)
            self._scan(node.body, True, in_sorted)
            self._scan(node.orelse, True, in_sorted)
            return
        if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or) \
                and node.values:
            # ``now or time.monotonic()``: the fallback operands are
            # governed by the guard's truthiness
            first_guards = self._refs_guard(node.values[0], self.guards)
            self._scan(node.values[0], esc, in_sorted)
            for v in node.values[1:]:
                self._scan(v, esc or first_guards, in_sorted)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._for_loop(node, esc, in_sorted)
            for child in ast.iter_child_nodes(node):
                self._scan(child, esc, in_sorted)
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            for gen in node.generators:
                disp = _is_set_expr(gen.iter)
                if disp is not None and not in_sorted:
                    self.info.unordered.append(
                        (f"comprehension over {disp}",
                         node.lineno))
        if isinstance(node, ast.Call):
            self._call(node, esc, in_sorted)
            _b, name = call_parts(node.func)
            arg_sorted = in_sorted or name == "sorted"
            for child in ast.iter_child_nodes(node):
                self._scan(child, esc, arg_sorted)
            return
        if isinstance(node, ast.Attribute):
            dotted = dotted_name(node)
            if dotted == "os.environ":
                self.info.envs.append(("os.environ", node.lineno))
        for child in ast.iter_child_nodes(node):
            self._scan(child, esc, in_sorted)

    def _for_loop(self, node: ast.AST, esc: bool,
                  in_sorted: bool) -> None:
        disp = _is_set_expr(node.iter)
        if disp is not None and not in_sorted:
            self.info.unordered.append(
                (f"iteration over {disp}", node.lineno))
        # DT305: float accumulation over unordered pool completion
        pool = _has_pool_iter(node.iter)
        if pool is not None:
            for n in ast.walk(node):
                if isinstance(n, ast.AugAssign) and \
                        isinstance(n.op, ast.Add) and \
                        not _int_constant(n.value):
                    self.info.facc.append(
                        (f"+= over {pool} results", n.lineno))

    def _call(self, node: ast.Call, esc: bool, in_sorted: bool) -> None:
        base, name = call_parts(node.func)
        dotted = dotted_name(node.func)
        # DT301 clocks
        if dotted in _CLOCK_DOTTED or \
                (dotted in _CLOCK_NOARG and not node.args):
            self.info.clocks.append((f"{dotted}()", node.lineno, esc))
        # DT302 ambient randomness
        rng = self._rng_display(node, base, name, dotted)
        if rng is not None:
            self.info.rngs.append((rng, node.lineno))
        # DT303 unsorted filesystem enumeration
        if dotted in _FS_UNORDERED and not in_sorted:
            self.info.unordered.append((f"unsorted {dotted}()",
                                        node.lineno))
        # DT303 set serialized through join/list/tuple
        ser = None
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join" and node.args:
            ser = ("join", node.args[0])
        elif base is None and name in _SERIALIZERS and node.args:
            ser = (name, node.args[0])
        if ser is not None and not in_sorted:
            disp = _is_set_expr(ser[1])
            if disp is not None:
                self.info.unordered.append(
                    (f"{ser[0]}() over {disp}", node.lineno))
        # DT304 env reads via os.getenv (os.environ handled on the
        # Attribute node so subscripts and .get both count)
        if dotted == "os.getenv":
            self.info.envs.append(("os.getenv()", node.lineno))
        # DT305 sum() over an unordered-pool generator
        if base is None and name == "sum" and node.args:
            pool = _has_pool_iter(node.args[0])
            if pool is not None:
                self.info.facc.append(
                    (f"sum() over {pool} results", node.lineno))
        # resolution hints for the call graph (concur's vocabulary)
        if name is not None:
            if isinstance(node.func, ast.Attribute):
                if base == "self":
                    self.info.calls.append(
                        ("self", None, name, node.lineno))
                elif base is not None:
                    self.info.calls.append(
                        ("attr", base, name, node.lineno))
            else:
                self.info.calls.append(
                    ("bare", None, name, node.lineno))

    @staticmethod
    def _rng_display(node: ast.Call, base: Optional[str],
                     name: Optional[str],
                     dotted: Optional[str]) -> Optional[str]:
        if dotted is None:
            return None
        if dotted in _RNG_DOTTED:
            return f"{dotted}()"
        if base is None and name in _RNG_BARE:
            return f"{name}()"
        if base is None and name == "hash" and node.args:
            return "builtin hash() (PYTHONHASHSEED-dependent)"
        for prefix in _RNG_MODULES:
            if dotted.startswith(prefix):
                tail = dotted[len(prefix):]
                if tail in _RNG_SEEDED_CTORS and node.args:
                    return None   # seeded: deterministic by contract
                return f"{dotted}()"
        return None


# ---------------------------------------------------------------------------
# the program
# ---------------------------------------------------------------------------

class Program:
    """Whole-program analysis context: feed modules with
    ``add_source``/``add_tree``, then ``analyze()`` -> (findings,
    suppressed). ``extra_roots`` extends the registry (tests)."""

    def __init__(self, extra_roots: Tuple[Tuple[str, str], ...] = ()):
        self.modules: Dict[str, _ModuleInfo] = {}
        self.findings: List[Finding] = []
        self.suppressed: List[Finding] = []
        self.extra_roots = tuple(extra_roots)
        # registry entries that resolved / didn't (corpus test surface)
        self.roots_matched: List[Tuple[str, str]] = []
        self.roots_missing: List[Tuple[str, str]] = []

    # -- loading -----------------------------------------------------------
    def add_source(self, src: str, path: str) -> None:
        path = path.replace(os.sep, "/")
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            self.findings.append(Finding(
                "parse-error", path, e.lineno or 0, "<module>",
                f"unparseable: {e.msg}"))
            return
        mod = _ModuleInfo(
            path, tree, parse_suppressions(src, "detlint"),
            set(line_comments(src, _ENTRY_RE)))
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                mod.classes[node.name] = node
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                mod.functions[node.name] = node
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                _b, ctor = call_parts(node.value.func)
                if ctor and ctor[:1].isupper():
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            mod.singletons[t.id] = ctor
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname is None:
                        continue   # "import x.y" binds only "x"
                    q = a.name
                    if q.startswith("pinot_tpu."):
                        q = q[len("pinot_tpu."):]
                    elif q == "pinot_tpu":
                        continue
                    mod.import_mods[a.asname] = q
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    parts = module_qual(path).split(".")[:-1]
                    if node.level > 1:
                        parts = parts[:len(parts) - (node.level - 1)]
                    if node.module:
                        parts = parts + node.module.split(".")
                    base = ".".join(parts)
                else:
                    base = node.module or ""
                    if base.startswith("pinot_tpu."):
                        base = base[len("pinot_tpu."):]
                    elif base == "pinot_tpu":
                        base = ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    mod.import_syms[a.asname or a.name] = (base, a.name)
        self.modules[path] = mod

    def add_tree(self, root: str, package: str = "pinot_tpu",
                 extra_files: Tuple[str, ...] = EXTRA_FILES) -> None:
        for full, rel in iter_py_files(root, package, extra_files):
            with open(full, "r", encoding="utf-8") as fh:
                self.add_source(fh.read(), rel)

    # -- analysis ----------------------------------------------------------
    def analyze(self) -> Tuple[List[Finding], List[Finding]]:
        fns = self._walk_all()
        self._build_indexes(fns)
        det = self._reach(fns)
        for fi in fns:
            plane = det.get(fi.fid)
            if plane is not None:
                self._rules_in_plane(fi, plane)
            for disp, line in fi.facc:
                self._emit(
                    "DT305", fi.path, line, fi.qualname,
                    f"{disp}: thread-completion order re-associates "
                    f"the floating-point sum, so same-input runs "
                    f"disagree in the last ulp — accumulate in "
                    f"submission order (iterate the futures list, "
                    f"not as_completed)")
        order = {r: i for i, r in enumerate(DETLINT_RULES)}
        self.findings.sort(
            key=lambda f: (f.path, f.line, order.get(f.rule, 99)))
        return self.findings, self.suppressed

    def _walk_all(self) -> List[_FnInfo]:
        fns: List[_FnInfo] = []

        def load(mod: _ModuleInfo, qualname: str,
                 cls_name: Optional[str], node: ast.AST) -> None:
            fi = _FnInfo(f"{mod.path}::{qualname}", qualname, mod.path,
                         mod, cls_name, node,
                         is_entry=node.lineno in mod.entry_lines)
            _FnWalker(fi).walk()
            fns.append(fi)

        for mod in self.modules.values():
            for cname, cnode in mod.classes.items():
                for stmt in cnode.body:
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        load(mod, f"{cname}.{stmt.name}", cname, stmt)
            for name, fnode in mod.functions.items():
                load(mod, name, None, fnode)
        return fns

    def _build_indexes(self, fns: List[_FnInfo]) -> None:
        self._by_fid = {fi.fid: fi for fi in fns}
        self._qual_path = {m.qual: p for p, m in self.modules.items()}
        self._resolver = CallResolver()
        for path, m in self.modules.items():
            self._resolver.add_module(path, m.functions.keys(),
                                      m.classes.keys(), m.singletons)
        for fi in fns:
            if fi.cls_name is not None:
                self._resolver.add_function(
                    fi.fid, fi.path, fi.cls_name,
                    fi.qualname.split(".", 1)[1])
        self._resolver.finalize()

    # -- resolution: shared resolver + import-alias follow-through ---------
    def _sym_target(self, mod: _ModuleInfo, alias: str
                    ) -> Optional[Tuple[str, str, Optional[str]]]:
        """-> ("mod", path, None) | ("sym", path, name) | None for an
        imported alias in ``mod``."""
        q = mod.import_mods.get(alias)
        if q is not None:
            p = self._qual_path.get(q)
            return ("mod", p, None) if p else None
        t = mod.import_syms.get(alias)
        if t is None:
            return None
        base, name = t
        p = self._qual_path.get(f"{base}.{name}" if base else name)
        if p is not None:
            return ("mod", p, None)   # "from . import ledger" style
        p = self._qual_path.get(base)
        if p is not None:
            return ("sym", p, name)
        return None

    def _resolve(self, fi: _FnInfo, kind: str, base: Optional[str],
                 name: str) -> Optional[str]:
        fid = self._resolver.resolve(fi.path, fi.cls_name, kind,
                                     base, name)
        if fid is not None:
            return fid
        mod = fi.module
        if kind == "bare":
            t = self._sym_target(mod, name)
            if t is not None and t[0] == "sym":
                _k, p, sym = t
                if sym in self.modules[p].functions:
                    return f"{p}::{sym}"
            return None
        if kind == "attr" and base is not None:
            # Cls.method(...) on a locally-defined or imported class
            cls_path = cls_name = None
            if base in mod.classes:
                cls_path, cls_name = fi.path, base
            else:
                t = self._sym_target(mod, base)
                if t is not None and t[0] == "sym" and \
                        t[2] in self.modules[t[1]].classes:
                    cls_path, cls_name = t[1], t[2]
                elif t is not None and t[0] == "mod":
                    if name in self.modules[t[1]].functions:
                        return f"{t[1]}::{name}"
            if cls_path is not None:
                return self._resolver.class_method(cls_path, cls_name,
                                                   name)
        return None

    # -- forward reachability from the registry ----------------------------
    def _reach(self, fns: List[_FnInfo]
               ) -> Dict[str, Tuple[str, Optional[str]]]:
        """fid -> (root display, immediate caller qualname or None)."""
        self.roots_matched, self.roots_missing = [], []
        det: Dict[str, Tuple[str, Optional[str]]] = {}
        queue: deque = deque()

        def seed(fid: str, display: str) -> None:
            if fid not in det:
                det[fid] = (display, None)
                queue.append(fid)

        for path, qualname in tuple(ROOTS) + self.extra_roots:
            fid = f"{path}::{qualname}"
            if fid in self._by_fid:
                self.roots_matched.append((path, qualname))
                seed(fid, f"{module_qual(path)}.{qualname}")
            elif path in self.modules:
                # the module is in the corpus but the function is gone:
                # the registry entry is stale (corpus test asserts
                # roots_missing == [])
                self.roots_missing.append((path, qualname))
        for fi in fns:
            if fi.is_entry:
                seed(fi.fid, f"{fi.module.qual}.{fi.qualname}")
        while queue:
            fid = queue.popleft()
            fi = self._by_fid[fid]
            root, _via = det[fid]
            for kind, base, name, _line in fi.calls:
                callee = self._resolve(fi, kind, base, name)
                if callee is not None and callee not in det and \
                        callee in self._by_fid:
                    det[callee] = (root, fi.qualname)
                    queue.append(callee)
        return det

    # -- emission ----------------------------------------------------------
    def _emit(self, rule: str, path: str, line: int, scope: str,
              message: str) -> None:
        mod = self.modules.get(path)
        sup = mod.suppress.get(line, set()) if mod else set()
        f = Finding(rule, path, line, scope, message)
        if rule in sup or "all" in sup:
            self.suppressed.append(f)
        else:
            self.findings.append(f)

    def _rules_in_plane(self, fi: _FnInfo,
                        plane: Tuple[str, Optional[str]]) -> None:
        root, via = plane
        where = f"entry point {root}" + \
            (f" via {via}" if via and via != fi.qualname else "")
        for disp, line, escaped in fi.clocks:
            if escaped:
                continue
            self._emit(
                "DT301", fi.path, line, fi.qualname,
                f"{disp} read on a deterministic-plane path "
                f"({where}) with no injectable now=/ts= escape "
                f"hatch: wall clock leaks into replayable decisions")
        for disp, line in fi.rngs:
            self._emit(
                "DT302", fi.path, line, fi.qualname,
                f"{disp}: ambient randomness on a deterministic-plane "
                f"path ({where}); draw deterministically from hashlib "
                f"over (seed, site, key) instead")
        for disp, line in fi.unordered:
            self._emit(
                "DT303", fi.path, line, fi.qualname,
                f"{disp} on a deterministic-plane path ({where}): "
                f"iteration order leaks into the output contract — "
                f"wrap in sorted(...)")
        for disp, line in fi.envs:
            self._emit(
                "DT304", fi.path, line, fi.qualname,
                f"{disp} read at query time on a deterministic-plane "
                f"path ({where}); parse once at startup (the "
                f"PINOT_DRIFT_RATIO precedent)")


# ---------------------------------------------------------------------------
# conveniences + baseline
# ---------------------------------------------------------------------------

def analyze_source(src: str, path: str,
                   extra_roots: Tuple[Tuple[str, str], ...] = ()
                   ) -> Tuple[List[Finding], List[Finding]]:
    """Single-module analysis (tests). Whole-program resolution still
    runs — over a corpus of one module."""
    prog = Program(extra_roots=extra_roots)
    prog.add_source(src, path)
    return prog.analyze()


def analyze_tree(root: str, package: str = "pinot_tpu"
                 ) -> Tuple[List[Finding], List[Finding]]:
    prog = Program()
    prog.add_tree(root, package)
    return prog.analyze()


def write_baseline(findings, path: str) -> None:
    from .astutil import write_baseline as _wb
    _wb(findings, path, comment=(
        "detlint ratchet baseline — grandfathered DT findings per "
        "file::scope::rule, each a vetted true-but-benign site. "
        "make_record::DT301: the time.gmtime() ts default is the "
        "documented live-mode fallback; deterministic emitters inject "
        "ts= through **fields (plan_alert_stream pins ts_fn), an "
        "escape hatch the structural is-None analysis cannot see "
        "through kwargs. Regenerate with `python tools/check_static.py "
        "--detlint-only --update-baseline`; new findings above these "
        "counts fail check_static, and counts that drop must be "
        "ratcheted down here."))
