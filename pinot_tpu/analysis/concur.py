"""Whole-program concurrency verifier: CC201–CC205 over the threaded
cluster plane.

The engine is deeply threaded — MicroBatchQueue leader/follower fusion,
scatter-gather pools with hedging, ingest ``_consume_loop`` threads, the
ForensicsRollupTask, five HBM-adjacent caches — and nearly every round
since 8 shipped a hand- or chaos-found race. Chaos soaks catch these
probabilistically; this pass makes thread-safety a tier-1 gate. It is
jaxlint's sibling (AST-based, stable rule ids, ratchet baseline at
``tools/concur_baseline.json``) but whole-program: guard maps, call
graphs and the lock-order graph are built across every module of
``pinot_tpu/`` before any rule fires.

Rules:

- **CC201 mixed-guard** — per class, infer each attribute's guard from
  the locks held at its mutation sites (``with self._lock:`` blocks,
  ``# holds-lock:`` methods). An attribute mutated BOTH under its
  inferred guard and outside it races: the unguarded sites are flagged.
  ``__init__`` is exempt (construction precedes sharing).
- **CC202 blocking-under-lock** — no HTTP call (``http_json`` /
  ``http_raw`` / ``urlopen`` / ``requests.*``), ``time.sleep``,
  ``Future.result``, zero-arg ``.join()``, subprocess, ``os.fsync`` /
  ``os.replace``, or device sync (``block_until_ready``,
  ``jax.device_get``, ``.item()``, hot-path ``np.asarray``) while a
  lock is held — directly or transitively through calls the resolver
  can follow. The round-11 seal-lock lesson (a flaky controller RPC
  under the table-wide seal lock stalled every partition) as a
  permanent rule.
- **CC203 lock-order-cycle** — the inter-class lock acquisition graph
  (nested ``with``-lock scopes plus calls made under a held lock,
  resolved through same-class methods, same-module functions,
  module-level singletons like ``global_metrics``, and corpus-unique
  method names) must be acyclic. A cycle is a potential deadlock; a
  self-edge on a non-reentrant ``Lock`` reached through an exact
  (same-class) call chain is a guaranteed one.
- **CC204 thread-local-escape** — the thread-local span tracer
  (``utils.spans``), ``Tracing`` request scope (``utils.trace``) and
  the accountant's thread→query attribution may not be captured into
  closures handed to executors/threads (``pool.submit``,
  ``threading.Thread(target=...)``, ``.map``): on the foreign thread
  they silently no-op or attribute to the wrong query. The explicit
  handoff APIs — ``span_tracer.start()/stop()``,
  ``Tracing.register()``, ``accountant.attach_thread()`` / explicit
  ``Span(...)`` construction — are exempt: a closure that performs its
  own handoff first owns its context.
- **CC205 check-then-act** — ``if key not in d: d[key] = ...`` (and
  membership / ``.get()`` / ``is None`` / truthiness checks whose body
  mutates the same attribute) on an attribute whose inferred guard is
  not held at the site. ``dict.setdefault`` is GIL-atomic and not
  flagged.

Annotations (trailing comments):

- ``# guarded-by: <lock>`` on a ``self.X = ...`` line pins X's guard
  explicitly (inference escape hatch — e.g. an attribute only ever
  mutated via exec'd plumbing the AST can't see). ``# guarded-by:
  none`` exempts the attribute from CC201/CC205 (single-thread or
  GIL-atomic by design).
- ``# holds-lock: <lock>`` on a ``def`` line declares a
  caller-holds-lock method: its body is analyzed as if ``self.<lock>``
  were held (utils/heat.SegmentHeat._entry is the canonical site).

Suppression: append ``# concur: ok <rule>`` (comma-separated rules or
``all``) to the offending line. Grandfathered-but-benign findings live
in the ratchet baseline (``tools/concur_baseline.json``), jaxlint
semantics: new findings above a ``file::scope::rule`` count fail
``tools/check_static.py``, and counts that DROP fail too until the
baseline is ratcheted down with ``--update-baseline``.

Known approximations (documented, deliberate): the resolver never
follows inheritance or duck-typed callables (``job.fn()``); ``with
other._lock:`` over a non-``self`` receiver is ignored; two INSTANCES
of one class count as one lock node (a self-edge between instances
reads as a self-deadlock — annotate or suppress); same-named classes
in different modules are kept distinct (guard maps, lock nodes and
self-call resolution are all module-qualified) but the corpus-unique
METHOD-name fallback for attribute calls is global — an ambiguous name
is simply not resolved; ``.wait()`` is never a CC202 blocker because
``Condition.wait`` under its own lock is the correct idiom and the AST
cannot tell conditions from events.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .astutil import (CallResolver, Finding, compare_baseline,
                      counts_of, iter_py_files, line_comments,
                      load_baseline, module_qual, suppress_regex)
from .astutil import call_parts as _call_parts
from .astutil import self_attr as _self_attr

__all__ = [
    "CONCUR_RULES", "Program", "analyze_tree", "analyze_source",
    "compare_baseline", "counts_of", "load_baseline", "write_baseline",
]

CONCUR_RULES = {
    "CC201": "mixed-guard: attribute mutated both under and outside "
             "its inferred lock",
    "CC202": "blocking call while holding a lock",
    "CC203": "lock-order cycle (potential deadlock)",
    "CC204": "thread-local state captured into a cross-thread closure",
    "CC205": "check-then-act on a guarded attribute without its lock",
    # never baselined (write_baseline drops it): a module that stops
    # parsing must fail the gate no matter what was grandfathered
    "parse-error": "module failed to parse",
}

_SUPPRESS_RE = suppress_regex("concur")
_GUARDED_RE = re.compile(r"guarded-by:\s*([\w]+)")
_HOLDS_RE = re.compile(r"holds-lock:\s*([\w,\s]+)")

# -- CC202 matchers ---------------------------------------------------------
_BLOCK_DOTTED = {
    ("time", "sleep"): "time.sleep",
    ("os", "system"): "os.system",
    ("os", "popen"): "os.popen",
    ("os", "fsync"): "os.fsync",
    ("os", "replace"): "os.replace",
    ("subprocess", "run"): "subprocess.run",
    ("subprocess", "call"): "subprocess.call",
    ("subprocess", "check_call"): "subprocess.check_call",
    ("subprocess", "check_output"): "subprocess.check_output",
    ("subprocess", "Popen"): "subprocess.Popen",
    ("socket", "create_connection"): "socket.create_connection",
    ("jax", "block_until_ready"): "jax.block_until_ready",
    ("jax", "device_get"): "jax.device_get",
    ("requests", "get"): "requests.get",
    ("requests", "post"): "requests.post",
    ("requests", "put"): "requests.put",
    ("requests", "delete"): "requests.delete",
    ("requests", "request"): "requests.request",
}
# bare or attribute-tail call names that block wherever they resolve
_BLOCK_NAMES = {
    "http_json": "http_json (HTTP RPC)",
    "http_raw": "http_raw (HTTP RPC)",
    "urlopen": "urlopen (HTTP)",
    "fsync": "os.fsync",
}
_NUMPY_NAMES = ("np", "numpy", "_np")
# host-sync matchers are CC202 blockers only in the device hot packages
# (np.asarray over host data under a registry lock is routine)
_HOT_PACKAGES = ("ops", "engine", "multistage", "parallel")

_MUTATING_METHODS = {"append", "extend", "add", "update", "setdefault",
                     "pop", "popitem", "clear", "remove", "discard",
                     "insert", "move_to_end"}

# -- CC204 vocabulary -------------------------------------------------------
# module-level conveniences of utils.spans — thread-local reads
_TL_BARE_CALLS = {"span", "annotate", "add_event", "tracing_active",
                  "device_fence"}
# receiver -> (thread-local methods are everything EXCEPT the handoffs)
_TL_RECEIVERS = {
    "span_tracer": {"start", "stop"},       # handoff: root your own tree
    "Tracing": {"register", "unregister"},  # handoff: own request scope
}
_TL_ATTR_CALLS = {"current_query_id"}       # accountant thread->query read
_HANDOFF_CALLS = {"start", "register", "attach_thread"}


# ---------------------------------------------------------------------------
# per-module model
# ---------------------------------------------------------------------------

@dataclass
class _ClassInfo:
    name: str
    module: "_ModuleInfo"
    node: ast.ClassDef
    # lock attribute -> kind ("Lock" | "RLock"); Condition aliases are
    # resolved into this map (the condition attr maps to its lock's id)
    locks: Dict[str, str] = field(default_factory=dict)
    aliases: Dict[str, str] = field(default_factory=dict)  # cond -> lock
    guard_ann: Dict[str, Optional[str]] = field(default_factory=dict)
    methods: Dict[str, ast.AST] = field(default_factory=dict)
    odict_attrs: Set[str] = field(default_factory=set)

    def lock_id(self, attr: str) -> Optional[str]:
        attr = self.aliases.get(attr, attr)
        if attr in self.locks:
            return f"{self.module.qual}.{self.name}.{attr}"
        return None


@dataclass
class _ModuleInfo:
    path: str                      # repo-relative, posix
    tree: ast.AST
    lines: List[str]
    suppress: Dict[int, Set[str]]
    classes: Dict[str, _ClassInfo] = field(default_factory=dict)
    mod_locks: Dict[str, str] = field(default_factory=dict)  # name->kind
    functions: Dict[str, ast.AST] = field(default_factory=dict)
    singletons: Dict[str, str] = field(default_factory=dict)  # name->cls
    # module-level mutable containers (dict/list/set/OrderedDict/...):
    # shared state for the CC201/CC205 module-global guard machinery
    mut_globals: Set[str] = field(default_factory=set)
    odict_globals: Set[str] = field(default_factory=set)

    @property
    def stem(self) -> str:
        return os.path.splitext(os.path.basename(self.path))[0]

    @property
    def qual(self) -> str:
        """Collision-free module qualifier ("engine.batch",
        "native.__init__"): bare stems repeat across packages
        (batch.py, __init__.py), and two same-named locks must not
        merge into one graph node."""
        return module_qual(self.path)

    def mod_lock_id(self, name: str) -> Optional[str]:
        if name in self.mod_locks:
            return f"{self.qual}.{name}"
        return None


@dataclass
class _FnInfo:
    """One analyzed function/method: its concurrency events."""
    fid: str                       # path::qualname
    qualname: str
    path: str
    module: _ModuleInfo
    cls: Optional[_ClassInfo]
    node: ast.AST
    holds: FrozenSet[str] = frozenset()
    # events: (data..., line, held-lockids)
    mutations: List[Tuple[str, int, FrozenSet[str], bool]] = \
        field(default_factory=list)
    # locked reads only (an unlocked dirty read is routine; a read
    # under a DIFFERENT lock than the mutation guard is the CC201
    # mixed-guard hazard). All event tuples end with ``nested``: the
    # event sits inside a nested def/lambda, which runs later on
    # whatever thread calls it — caller-holds inference never applies.
    reads: List[Tuple[str, int, FrozenSet[str], bool]] = \
        field(default_factory=list)
    acquires: List[Tuple[str, int, FrozenSet[str], bool]] = \
        field(default_factory=list)
    calls: List[Tuple[str, Optional[str], str, int, FrozenSet[str],
                      bool]] = \
        field(default_factory=list)   # (kind, base, name, line, held)
    blocks: List[Tuple[str, int, FrozenSet[str], bool]] = \
        field(default_factory=list)
    cta: List[Tuple[str, int, FrozenSet[str], bool]] = \
        field(default_factory=list)
    # same events over module-level globals ("<stem>:NAME" ids)
    g_mutations: List[Tuple[str, int, FrozenSet[str], bool]] = \
        field(default_factory=list)
    g_reads: List[Tuple[str, int, FrozenSet[str], bool]] = \
        field(default_factory=list)
    g_cta: List[Tuple[str, int, FrozenSet[str], bool]] = \
        field(default_factory=list)
    # non-GIL-atomic OrderedDict LRU ops (move_to_end/popitem):
    # (display name, is-global, line, held, nested)
    lru_ops: List[Tuple[str, bool, int, FrozenSet[str], bool]] = \
        field(default_factory=list)
    escapes: List[Tuple[int, str]] = field(default_factory=list)
    # summaries (filled by fixpoint)
    locks_any: Set[str] = field(default_factory=set)
    blocking_reason: Optional[str] = None
    # locks SOMETIMES held when this function runs (union over call
    # sites): guard *evidence* — a mutation inside a helper that one
    # caller locks is lock-guarded state, even when another caller
    # (the defect) doesn't lock
    holds_union: FrozenSet[str] = frozenset()


def _is_lock_ctor(value: ast.AST) -> Optional[str]:
    """'Lock' | 'RLock' | 'Condition' when value constructs one."""
    if isinstance(value, ast.Call):
        _b, a = _call_parts(value.func)
        if a in ("Lock", "RLock", "Condition"):
            return a
    return None


_CONTAINER_CTORS = {"dict", "list", "set", "OrderedDict",
                    "defaultdict", "deque", "Counter"}


def _container_ctor(value: ast.AST) -> Optional[str]:
    """Ctor name when ``value`` builds a mutable container (literal or
    dict()/OrderedDict()/... call), else None."""
    if isinstance(value, (ast.Dict, ast.List, ast.Set)):
        return "dict" if isinstance(value, ast.Dict) else "list"
    if isinstance(value, ast.Call):
        _b, name = _call_parts(value.func)
        if name in _CONTAINER_CTORS:
            return name
    return None


_line_comments = line_comments


# ---------------------------------------------------------------------------
# the event walker
# ---------------------------------------------------------------------------

class _FnWalker:
    """Walks one function body tracking the set of held locks, emitting
    mutation / acquire / call / blocking / check-then-act events and the
    CC204 closure-escape findings."""

    def __init__(self, prog: "Program", info: _FnInfo):
        self.prog = prog
        self.info = info
        self.mod = info.module
        self.cls = info.cls
        self.hot = info.path.startswith(
            tuple(f"pinot_tpu/{p}/" for p in _HOT_PACKAGES))
        # nested defs/lambdas by name (for CC204 submit-target lookup)
        self.nested: Dict[str, ast.AST] = {}

    # -- lock recognition --------------------------------------------------
    def _with_lock_id(self, ctx: ast.AST) -> Optional[str]:
        a = _self_attr(ctx)
        if a is None and isinstance(ctx, ast.Call):
            a = _self_attr(ctx.func)          # with self._lock() style
        if a is not None and self.cls is not None:
            return self.cls.lock_id(a)
        if isinstance(ctx, ast.Name):
            return self.mod.mod_lock_id(ctx.id)
        return None

    # -- walk --------------------------------------------------------------
    def walk(self) -> None:
        body = getattr(self.info.node, "body", [])
        for stmt in body:
            self._scan(stmt, self.info.holds, nested=False)
        self._scan_escapes(self.info.node)

    def _scan(self, node: ast.AST, held: FrozenSet[str],
              nested: bool) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # items acquire LEFT TO RIGHT: `with a, b:` holds a while
            # acquiring b, exactly like the nested spelling — the held
            # set accumulates per item so the a->b lock-order edge (and
            # blocking in later context expressions) is recorded
            inner = held
            for item in node.items:
                self._scan(item.context_expr, inner, nested)
                lid = self._with_lock_id(item.context_expr)
                if lid is not None:
                    self.info.acquires.append(
                        (lid, node.lineno, inner, nested))
                    inner = inner.union((lid,))
            for stmt in node.body:
                self._scan(stmt, inner, nested)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.nested[node.name] = node
            # a nested def runs later, on whatever thread calls it: its
            # body is analyzed lock-free (CC201 sites in it are real —
            # the closure does not inherit the enclosing critical
            # section's exclusion)
            for stmt in node.body:
                self._scan(stmt, frozenset(), True)
            return
        if isinstance(node, ast.Lambda):
            self._scan(node.body, frozenset(), True)
            return
        if isinstance(node, ast.If):
            self._check_then_act(node, held, nested)
            for child in ast.iter_child_nodes(node):
                self._scan(child, held, nested)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                self._mutation_target(t, node.lineno, held, nested)
            for child in ast.iter_child_nodes(node):
                self._scan(child, held, nested)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    a = _self_attr(t.value)
                    if a is not None:
                        self.info.mutations.append(
                            (a, node.lineno, held, nested))
                    elif isinstance(t.value, ast.Name) and \
                            t.value.id in self.mod.mut_globals:
                        self.info.g_mutations.append(
                            (t.value.id, node.lineno, held, nested))
            for child in ast.iter_child_nodes(node):
                self._scan(child, held, nested)
            return
        if isinstance(node, ast.Call):
            self._call(node, held, nested)
            for child in ast.iter_child_nodes(node):
                self._scan(child, held, nested)
            return
        if isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Load) and held:
            a = _self_attr(node)
            if a is not None and (self.cls is None
                                  or self.cls.lock_id(a) is None):
                self.info.reads.append((a, node.lineno, held, nested))
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load) and held and \
                node.id in self.mod.mut_globals:
            self.info.g_reads.append(
                (node.id, node.lineno, held, nested))
        for child in ast.iter_child_nodes(node):
            self._scan(child, held, nested)

    def _mutation_target(self, t: ast.AST, line: int,
                         held: FrozenSet[str], nested: bool) -> None:
        a = _self_attr(t)
        if a is None and isinstance(t, ast.Subscript):
            a = _self_attr(t.value)
            if a is None and isinstance(t.value, ast.Name) and \
                    t.value.id in self.mod.mut_globals:
                self.info.g_mutations.append(
                    (t.value.id, line, held, nested))
        if a is None and isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._mutation_target(el, line, held, nested)
            return
        if a is not None:
            self.info.mutations.append((a, line, held, nested))

    # -- calls: mutations via methods, blocking, resolution hints ----------
    def _call(self, node: ast.Call, held: FrozenSet[str],
              nested: bool) -> None:
        func = node.func
        base, name = _call_parts(func)
        # self.attr.append(...) / GLOBAL.append(...) style mutations
        if isinstance(func, ast.Attribute) and \
                func.attr in _MUTATING_METHODS:
            a = _self_attr(func.value)
            if a is not None:
                self.info.mutations.append(
                    (a, node.lineno, held, nested))
                if func.attr in ("move_to_end", "popitem") and \
                        self.cls is not None and \
                        a in self.cls.odict_attrs:
                    self.info.lru_ops.append(
                        (f"self.{a}", False, node.lineno, held,
                         nested))
            elif isinstance(func.value, ast.Name) and \
                    func.value.id in self.mod.mut_globals:
                g = func.value.id
                self.info.g_mutations.append(
                    (g, node.lineno, held, nested))
                if func.attr in ("move_to_end", "popitem") and \
                        g in self.mod.odict_globals:
                    self.info.lru_ops.append(
                        (g, True, node.lineno, held, nested))
        # direct blocking matches
        reason = self._blocking_reason(node, base, name)
        if reason is not None:
            self.info.blocks.append((reason, node.lineno, held, nested))
        # resolution hints for the call graph
        if name is not None:
            if isinstance(func, ast.Attribute):
                if base == "self":
                    self.info.calls.append(
                        ("self", None, name, node.lineno, held, nested))
                elif base is not None:
                    self.info.calls.append(
                        ("attr", base, name, node.lineno, held, nested))
            else:
                self.info.calls.append(
                    ("bare", None, name, node.lineno, held, nested))

    def _blocking_reason(self, node: ast.Call, base: Optional[str],
                         name: Optional[str]) -> Optional[str]:
        if base is not None and (base, name) in _BLOCK_DOTTED:
            return _BLOCK_DOTTED[(base, name)]
        if name in _BLOCK_NAMES:
            return _BLOCK_NAMES[name]
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr == "block_until_ready":
                return ".block_until_ready() device sync"
            if attr == "result":
                return "Future.result() wait"
            if attr == "join" and not node.args and not node.keywords:
                return ".join() thread wait"
            if attr == "item" and not node.args and self.hot:
                return ".item() device sync"
            if attr in ("asarray", "array") and base in _NUMPY_NAMES \
                    and self.hot:
                return f"{base}.{attr}() device transfer"
        return None

    # -- CC205 -------------------------------------------------------------
    def _state_name(self, node: ast.AST) -> Optional[Tuple[str, bool]]:
        """(name, is_global) when ``node`` denotes shared state: a
        self attribute or a module-level mutable container."""
        a = _self_attr(node)
        if a is not None:
            return a, False
        if isinstance(node, ast.Name) and \
                node.id in self.mod.mut_globals:
            return node.id, True
        return None

    def _test_reads(self, test: ast.AST) -> Set[Tuple[str, bool]]:
        """Shared-state names (self attributes / module globals) the
        if-test examines in a check-then-act-prone way (membership,
        .get, is-None, truthiness)."""
        reads: Set[Tuple[str, bool]] = set()

        def note(node: ast.AST) -> None:
            s = self._state_name(node)
            if s is not None:
                reads.add(s)

        for n in ast.walk(test):
            if isinstance(n, ast.Compare):
                if any(isinstance(op, (ast.In, ast.NotIn))
                       for op in n.ops):
                    for e in n.comparators:
                        note(e)
                if any(isinstance(op, (ast.Is, ast.IsNot))
                       for op in n.ops):
                    note(n.left)
                    if isinstance(n.left, ast.Subscript):
                        note(n.left.value)
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "get":
                note(n.func.value)
            elif isinstance(n, ast.UnaryOp) and \
                    isinstance(n.op, ast.Not):
                note(n.operand)
        note(test)
        return reads

    def _check_then_act(self, node: ast.If, held: FrozenSet[str],
                        nested: bool) -> None:
        reads = self._test_reads(node.test)
        if not reads:
            return
        muts: Set[Tuple[str, bool]] = set()

        def note(t: ast.AST) -> None:
            s = self._state_name(t)
            if s is None and isinstance(t, ast.Subscript):
                s = self._state_name(t.value)
            if s is not None:
                muts.add(s)

        def scan(n: ast.AST) -> None:
            # prune nested defs/lambdas: their mutations run later, on
            # another thread, usually under their own locking — they
            # are not part of THIS check-then-act window (ast.walk
            # cannot prune, so recurse manually)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                tgts = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in tgts:
                    note(t)
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in (_MUTATING_METHODS -
                                    {"setdefault"}):
                note(n.func.value)
            for child in ast.iter_child_nodes(n):
                scan(child)

        for stmt in node.body:
            scan(stmt)
        for name, is_glob in sorted(reads & muts):
            if is_glob:
                self.info.g_cta.append(
                    (name, node.lineno, held, nested))
            else:
                self.info.cta.append((name, node.lineno, held, nested))

    # -- CC204 -------------------------------------------------------------
    def _scan_escapes(self, fn: ast.AST) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            target = self._submit_target(node)
            if target is None:
                continue
            tl = self._thread_local_uses(target)
            if tl:
                self.info.escapes.append((node.lineno, tl[0]))

    def _submit_target(self, node: ast.Call) -> Optional[ast.AST]:
        base, name = _call_parts(node.func)
        cand: Optional[ast.AST] = None
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("submit", "map", "apply_async") \
                and node.args:
            cand = node.args[0]
        elif name == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    cand = kw.value
        if cand is None:
            return None
        if isinstance(cand, ast.Call):   # functools.partial(f, ...)
            _b, n2 = _call_parts(cand.func)
            if n2 == "partial" and cand.args:
                cand = cand.args[0]
        if isinstance(cand, ast.Lambda):
            return cand
        if isinstance(cand, ast.Name) and cand.id in self.nested:
            return self.nested[cand.id]
        return None

    def _thread_local_uses(self, target: ast.AST) -> List[str]:
        uses: List[str] = []
        handed_off = False
        for n in ast.walk(target):
            if not isinstance(n, ast.Call):
                continue
            base, name = _call_parts(n.func)
            # handoff must be the real API: span_tracer.start(),
            # Tracing.register(), or any-receiver attach_thread() — a
            # bare call to some unrelated start()/register() helper is
            # no handoff and must not silence the rule
            if (base == "span_tracer" and name == "start") or \
                    (base == "Tracing" and name == "register") or \
                    name == "attach_thread":
                handed_off = True
            if base is None and name in _TL_BARE_CALLS:
                uses.append(f"{name}()")
            elif base in _TL_RECEIVERS and \
                    name not in _TL_RECEIVERS[base]:
                uses.append(f"{base}.{name}()")
            elif name in _TL_ATTR_CALLS:
                uses.append(f"{name}()")
        return [] if handed_off else uses


# ---------------------------------------------------------------------------
# the program
# ---------------------------------------------------------------------------

class Program:
    """Whole-program analysis context: feed modules with
    ``add_source``/``add_tree``, then ``analyze()`` -> (findings,
    suppressed). Findings carry jaxlint-compatible keys for the ratchet
    baseline."""

    def __init__(self):
        self.modules: Dict[str, _ModuleInfo] = {}
        self.findings: List[Finding] = []
        self.suppressed: List[Finding] = []

    # -- loading -----------------------------------------------------------
    def add_source(self, src: str, path: str) -> None:
        path = path.replace(os.sep, "/")
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            self.findings.append(Finding(
                "parse-error", path, e.lineno or 0, "<module>",
                f"unparseable: {e.msg}"))
            return
        suppress = {
            i: {r.strip() for r in spec.split(",") if r.strip()}
            for i, spec in _line_comments(src, _SUPPRESS_RE).items()}
        mod = _ModuleInfo(path, tree, src.splitlines(), suppress)
        guarded = _line_comments(src, _GUARDED_RE)
        holds = _line_comments(src, _HOLDS_RE)
        mod._holds = holds  # type: ignore[attr-defined]
        mod._guard_ann = {}  # type: ignore[attr-defined]
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self._load_class(mod, node, guarded)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                mod.functions[node.name] = node
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                if node.value is None:
                    continue
                kind = _is_lock_ctor(node.value)
                ctor = _container_ctor(node.value)
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if kind in ("Lock", "RLock"):
                        mod.mod_locks[t.id] = kind
                        continue
                    if ctor is not None:
                        mod.mut_globals.add(t.id)
                        if ctor == "OrderedDict":
                            mod.odict_globals.add(t.id)
                        ann = guarded.get(node.lineno)
                        if ann is not None:
                            mod._guard_ann[t.id] = \
                                None if ann == "none" else ann
                    if isinstance(node.value, ast.Call):
                        _b, c2 = _call_parts(node.value.func)
                        if c2 and c2[:1].isupper():
                            mod.singletons[t.id] = c2
        self.modules[path] = mod

    def _load_class(self, mod: _ModuleInfo, node: ast.ClassDef,
                    guarded: Dict[int, str]) -> None:
        ci = _ClassInfo(node.name, mod, node)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[stmt.name] = stmt
            elif isinstance(stmt, ast.Assign):
                kind = _is_lock_ctor(stmt.value)
                if kind in ("Lock", "RLock"):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            ci.locks[t.id] = kind
        # locks assigned in methods (the normal __init__ pattern)
        for m in ci.methods.values():
            for n in ast.walk(m):
                if not isinstance(n, (ast.Assign, ast.AnnAssign)) or \
                        n.value is None:
                    continue
                n_targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                if _container_ctor(n.value) == "OrderedDict":
                    for t in n_targets:
                        a = _self_attr(t)
                        if a is not None:
                            ci.odict_attrs.add(a)
                kind = _is_lock_ctor(n.value)
                if kind is None:
                    continue
                for t in n_targets:
                    a = _self_attr(t)
                    if a is None:
                        continue
                    if kind in ("Lock", "RLock"):
                        ci.locks[a] = kind
                    elif kind == "Condition":
                        # Condition(self._lock) aliases the lock;
                        # Condition() owns a private one
                        arg = n.value.args[0] if n.value.args else None
                        inner = _self_attr(arg) if arg is not None \
                            else None
                        if inner is not None:
                            ci.aliases[a] = inner
                        else:
                            ci.locks[a] = "Lock"
        # guarded-by annotations: pin the attr(s) assigned on that line
        for line, lock in guarded.items():
            target = self._attr_on_line(node, line)
            if target is not None:
                ci.guard_ann[target] = None if lock == "none" else lock
        mod.classes[node.name] = ci

    @staticmethod
    def _attr_on_line(cls_node: ast.ClassDef,
                      line: int) -> Optional[str]:
        for n in ast.walk(cls_node):
            if isinstance(n, (ast.Assign, ast.AugAssign)) and \
                    n.lineno == line:
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in targets:
                    a = _self_attr(t)
                    if a is None and isinstance(t, ast.Subscript):
                        a = _self_attr(t.value)
                    if a is not None:
                        return a
        return None

    def add_tree(self, root: str, package: str = "pinot_tpu") -> None:
        for full, rel in iter_py_files(root, package):
            with open(full, "r", encoding="utf-8") as fh:
                self.add_source(fh.read(), rel)

    # -- analysis ----------------------------------------------------------
    def analyze(self) -> Tuple[List[Finding], List[Finding]]:
        fns = self._walk_all()
        self._build_indexes(fns)
        self._infer_caller_holds(fns)
        self._fixpoint(fns)
        self._mod_guards: Dict[str, Dict[str, Set[str]]] = {}
        for fi in fns:
            for name, _line, held, nested in fi.g_mutations:
                evidence = held if nested else held | fi.holds_union
                if evidence:
                    self._mod_guards.setdefault(
                        fi.path, {}).setdefault(
                            name, set()).update(evidence)
        for path, mod in self.modules.items():
            for name, lock in getattr(mod, "_guard_ann", {}).items():
                d = self._mod_guards.setdefault(path, {})
                d[name] = set() if lock is None \
                    else {f"{mod.qual}.{lock}"}
        for fi in fns:
            self._rule_cc201_cc205(fi)
            self._rule_globals(fi)
            self._rule_cc202(fi)
            self._rule_cc204(fi)
        self._rule_cc203(fns)
        order = {r: i for i, r in enumerate(CONCUR_RULES)}
        self.findings.sort(
            key=lambda f: (f.path, f.line, order.get(f.rule, 99)))
        return self.findings, self.suppressed

    def _walk_all(self) -> List[_FnInfo]:
        fns: List[_FnInfo] = []
        for mod in self.modules.values():
            holds_ann = getattr(mod, "_holds", {})
            for ci in mod.classes.values():
                for name, m in ci.methods.items():
                    holds: Set[str] = set()
                    spec = holds_ann.get(m.lineno)
                    if spec:
                        for tok in spec.split(","):
                            lid = ci.lock_id(tok.strip())
                            if lid:
                                holds.add(lid)
                    fi = _FnInfo(
                        f"{mod.path}::{ci.name}.{name}",
                        f"{ci.name}.{name}", mod.path, mod, ci, m,
                        frozenset(holds))
                    _FnWalker(self, fi).walk()
                    fns.append(fi)
            for name, f in mod.functions.items():
                fi = _FnInfo(f"{mod.path}::{name}", name, mod.path,
                             mod, None, f)
                _FnWalker(self, fi).walk()
                fns.append(fi)
        return fns

    def _build_indexes(self, fns: List[_FnInfo]) -> None:
        self._by_fid = {fi.fid: fi for fi in fns}
        # the shared corpus-wide resolver (analysis/astutil.py): exact
        # for self-calls and same-module bare calls, singleton- and
        # unique-METHOD-name-based for attribute calls
        self._resolver = CallResolver()
        for path, m in self.modules.items():
            self._resolver.add_module(path, m.functions.keys(),
                                      m.classes.keys(), m.singletons)
        for fi in fns:
            if fi.cls is not None:
                self._resolver.add_function(
                    fi.fid, fi.path, fi.cls.name,
                    fi.qualname.split(".", 1)[1])
        self._resolver.finalize()

    def _resolve(self, fi: _FnInfo, kind: str, base: Optional[str],
                 name: str) -> Optional[_FnInfo]:
        """Resolve one call event to an analyzed function, or None
        (approximation documented in the module docstring)."""
        fid = self._resolver.resolve(
            fi.path, fi.cls.name if fi.cls is not None else None,
            kind, base, name)
        return self._by_fid.get(fid) if fid else None

    def _infer_caller_holds(self, fns: List[_FnInfo]) -> None:
        """Caller-holds-lock inference: a PRIVATE method (``_name``,
        not dunder) whose every resolved same-class call site holds
        lock L is analyzed as holding L — the ``_run_locked`` /
        ``_purge_locked`` idiom, without demanding an annotation at
        each site. Monotone (held sets only grow from the annotation
        floor), so the fixpoint converges. Public methods are API
        surface callable from anywhere and never inferred."""
        inferred: Dict[str, Set[str]] = {
            fi.fid: set(fi.holds) for fi in fns}
        union_h: Dict[str, Set[str]] = {
            fi.fid: set(fi.holds) for fi in fns}
        # callee fid -> [(caller fid, held-at-site, nested-site)]. A
        # call from a nested closure stays IN the site list: the
        # closure may run later on any thread, so it voids the
        # always-held intersection (held is empty there) instead of
        # being ignored — skipping it would wrongly infer "always
        # locked" from the remaining locked sites.
        sites: Dict[str, List[Tuple[str, FrozenSet[str], bool]]] = {}
        for fi in fns:
            for kind, base, name, _line, held, nested in fi.calls:
                if kind != "self":
                    continue
                callee = self._resolve(fi, kind, base, name)
                if callee is None:
                    continue
                mname = callee.qualname.rsplit(".", 1)[-1]
                if not mname.startswith("_") or mname.startswith("__"):
                    continue
                sites.setdefault(callee.fid, []).append(
                    (fi.fid, held, nested))
        # monotone (sets only grow), so this terminates; iterate to
        # the true fixpoint — a hard round cap would silently
        # under-propagate on deep private-helper chains
        while True:
            changed = False
            for fid, callers in sites.items():
                cand: Optional[Set[str]] = None
                some: Set[str] = set()
                for caller_fid, held, nested_site in callers:
                    eff = set(held) if nested_site \
                        else set(held) | inferred[caller_fid]
                    cand = eff if cand is None else cand & eff
                    some |= set(held) if nested_site \
                        else set(held) | union_h[caller_fid]
                new = inferred[fid] | (cand or set())
                if new != inferred[fid]:
                    inferred[fid] = new
                    changed = True
                new_u = union_h[fid] | some
                if new_u != union_h[fid]:
                    union_h[fid] = new_u
                    changed = True
            if not changed:
                break
        for fi in fns:
            fi.holds_union = frozenset(union_h[fi.fid]
                                       | inferred[fi.fid])
            extra = frozenset(inferred[fi.fid])
            if not extra:
                continue
            fi.holds = extra
            fi.mutations = [(a, l, h if n else h | extra, n)
                            for a, l, h, n in fi.mutations]
            fi.reads = [(a, l, h if n else h | extra, n)
                        for a, l, h, n in fi.reads]
            fi.cta = [(a, l, h if n else h | extra, n)
                      for a, l, h, n in fi.cta]
            fi.g_mutations = [(a, l, h if n else h | extra, n)
                              for a, l, h, n in fi.g_mutations]
            fi.g_reads = [(a, l, h if n else h | extra, n)
                          for a, l, h, n in fi.g_reads]
            fi.g_cta = [(a, l, h if n else h | extra, n)
                        for a, l, h, n in fi.g_cta]
            fi.lru_ops = [(a, g, l, h if n else h | extra, n)
                          for a, g, l, h, n in fi.lru_ops]
            fi.blocks = [(r, l, h if n else h | extra, n)
                         for r, l, h, n in fi.blocks]
            fi.acquires = [(a, l, h if n else h | extra, n)
                           for a, l, h, n in fi.acquires]
            fi.calls = [(k, b, n, l, h if nst else h | extra, nst)
                        for k, b, n, l, h, nst in fi.calls]

    def _fixpoint(self, fns: List[_FnInfo]) -> None:
        """Propagate 'acquires locks' and 'blocks' through the resolved
        call graph to a fixpoint (cycles converge: the sets only
        grow)."""
        for fi in fns:
            fi.locks_any = {lid for lid, _l, _h, _n in fi.acquires}
            if fi.blocks:
                fi.blocking_reason = fi.blocks[0][0]  # incl. nested:
                # a fn whose closure blocks still dispatches that work
        # monotone like the caller-holds inference: locks_any only
        # grows and blocking_reason is set at most once per fn
        changed = True
        while changed:
            changed = False
            for fi in fns:
                for kind, base, name, _line, _held, _n in fi.calls:
                    callee = self._resolve(fi, kind, base, name)
                    if callee is None:
                        continue
                    new = callee.locks_any - fi.locks_any
                    if new:
                        fi.locks_any |= new
                        changed = True
                    if fi.blocking_reason is None and \
                            callee.blocking_reason is not None:
                        fi.blocking_reason = (
                            f"{callee.qualname}() -> "
                            f"{callee.blocking_reason}")
                        changed = True

    # -- emission ----------------------------------------------------------
    def _emit(self, rule: str, path: str, line: int, scope: str,
              message: str) -> None:
        mod = self.modules.get(path)
        sup = mod.suppress.get(line, set()) if mod else set()
        f = Finding(rule, path, line, scope, message)
        if rule in sup or "all" in sup:
            self.suppressed.append(f)
        else:
            self.findings.append(f)

    # -- CC201 + CC205 -----------------------------------------------------
    def _class_guards(self, ci: _ClassInfo,
                      fns_by_cls: Dict[str, List[_FnInfo]]
                      ) -> Dict[str, Set[str]]:
        guards: Dict[str, Set[str]] = {}
        for fi in fns_by_cls.get((ci.module.path, ci.name), []):
            if fi.qualname.endswith(".__init__"):
                continue
            for attr, _line, held, nested in fi.mutations:
                evidence = held if nested else held | fi.holds_union
                if evidence and ci.lock_id(attr) is None:
                    guards.setdefault(attr, set()).update(evidence)
        for attr, lock in ci.guard_ann.items():
            if lock is None:
                guards.pop(attr, None)
                guards[attr] = set()      # annotated unguarded: exempt
            else:
                lid = ci.lock_id(lock) or \
                    f"{ci.module.qual}.{ci.name}.{lock}"
                guards[attr] = {lid}
        return guards

    def _rule_cc201_cc205(self, fi: _FnInfo) -> None:
        if fi.cls is None:
            return
        ci = fi.cls
        if not hasattr(self, "_guard_cache"):
            self._guard_cache: Dict[int, Dict[str, Set[str]]] = {}
            # keyed by (module path, class name): bare class names
            # repeat across modules (_Conn, Pred, S) and an unrelated
            # namesake's locked mutations must not poison this class's
            # guard inference
            self._fns_by_cls: Dict[Tuple[str, str],
                                   List[_FnInfo]] = {}
            for other in self._by_fid.values():
                if other.cls is not None:
                    self._fns_by_cls.setdefault(
                        (other.path, other.cls.name), []).append(other)
        guards = self._guard_cache.get(id(ci))
        if guards is None:
            guards = self._class_guards(ci, self._fns_by_cls)
            self._guard_cache[id(ci)] = guards
        if fi.qualname.endswith(".__init__"):
            return
        for attr, line, held, _nested in fi.mutations:
            g = guards.get(attr)
            if not g:
                continue
            if held & g:
                continue
            locks = "/".join(sorted(g))
            self._emit(
                "CC201", fi.path, line, fi.qualname,
                f"self.{attr} is guarded by {locks} at other mutation "
                f"sites but mutated here without it")
        mut_sites = {(a, l) for a, l, _h, _n in fi.mutations}
        seen_reads: Set[Tuple[str, int]] = set()
        for attr, line, held, _nested in fi.reads:
            g = guards.get(attr)
            if not g or held & g or (attr, line) in mut_sites \
                    or (attr, line) in seen_reads:
                continue
            seen_reads.add((attr, line))
            locks = "/".join(sorted(g))
            other = "/".join(sorted(held))
            self._emit(
                "CC201", fi.path, line, fi.qualname,
                f"self.{attr} read under {other} but mutated under "
                f"{locks} elsewhere: two locks guard the same state, "
                f"so neither excludes the other")
        for attr, line, held, _nested in fi.cta:
            g = guards.get(attr)
            if not g:
                continue
            if held & g:
                continue
            locks = "/".join(sorted(g))
            self._emit(
                "CC205", fi.path, line, fi.qualname,
                f"check-then-act on self.{attr} without {locks}: the "
                f"check and the mutation are not atomic")
        for disp, is_glob, line, held, _nested in fi.lru_ops:
            if is_glob or held:
                continue
            if guards.get(disp[5:]):
                continue   # guarded elsewhere: the mixed-guard rule owns it
            self._emit(
                "CC201", fi.path, line, fi.qualname,
                f"{disp}.move_to_end/popitem is a multi-step "
                f"linked-list relink (not GIL-atomic) and no lock "
                f"guards it: concurrent LRU traffic corrupts the "
                f"OrderedDict")

    # -- CC201/CC205 over module-level globals -----------------------------
    def _rule_globals(self, fi: _FnInfo) -> None:
        guards = self._mod_guards.get(fi.path, {})
        qual = fi.module.qual
        for name, line, held, _nested in fi.g_mutations:
            g = guards.get(name)
            if not g or held & g:
                continue
            locks = "/".join(sorted(g))
            self._emit(
                "CC201", fi.path, line, fi.qualname,
                f"{name} is guarded by {locks} at other mutation "
                f"sites but mutated here without it")
        mut_sites = {(n, l) for n, l, _h, _ns in fi.g_mutations}
        seen: Set[Tuple[str, int]] = set()
        for name, line, held, _nested in fi.g_reads:
            g = guards.get(name)
            if not g or held & g or (name, line) in mut_sites \
                    or (name, line) in seen:
                continue
            seen.add((name, line))
            locks = "/".join(sorted(g))
            other = "/".join(sorted(held))
            self._emit(
                "CC201", fi.path, line, fi.qualname,
                f"{name} read under {other} but mutated under {locks} "
                f"elsewhere: two locks guard the same state, so "
                f"neither excludes the other")
        for name, line, held, _nested in fi.g_cta:
            g = guards.get(name)
            if not g or held & g:
                continue
            locks = "/".join(sorted(g))
            self._emit(
                "CC205", fi.path, line, fi.qualname,
                f"check-then-act on {name} without {locks}: the check "
                f"and the mutation are not atomic")
        for disp, is_glob, line, held, _nested in fi.lru_ops:
            if not is_glob or held:
                continue
            if guards.get(disp):
                continue   # guarded elsewhere: the mixed-guard rule owns it
            self._emit(
                "CC201", fi.path, line, fi.qualname,
                f"{qual}.{disp}.move_to_end/popitem is a multi-step "
                f"linked-list relink (not GIL-atomic) and no lock "
                f"guards it: concurrent LRU traffic corrupts the "
                f"OrderedDict")

    # -- CC202 -------------------------------------------------------------
    def _rule_cc202(self, fi: _FnInfo) -> None:
        for reason, line, held, _nested in fi.blocks:
            if not held:
                continue
            locks = "/".join(sorted(held))
            self._emit(
                "CC202", fi.path, line, fi.qualname,
                f"{reason} while holding {locks}: every thread "
                f"contending on the lock stalls behind it")
        for kind, base, name, line, held, _nested in fi.calls:
            if not held:
                continue
            callee = self._resolve(fi, kind, base, name)
            if callee is None or callee.blocking_reason is None:
                continue
            # a direct match on the same line already reported it
            if any(line == bl and held == bh
                   for _r, bl, bh, _bn in fi.blocks):
                continue
            locks = "/".join(sorted(held))
            self._emit(
                "CC202", fi.path, line, fi.qualname,
                f"{callee.qualname}() blocks "
                f"({callee.blocking_reason}) and is called holding "
                f"{locks}")

    # -- CC203 -------------------------------------------------------------
    def _rule_cc203(self, fns: List[_FnInfo]) -> None:
        # edges: lock A held -> lock B acquired (directly or via a
        # resolved call that acquires B somewhere inside)
        edges: Dict[str, Dict[str, Tuple[str, int, str]]] = {}

        def add_edge(a: str, b: str, path: str, line: int,
                     scope: str) -> None:
            edges.setdefault(a, {})
            if b not in edges[a]:
                edges[a][b] = (path, line, scope)

        lock_kinds: Dict[str, str] = {}
        for mod in self.modules.values():
            for ci in mod.classes.values():
                for attr, kind in ci.locks.items():
                    lock_kinds[f"{mod.qual}.{ci.name}.{attr}"] = kind
            for name, kind in mod.mod_locks.items():
                lock_kinds[f"{mod.qual}.{name}"] = kind

        for fi in fns:
            for lid, line, held, _nested in fi.acquires:
                for a in held:
                    if a != lid:
                        add_edge(a, lid, fi.path, line, fi.qualname)
                    elif lock_kinds.get(lid) == "Lock":
                        self._emit(
                            "CC203", fi.path, line, fi.qualname,
                            f"{lid} re-acquired while already held: "
                            f"non-reentrant Lock self-deadlock")
            for kind, base, name, line, held, _nested in fi.calls:
                if not held:
                    continue
                callee = self._resolve(fi, kind, base, name)
                if callee is None:
                    continue
                for a in held:
                    for b in callee.locks_any:
                        if a == b:
                            # a self-edge through a call chain is a
                            # guaranteed deadlock only for exact
                            # same-class resolution on a plain Lock
                            if kind == "self" and \
                                    lock_kinds.get(a) == "Lock":
                                self._emit(
                                    "CC203", fi.path, line,
                                    fi.qualname,
                                    f"{callee.qualname}() re-acquires "
                                    f"{a} already held here: "
                                    f"non-reentrant Lock "
                                    f"self-deadlock")
                            continue
                        add_edge(a, b, fi.path, line,
                                 f"{fi.qualname}->{callee.qualname}")

        # cycle detection over the edge graph (iterative DFS)
        seen_cycles: Set[FrozenSet[str]] = set()
        for start in sorted(edges):
            stack = [(start, [start])]
            while stack:
                node, trail = stack.pop()
                for nxt in sorted(edges.get(node, {})):
                    if nxt == start and len(trail) > 1:
                        cyc = frozenset(trail)
                        if cyc in seen_cycles:
                            continue
                        seen_cycles.add(cyc)
                        sites = []
                        ring = trail + [start]
                        for i in range(len(ring) - 1):
                            p, l, s = edges[ring[i]][ring[i + 1]]
                            sites.append((p, l, s))
                        path, line, scope = min(sites)
                        order = " -> ".join(ring)
                        self._emit(
                            "CC203", path, line, scope,
                            f"lock-order cycle {order}: threads "
                            f"taking these locks in different orders "
                            f"can deadlock")
                    elif nxt not in trail and len(trail) < 6:
                        stack.append((nxt, trail + [nxt]))

    # -- CC204 -------------------------------------------------------------
    def _rule_cc204(self, fi: _FnInfo) -> None:
        for line, api in fi.escapes:
            self._emit(
                "CC204", fi.path, line, fi.qualname,
                f"closure submitted to another thread reads "
                f"thread-local state via {api}; on the pool thread it "
                f"silently no-ops or attributes to the wrong query — "
                f"hand off explicitly (span_tracer.start/stop, "
                f"Tracing.register, attach_thread, or build Span "
                f"objects)")


# ---------------------------------------------------------------------------
# conveniences + baseline
# ---------------------------------------------------------------------------

def analyze_source(src: str, path: str
                   ) -> Tuple[List[Finding], List[Finding]]:
    """Single-module analysis (tests). Whole-program resolution still
    runs — over a corpus of one module."""
    prog = Program()
    prog.add_source(src, path)
    return prog.analyze()


def analyze_tree(root: str, package: str = "pinot_tpu"
                 ) -> Tuple[List[Finding], List[Finding]]:
    prog = Program()
    prog.add_tree(root, package)
    return prog.analyze()


def write_baseline(findings, path: str) -> None:
    from .astutil import write_baseline as _wb
    _wb(findings, path, comment=(
        "concur ratchet baseline — grandfathered CC findings per "
        "file::scope::rule. Regenerate with `python tools/"
        "check_static.py --concur-only --update-baseline`; new "
        "findings above these counts fail check_static, and counts "
        "that drop must be ratcheted down here."))
