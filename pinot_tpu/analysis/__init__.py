"""Static analysis over the engine's contract surfaces.

Four passes, one goal: hazards that today corrupt results, retrace,
race or drift silently at RUN time must fail loudly at PLAN / LINT
time, before a TPU is ever attached ("Query Processing on Tensor
Computation Runtimes": relational-on-tensor stacks live or die by
static shape/dtype contracts).

- plan_verify: abstract shape/dtype inference over the ops/ir.py kernel
  plan tree — index bounds, plan-cache hashability, lossless carrier
  narrowing, SUM accumulator width, compaction-capacity invariants,
  strategy gates. Wired into query/planner.py as a fail-fast post-plan
  step and into ops/plan_cache.py as a debug assertion.
- jaxlint: AST rules over the package source — host syncs in device hot
  paths, jax.jit constructed inside loops, non-static Python state read
  under trace, unlocked mutation of shared registries. Allowlists plus a
  checked-in ratchet baseline (tools/jaxlint_baseline.json) grandfather
  the legitimate host-side sites.
- concur: whole-program concurrency verifier (CC201–CC205) — lock
  guard-map inference (incl. caller-holds-lock), blocking calls under
  held locks, lock-order cycles over the resolved call graph,
  thread-local state escaping into pool closures, check-then-act.
  Ratcheted at tools/concur_baseline.json.
- detlint: whole-program determinism & replay-safety verifier
  (DT301–DT305) — wall-clock reads without an injectable escape hatch,
  ambient randomness, unordered-collection serialization, query-time
  os.environ reads, and completion-order float accumulation, taint
  propagated from the deterministic-plane entry registry (chaos / SLO /
  alert / shed / replay planes) through the shared call resolver
  (astutil.py). Ratcheted at tools/detlint_baseline.json.

Shared plumbing (Finding, ratchet baselines, suppression comments, the
corpus-wide call resolver) lives in astutil.py.

`tools/check_static.py` runs all four passes (the three lint passes
over the tree, the plan verifier over every plan the planner produces
for the SSB + taxi + fuzzer query corpus) and gates tier-1 alongside
tools/check_ledger.py.
"""
from .plan_verify import (Diagnostic, PlanVerificationError,  # noqa: F401
                          RULES, check_compiled_plan, format_diagnostics,
                          verify_compiled_plan, verify_kernel_plan)
from .jaxlint import (Finding, LINT_RULES, compare_baseline,  # noqa: F401
                      lint_source, lint_tree, load_baseline,
                      write_baseline)
from .concur import (CONCUR_RULES, Program,  # noqa: F401
                     analyze_source, analyze_tree)
from .detlint import DETLINT_RULES, ROOTS  # noqa: F401
