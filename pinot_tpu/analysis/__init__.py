"""Static analysis over the engine's two contract surfaces.

Two passes, one goal: hazards that today corrupt results or retrace
silently at RUN time must fail loudly at PLAN / LINT time, before a TPU
is ever attached ("Query Processing on Tensor Computation Runtimes":
relational-on-tensor stacks live or die by static shape/dtype contracts).

- plan_verify: abstract shape/dtype inference over the ops/ir.py kernel
  plan tree — index bounds, plan-cache hashability, lossless carrier
  narrowing, SUM accumulator width, compaction-capacity invariants,
  strategy gates. Wired into query/planner.py as a fail-fast post-plan
  step and into ops/plan_cache.py as a debug assertion.
- jaxlint: AST rules over the package source — host syncs in device hot
  paths, jax.jit constructed inside loops, non-static Python state read
  under trace, unlocked mutation of shared registries. Allowlists plus a
  checked-in ratchet baseline (tools/jaxlint_baseline.json) grandfather
  the legitimate host-side sites.

`tools/check_static.py` runs both passes (the linter over the tree, the
verifier over every plan the planner produces for the SSB + taxi +
fuzzer query corpus) and gates tier-1 alongside tools/check_ledger.py.
"""
from .plan_verify import (Diagnostic, PlanVerificationError,  # noqa: F401
                          RULES, check_compiled_plan, format_diagnostics,
                          verify_compiled_plan, verify_kernel_plan)
from .jaxlint import (Finding, LINT_RULES, compare_baseline,  # noqa: F401
                      lint_source, lint_tree, load_baseline,
                      write_baseline)
