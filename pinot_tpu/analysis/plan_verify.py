"""Plan-IR static verifier: abstract shape/dtype checks over ops/ir.py.

The engine compiles one XLA binary per plan SHAPE and re-parameterizes
per query (ops/ir.py), so a single bad plan invariant — an out-of-range
column index, an unhashable plan node poisoning the cache key, a lossy
payload-dtype narrowing, an int accumulator that overflows at segment
scale, a compaction capacity off the /4 quantization ladder, a sketch
aggregation reaching the compact path — corrupts results or retraces on
every query instead of failing once at plan time. This module re-derives
each invariant from the plan tree (plus segment metadata when available)
and reports structured diagnostics.

Two entry points:

- ``verify_kernel_plan(plan, ...)``: structural rules over a bare
  KernelPlan / SelectPlan — everything derivable without a segment.
  ops/plan_cache.py runs this as a debug assertion on every cache miss.
- ``verify_compiled_plan(cp)``: the full rule set over a planner
  CompiledPlan — index bounds against the real column/param bindings,
  param kind/dtype checks, metadata-derived value ranges vs the claimed
  AggSpec.bits, cost-model slots_cap consistency. query/planner.py runs
  this fail-fast after every kernel/kselect plan (PINOT_PLAN_VERIFY=0
  disables).

Rule catalog (stable ids — tests assert them, diagnostics print them):

    PV101  column index out of bounds
    PV102  parameter index out of bounds
    PV103  plan structure not hashable / not frozen-tuple-only
    PV104  lossy carrier-dtype narrowing (claimed bits/sign too small)
    PV105  integral SUM accumulator can overflow at full selectivity
    PV106  compact slots_cap violates capacity invariants
    PV107  strategy gate violation (e.g. sketch agg on the compact path)
    PV108  malformed AggSpec (kind/card/bits out of contract)
    PV109  malformed value/predicate expression (op, arity, IN width)
    PV110  malformed group keys (cardinality, key_exprs parallelism)
    PV111  parameter kind/dtype mismatch for a predicate/value node
    PV112  malformed SelectPlan (k, order-key packing)
    PV201  fused exchange partition-spec/key-dtype inconsistency
    PV202  fused per-shard shape instability across a collective
    PV203  fused-stage accumulator width overflow

The PV2xx family covers the cross-stage fused IR (ops/ir.FusedPlan):
``verify_fused_plan`` / ``check_fused_plan`` run fail-fast in
multistage/fused.py before the whole-plan program is staged.
"""
from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..ops import ir
from ..query.sql import SqlError

RULES = {
    "PV101": "column index out of bounds",
    "PV102": "parameter index out of bounds",
    "PV103": "plan structure not hashable (plan-cache key contract)",
    "PV104": "lossy carrier-dtype narrowing",
    "PV105": "integral SUM accumulator overflow at segment scale",
    "PV106": "compact slots_cap capacity invariant violation",
    "PV107": "group-by strategy gate violation",
    "PV108": "malformed AggSpec",
    "PV109": "malformed value/predicate expression",
    "PV110": "malformed group keys",
    "PV111": "parameter kind/dtype mismatch",
    "PV112": "malformed SelectPlan",
    # fused cross-stage IR (ops/ir.FusedPlan — whole-plan mesh
    # compilation, round 16): the fail-fast contract survives fusion
    "PV201": "fused exchange partition-spec/key-dtype inconsistency",
    "PV202": "fused per-shard shape instability across a collective",
    "PV203": "fused-stage accumulator width overflow",
}


@dataclass(frozen=True)
class Diagnostic:
    rule: str       # PVxxx
    path: str       # location in the plan tree, e.g. "aggs[1].value.lhs"
    message: str
    fix: str = ""   # suggested fix
    # "error" diagnostics fail the planner fail-fast and check_static;
    # "warn" is advisory (reported, never query-killing) — used where
    # the hazard degrades to exact numpy-wrap parity rather than silent
    # divergence (PV105)
    severity: str = "error"

    def __str__(self) -> str:
        s = f"{self.rule} at {self.path}: {self.message}"
        if self.severity != "error":
            s = f"[{self.severity}] " + s
        return s + (f" (fix: {self.fix})" if self.fix else "")


class PlanVerificationError(SqlError):
    """A planned kernel violates a static invariant. Deliberately NOT a
    PlanError: PlanError means 'host path, please' and is caught; a
    verification failure is a bug that must surface, not a fallback."""

    def __init__(self, diagnostics: List[Diagnostic]):
        self.diagnostics = diagnostics
        super().__init__("plan verification failed:\n"
                         + format_diagnostics(diagnostics))


def format_diagnostics(diags: Sequence[Diagnostic]) -> str:
    return "\n".join(f"  {d}" for d in diags)


# ---------------------------------------------------------------------------
# expression walkers
# ---------------------------------------------------------------------------

# device scalar functions with a kernels._eval_func lowering -> result
# kind ('int' | 'float' | 'same' = follows the argument)
_DEVICE_FUNC_KIND = {
    "cast_long": "int", "cast_int": "int",
    "cast_double": "float", "cast_float": "float",
    "abs": "same", "floor": "float", "ceil": "float", "sqrt": "float",
    "exp": "float", "ln": "float",
    "year": "int", "month": "int", "day": "int", "quarter": "int",
    "dayofweek": "int", "hour": "int", "minute": "int", "second": "int",
    "millisecond": "int",
    "trunc_second": "int", "trunc_minute": "int", "trunc_hour": "int",
    "trunc_day": "int", "trunc_week": "int", "trunc_month": "int",
    "trunc_quarter": "int", "trunc_year": "int",
}

_BIN_OPS = ("+", "-", "*", "/", "%", "//")
_CMP_OPS = ("==", "!=", "<", "<=", ">", ">=")
_MV_MODES = ("sum", "count", "min", "max")

_SKETCH_KINDS = ("distinct_count_hll", "distinct_count_theta",
                 "percentile_sketch", "raw_hll", "raw_theta",
                 "percentile_raw_sketch")
_AGG_KINDS = ("count", "sum", "min", "max", "avg",
              "distinct_count") + _SKETCH_KINDS
_COMPACT_AGG_KINDS = ("count", "sum", "avg", "min", "max")


class _Ctx:
    """Shared verification context: bounds, bindings, sink."""

    def __init__(self, n_cols: Optional[int], n_params: Optional[int],
                 params: Optional[Sequence[Any]] = None,
                 col_names: Optional[Sequence[str]] = None,
                 segment: Any = None):
        self.n_cols = n_cols
        self.n_params = n_params
        self.params = params
        self.col_names = col_names
        self.segment = segment
        self.out: List[Diagnostic] = []

    def diag(self, rule: str, path: str, message: str, fix: str = "",
             severity: str = "error") -> None:
        self.out.append(Diagnostic(rule, path, message, fix, severity))

    def check_col(self, idx: Any, path: str) -> None:
        if not isinstance(idx, (int, np.integer)):
            self.diag("PV101", path, f"column index {idx!r} is not an int")
            return
        if self.n_cols is not None and not 0 <= idx < self.n_cols:
            self.diag("PV101", path,
                      f"column index {int(idx)} outside [0, {self.n_cols})",
                      "bind the column through _Binder.bind_col")

    def check_param(self, idx: Any, path: str) -> None:
        if idx is None:
            return
        if not isinstance(idx, (int, np.integer)):
            self.diag("PV102", path, f"param index {idx!r} is not an int")
            return
        if self.n_params is not None and not 0 <= idx < self.n_params:
            self.diag("PV102", path,
                      f"param index {int(idx)} outside [0, {self.n_params})",
                      "bind the value through _Binder.add_param")

    def param_value(self, idx: Optional[int]) -> Any:
        if self.params is None or idx is None \
                or not isinstance(idx, (int, np.integer)) \
                or not 0 <= idx < len(self.params):
            return None
        return self.params[idx]

    def column_meta(self, col_idx: Any):
        if self.segment is None or self.col_names is None \
                or not isinstance(col_idx, (int, np.integer)) \
                or not 0 <= col_idx < len(self.col_names):
            return None
        return self.segment.columns.get(self.col_names[col_idx])


def _is_marker(v: Any) -> bool:
    """Planner symbolic params: ('dictvals', name), ('nullmask', name),
    ('docmask', mask), ('validdocs', None), ('hash64', name)."""
    return isinstance(v, tuple) and len(v) == 2 and isinstance(v[0], str)


def _walk_value(ve: Any, path: str, c: _Ctx) -> Optional[str]:
    """Abstract dtype inference ('int' | 'float' | None=unknown) with
    structural validation along the way."""
    if isinstance(ve, ir.Col):
        c.check_col(ve.col, path + ".col")
        c.check_param(ve.dict_param, path + ".dict_param")
        if ve.dict_param is not None:
            pv = c.param_value(ve.dict_param)
            if pv is not None and not _is_marker(pv) \
                    and not isinstance(pv, np.ndarray):
                c.diag("PV111", path,
                       f"dict_param resolves to {type(pv).__name__}, "
                       "expected a ('dictvals'|'hash64', col) marker or "
                       "a device values array")
        m = c.column_meta(ve.col)
        if m is not None and getattr(m, "data_type", None) is not None \
                and m.data_type.is_numeric:
            return "int" if m.data_type.is_integral else "float"
        return None
    if isinstance(ve, ir.Lit):
        c.check_param(ve.param, path + ".param")
        pv = c.param_value(ve.param)
        if isinstance(pv, (np.floating, float)):
            return "float"
        if isinstance(pv, (np.integer, int)) and not isinstance(pv, bool):
            return "int"
        return None
    if isinstance(ve, ir.MvReduce):
        c.check_col(ve.col, path + ".col")
        c.check_param(ve.dict_param, path + ".dict_param")
        if ve.mode not in _MV_MODES:
            c.diag("PV109", path + ".mode",
                   f"MvReduce mode {ve.mode!r} not in {_MV_MODES}")
        return "int" if ve.mode == "count" else None
    if isinstance(ve, ir.Bin):
        if ve.op not in _BIN_OPS:
            c.diag("PV109", path + ".op",
                   f"binary op {ve.op!r} not in {_BIN_OPS}")
        lk = _walk_value(ve.lhs, path + ".lhs", c)
        rk = _walk_value(ve.rhs, path + ".rhs", c)
        if ve.op == "/":
            return "float"   # SQL division is double division
        if lk == "float" or rk == "float":
            return "float"
        if lk == "int" and rk == "int":
            return "int"
        return None
    if isinstance(ve, ir.Func):
        kind = _DEVICE_FUNC_KIND.get(ve.name)
        if kind is None:
            c.diag("PV109", path + ".name",
                   f"no device lowering for function {ve.name!r}",
                   "route through query/functions.py host path")
            kind = "same"
        if not isinstance(ve.args, tuple) or len(ve.args) != 1:
            c.diag("PV109", path + ".args",
                   f"device function {ve.name!r} takes exactly one "
                   f"argument, got {len(getattr(ve, 'args', ()))}")
            return None
        ak = _walk_value(ve.args[0], path + ".args[0]", c)
        return ak if kind == "same" else kind
    if isinstance(ve, ir.Case):
        if not isinstance(ve.whens, tuple) or not ve.whens:
            c.diag("PV109", path + ".whens",
                   "CASE needs at least one WHEN arm as a tuple")
            return None
        kinds = []
        for i, (pred, val) in enumerate(ve.whens):
            _walk_pred(pred, f"{path}.whens[{i}][0]", c)
            kinds.append(_walk_value(val, f"{path}.whens[{i}][1]", c))
        kinds.append(_walk_value(ve.else_, path + ".else_", c))
        if "float" in kinds:
            return "float"
        return "int" if all(k == "int" for k in kinds) else None
    c.diag("PV109", path, f"unknown value expression {type(ve).__name__}")
    return None


def _walk_pred(p: Any, path: str, c: _Ctx) -> None:
    if isinstance(p, (ir.TrueP, ir.FalseP)):
        return
    if isinstance(p, ir.EqId):
        c.check_col(p.col, path + ".col")
        c.check_param(p.param, path + ".param")
        pv = c.param_value(p.param)
        if pv is not None and not _is_marker(pv) and not isinstance(
                pv, (int, np.integer)):
            c.diag("PV111", path + ".param",
                   f"EqId expects an integer dict id, got "
                   f"{type(pv).__name__}")
        return
    if isinstance(p, ir.IdRange):
        c.check_col(p.col, path + ".col")
        c.check_param(p.lo_param, path + ".lo_param")
        c.check_param(p.hi_param, path + ".hi_param")
        if p.lo_param is None and p.hi_param is None:
            c.diag("PV109", path, "IdRange with neither bound",
                   "fold to TrueP in the planner")
        for which in ("lo_param", "hi_param"):
            pv = c.param_value(getattr(p, which))
            if pv is not None and not _is_marker(pv) and not isinstance(
                    pv, (int, np.integer)):
                c.diag("PV111", f"{path}.{which}",
                       f"IdRange bound must be an integer id, got "
                       f"{type(pv).__name__}")
        return
    if isinstance(p, ir.InSet):
        c.check_col(p.col, path + ".col")
        c.check_param(p.param, path + ".param")
        if not isinstance(p.n, (int, np.integer)) or p.n < 1:
            c.diag("PV109", path + ".n", f"InSet n={p.n!r} must be >= 1")
        elif p.n & (p.n - 1):
            c.diag("PV109", path + ".n",
                   f"InSet n={int(p.n)} is not a power of two",
                   "pad through planner._pad_dup to bound recompiles")
        pv = c.param_value(p.param)
        if isinstance(pv, np.ndarray):
            if pv.ndim != 1 or len(pv) != p.n:
                c.diag("PV111", path + ".param",
                       f"InSet param shape {pv.shape} != ({int(p.n)},)")
            elif len(pv) > 1 and not bool(np.all(pv[:-1] <= pv[1:])):
                c.diag("PV111", path + ".param",
                       "InSet values must be sorted ascending (the "
                       "kernel's sorted-membership search requires it)")
        return
    if isinstance(p, ir.InBitmap):
        c.check_col(p.col, path + ".col")
        c.check_param(p.param, path + ".param")
        pv = c.param_value(p.param)
        if isinstance(pv, np.ndarray):
            if pv.dtype != np.bool_ or pv.ndim != 1:
                c.diag("PV111", path + ".param",
                       f"InBitmap param must be a 1-D bool presence "
                       f"table, got {pv.dtype} ndim={pv.ndim}")
            else:
                m = c.column_meta(p.col)
                card = getattr(m, "cardinality", None)
                if card and len(pv) != card:
                    c.diag("PV111", path + ".param",
                           f"presence table length {len(pv)} != column "
                           f"cardinality {card}")
        return
    if isinstance(p, ir.Cmp):
        if p.op not in _CMP_OPS:
            c.diag("PV109", path + ".op",
                   f"comparison op {p.op!r} not in {_CMP_OPS}")
        _walk_value(p.lhs, path + ".lhs", c)
        c.check_param(p.param, path + ".param")
        return
    if isinstance(p, ir.MaskParam):
        c.check_param(p.param, path + ".param")
        pv = c.param_value(p.param)
        if pv is not None and not _is_marker(pv):
            if not (isinstance(pv, np.ndarray) and pv.dtype == np.bool_):
                c.diag("PV111", path + ".param",
                       f"MaskParam expects a bool mask or marker, got "
                       f"{type(pv).__name__}")
        return
    if isinstance(p, (ir.And, ir.Or)):
        if not isinstance(p.children, tuple) or len(p.children) < 1:
            c.diag("PV109", path + ".children",
                   f"{type(p).__name__} needs a non-empty child tuple")
            return
        for i, ch in enumerate(p.children):
            _walk_pred(ch, f"{path}.children[{i}]", c)
        return
    if isinstance(p, ir.Not):
        _walk_pred(p.child, path + ".child", c)
        return
    c.diag("PV109", path, f"unknown predicate {type(p).__name__}")


# ---------------------------------------------------------------------------
# hashability (the plan-cache key contract)
# ---------------------------------------------------------------------------

_FROZEN_IR_TYPES = (
    ir.Col, ir.Lit, ir.Bin, ir.MvReduce, ir.Func, ir.Case,
    ir.TrueP, ir.FalseP, ir.EqId, ir.IdRange, ir.InSet, ir.InBitmap,
    ir.Cmp, ir.MaskParam, ir.And, ir.Or, ir.Not,
    ir.AggSpec, ir.KernelPlan, ir.SelectPlan,
)


def _check_hashable(obj: Any, path: str, c: _Ctx) -> None:
    if obj is None or isinstance(obj, (str, bool, int, float,
                                       np.integer, np.bool_)):
        return
    if isinstance(obj, tuple):
        for i, v in enumerate(obj):
            _check_hashable(v, f"{path}[{i}]", c)
        return
    if isinstance(obj, _FROZEN_IR_TYPES):
        for f in dataclasses.fields(obj):
            _check_hashable(getattr(obj, f.name), f"{path}.{f.name}", c)
        return
    if isinstance(obj, (list, dict, set, np.ndarray)):
        c.diag("PV103", path,
               f"mutable {type(obj).__name__} inside the plan structure "
               "breaks the plan-cache key contract",
               "store a tuple in the plan; ship arrays as runtime params")
        return
    c.diag("PV103", path,
           f"non-IR node {type(obj).__name__} in the plan structure "
           "(frozen, tuple-only contract)")


# ---------------------------------------------------------------------------
# aggregation width rules (PV104/PV105)
# ---------------------------------------------------------------------------

def _ir_range(ve: Any, c: _Ctx) -> Optional[Tuple[float, float]]:
    """Metadata-derived value interval of an IR value expression — the
    verifier-side mirror of SegmentPlanner._range_of (which works on the
    SQL AST). Must stay at least as conservative."""
    if isinstance(ve, ir.Col):
        m = c.column_meta(ve.col)
        if m is None or getattr(m, "data_type", None) is None \
                or not m.data_type.is_numeric:
            return None
        if m.min is None or m.max is None:
            return None
        return float(m.min), float(m.max)
    if isinstance(ve, ir.Lit):
        pv = c.param_value(ve.param)
        if isinstance(pv, (int, float, np.integer, np.floating)) \
                and not isinstance(pv, bool):
            return float(pv), float(pv)
        return None
    if isinstance(ve, ir.MvReduce):
        m = c.column_meta(ve.col)
        if m is None:
            return None
        mv = float(getattr(m, "max_values", None) or 1)
        if ve.mode == "count":
            return 0.0, mv
        if m.min is None or m.max is None \
                or not m.data_type.is_numeric:
            return None
        if ve.mode == "sum":
            return (min(0.0, float(m.min) * mv), float(m.max) * mv)
        return float(m.min), float(m.max)
    if isinstance(ve, ir.Bin):
        lr = _ir_range(ve.lhs, c)
        rr = _ir_range(ve.rhs, c)
        if lr is None or rr is None:
            return None
        (a, b), (d, e) = lr, rr
        if ve.op == "+":
            return a + d, b + e
        if ve.op == "-":
            return a - e, b - d
        if ve.op == "*":
            corners = (a * d, a * e, b * d, b * e)
            return min(corners), max(corners)
        return None
    return None


def _check_agg_widths(plan: ir.KernelPlan, c: _Ctx,
                      n_docs: Optional[int]) -> None:
    from ..query.planner import SegmentPlanner
    for i, spec in enumerate(plan.aggs):
        path = f"aggs[{i}]"
        if spec.kind not in ("sum", "avg") or not spec.integral:
            continue
        # PV104a: the carrier the COMPACT path narrows this payload to
        # (_payload_columns via kernels.sum_carrier_dtype) must exist —
        # only that path narrows, so dense plans are out of scope. No
        # bits exemption: _payload_columns raises a carrier-less build
        # into a ValueError, so the verifier must catch the same set at
        # plan time (including the bits=63 unprofiled sentinel).
        if plan.strategy == "compact":
            from ..ops.kernels import sum_carrier_dtype
            if sum_carrier_dtype(spec.bits) is None:
                c.diag("PV104", path + ".bits",
                       f"claimed {spec.bits} magnitude bits, but no "
                       "exact integer carrier of that width exists on "
                       "this platform (jax_enable_x64 off) — the "
                       "compact-path narrowing (_payload_columns) "
                       "refuses to build this kernel",
                       "enable x64 or demote the aggregation to float")
        # PV104b: the claimed bits/sign must actually bound the value —
        # a too-small claim silently truncates in the int32 carrier and
        # under-sizes the int8 limb decomposition
        if c.segment is not None and spec.value is not None:
            rng = _ir_range(spec.value, c)
            true_bits, true_signed = SegmentPlanner._bits_for(rng)
            if rng is not None and spec.bits < true_bits:
                c.diag("PV104", path + ".bits",
                       f"claims {spec.bits} magnitude bits but column "
                       f"metadata bounds the value at {true_bits} bits "
                       f"(range {rng[0]:g}..{rng[1]:g}) — the narrowed "
                       "carrier/limb decomposition would truncate",
                       "recompute bits via planner._bits_for")
            if rng is not None and not spec.signed and true_signed:
                c.diag("PV104", path + ".signed",
                       "claims a non-negative value but metadata says "
                       f"the range reaches {rng[0]:g}",
                       "keep signed=True unless min >= 0 is proven")
        # PV105 (warn): a PROVEN magnitude bound plus the row count must
        # fit the 63-bit accumulator at full selectivity. Advisory, not
        # query-killing: if the sum does overflow it wraps in exact
        # lockstep with the numpy int64 host/oracle path (and the
        # reference's Java long), and real filters rarely match every
        # row — but the bench/dashboard author should know. bits == 63
        # is the 'unprofiled' sentinel and exempt.
        if n_docs and spec.bits < 63:
            need = spec.bits + max(int(n_docs - 1).bit_length(), 1)
            if need > 63:
                c.diag("PV105", path + ".bits",
                       f"SUM of {spec.bits}-bit values over {n_docs} "
                       f"rows needs {need} accumulator bits > 63 — "
                       "wraps int64 (numpy-parity) when every row "
                       "matches",
                       "shard the segment or demote to float "
                       "accumulation", severity="warn")


# ---------------------------------------------------------------------------
# strategy / capacity rules (PV106/PV107/PV110)
# ---------------------------------------------------------------------------

def _check_strategy(plan: ir.KernelPlan, c: _Ctx) -> None:
    from ..ops.kernels import COMPACT_GROUP_LIMIT, GROUPED_HLL_LIMIT
    from ..query.planner import MAX_DENSE_GROUPS, MAX_DISTINCT_MATRIX

    if plan.strategy not in ("dense", "compact"):
        c.diag("PV107", "strategy",
               f"unknown strategy {plan.strategy!r}")
        return
    space = plan.group_space
    has_expr_keys = any(e is not None for e in (plan.key_exprs or ()))
    if plan.strategy == "compact":
        if not plan.is_group_by:
            c.diag("PV107", "strategy",
                   "compact strategy without group keys")
        if has_expr_keys:
            c.diag("PV107", "key_exprs",
                   "expression group keys cannot compact (no key column "
                   "to gather)", "plan the dense strategy")
        if space > COMPACT_GROUP_LIMIT:
            c.diag("PV107", "group_keys",
                   f"group space {space} exceeds COMPACT_GROUP_LIMIT "
                   f"{COMPACT_GROUP_LIMIT}")
        for i, spec in enumerate(plan.aggs):
            if spec.kind not in _COMPACT_AGG_KINDS:
                c.diag("PV107", f"aggs[{i}].kind",
                       f"{spec.kind!r} aggregation on the compact path "
                       f"(gate allows {_COMPACT_AGG_KINDS})",
                       "plan dense or route to the host registry")
            if isinstance(spec.value, ir.MvReduce):
                c.diag("PV107", f"aggs[{i}].value",
                       "MV payloads are (bucket, maxValues) matrices; "
                       "the row compaction primitive is 1-D",
                       "plan the dense strategy")
            if spec.null_param is not None:
                c.diag("PV107", f"aggs[{i}].null_param",
                       "per-agg null masking has no compact lowering "
                       "(the planner hosts null-aware group-bys)")
    elif plan.is_group_by and space > MAX_DENSE_GROUPS:
        c.diag("PV107", "group_keys",
               f"dense one-hot over group space {space} exceeds "
               f"MAX_DENSE_GROUPS {MAX_DENSE_GROUPS}")
    if plan.is_group_by:
        for i, spec in enumerate(plan.aggs):
            if spec.kind in ("distinct_count_theta", "percentile_sketch",
                             "raw_theta", "percentile_raw_sketch"):
                c.diag("PV107", f"aggs[{i}].kind",
                       f"grouped {spec.kind!r} has no device lowering "
                       "(host registry only)")
            if spec.kind == "distinct_count" and spec.card \
                    and space * spec.card > MAX_DISTINCT_MATRIX:
                c.diag("PV107", f"aggs[{i}].card",
                       f"grouped DISTINCTCOUNT presence matrix "
                       f"{space}x{spec.card} exceeds MAX_DISTINCT_MATRIX")
            if spec.kind in ("distinct_count_hll", "raw_hll") and spec.card:
                r_levels = 64 - spec.card + 1
                if space * (1 << spec.card) * r_levels > GROUPED_HLL_LIMIT:
                    c.diag("PV107", f"aggs[{i}].card",
                           "grouped HLL presence bitmap exceeds "
                           "GROUPED_HLL_LIMIT")


def _check_group_keys(plan: ir.KernelPlan, c: _Ctx,
                      group_decoders: Optional[Sequence[tuple]] = None
                      ) -> None:
    for i, gk in enumerate(plan.group_keys):
        path = f"group_keys[{i}]"
        if not (isinstance(gk, tuple) and len(gk) == 2):
            c.diag("PV110", path, f"expected (col, card), got {gk!r}")
            continue
        idx, card = gk
        if not isinstance(card, (int, np.integer)) or card < 1:
            c.diag("PV110", path, f"cardinality {card!r} must be >= 1")
        kexpr = plan.key_exprs[i] if plan.key_exprs \
            and i < len(plan.key_exprs) else None
        if kexpr is None:
            c.check_col(idx, path + "[0]")
        else:
            _walk_value(kexpr, f"key_exprs[{i}]", c)
    if plan.key_exprs and len(plan.key_exprs) != len(plan.group_keys):
        c.diag("PV110", "key_exprs",
               f"{len(plan.key_exprs)} key_exprs for "
               f"{len(plan.group_keys)} group keys")
    if group_decoders is not None and plan.group_keys:
        if len(group_decoders) != len(plan.group_keys):
            c.diag("PV110", "group_decoders",
                   f"{len(group_decoders)} decoders for "
                   f"{len(plan.group_keys)} group keys")
        else:
            for i, (dec, (idx, card)) in enumerate(
                    zip(group_decoders, plan.group_keys)):
                if dec[-1] != card:
                    c.diag("PV110", f"group_decoders[{i}]",
                           f"decoder cardinality {dec[-1]} != plan key "
                           f"cardinality {card}")
                if dec[0] == "dict" and c.segment is not None:
                    m = c.segment.columns.get(dec[1])
                    if m is not None and m.cardinality != card:
                        c.diag("PV110", f"group_keys[{i}]",
                               f"key cardinality {card} != segment "
                               f"dictionary cardinality {m.cardinality} "
                               f"for column {dec[1]!r}")


def _check_slots_cap(plan: ir.KernelPlan, c: _Ctx, slots_cap: Optional[int],
                     bucket: Optional[int], n_docs: Optional[int],
                     est_sel: Optional[float]) -> None:
    if slots_cap is None:
        return
    from ..ops.compact import STAGE, XLA_MIN_SLOTS, full_slots_cap
    if plan.strategy != "compact":
        c.diag("PV106", "slots_cap",
               f"slots_cap={slots_cap} on the {plan.strategy!r} strategy "
               "(capacity only applies to the compact path)")
        return
    if not isinstance(slots_cap, (int, np.integer)) or slots_cap < 1:
        c.diag("PV106", "slots_cap", f"slots_cap {slots_cap!r} invalid")
        return
    if slots_cap < XLA_MIN_SLOTS:
        c.diag("PV106", "slots_cap",
               f"slots_cap {slots_cap} below XLA_MIN_SLOTS "
               f"{XLA_MIN_SLOTS} (ladder/post shapes degenerate)")
    if bucket is not None and slots_cap > full_slots_cap(bucket):
        c.diag("PV106", "slots_cap",
               f"slots_cap {slots_cap} exceeds full_slots_cap(bucket="
               f"{bucket}) = {full_slots_cap(bucket)} — capacity beyond "
               "the no-overflow bound wastes the whole post-aggregation")
    full = full_slots_cap(n_docs) if n_docs else None
    pow2 = slots_cap & (slots_cap - 1) == 0
    if not pow2 and slots_cap != full and slots_cap != 3 * STAGE:
        c.diag("PV106", "slots_cap",
               f"slots_cap {slots_cap} is not on the capacity "
               "quantization ladder (power of two, the Pallas staging "
               f"floor {3 * STAGE}, or full_slots_cap) — nearby "
               "selectivity estimates would stop sharing one kernel "
               "cache entry and retrace",
               "quantize via multistage/costs.compact_slots_cap")
    if est_sel is not None and n_docs:
        import jax

        from ..multistage.costs import compact_slots_cap
        from ..ops.kernels import cpu_scatter_default
        platform = jax.default_backend()
        expect = compact_slots_cap(n_docs, est_sel, platform,
                                   cpu_scatter_default(platform))
        if slots_cap != expect:
            c.diag("PV106", "slots_cap",
                   f"slots_cap {slots_cap} disagrees with "
                   f"multistage/costs.compact_slots_cap(n_docs={n_docs},"
                   f" sel={est_sel:.3g}) = {expect}",
                   "derive the capacity from the cost model only")


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def verify_kernel_plan(plan: ir.KernelPlan, *,
                       n_cols: Optional[int] = None,
                       n_params: Optional[int] = None,
                       bucket: Optional[int] = None,
                       n_docs: Optional[int] = None,
                       params: Optional[Sequence[Any]] = None,
                       col_names: Optional[Sequence[str]] = None,
                       segment: Any = None,
                       slots_cap: Optional[int] = None,
                       est_selectivity: Optional[float] = None,
                       group_decoders: Optional[Sequence[tuple]] = None,
                       ) -> List[Diagnostic]:
    """Verify one KernelPlan. Context arguments are all optional —
    rules that need absent context simply don't run, so the same entry
    serves the planner (full context) and the plan cache (structure
    only)."""
    c = _Ctx(n_cols, n_params, params, col_names, segment)
    if not isinstance(plan, ir.KernelPlan):
        c.diag("PV103", "plan", f"not a KernelPlan: {type(plan).__name__}")
        return c.out
    _check_hashable(plan, "plan", c)
    try:
        hash(plan)
    except TypeError as e:
        c.diag("PV103", "plan", f"hash() failed: {e}",
               "plan structures must be frozen tuples of scalars")
    _walk_pred(plan.pred, "pred", c)
    if not isinstance(plan.aggs, tuple):
        c.diag("PV103", "aggs", "aggs must be a tuple")
        return c.out
    for i, spec in enumerate(plan.aggs):
        _check_agg_spec(i, spec, c)
    _check_group_keys(plan, c, group_decoders)
    _check_strategy(plan, c)
    _check_agg_widths(plan, c, n_docs)
    _check_slots_cap(plan, c, slots_cap, bucket, n_docs, est_selectivity)
    return c.out


def _check_agg_spec(i: int, spec: Any, c: _Ctx) -> None:
    path = f"aggs[{i}]"
    if not isinstance(spec, ir.AggSpec):
        c.diag("PV108", path, f"not an AggSpec: {type(spec).__name__}")
        return
    if spec.kind not in _AGG_KINDS:
        c.diag("PV108", path + ".kind",
               f"unknown aggregation kind {spec.kind!r}")
    if spec.kind == "count":
        if spec.value is not None:
            c.diag("PV108", path + ".value",
                   "COUNT carries no value expression (rides the "
                   "shared mask/count row)")
    elif spec.value is None:
        c.diag("PV108", path + ".value",
               f"{spec.kind} needs a value expression")
    else:
        _walk_value(spec.value, path + ".value", c)
    if not isinstance(spec.bits, (int, np.integer)) \
            or not 1 <= spec.bits <= 63:
        c.diag("PV108", path + ".bits",
               f"bits={spec.bits!r} outside [1, 63]")
    if spec.kind == "distinct_count":
        if not isinstance(spec.card, (int, np.integer)) or spec.card < 1:
            c.diag("PV108", path + ".card",
                   f"DISTINCTCOUNT needs the dictionary cardinality, "
                   f"got {spec.card!r}")
        elif c.segment is not None and isinstance(spec.value, ir.Col):
            m = c.column_meta(spec.value.col)
            if m is not None and m.cardinality != spec.card:
                c.diag("PV108", path + ".card",
                       f"card {spec.card} != column cardinality "
                       f"{m.cardinality}")
    if spec.kind in ("distinct_count_hll", "raw_hll"):
        if not isinstance(spec.card, (int, np.integer)) \
                or not 4 <= spec.card <= 16:
            c.diag("PV108", path + ".card",
                   f"HLL log2m {spec.card!r} outside [4, 16]")
    if spec.kind in ("distinct_count_theta", "raw_theta"):
        if not isinstance(spec.card, (int, np.integer)) \
                or not 1 <= spec.card <= (1 << 16):
            c.diag("PV108", path + ".card",
                   f"theta k {spec.card!r} outside [1, 65536]")
    c.check_param(spec.null_param, path + ".null_param")


def verify_select_plan(sp: Any, *,
                       n_cols: Optional[int] = None,
                       n_params: Optional[int] = None,
                       bucket: Optional[int] = None,
                       params: Optional[Sequence[Any]] = None,
                       col_names: Optional[Sequence[str]] = None,
                       segment: Any = None) -> List[Diagnostic]:
    c = _Ctx(n_cols, n_params, params, col_names, segment)
    if not isinstance(sp, ir.SelectPlan):
        c.diag("PV103", "select", f"not a SelectPlan: {type(sp).__name__}")
        return c.out
    _check_hashable(sp, "select", c)
    _walk_pred(sp.pred, "select.pred", c)
    for i, col in enumerate(sp.select_cols):
        c.check_col(col, f"select.select_cols[{i}]")
    if not isinstance(sp.k, (int, np.integer)) or sp.k < 1:
        c.diag("PV112", "select.k", f"k={sp.k!r} must be >= 1")
    elif bucket is not None and sp.k > bucket:
        c.diag("PV112", "select.k",
               f"k={sp.k} exceeds the segment bucket {bucket} "
               "(lax.top_k requires k <= operand length)")
    span = 1
    raw_keys = 0
    for j, entry in enumerate(sp.order):
        path = f"select.order[{j}]"
        if not (isinstance(entry, tuple) and len(entry) == 3):
            c.diag("PV112", path, f"expected (col, desc, card): {entry!r}")
            continue
        col, _desc, card = entry
        c.check_col(col, path + "[0]")
        if card:
            span *= max(int(card), 1)
        else:
            raw_keys += 1
    if raw_keys and len(sp.order) != 1:
        c.diag("PV112", "select.order",
               "a raw (card=0) order key cannot radix-pack with other "
               "keys; the planner only emits it alone")
    if span >= 1 << 62:
        c.diag("PV112", "select.order",
               f"composite order-key span {span} does not fit 63 bits "
               "(negation could wrap past the unmatched sentinel)")
    return c.out


def verify_compiled_plan(cp: Any) -> List[Diagnostic]:
    """Full verification of a planner CompiledPlan ('kernel'/'kselect'
    kinds; other kinds verify trivially)."""
    if getattr(cp, "kind", None) == "kernel" and cp.kernel_plan is not None:
        return verify_kernel_plan(
            cp.kernel_plan,
            n_cols=len(cp.col_names), n_params=len(cp.params),
            bucket=cp.segment.bucket, n_docs=cp.segment.n_docs,
            params=cp.params, col_names=cp.col_names, segment=cp.segment,
            slots_cap=cp.slots_cap, est_selectivity=cp.est_selectivity,
            group_decoders=cp.group_decoders or None)
    if getattr(cp, "kind", None) == "kselect" and cp.select_plan is not None:
        return verify_select_plan(
            cp.select_plan,
            n_cols=len(cp.col_names), n_params=len(cp.params),
            bucket=cp.segment.bucket, params=cp.params,
            col_names=cp.col_names, segment=cp.segment)
    return []


def verify_fused_plan(fp: "ir.FusedPlan") -> List[Diagnostic]:
    """PV2xx rules over a fused whole-plan IR (ops/ir.FusedPlan).

    The fused program is ONE shard_map over every stage, so one bad
    static — an exchange partitioned differently from the mesh, a key
    dtype the int32 collective cannot carry, a stage whose per-shard
    shape drifts across the all_to_all, a canonical-position domain
    past the accumulator — corrupts every query sharing the shape.
    Rules:

        PV201  exchange partition-spec/key-dtype consistency: every
               exchange runs over the plan's one mesh (partitions
               equal across stages and to the plan), key dtype is the
               int32 the collectives are lowered for, hash exchanges
               carry a pow2 bucket cap, key slots name joined tables
        PV202  per-shard shape stability across collective boundaries:
               base_rows divides over the mesh; a hash exchange's
               received shape (partitions * cap) must cover the shard
               it was fed (rows are dropped silently otherwise);
               max_dup/build_rows are pow2 statics within the dense
               candidate bound
        PV203  accumulator widths: pos_bound (base_rows * prod
               max_dup) must fit the int32 accumulator — the canonical
               row order cannot be restored past it
    """
    c = _Ctx(None, None)
    n_stages = len(fp.stages)
    if fp.partitions < 1:
        c.diag("PV201", "fused.partitions",
               f"mesh partition count {fp.partitions} < 1")
    if fp.acc_dtype != "int32":
        c.diag("PV201", "fused.acc_dtype",
               f"accumulator dtype {fp.acc_dtype!r} is not the int32 "
               "the collective lowering carries")
    if fp.base_rows < 1 or fp.base_rows % max(fp.partitions, 1):
        c.diag("PV202", "fused.base_rows",
               f"probe seed of {fp.base_rows} rows does not shard "
               f"evenly over {fp.partitions} devices")
    shard_rows = fp.base_rows // max(fp.partitions, 1)
    pos_bound = fp.base_rows
    for i, st in enumerate(fp.stages):
        path = f"fused.stages[{i}]"
        ex = st.exchange
        if ex.kind not in ("hash", "broadcast"):
            c.diag("PV201", path + ".exchange.kind",
                   f"unknown exchange kind {ex.kind!r}")
        if ex.partitions != fp.partitions:
            c.diag("PV201", path + ".exchange.partitions",
                   f"exchange partitioned over {ex.partitions} devices "
                   f"but the fused mesh has {fp.partitions}",
                   fix="every stage of one fused program shares one "
                       "mesh; replan or route mailbox")
        if ex.key_dtype != "int32":
            c.diag("PV201", path + ".exchange.key_dtype",
                   f"key dtype {ex.key_dtype!r}; the collectives are "
                   "lowered for int32 codes")
        if not ex.key_slots:
            c.diag("PV201", path + ".exchange.key_slots",
                   "exchange carries no key columns")
        for s, owner in enumerate(ex.key_slots):
            if not 0 <= owner <= i:
                c.diag("PV201", path + f".exchange.key_slots[{s}]",
                       f"key slot gathers from table ordinal {owner}, "
                       f"not joined before stage {i}")
        if st.how not in ("inner", "left"):
            c.diag("PV201", path + ".how",
                   f"fused lowering has no {st.how!r} join body")
        if st.max_dup < 1 or st.max_dup & (st.max_dup - 1):
            c.diag("PV202", path + ".max_dup",
                   f"max_dup {st.max_dup} is not a pow2 static")
        if st.build_rows < 1 or st.build_rows & (st.build_rows - 1):
            c.diag("PV202", path + ".build_rows",
                   f"padded build side {st.build_rows} is not pow2 "
                   "(the padded shape is the compile signature)")
        if ex.kind == "hash":
            if ex.cap < 1 or ex.cap & (ex.cap - 1):
                c.diag("PV201", path + ".exchange.cap",
                       f"hash-exchange bucket cap {ex.cap} is not a "
                       "pow2 static")
            elif ex.partitions * ex.cap < shard_rows:
                c.diag("PV202", path + ".exchange.cap",
                       f"received shape {ex.partitions}x{ex.cap} cannot "
                       f"cover the {shard_rows}-row shard it is fed — "
                       "a full bucket would drop live rows silently",
                       fix="raise the bucket cap (slack) or fall back "
                           "to the mailbox plane")
            # post-exchange, every device probes its received buckets
            shard_rows = ex.partitions * ex.cap
        elif ex.cap:
            c.diag("PV201", path + ".exchange.cap",
                   "broadcast exchanges have no bucket; cap must be 0")
        shard_rows *= st.max_dup
        pos_bound *= st.max_dup
    if fp.pos_bound != pos_bound and not any(
            d.rule == "PV202" for d in c.out):
        c.diag("PV202", "fused.pos_bound",
               f"declared pos_bound {fp.pos_bound} != base_rows * "
               f"prod(max_dup) = {pos_bound}")
    if pos_bound > 2**31 - 1 or fp.pos_bound > 2**31 - 1:
        c.diag("PV203", "fused.pos_bound",
               f"canonical-position domain {max(pos_bound, fp.pos_bound)}"
               " overflows the int32 accumulator — order restoration "
               "would alias rows",
               fix="route the plan to the mailbox plane (the fused "
                   "planner's eligibility gate should have)")
    if fp.n_tables != n_stages + 1:
        c.diag("PV202", "fused.n_tables",
               f"{fp.n_tables} tables with {n_stages} join stages "
               "(want n_stages + 1)")
    return c.out


def check_fused_plan(fp: Any) -> None:
    """Fail-fast pre-compile hook (multistage/fused.py): raise on any
    ERROR diagnostic before the whole-plan program is staged.
    PINOT_PLAN_VERIFY=0 disables, like check_compiled_plan."""
    if not verification_enabled():
        return
    errors = [d for d in verify_fused_plan(fp) if d.severity == "error"]
    if errors:
        raise PlanVerificationError(errors)


def verification_enabled() -> bool:
    return os.environ.get("PINOT_PLAN_VERIFY", "1") != "0"


def check_compiled_plan(cp: Any) -> None:
    """Fail-fast post-plan hook (query/planner.py): raise
    PlanVerificationError on any ERROR diagnostic ("warn" is advisory —
    surfaced by tools/check_static.py, never query-killing).
    PINOT_PLAN_VERIFY=0 disables (the check_static CLI uses it to
    collect instead of crash)."""
    if not verification_enabled():
        return
    errors = [d for d in verify_compiled_plan(cp) if d.severity == "error"]
    if errors:
        raise PlanVerificationError(errors)


def debug_check_cache_plan(plan: Any, bucket: Optional[int] = None) -> None:
    """Structure-only debug assertion for ops/plan_cache.py: every plan
    entering the cache must be hashable and gate-consistent. Runs the
    cheap rule subset (no segment context); stripped under python -O
    along with the caller's assert."""
    if not verification_enabled() or not isinstance(plan, ir.KernelPlan):
        return
    diags = [d for d in verify_kernel_plan(plan, bucket=bucket)
             if d.severity == "error"]
    assert not diags, ("plan-cache received an invalid plan:\n"
                       + format_diagnostics(diags))
