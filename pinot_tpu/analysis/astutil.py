"""Shared AST plumbing for the static-analysis passes.

jaxlint (round 8) and concur (round 15) each grew their own copies of
the same machinery: the ``Finding`` record + ratchet-baseline helpers,
``# <tool>: ok <rule>`` suppression parsing, call-name decomposition,
the ``pinot_tpu/``-tree module walk, and (concur only) the corpus-wide
call resolver. detlint (round 23) is the third consumer — instead of a
third fork, the shared pieces live here and the passes import them.

The call-resolution contract (concur's, unchanged):

- a ``self.m()`` call resolves EXACTLY within its own (module, class);
- a bare ``f()`` call resolves EXACTLY to a same-module top-level
  function;
- an ``obj.m()`` attribute call resolves through the module-level
  singleton map (``global_metrics = MetricsRegistry()`` style) when the
  singleton name is corpus-unique and its class lives in exactly one
  module, else through the corpus-unique METHOD-name fallback — an
  ambiguous name is simply not resolved (approximation documented in
  concur's module docstring).

Function ids ("fids") are ``path::qualname`` — ``path`` repo-relative
posix, ``qualname`` either ``fn`` (module function) or ``Cls.method``.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple


# ---------------------------------------------------------------------------
# findings + ratchet baseline
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Finding:
    rule: str
    path: str       # repo-relative, posix separators
    line: int
    scope: str      # enclosing qualname, e.g. "KernelPlanCache.entry"
    message: str

    @property
    def key(self) -> str:
        """Baseline key: line numbers drift, (file, scope, rule) don't."""
        return f"{self.path}::{self.scope}::{self.rule}"

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.scope}: "
                f"{self.message}")


def counts_of(findings: Sequence[Finding]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for f in findings:
        out[f.key] = out.get(f.key, 0) + 1
    return out


def load_baseline(path: str) -> Dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return dict(data.get("counts", {}))


def write_baseline(findings: Sequence[Finding], path: str,
                   comment: Optional[str] = None) -> None:
    # parse-error can never be grandfathered: a module that stops
    # parsing must fail the gate even right after --update-baseline
    findings = [f for f in findings if f.rule != "parse-error"]
    data = {
        "comment": comment or (
            "ratchet baseline — grandfathered findings per "
            "file::scope::rule. Regenerate with "
            "`python tools/check_static.py --update-baseline`; "
            "new findings above these counts fail check_static, "
            "and counts that drop must be ratcheted down here."),
        "version": 1,
        "counts": dict(sorted(counts_of(findings).items())),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=False)
        fh.write("\n")


def compare_baseline(findings: Sequence[Finding], baseline: Dict[str, int]
                     ) -> Tuple[List[Finding], List[Tuple[str, int, int]]]:
    """-> (new_findings, stale_entries).

    new_findings: findings in keys whose count exceeds the baseline
    (the whole key's findings are reported so the offender is visible).
    stale_entries: (key, baseline_count, actual_count) where the actual
    count dropped below the baseline — ratchet the baseline down.
    """
    actual = counts_of(findings)
    new: List[Finding] = []
    for key, n in sorted(actual.items()):
        allowed = baseline.get(key, 0)
        if n > allowed:
            new.extend(sorted((f for f in findings if f.key == key),
                              key=lambda f: f.line))
    stale = [(key, allowed, actual.get(key, 0))
             for key, allowed in sorted(baseline.items())
             if actual.get(key, 0) < allowed]
    return new, stale


# ---------------------------------------------------------------------------
# comments: suppressions + annotations
# ---------------------------------------------------------------------------

def suppress_regex(tool: str) -> re.Pattern:
    """The ``# <tool>: ok <rules>`` suppression-comment pattern."""
    return re.compile(rf"{tool}:\s*ok\s+([\w,\- ]+)")


def parse_suppressions(src: str, tool: str) -> Dict[int, Set[str]]:
    """line -> set of suppressed rule names (or {"all"})."""
    out: Dict[int, Set[str]] = {}
    rx = suppress_regex(tool)
    for i, line in enumerate(src.splitlines(), start=1):
        m = rx.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",")
                      if r.strip()}
    return out


def line_comments(src: str, regex: re.Pattern) -> Dict[int, str]:
    """line -> first capture group of ``regex`` on that line."""
    out: Dict[int, str] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = regex.search(line)
        if m:
            out[i] = m.group(1)
    return out


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------

def call_parts(func: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """('np', 'asarray') for np.asarray(...); (None, 'int') for
    int(...); (None, None) for anything deeper."""
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return func.value.id, func.attr
    if isinstance(func, ast.Name):
        return None, func.id
    return None, None


def self_attr(node: ast.AST) -> Optional[str]:
    """'x' for a ``self.x`` attribute node, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """'os.environ' for the nested attribute chain, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


# ---------------------------------------------------------------------------
# tree walking
# ---------------------------------------------------------------------------

def iter_py_files(root: str, package: str = "pinot_tpu",
                  extra_files: Iterable[str] = ()
                  ) -> Iterator[Tuple[str, str]]:
    """Yield (absolute, repo-relative-posix) for every analyzable .py
    under <root>/<package> (sorted, __pycache__ and *_pb2.py skipped),
    then each existing ``extra_files`` repo-relative path."""
    pkg_dir = os.path.join(root, package)
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py") or fn.endswith("_pb2.py"):
                continue
            full = os.path.join(dirpath, fn)
            yield full, os.path.relpath(full, root).replace(os.sep, "/")
    for rel in extra_files:
        full = os.path.join(root, rel.replace("/", os.sep))
        if os.path.exists(full):
            yield full, rel.replace(os.sep, "/")


def module_qual(path: str) -> str:
    """Collision-free module qualifier ("engine.batch",
    "native.__init__"): bare stems repeat across packages (batch.py,
    __init__.py), and two same-named entities must not merge into one
    graph node."""
    q = path
    if q.startswith("pinot_tpu/"):
        q = q[len("pinot_tpu/"):]
    return os.path.splitext(q)[0].replace("/", ".")


# ---------------------------------------------------------------------------
# the corpus-wide call resolver
# ---------------------------------------------------------------------------

class CallResolver:
    """Whole-program call resolution over fids (module docstring).

    Feed with ``add_module`` (once per module) + ``add_function`` (once
    per class METHOD — bare module functions resolve through the
    module's function-name set and the ``path::name`` fid convention),
    then ``finalize()``, then ``resolve()``.
    """

    def __init__(self):
        self._mod_fns: Dict[str, Set[str]] = {}
        self._cls_paths: Dict[str, List[str]] = {}
        self._class_names: Set[str] = set()
        self._by_method: Dict[str, List[str]] = {}
        self._class_fid: Dict[Tuple[str, str, str], str] = {}
        self._raw_singletons: List[Tuple[str, str]] = []
        self._singleton_cls: Dict[str, str] = {}

    def add_module(self, path: str, function_names: Iterable[str],
                   class_names: Iterable[str],
                   singletons: Dict[str, str]) -> None:
        self._mod_fns[path] = set(function_names)
        for c in class_names:
            self._class_names.add(c)
            self._cls_paths.setdefault(c, []).append(path)
        for name, ctor in singletons.items():
            self._raw_singletons.append((name, ctor))

    def add_function(self, fid: str, path: str, cls_name: str,
                     method_name: str) -> None:
        self._by_method.setdefault(method_name, []).append(fid)
        self._class_fid[(path, cls_name, method_name)] = fid

    def finalize(self) -> None:
        # module-level singleton name -> class, corpus-wide and unique:
        # two same-named singletons of different classes are ambiguous
        # and dropped (refusing beats misresolving)
        dropped: Set[str] = set()
        for name, ctor in self._raw_singletons:
            if ctor not in self._class_names:
                continue
            if name in self._singleton_cls and \
                    self._singleton_cls[name] != ctor:
                dropped.add(name)
            self._singleton_cls[name] = ctor
        for name in dropped:
            self._singleton_cls.pop(name, None)

    def class_method(self, path: str, cls_name: str,
                     method_name: str) -> Optional[str]:
        """fid of an exactly-located class method, or None — for
        callers that resolved (path, class) themselves (detlint's
        imported-class follow-through)."""
        return self._class_fid.get((path, cls_name, method_name))

    def resolve(self, path: str, cls_name: Optional[str], kind: str,
                base: Optional[str], name: str) -> Optional[str]:
        """Resolve one call event to a callee fid, or None. ``kind``
        is "self" | "bare" | "attr" (concur's event vocabulary)."""
        if kind == "self" and cls_name is not None:
            return self._class_fid.get((path, cls_name, name))
        if kind == "bare":
            if name in self._mod_fns.get(path, ()):
                return f"{path}::{name}"
            return None
        if kind == "attr" and base is not None:
            cls = self._singleton_cls.get(base)
            if cls is not None:
                paths = self._cls_paths.get(cls, [])
                if len(paths) != 1:
                    return None   # ambiguous class name: refuse
                return self._class_fid.get((paths[0], cls, name))
            fids = self._by_method.get(name, [])
            if len(fids) == 1:
                return fids[0]
        return None
