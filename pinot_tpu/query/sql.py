"""SQL subset compiler: text -> AST.

Reference parity: pinot-common/.../sql/parsers/CalciteSqlParser
.compileToPinotQuery (used at BaseSingleStageBrokerRequestHandler.java:256)
compiles SQL to the PinotQuery thrift IR. We hand-roll a tokenizer +
recursive-descent parser for the OLAP subset (no Calcite in a TPU-native
stack): SELECT projections/aggregations, WHERE with AND/OR/NOT,
comparisons, BETWEEN, IN, LIKE, IS [NOT] NULL, GROUP BY, HAVING,
ORDER BY ... ASC|DESC, LIMIT/OFFSET, arithmetic expressions, aliases,
window functions (fn(...) OVER (PARTITION BY ... ORDER BY ... [frame])),
set operations (UNION/INTERSECT/EXCEPT [ALL], INTERSECT binds tighter),
and subqueries (expr [NOT] IN (SELECT ...), scalar (SELECT ...)).

Grammar (precedence climbing for booleans and arithmetic):
    query      := SELECT selectList FROM ident [WHERE orExpr]
                  [GROUP BY exprList] [HAVING orExpr]
                  [ORDER BY orderList] [LIMIT n [OFFSET n] | LIMIT o, n]
    orExpr     := andExpr (OR andExpr)*
    andExpr    := notExpr (AND notExpr)*
    notExpr    := NOT notExpr | predicate
    predicate  := addExpr ((=|!=|<>|<|<=|>|>=) addExpr
                 | [NOT] BETWEEN addExpr AND addExpr
                 | [NOT] IN '(' literalList ')'
                 | [NOT] LIKE string
                 | IS [NOT] NULL)?
                 | '(' orExpr ')'
    addExpr    := mulExpr ((+|-) mulExpr)*
    mulExpr    := unary ((*|/|%) unary)*
    unary      := [-] atom
    atom       := literal | ident | ident '(' [DISTINCT] args ')' | '(' addExpr ')' | '*'
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple, Union


class SqlError(ValueError):
    pass


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Identifier:
    name: str


@dataclass(frozen=True)
class Literal:
    value: Any  # int | float | str | bool | None


@dataclass(frozen=True)
class Star:
    pass


@dataclass(frozen=True)
class FuncCall:
    name: str  # lowercased
    args: Tuple[Any, ...]
    distinct: bool = False


@dataclass(frozen=True)
class BinaryOp:
    op: str
    lhs: Any
    rhs: Any


@dataclass(frozen=True)
class Comparison:
    op: str  # == != < <= > >=
    lhs: Any
    rhs: Any


@dataclass(frozen=True)
class Between:
    expr: Any
    lo: Any
    hi: Any
    negated: bool = False


@dataclass(frozen=True)
class InList:
    expr: Any
    values: Tuple[Any, ...]
    negated: bool = False


@dataclass(frozen=True)
class Like:
    expr: Any
    pattern: str
    negated: bool = False


@dataclass(frozen=True)
class IsNull:
    expr: Any
    negated: bool = False


@dataclass(frozen=True)
class CaseWhen:
    whens: Tuple[Tuple[Any, Any], ...]  # (condition, result) pairs
    else_: Any = None


@dataclass(frozen=True)
class Cast:
    expr: Any
    type_name: str  # lowercased target type


@dataclass(frozen=True)
class BoolAnd:
    children: Tuple[Any, ...]


@dataclass(frozen=True)
class BoolOr:
    children: Tuple[Any, ...]


@dataclass(frozen=True)
class BoolNot:
    child: Any


@dataclass(frozen=True)
class WindowSpec:
    """OVER (...) clause: partitioning, intra-partition order, frame.

    frame is None (default: whole partition without ORDER BY, RANGE
    UNBOUNDED PRECEDING..CURRENT ROW with) or ("rows", lo, hi) where
    lo/hi are int offsets relative to the current row and None means
    unbounded on that side — the subset Pinot's WindowNode supports
    (pinot-query-planner WindowNode / runtime/operator/window/)."""
    partition_by: Tuple[Any, ...] = ()
    order_by: Tuple["OrderItem", ...] = ()
    frame: Optional[Tuple[str, Optional[int], Optional[int]]] = None


@dataclass(frozen=True)
class WindowFunc:
    func: "FuncCall"
    spec: WindowSpec


@dataclass(frozen=True)
class InSubquery:
    """expr [NOT] IN (SELECT ...) — broker evaluates the subquery first and
    rewrites to InList (IN_SUBQUERY / IdSet rewrite analog)."""
    expr: Any
    stmt: Any  # SelectStmt
    negated: bool = False


@dataclass(frozen=True)
class ScalarSubquery:
    """(SELECT ...) used as a value; must reduce to one row, one column."""
    stmt: Any


@dataclass(frozen=True)
class Exists:
    """EXISTS (SELECT ...). NOT EXISTS arrives as BoolNot(Exists(...)).
    The broker resolves it before planning: uncorrelated -> run with
    LIMIT 1 and fold to a constant predicate; equality-correlated ->
    decorrelate into the IN-subquery (IdSet) machinery. Reference:
    Calcite's SubQueryRemoveRule semi-join rewrite behind
    QueryEnvironment.java:126."""
    stmt: Any  # SelectStmt


@dataclass(frozen=True)
class SelectItem:
    expr: Any
    alias: Optional[str] = None


@dataclass(frozen=True)
class OrderItem:
    expr: Any
    ascending: bool = True


@dataclass(frozen=True)
class TableRef:
    name: str
    alias: Optional[str] = None

    @property
    def label(self) -> str:
        return self.alias or self.name


@dataclass(frozen=True)
class JoinClause:
    table: TableRef
    on: Any                 # boolean expression over qualified identifiers
    join_type: str = "inner"  # inner | left


@dataclass
class SelectStmt:
    select: List[SelectItem]
    table: str
    distinct: bool = False
    table_alias: Optional[str] = None
    joins: List[JoinClause] = field(default_factory=list)
    where: Optional[Any] = None
    group_by: List[Any] = field(default_factory=list)
    having: Optional[Any] = None
    order_by: List[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0
    options: dict = field(default_factory=dict)
    explain: bool = False
    # EXPLAIN ANALYZE: execute for real and render the span tree
    # (utils/spans.py) instead of the static operator tree
    analyze: bool = False
    # WITH name [(cols)] AS (stmt), ... — materialized by the broker
    # before the main statement runs (QueryEnvironment.java:126 CTE
    # support analog)
    ctes: List["CteDef"] = field(default_factory=list)


@dataclass
class CteDef:
    name: str
    columns: Optional[List[str]]   # optional column alias list
    stmt: Any                      # SelectStmt | SetOpStmt


@dataclass
class DdlStmt:
    """CREATE [OR REPLACE] VIEW name AS <select> | DROP VIEW [IF EXISTS]
    name. Views are named stored queries the broker expands into CTEs at
    reference time (QueryEnvironment.java:126 view catalog analog)."""
    kind: str                      # "create_view" | "drop_view"
    name: str
    stmt: Any = None               # the view body (create only)
    or_replace: bool = False
    if_exists: bool = False


@dataclass
class SetOpStmt:
    """Compound query: left (UNION|INTERSECT|EXCEPT) [ALL] right, with
    compound-level ORDER BY / LIMIT. Mirrors the v2 engine's set
    operators (pinot-query-runtime/.../runtime/operator/set/)."""
    op: str               # union | intersect | except
    all: bool
    left: Any             # SelectStmt | SetOpStmt
    right: Any
    order_by: List[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0
    options: dict = field(default_factory=dict)
    explain: bool = False
    analyze: bool = False
    ctes: List[CteDef] = field(default_factory=list)


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
    | (?P<string>'(?:[^']|'')*')
    | (?P<dqident>"(?:[^"]|"")*")
    | (?P<ident>[$A-Za-z_][A-Za-z_0-9$.]*)
    | (?P<op><=|>=|!=|<>|=|<|>|\(|\)|\[|\]|,|\*|\+|-|/|%|;)
    )""", re.VERBOSE)

# functions whose call arguments may be boolean predicates (funnel step
# expressions; STEPS(...) is the nested wrapper inside FUNNELCOUNT)
_BOOL_ARG_FUNCS = {"funnelcount", "funnelmaxstep", "funnelmatchstep",
                   "funnelcompletecount", "steps"}

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "and", "or", "not", "between", "in", "like", "is", "null",
    "as", "asc", "desc", "distinct", "true", "false", "option",
    "join", "on", "left", "right", "inner", "outer", "cross", "full",
    "explain",  # 'plan'/'for' stay contextual: valid column names elsewhere
    "case", "when", "then", "else", "end", "cast",
    # 'exists' stays contextual (a valid column name); predicate() only
    # treats it as EXISTS(...) when immediately followed by '('
    "over", "partition", "union", "intersect", "except", "all",
    # frame keywords (rows/range/unbounded/preceding/following/current)
    # stay contextual: they are common column names
}


@dataclass
class Token:
    kind: str  # number|string|ident|op|kw|eof
    value: Any
    pos: int


def tokenize(sql: str) -> List[Token]:
    tokens: List[Token] = []
    pos = 0
    while pos < len(sql):
        if sql[pos].isspace():
            pos += 1
            continue
        m = _TOKEN_RE.match(sql, pos)
        if not m or m.end() == pos:
            raise SqlError(f"cannot tokenize at {pos}: {sql[pos:pos+20]!r}")
        if m.group("number") is not None:
            txt = m.group("number")
            val = float(txt) if ("." in txt or "e" in txt or "E" in txt) \
                else int(txt)
            tokens.append(Token("number", val, pos))
        elif m.group("string") is not None:
            s = m.group("string")[1:-1].replace("''", "'")
            tokens.append(Token("string", s, pos))
        elif m.group("dqident") is not None:
            s = m.group("dqident")[1:-1].replace('""', '"')
            tokens.append(Token("ident", s, pos))
        elif m.group("ident") is not None:
            txt = m.group("ident")
            if txt.lower() in KEYWORDS:
                tokens.append(Token("kw", txt.lower(), pos))
            else:
                tokens.append(Token("ident", txt, pos))
        else:
            tokens.append(Token("op", m.group("op"), pos))
        pos = m.end()
    tokens.append(Token("eof", None, pos))
    return tokens


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------

class _Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.tokens = tokenize(sql)
        self.i = 0

    # -- token helpers -----------------------------------------------------
    def peek(self) -> Token:
        return self.tokens[self.i]

    def next(self) -> Token:
        t = self.tokens[self.i]
        self.i += 1
        return t

    def accept_kw(self, *kws: str) -> Optional[str]:
        t = self.peek()
        if t.kind == "kw" and t.value in kws:
            self.i += 1
            return t.value
        return None

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise SqlError(f"expected {kw.upper()} at {self.peek().pos} "
                           f"in {self.sql!r}")

    def accept_op(self, *ops: str) -> Optional[str]:
        t = self.peek()
        if t.kind == "op" and t.value in ops:
            self.i += 1
            return t.value
        return None

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise SqlError(f"expected {op!r} at {self.peek().pos} "
                           f"in {self.sql!r}")

    # -- grammar -----------------------------------------------------------
    def parse(self) -> Union[SelectStmt, "SetOpStmt", DdlStmt]:
        ddl = self._view_ddl()
        if ddl is not None:
            self.accept_op(";")
            if self.peek().kind != "eof":
                t = self.peek()
                raise SqlError(
                    f"unexpected trailing token {t.value!r} at {t.pos}")
            return ddl
        explain = analyze = False
        if self.accept_kw("explain"):
            # contextual: EXPLAIN [PLAN FOR | ANALYZE] SELECT ...
            t = self.peek()
            if t.kind == "ident" and t.value.lower() == "plan":
                self.next()
                t2 = self.next()
                if not (t2.kind == "ident" and t2.value.lower() == "for"):
                    raise SqlError(f"expected FOR after EXPLAIN PLAN "
                                   f"at {t2.pos}")
                explain = True
            elif t.kind == "ident" and t.value.lower() == "analyze":
                self.next()
                analyze = True  # executes the query; renders the span tree
            else:
                explain = True
        ctes = self._with_clause()
        stmt = self.compound()
        stmt.ctes = ctes
        self.accept_op(";")
        if self.peek().kind != "eof":
            t = self.peek()
            raise SqlError(f"unexpected trailing token {t.value!r} at {t.pos}")
        stmt.explain = explain
        stmt.analyze = analyze
        return stmt

    def _view_ddl(self) -> Optional[DdlStmt]:
        """'create'/'drop' stay contextual column names; only the
        statement-head position treats them as DDL (the 'with' trick)."""
        t = self.peek()
        word = str(t.value).lower() if t.kind == "ident" else ""
        if word == "create":
            save = self.i
            self.next()
            or_replace = False
            nt = self.peek()
            if nt.kind == "kw" and nt.value == "or":
                self.next()
                rt = self.next()
                if not (rt.kind == "ident"
                        and str(rt.value).lower() == "replace"):
                    raise SqlError(f"expected REPLACE at {rt.pos}")
                or_replace = True
            vt = self.peek()
            if not (vt.kind == "ident"
                    and str(vt.value).lower() == "view"):
                if or_replace:
                    raise SqlError(f"expected VIEW at {vt.pos}")
                self.i = save       # CREATE <something else>: not ours
                return None
            self.next()
            name_t = self.next()
            if name_t.kind != "ident":
                raise SqlError(f"expected view name at {name_t.pos}")
            self.expect_kw("as")
            ctes = self._with_clause()
            body = self.compound()
            body.ctes = ctes
            return DdlStmt("create_view", name_t.value, body,
                           or_replace=or_replace)
        if word == "drop":
            save = self.i
            self.next()
            vt = self.peek()
            if not (vt.kind == "ident"
                    and str(vt.value).lower() == "view"):
                self.i = save
                return None
            self.next()
            if_exists = False
            it = self.peek()
            nx = self.tokens[self.i + 1] if it.kind != "eof" else it
            # commit to IF EXISTS only on the two-token form, so a view
            # actually NAMED "if" can still be dropped
            if it.kind == "ident" and str(it.value).lower() == "if" \
                    and nx.kind == "ident" \
                    and str(nx.value).lower() == "exists":
                self.next()
                self.next()
                if_exists = True
            name_t = self.next()
            if name_t.kind != "ident":
                raise SqlError(f"expected view name at {name_t.pos}")
            return DdlStmt("drop_view", name_t.value, if_exists=if_exists)
        return None

    def _with_clause(self) -> List[CteDef]:
        """WITH name [(col, ...)] AS ( stmt ) [, ...] — 'with' stays
        contextual (a valid column name elsewhere); only the statement
        head position treats it as a keyword."""
        t = self.peek()
        if not (t.kind == "ident" and str(t.value).lower() == "with"):
            return []
        self.next()
        out: List[CteDef] = []
        while True:
            nt = self.next()
            if nt.kind != "ident":
                raise SqlError(f"expected CTE name at {nt.pos}")
            cols = None
            if self.accept_op("("):
                cols = []
                while True:
                    c = self.next()
                    if c.kind != "ident":
                        raise SqlError(f"expected CTE column at {c.pos}")
                    cols.append(str(c.value))
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            self.expect_kw("as")
            self.expect_op("(")
            sub = self.compound()
            self.expect_op(")")
            out.append(CteDef(str(nt.value), cols, sub))
            if not self.accept_op(","):
                return out

    def compound(self) -> Union[SelectStmt, "SetOpStmt"]:
        """select_core ((UNION|EXCEPT) [ALL] select_core)* with INTERSECT
        binding tighter, then compound-level ORDER BY/LIMIT/OPTION; a lone
        select keeps its trailing clauses on the SelectStmt itself."""
        left = self.intersect_term()
        while True:
            op = self.accept_kw("union", "except")
            if op is None:
                break
            all_ = bool(self.accept_kw("all"))
            self.accept_kw("distinct")
            left = SetOpStmt(op, all_, left, self.intersect_term())
        self.trailing_clauses(left)
        return left

    def intersect_term(self) -> Union[SelectStmt, "SetOpStmt"]:
        left = self.select_core()
        while self.accept_kw("intersect"):
            all_ = bool(self.accept_kw("all"))
            self.accept_kw("distinct")
            left = SetOpStmt("intersect", all_, left, self.select_core())
        return left

    def trailing_clauses(self, stmt) -> None:
        if self.accept_kw("order"):
            self.expect_kw("by")
            stmt.order_by = self.order_list()
        if self.accept_kw("limit"):
            n = self.next()
            if n.kind != "number":
                raise SqlError(f"expected LIMIT count at {n.pos}")
            if self.accept_op(","):
                n2 = self.next()  # LIMIT offset, count (MySQL style)
                stmt.offset, stmt.limit = int(n.value), int(n2.value)
            else:
                stmt.limit = int(n.value)
                if self.accept_kw("offset"):
                    n2 = self.next()
                    stmt.offset = int(n2.value)
        if self.accept_kw("option"):
            # OPTION(k=v, ...) — query options (QueryOptionsUtils analog)
            self.expect_op("(")
            while True:
                k = self.next()
                self.expect_op("=")
                v = self.next()
                stmt.options[str(k.value)] = v.value
                if not self.accept_op(","):
                    break
            self.expect_op(")")

    def select_core(self) -> SelectStmt:
        self.expect_kw("select")
        distinct = bool(self.accept_kw("distinct"))
        select = self.select_list()
        self.expect_kw("from")
        base = self.table_ref()
        stmt = SelectStmt(select=select, table=base.name, distinct=distinct,
                          table_alias=base.alias)
        while True:
            jt = None
            if self.accept_kw("join"):
                jt = "inner"
            elif self.accept_kw("inner"):
                self.expect_kw("join")
                jt = "inner"
            elif self.accept_kw("left"):
                self.accept_kw("outer")
                self.expect_kw("join")
                jt = "left"
            elif self.accept_kw("right"):
                self.accept_kw("outer")
                self.expect_kw("join")
                jt = "right"
            elif self.accept_kw("full"):
                self.accept_kw("outer")
                self.expect_kw("join")
                jt = "full"
            elif self.accept_kw("cross"):
                self.expect_kw("join")
                jt = "cross"
            if jt is None:
                break
            tref = self.table_ref()
            if jt == "cross":
                cond = None             # cartesian product: no ON clause
            else:
                self.expect_kw("on")
                cond = self.or_expr()
            stmt.joins.append(JoinClause(tref, cond, jt))
        if self.accept_kw("where"):
            stmt.where = self.or_expr()
        if self.accept_kw("group"):
            self.expect_kw("by")
            stmt.group_by = self.expr_list()
        if self.accept_kw("having"):
            stmt.having = self.or_expr()
        return stmt

    def table_ref(self) -> TableRef:
        t = self.next()
        if t.kind != "ident":
            raise SqlError(f"expected table name at {t.pos}")
        alias = None
        if self.accept_kw("as"):
            alias = str(self.next().value)
        elif self.peek().kind == "ident":
            alias = str(self.next().value)
        return TableRef(t.value, alias)

    def select_list(self) -> List[SelectItem]:
        items = [self.select_item()]
        while self.accept_op(","):
            items.append(self.select_item())
        return items

    def select_item(self) -> SelectItem:
        if self.peek().kind == "op" and self.peek().value == "*":
            self.next()
            return SelectItem(Star())
        expr = self.add_expr()
        alias = None
        if self.accept_kw("as"):
            t = self.next()
            alias = str(t.value)
        elif self.peek().kind == "ident":
            alias = str(self.next().value)
        return SelectItem(expr, alias)

    def expr_list(self) -> List[Any]:
        out = [self.add_expr()]
        while self.accept_op(","):
            out.append(self.add_expr())
        return out

    def order_list(self) -> List[OrderItem]:
        out = []
        while True:
            e = self.add_expr()
            asc = True
            if self.accept_kw("desc"):
                asc = False
            else:
                self.accept_kw("asc")
            out.append(OrderItem(e, asc))
            if not self.accept_op(","):
                return out

    # boolean layer
    def or_expr(self) -> Any:
        children = [self.and_expr()]
        while self.accept_kw("or"):
            children.append(self.and_expr())
        return children[0] if len(children) == 1 else BoolOr(tuple(children))

    def and_expr(self) -> Any:
        children = [self.not_expr()]
        while self.accept_kw("and"):
            children.append(self.not_expr())
        return children[0] if len(children) == 1 else BoolAnd(tuple(children))

    def not_expr(self) -> Any:
        if self.accept_kw("not"):
            return BoolNot(self.not_expr())
        return self.predicate()

    def predicate(self) -> Any:
        t = self.peek()
        if t.kind == "ident" and t.value.lower() == "exists" \
                and self.tokens[self.i + 1].kind == "op" \
                and self.tokens[self.i + 1].value == "(":
            self.next()
            self.expect_op("(")
            sub = self.select_core()
            self.trailing_clauses(sub)
            self.expect_op(")")
            return Exists(sub)
        # parenthesized boolean vs parenthesized arithmetic: try boolean
        if self.peek().kind == "op" and self.peek().value == "(":
            save = self.i
            self.next()
            try:
                inner = self.or_expr()
                self.expect_op(")")
                if isinstance(inner, (BoolAnd, BoolOr, BoolNot, Comparison,
                                      Between, InList, Like, IsNull,
                                      Exists)):
                    return inner
                # plain value in parens: fall through to comparison tail
                return self.predicate_tail(inner)
            except SqlError:
                self.i = save
        lhs = self.add_expr()
        return self.predicate_tail(lhs)

    def predicate_tail(self, lhs: Any) -> Any:
        op = self.accept_op("=", "!=", "<>", "<", "<=", ">", ">=")
        if op:
            rhs = self.add_expr()
            norm = {"=": "==", "<>": "!="}.get(op, op)
            return Comparison(norm, lhs, rhs)
        negated = bool(self.accept_kw("not"))
        if self.accept_kw("between"):
            lo = self.add_expr()
            self.expect_kw("and")
            hi = self.add_expr()
            return Between(lhs, lo, hi, negated)
        if self.accept_kw("in"):
            self.expect_op("(")
            if self.peek().kind == "kw" and self.peek().value == "select":
                sub = self.select_core()
                self.trailing_clauses(sub)
                self.expect_op(")")
                return InSubquery(lhs, sub, negated)
            vals = [self.literal()]
            while self.accept_op(","):
                vals.append(self.literal())
            self.expect_op(")")
            return InList(lhs, tuple(vals), negated)
        if self.accept_kw("like"):
            t = self.next()
            if t.kind != "string":
                raise SqlError(f"LIKE needs a string pattern at {t.pos}")
            return Like(lhs, t.value, negated)
        if self.accept_kw("is"):
            neg = bool(self.accept_kw("not"))
            self.expect_kw("null")
            return IsNull(lhs, neg)
        if negated:
            raise SqlError(f"dangling NOT at {self.peek().pos}")
        return lhs  # bare expression used as boolean (planner rejects later)

    def literal(self) -> Literal:
        t = self.next()
        if t.kind == "number":
            return Literal(t.value)
        if t.kind == "string":
            return Literal(t.value)
        if t.kind == "kw" and t.value in ("true", "false"):
            return Literal(t.value == "true")
        if t.kind == "kw" and t.value == "null":
            return Literal(None)
        if t.kind == "op" and t.value == "-":
            inner = self.literal()
            return Literal(-inner.value)
        raise SqlError(f"expected literal at {t.pos}")

    # arithmetic layer
    def add_expr(self) -> Any:
        lhs = self.mul_expr()
        while True:
            op = self.accept_op("+", "-")
            if not op:
                return lhs
            lhs = BinaryOp(op, lhs, self.mul_expr())

    def mul_expr(self) -> Any:
        lhs = self.unary()
        while True:
            op = self.accept_op("*", "/", "%")
            if not op:
                return lhs
            lhs = BinaryOp(op, lhs, self.unary())

    def unary(self) -> Any:
        if self.accept_op("-"):
            inner = self.unary()
            if isinstance(inner, Literal):
                return Literal(-inner.value)
            return BinaryOp("-", Literal(0), inner)
        return self.atom()

    def atom(self) -> Any:
        t = self.next()
        if t.kind == "number":
            return Literal(t.value)
        if t.kind == "string":
            return Literal(t.value)
        if t.kind == "kw" and t.value in ("true", "false"):
            return Literal(t.value == "true")
        if t.kind == "kw" and t.value == "null":
            return Literal(None)
        if t.kind == "kw" and t.value == "case":
            return self.case_expr()
        if t.kind == "kw" and t.value == "cast":
            # CAST(expr AS type)
            self.expect_op("(")
            inner = self.add_expr()
            self.expect_kw("as")
            tt = self.next()
            if tt.kind not in ("ident", "kw"):
                raise SqlError(f"expected type name at {tt.pos}")
            self.expect_op(")")
            return Cast(inner, str(tt.value).lower())
        if t.kind == "op" and t.value == "(":
            if self.peek().kind == "kw" and self.peek().value == "select":
                sub = self.select_core()
                self.trailing_clauses(sub)
                self.expect_op(")")
                return ScalarSubquery(sub)
            e = self.add_expr()
            self.expect_op(")")
            return e
        if t.kind == "op" and t.value == "*":
            return Star()
        if t.kind == "ident" and t.value.lower() == "array" \
                and self.peek().kind == "op" and self.peek().value == "[":
            # ARRAY[1.0, 2.0, ...] literal (vector queries); elements must
            # be numeric literals
            self.next()
            vals: List[Any] = []
            if not (self.peek().kind == "op" and self.peek().value == "]"):
                vals.append(self.literal().value)
                while self.accept_op(","):
                    vals.append(self.literal().value)
            self.expect_op("]")
            return Literal(tuple(vals))
        if t.kind == "ident":
            if self.peek().kind == "op" and self.peek().value == "(":
                self.next()
                distinct = bool(self.accept_kw("distinct"))
                # the funnel family takes boolean step predicates as
                # arguments (FunnelBaseAggregationFunction: stepExpression
                # args) — parse those args with the boolean grammar
                argp = self.or_expr if t.value.lower() in _BOOL_ARG_FUNCS \
                    else self.add_expr
                args: List[Any] = []
                if not (self.peek().kind == "op" and self.peek().value == ")"):
                    if self.peek().kind == "op" and self.peek().value == "*":
                        self.next()
                        args.append(Star())
                    else:
                        args.append(argp())
                    while self.accept_op(","):
                        args.append(argp())
                self.expect_op(")")
                fc = FuncCall(t.value.lower(), tuple(args), distinct)
                if self.accept_kw("over"):
                    return WindowFunc(fc, self.window_spec())
                return fc
            return Identifier(t.value)
        raise SqlError(f"unexpected token {t.value!r} at {t.pos}")

    def window_spec(self) -> WindowSpec:
        """OVER ( [PARTITION BY exprs] [ORDER BY order] [frame] ). Frame
        keywords (ROWS/RANGE/UNBOUNDED/PRECEDING/FOLLOWING/CURRENT/ROW)
        are contextual identifiers — they stay valid column names."""
        self.expect_op("(")
        partition: List[Any] = []
        order: List[OrderItem] = []
        frame = None
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition = self.expr_list()
        if self.accept_kw("order"):
            self.expect_kw("by")
            order = self.order_list()
        t = self.peek()
        if t.kind == "ident" and t.value.lower() in ("rows", "range"):
            mode = self.next().value.lower()
            frame = self._frame(mode)
        self.expect_op(")")
        return WindowSpec(tuple(partition), tuple(order), frame)

    def _frame(self, mode: str) -> Tuple[str, Optional[int], Optional[int]]:
        def ctx_ident(*words: str) -> str:
            t = self.next()
            w = str(t.value).lower() if t.kind in ("ident", "kw") else ""
            if w not in words:
                raise SqlError(f"expected {'|'.join(words).upper()} "
                               f"at {t.pos}")
            return w

        def bound(is_lo: bool) -> Optional[int]:
            t = self.peek()
            if t.kind == "ident" and t.value.lower() == "unbounded":
                self.next()
                side = ctx_ident("preceding", "following")
                if side == ("preceding" if is_lo else "following"):
                    return None
                raise SqlError(f"UNBOUNDED {side.upper()} on the "
                               f"{'lower' if is_lo else 'upper'} bound")
            if t.kind == "ident" and t.value.lower() == "current":
                self.next()
                ctx_ident("row")
                return 0
            if t.kind == "number":
                raw = self.next().value
                # ROWS offsets are row counts (ints); RANGE offsets are
                # values on the ORDER BY key and may be fractional
                n = (float(raw) if mode == "range" and "." in str(raw)
                     else int(raw))
                side = ctx_ident("preceding", "following")
                return -n if side == "preceding" else n
            raise SqlError(f"expected frame bound at {t.pos}")

        if self.accept_kw("between"):
            lo = bound(True)
            self.expect_kw("and")
            hi = bound(False)
        else:
            lo, hi = bound(True), 0
        return (mode, lo, hi)

    def case_expr(self) -> CaseWhen:
        """CASE [operand] WHEN cond THEN val ... [ELSE val] END.

        The simple form (CASE x WHEN v THEN ...) desugars into the searched
        form with equality conditions, which is how Calcite normalizes it."""
        operand = None
        if not (self.peek().kind == "kw" and self.peek().value == "when"):
            operand = self.add_expr()
        whens = []
        while self.accept_kw("when"):
            cond = self.or_expr() if operand is None else \
                Comparison("==", operand, self.add_expr())
            self.expect_kw("then")
            whens.append((cond, self.add_expr()))
        if not whens:
            raise SqlError(f"CASE needs at least one WHEN at "
                           f"{self.peek().pos}")
        else_ = None
        if self.accept_kw("else"):
            else_ = self.add_expr()
        self.expect_kw("end")
        return CaseWhen(tuple(whens), else_)


def ast_children(e: Any) -> Tuple[Any, ...]:
    """Immediate sub-expressions of any AST node (generic walker support)."""
    if isinstance(e, FuncCall):
        return e.args
    if isinstance(e, (BinaryOp, Comparison)):
        return (e.lhs, e.rhs)
    if isinstance(e, (BoolAnd, BoolOr)):
        return e.children
    if isinstance(e, BoolNot):
        return (e.child,)
    if isinstance(e, Between):
        return (e.expr, e.lo, e.hi)
    if isinstance(e, (InList, Like, IsNull)):
        return (e.expr,)
    if isinstance(e, CaseWhen):
        out = [x for w in e.whens for x in w]
        if e.else_ is not None:
            out.append(e.else_)
        return tuple(out)
    if isinstance(e, Cast):
        return (e.expr,)
    if isinstance(e, WindowFunc):
        return (e.func.args + e.spec.partition_by
                + tuple(o.expr for o in e.spec.order_by))
    if isinstance(e, InSubquery):
        return (e.expr,)
    return ()


def map_expr(e: Any, fn) -> Any:
    """Bottom-up AST rewrite: rebuild each node from transformed children,
    then apply fn to the rebuilt node. fn returns the (possibly replaced)
    node."""
    if isinstance(e, FuncCall):
        e = FuncCall(e.name, tuple(map_expr(a, fn) for a in e.args),
                     e.distinct)
    elif isinstance(e, BinaryOp):
        e = BinaryOp(e.op, map_expr(e.lhs, fn), map_expr(e.rhs, fn))
    elif isinstance(e, Comparison):
        e = Comparison(e.op, map_expr(e.lhs, fn), map_expr(e.rhs, fn))
    elif isinstance(e, BoolAnd):
        e = BoolAnd(tuple(map_expr(c, fn) for c in e.children))
    elif isinstance(e, BoolOr):
        e = BoolOr(tuple(map_expr(c, fn) for c in e.children))
    elif isinstance(e, BoolNot):
        e = BoolNot(map_expr(e.child, fn))
    elif isinstance(e, Between):
        e = Between(map_expr(e.expr, fn), map_expr(e.lo, fn),
                    map_expr(e.hi, fn), e.negated)
    elif isinstance(e, InList):
        e = InList(map_expr(e.expr, fn), e.values, e.negated)
    elif isinstance(e, Like):
        e = Like(map_expr(e.expr, fn), e.pattern, e.negated)
    elif isinstance(e, IsNull):
        e = IsNull(map_expr(e.expr, fn), e.negated)
    elif isinstance(e, CaseWhen):
        e = CaseWhen(tuple((map_expr(c, fn), map_expr(v, fn))
                           for c, v in e.whens),
                     map_expr(e.else_, fn) if e.else_ is not None else None)
    elif isinstance(e, Cast):
        e = Cast(map_expr(e.expr, fn), e.type_name)
    elif isinstance(e, WindowFunc):
        e = WindowFunc(
            map_expr(e.func, fn),
            WindowSpec(tuple(map_expr(p, fn) for p in e.spec.partition_by),
                       tuple(OrderItem(map_expr(o.expr, fn), o.ascending)
                             for o in e.spec.order_by), e.spec.frame))
    elif isinstance(e, InSubquery):
        e = InSubquery(map_expr(e.expr, fn), e.stmt, e.negated)
    return fn(e)


def collect_identifiers(e: Any, out: Optional[set] = None) -> set:
    if out is None:
        out = set()
    if isinstance(e, Identifier):
        out.add(e.name)
    for c in ast_children(e):
        collect_identifiers(c, out)
    return out


def _sql_literal(v: Any) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    if isinstance(v, tuple):
        return "ARRAY[" + ", ".join(_sql_literal(x) for x in v) + "]"
    return repr(v)


def expr_to_sql(e: Any) -> str:
    """Render an expression AST back to SQL text (round-trips through
    parse_sql). Used by the cluster broker to dispatch sub-statements
    (set-op branches, subqueries) over the wire as SQL."""
    if isinstance(e, Identifier):
        return e.name
    if isinstance(e, Literal):
        return _sql_literal(e.value)
    if isinstance(e, Star):
        return "*"
    if isinstance(e, FuncCall):
        d = "DISTINCT " if e.distinct else ""
        return f"{e.name}({d}{', '.join(expr_to_sql(a) for a in e.args)})"
    if isinstance(e, BinaryOp):
        return f"({expr_to_sql(e.lhs)} {e.op} {expr_to_sql(e.rhs)})"
    if isinstance(e, Comparison):
        op = {"==": "="}.get(e.op, e.op)
        return f"{expr_to_sql(e.lhs)} {op} {expr_to_sql(e.rhs)}"
    if isinstance(e, Between):
        n = "NOT " if e.negated else ""
        return (f"{expr_to_sql(e.expr)} {n}BETWEEN {expr_to_sql(e.lo)} "
                f"AND {expr_to_sql(e.hi)}")
    if isinstance(e, InList):
        n = "NOT " if e.negated else ""
        vals = ", ".join(_sql_literal(v.value) for v in e.values)
        return f"{expr_to_sql(e.expr)} {n}IN ({vals})"
    if isinstance(e, Like):
        n = "NOT " if e.negated else ""
        return f"{expr_to_sql(e.expr)} {n}LIKE {_sql_literal(e.pattern)}"
    if isinstance(e, IsNull):
        n = "NOT " if e.negated else ""
        return f"{expr_to_sql(e.expr)} IS {n}NULL"
    if isinstance(e, BoolAnd):
        return "(" + " AND ".join(expr_to_sql(c) for c in e.children) + ")"
    if isinstance(e, BoolOr):
        return "(" + " OR ".join(expr_to_sql(c) for c in e.children) + ")"
    if isinstance(e, BoolNot):
        return f"NOT ({expr_to_sql(e.child)})"
    if isinstance(e, CaseWhen):
        parts = ["CASE"]
        for c, v in e.whens:
            parts.append(f"WHEN {expr_to_sql(c)} THEN {expr_to_sql(v)}")
        if e.else_ is not None:
            parts.append(f"ELSE {expr_to_sql(e.else_)}")
        parts.append("END")
        return " ".join(parts)
    if isinstance(e, Cast):
        return f"CAST({expr_to_sql(e.expr)} AS {e.type_name})"
    if isinstance(e, WindowFunc):
        spec = []
        if e.spec.partition_by:
            spec.append("PARTITION BY " + ", ".join(
                expr_to_sql(p) for p in e.spec.partition_by))
        if e.spec.order_by:
            spec.append("ORDER BY " + ", ".join(
                expr_to_sql(o.expr) + ("" if o.ascending else " DESC")
                for o in e.spec.order_by))
        if e.spec.frame is not None:
            mode, lo, hi = e.spec.frame

            def bound(b, is_lo):
                if b is None:
                    return ("UNBOUNDED PRECEDING" if is_lo
                            else "UNBOUNDED FOLLOWING")
                if b == 0:
                    return "CURRENT ROW"
                return (f"{-b} PRECEDING" if b < 0 else f"{b} FOLLOWING")
            spec.append(f"{mode.upper()} BETWEEN {bound(lo, True)} "
                        f"AND {bound(hi, False)}")
        return f"{expr_to_sql(e.func)} OVER ({' '.join(spec)})"
    if isinstance(e, InSubquery):
        n = "NOT " if e.negated else ""
        return f"{expr_to_sql(e.expr)} {n}IN ({to_sql(e.stmt)})"
    if isinstance(e, ScalarSubquery):
        return f"({to_sql(e.stmt)})"
    if isinstance(e, Exists):
        return f"EXISTS ({to_sql(e.stmt)})"
    raise SqlError(f"cannot render {type(e).__name__} to SQL")


def to_sql(stmt: Union[SelectStmt, SetOpStmt]) -> str:
    """Render a statement AST back to SQL text."""
    if isinstance(stmt, SetOpStmt):
        op = stmt.op.upper() + (" ALL" if stmt.all else "")
        parts = [f"{to_sql(stmt.left)} {op} {to_sql(stmt.right)}"]
    else:
        sel = []
        for item in stmt.select:
            s = expr_to_sql(item.expr)
            if item.alias:
                s += f' AS "{item.alias}"'
            sel.append(s)
        d = "DISTINCT " if stmt.distinct else ""
        base = stmt.table + (f" AS {stmt.table_alias}"
                             if stmt.table_alias else "")
        parts = [f"SELECT {d}{', '.join(sel)} FROM {base}"]
        for j in stmt.joins:
            jt = "LEFT JOIN" if j.join_type == "left" else "JOIN"
            t = j.table.name + (f" AS {j.table.alias}"
                                if j.table.alias else "")
            parts.append(f"{jt} {t} ON {expr_to_sql(j.on)}")
        if stmt.where is not None:
            parts.append(f"WHERE {expr_to_sql(stmt.where)}")
        if stmt.group_by:
            parts.append("GROUP BY " + ", ".join(
                expr_to_sql(g) for g in stmt.group_by))
        if stmt.having is not None:
            parts.append(f"HAVING {expr_to_sql(stmt.having)}")
    if stmt.order_by:
        parts.append("ORDER BY " + ", ".join(
            expr_to_sql(o.expr) + ("" if o.ascending else " DESC")
            for o in stmt.order_by))
    if stmt.limit is not None:
        parts.append(f"LIMIT {stmt.limit}")
        if stmt.offset:
            parts.append(f"OFFSET {stmt.offset}")
    if stmt.options:
        parts.append("OPTION(" + ", ".join(
            f"{k}={v}" for k, v in stmt.options.items()) + ")")
    return " ".join(parts)


def parse_sql(sql: str) -> Union[SelectStmt, SetOpStmt, DdlStmt]:
    return _Parser(sql).parse()
