from .sql import parse_sql  # noqa: F401
from .context import QueryContext  # noqa: F401
