"""Logical query context: classified, validated query — table-level,
segment-independent.

Reference parity: pinot-core/.../query/request/context/QueryContext (built
by BrokerRequestToQueryContextConverter): holds select expressions,
aggregations, group-by expressions, filter, having, order-by, limit. The
planner (planner.py) lowers this to per-segment kernel plans.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .sql import (Between, BinaryOp, BoolAnd, BoolNot, BoolOr, CaseWhen,
                  Cast, Comparison, FuncCall, Identifier, InList, IsNull,
                  Like, Literal, OrderItem, SelectStmt, SqlError, Star,
                  ast_children, collect_identifiers)

from ..ops.aggregations import AGG_NAME_TO_KIND as AGG_FUNCS  # noqa: F401
from ..ops.aggregations import is_agg_name, resolve_call


@dataclass(frozen=True)
class AggExpr:
    kind: str          # count | sum | ... (ops/aggregations.py registry)
    arg: Any           # value expression AST (None for COUNT(*))
    label: str
    arg2: Any = None   # second value expression (covar, *withtime)
    params: Tuple[Any, ...] = ()  # literal params (percentile p, ...)

    def key(self) -> str:
        return self.label


@dataclass
class GapfillSpec:
    """Broker-reduce time-bucket gapfill (round-4, VERDICT r3 item 7;
    reference: pinot-core/.../query/reduce/GapfillProcessor.java:50).
    Extracted from GAPFILL(timeExpr, start, end, interval,
    FILL(col, 'FILL_PREVIOUS_VALUE'|'FILL_DEFAULT_VALUE')...,
    TIMESERIESON(col, ...)) in the select list."""
    time_label: str
    start: int
    end: int
    interval: int
    fills: Dict[str, str]              # env label -> previous | default
    series_labels: List[str]


@dataclass
class QueryContext:
    table: str
    select_items: List[Any]            # AggExpr | expr AST (group key / selection)
    labels: List[str]                  # output column names in select order
    aggregations: List[AggExpr]
    group_by: List[Any]
    filter: Optional[Any]
    having: Optional[Any]
    order_by: List[OrderItem]
    limit: Optional[int]
    offset: int
    options: dict = field(default_factory=dict)
    gapfill: Optional[GapfillSpec] = None

    @property
    def is_aggregation(self) -> bool:
        return len(self.aggregations) > 0

    @property
    def is_group_by(self) -> bool:
        return len(self.group_by) > 0


def _expr_label(e: Any) -> str:
    if isinstance(e, Identifier):
        return e.name
    if isinstance(e, Literal):
        return repr(e.value)
    if isinstance(e, Star):
        return "*"
    if isinstance(e, FuncCall):
        inner = ",".join(_expr_label(a) for a in e.args)
        d = "distinct " if e.distinct else ""
        return f"{e.name}({d}{inner})"
    if isinstance(e, BinaryOp):
        return f"({_expr_label(e.lhs)}{e.op}{_expr_label(e.rhs)})"
    if isinstance(e, Comparison):
        return f"({_expr_label(e.lhs)}{e.op}{_expr_label(e.rhs)})"
    if isinstance(e, CaseWhen):
        parts = " ".join(f"when {_expr_label(c)} then {_expr_label(v)}"
                         for c, v in e.whens)
        tail = f" else {_expr_label(e.else_)}" if e.else_ is not None else ""
        return f"case {parts}{tail} end"
    if isinstance(e, Cast):
        return f"cast({_expr_label(e.expr)} as {e.type_name})"
    return str(e)


def _find_aggs(e: Any, out: List[FuncCall]) -> None:
    if isinstance(e, FuncCall):
        if is_agg_name(e.name) or (e.name == "count" and e.distinct):
            out.append(e)
            return
    for a in ast_children(e):
        _find_aggs(a, out)


def _extract_gapfill(stmt: SelectStmt
                     ) -> Tuple[SelectStmt, Optional[GapfillSpec]]:
    """Pull a GAPFILL(...) wrapper off the select list, leaving the bare
    time expression in its place (planning stays unchanged; the fill
    happens at broker reduce — GapfillProcessor analog)."""
    from .sql import SelectItem
    spec: Optional[GapfillSpec] = None
    alias_map = {item.alias: _expr_label(item.expr)
                 for item in stmt.select
                 if item.alias and not isinstance(item.expr, Star)}

    def _target_label(e: Any) -> str:
        if isinstance(e, Identifier) and e.name in alias_map:
            return alias_map[e.name]
        return _expr_label(e)

    new_select: List[SelectItem] = []
    for item in stmt.select:
        e = item.expr
        if not (isinstance(e, FuncCall) and e.name == "gapfill"):
            new_select.append(item)
            continue
        if spec is not None:
            raise SqlError("multiple GAPFILL expressions")
        args = e.args
        if len(args) < 4:
            raise SqlError(
                "GAPFILL needs (timeExpr, start, end, interval"
                "[, FILL(col, mode)..., TIMESERIESON(col, ...)])")
        nums = []
        for a, what in zip(args[1:4], ("start", "end", "interval")):
            if not isinstance(a, Literal) or isinstance(a.value, str):
                raise SqlError(f"GAPFILL {what} must be a numeric literal")
            nums.append(int(a.value))
        start, end, interval = nums
        if interval <= 0 or end <= start:
            raise SqlError("GAPFILL needs end > start and interval > 0")
        fills: Dict[str, str] = {}
        series: List[str] = []
        for a in args[4:]:
            if isinstance(a, FuncCall) and a.name == "fill":
                if len(a.args) != 2 or not isinstance(a.args[1], Literal):
                    raise SqlError("FILL needs (column, 'mode')")
                mode = str(a.args[1].value).upper()
                if mode not in ("FILL_PREVIOUS_VALUE",
                                "FILL_DEFAULT_VALUE"):
                    raise SqlError(f"unknown FILL mode {a.args[1].value!r}")
                fills[_target_label(a.args[0])] = \
                    "previous" if mode == "FILL_PREVIOUS_VALUE" \
                    else "default"
            elif isinstance(a, FuncCall) and a.name == "timeserieson":
                if not a.args:
                    raise SqlError("TIMESERIESON needs >= 1 column")
                series = [_target_label(x) for x in a.args]
            else:
                raise SqlError(
                    "GAPFILL extras must be FILL(...) or TIMESERIESON(...)")
        time_expr = args[0]
        spec = GapfillSpec(_expr_label(time_expr), start, end, interval,
                           fills, series)
        new_select.append(SelectItem(time_expr, item.alias))
    if spec is None:
        return stmt, None
    import dataclasses as _dc
    return _dc.replace(stmt, select=new_select), spec


def build_query_context(stmt: SelectStmt) -> QueryContext:
    stmt, gapfill_spec = _extract_gapfill(stmt)
    aggregations: List[AggExpr] = []
    select_items: List[Any] = []
    labels: List[str] = []

    def register_agg(fc: FuncCall) -> AggExpr:
        args = fc.args
        if fc.name == "count" and not fc.distinct and \
                (not args or isinstance(args[0], Star)):
            resolved = ("count", None, None, ())
        else:
            resolved = resolve_call(fc.name, args, fc.distinct)
            if resolved is None:
                raise SqlError(f"unknown aggregation {fc.name!r}")
        kind, arg, arg2, params = resolved
        agg = AggExpr(kind, arg, _expr_label(fc), arg2, params)
        for existing in aggregations:
            if existing == agg:
                return existing
        aggregations.append(agg)
        return agg

    def _resolve_ordinal(e: Any, grouping: bool = False) -> Any:
        """GROUP BY 2 / ORDER BY 2 name the 2nd select item (Calcite
        ordinal scope resolution; SqlToRelConverter)."""
        if isinstance(e, Literal) and isinstance(e.value, int) \
                and not isinstance(e.value, bool) \
                and 1 <= e.value <= len(stmt.select) \
                and not isinstance(stmt.select[e.value - 1].expr, Star):
            target = stmt.select[e.value - 1].expr
            if grouping:
                found: List[FuncCall] = []
                _find_aggs(target, found)
                if found:
                    raise SqlError("aggregate functions are not allowed in "
                                   f"GROUP BY (ordinal {e.value})")
            return target
        return e

    group_by = [_resolve_ordinal(g, grouping=True) for g in stmt.group_by]
    group_labels = {_expr_label(g) for g in group_by}
    import dataclasses as _dc
    stmt = _dc.replace(stmt, order_by=[
        OrderItem(_resolve_ordinal(o.expr), o.ascending)
        for o in stmt.order_by], group_by=group_by)

    for item in stmt.select:
        e = item.expr
        if isinstance(e, Star):
            if stmt.distinct:
                raise SqlError("SELECT DISTINCT * not supported")
            select_items.append(Star())
            labels.append("*")
            continue
        found: List[FuncCall] = []
        _find_aggs(e, found)
        if found:
            if isinstance(e, FuncCall) and e in found:
                agg = register_agg(e)
                select_items.append(agg)
                labels.append(item.alias or agg.label)
            else:
                # post-aggregation expression (PostAggregationHandler
                # analog): register inner aggs, evaluate the expression
                # over finalized values at reduce
                for fc in found:
                    register_agg(fc)
                select_items.append(e)
                labels.append(item.alias or _expr_label(e))
        else:
            select_items.append(e)
            labels.append(item.alias or _expr_label(e))
            if group_by and _expr_label(e) not in group_labels \
                    and not _keys_only(e, group_by):
                # expressions over group keys compute at reduce; anything
                # referencing non-grouped columns is a user error
                raise SqlError(
                    f"non-aggregate select column "
                    f"{_expr_label(e)!r} must appear in GROUP BY")

    # register aggs appearing only in HAVING / ORDER BY so partials exist
    for extra in ([stmt.having] if stmt.having else []) + \
                 [o.expr for o in stmt.order_by]:
        found = []
        _find_aggs(extra, found)
        for fc in found:
            register_agg(fc)

    if aggregations:
        bad = [i for i in select_items
               if not isinstance(i, AggExpr) and not _is_group_key(i, group_by)
               and not _find_aggs_present(i)
               and not _keys_only(i, group_by)]
        if bad:
            raise SqlError(f"selection columns mixed with aggregations: {bad}")

    if stmt.distinct:
        # SELECT DISTINCT a, b == group-by on the select expressions with a
        # hidden aggregation (DistinctOperator analog: the group-by engine
        # IS the distinct engine; dictionary path stays device-resident)
        if aggregations:
            raise SqlError("SELECT DISTINCT with aggregations not supported")
        group_by = list(select_items)
    if group_by and not aggregations:
        # plain GROUP BY / DISTINCT: synthesize a hidden COUNT(*) so every
        # execution path (kernel, host, fast) has a mergeable state; reduce
        # projects only select_items so it never surfaces
        aggregations.append(AggExpr("count", None, "count(*)"))

    # Pinot applies the default LIMIT 10 at compile time
    # (CalciteSqlParser DEFAULT_SELECTION_LIMIT analog); doing the same here
    # bounds per-segment selection materialization, not just the reduce.
    limit = stmt.limit
    if limit is None and not (aggregations and not group_by):
        limit = 10

    # ORDER BY may reference a select alias (Calcite scope resolution);
    # substitute the aliased expression so evaluators see real columns
    alias_exprs = {item.alias: item.expr for item in stmt.select
                   if item.alias and not isinstance(item.expr, Star)}
    order_by = [
        OrderItem(alias_exprs[o.expr.name], o.ascending)
        if isinstance(o.expr, Identifier) and o.expr.name in alias_exprs
        else o for o in stmt.order_by]

    if gapfill_spec is not None and not group_by:
        raise SqlError("GAPFILL requires a GROUP BY over the time bucket")
    return QueryContext(
        table=stmt.table,
        select_items=select_items,
        labels=labels,
        aggregations=aggregations,
        group_by=group_by,
        filter=stmt.where,
        having=stmt.having,
        order_by=order_by,
        limit=limit,
        offset=stmt.offset,
        options=stmt.options,
        gapfill=gapfill_spec,
    )


def _is_group_key(e: Any, group_by: List[Any]) -> bool:
    lbl = _expr_label(e)
    return any(_expr_label(g) == lbl for g in group_by)


def _find_aggs_present(e: Any) -> bool:
    found: List[FuncCall] = []
    _find_aggs(e, found)
    return bool(found)


def _keys_only(e: Any, group_by: List[Any]) -> bool:
    """Expression derivable from the group keys (computable at reduce).

    An expression is covered when it IS a group key (label match), is a
    literal, or every sub-expression is covered. A bare column that is not
    itself a key is NOT covered even if some key mentions it — reduce only
    has key values in scope (SELECT val ... GROUP BY ABS(val) must fail
    here with a clear error, not at reduce)."""
    if not group_by:
        return False
    group_labels = {_expr_label(g) for g in group_by}

    def covered(x: Any) -> bool:
        if _expr_label(x) in group_labels:
            return True
        if isinstance(x, Literal):
            return True
        if isinstance(x, Identifier):
            return False
        kids = ast_children(x)
        return bool(kids) and all(covered(c) for c in kids)

    return covered(e)
