"""Geospatial scalar functions (the ST_* family).

Reference parity: pinot-core/src/main/java/org/apache/pinot/core/
geospatial/transform/function/ — StPointFunction, StDistanceFunction,
StContainsFunction, StWithinFunction, StAreaFunction, StAsTextFunction,
StAsBinaryFunction, StGeogFromTextFunction, StGeomFromTextFunction,
StGeogFromWKBFunction, StGeomFromWKBFunction, StGeometryTypeFunction,
StEqualsFunction, GeoToH3Function — plus ScalarFunctions.java (the
v2-engine scalar mirror). Function NAMES match the reference's SQL
surface (stPoint, stDistance, ..., geoToH3) so queries port verbatim;
geoToH3 returns this framework's grid cell id (geo/cells.py), the drop-in
role H3 ids play in the reference.

Vectorization: columns arrive as object arrays of WKB-hex/WKT; geometry
decoding happens once per array, and point-only arrays collapse to
lng/lat float64 planes so stDistance over a column is one haversine
sweep (no per-row python in the hot path). Dictionary-encoded columns
additionally evaluate once per dictionary value (host_eval's gather).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..geo import cells as _cells
from ..geo import geometry as _geom
from ..geo.geometry import Geometry
from .functions import register
from .sql import SqlError


def _to_geoms(v, geography: Optional[bool] = None) -> List[Geometry]:
    a = np.atleast_1d(np.asarray(v, dtype=object))
    return [_geom.coerce(x, geography) for x in a.ravel()]


def _point_planes(gs: List[Geometry]
                  ) -> Optional[Tuple[np.ndarray, np.ndarray, bool]]:
    """(lng, lat, geography) planes when every geometry is a point."""
    if not all(g is not None and g.kind == "point" for g in gs):
        return None
    lng = np.fromiter((g.lng for g in gs), dtype=np.float64, count=len(gs))
    lat = np.fromiter((g.lat for g in gs), dtype=np.float64, count=len(gs))
    return lng, lat, any(g.geography for g in gs)


def _obj(items) -> np.ndarray:
    out = np.empty(len(items), dtype=object)
    out[:] = items
    return out


@register("stpoint", 2, 3)
def _st_point(x, y, geog=None):
    xs = np.atleast_1d(np.asarray(x, dtype=np.float64))
    ys = np.atleast_1d(np.asarray(y, dtype=np.float64))
    xs, ys = np.broadcast_arrays(xs, ys)
    g = bool(np.atleast_1d(np.asarray(geog))[0]) if geog is not None \
        else False
    return _obj([_geom.to_wkb(Geometry.point(float(a), float(b), g)).hex()
                 for a, b in zip(xs.ravel(), ys.ravel())])


def _from_text(v, geography: bool) -> np.ndarray:
    a = np.atleast_1d(np.asarray(v, dtype=object))
    return _obj([_geom.to_wkb(_geom.parse_wkt(str(t), geography)).hex()
                 for t in a.ravel()])


def _from_wkb(v, geography: bool) -> np.ndarray:
    gs = _to_geoms(v, geography)
    return _obj([_geom.to_wkb(g).hex() for g in gs])


register("stgeogfromtext", 1)(lambda v: _from_text(v, True))
register("stgeomfromtext", 1)(lambda v: _from_text(v, False))
register("stgeogfromwkb", 1)(lambda v: _from_wkb(v, True))
register("stgeomfromwkb", 1)(lambda v: _from_wkb(v, False))


@register("stastext", 1)
def _st_as_text(v):
    return _obj([_geom.to_wkt(g) for g in _to_geoms(v)])


@register("stasbinary", 1)
def _st_as_binary(v):
    return _obj([_geom.to_wkb(g).hex() for g in _to_geoms(v)])


@register("stgeometrytype", 1)
def _st_geometry_type(v):
    return _obj([g.type_name() for g in _to_geoms(v)])


@register("stdistance", 2)
def _st_distance(a, b):
    ga = _to_geoms(a)
    gb = _to_geoms(b)
    if len(ga) == 1 and len(gb) > 1:
        ga, gb = gb, ga
    pa = _point_planes(ga)
    if pa is not None and len(gb) == 1 and gb[0] is not None \
            and gb[0].kind == "point":
        q = gb[0]
        geog = pa[2] or q.geography
        if geog:
            return _cells.haversine_m(pa[1], pa[0], q.lat, q.lng)
        return np.hypot(pa[0] - q.lng, pa[1] - q.lat)
    if len(gb) == 1:
        gb = gb * len(ga)
    return np.asarray([_geom.distance(x, y) if x and y else np.nan
                       for x, y in zip(ga, gb)], dtype=np.float64)


def _containment(outer, inner) -> np.ndarray:
    go = _to_geoms(outer)
    gi = _to_geoms(inner)
    n = max(len(go), len(gi))
    if len(go) == 1:
        # literal polygon vs point column: one vectorized ray-cast
        pi = _point_planes(gi)
        if pi is not None and go[0] is not None \
                and go[0].kind == "polygon":
            m = _geom.points_in_polygon(pi[0], pi[1], go[0])
            return m.astype(np.int32)
        go = go * n
    if len(gi) == 1:
        gi = gi * n
    out = np.asarray([1 if (a and b and _geom.contains(a, b)) else 0
                      for a, b in zip(go, gi)], dtype=np.int32)
    return out


# ST_Contains(a, b): a contains b.  ST_Within(a, b): a within b.
register("stcontains", 2)(lambda a, b: _containment(a, b))
register("stwithin", 2)(lambda a, b: _containment(b, a))


@register("stequals", 2)
def _st_equals(a, b):
    ga = _to_geoms(a)
    gb = _to_geoms(b)
    n = max(len(ga), len(gb))
    if len(ga) == 1:
        ga = ga * n
    if len(gb) == 1:
        gb = gb * n
    return np.asarray([1 if (x and y and x == y) else 0
                       for x, y in zip(ga, gb)], dtype=np.int32)


@register("starea", 1)
def _st_area(v):
    return np.asarray([_geom.area(g) if g else np.nan
                       for g in _to_geoms(v)], dtype=np.float64)


@register("geotoh3", 2, 3)
def _geo_to_h3(*args):
    """geoToH3(geometry, res) | geoToH3(lng, lat, res) -> grid cell id."""
    if len(args) == 3:
        lng, lat, res = args
        lngs = np.atleast_1d(np.asarray(lng, dtype=np.float64))
        lats = np.atleast_1d(np.asarray(lat, dtype=np.float64))
        r = int(np.atleast_1d(np.asarray(res))[0])
        return _cells.lat_lng_to_cell(lats, lngs, r).astype(np.int64)
    v, res = args
    r = int(np.atleast_1d(np.asarray(res))[0])
    pts = _point_planes(_to_geoms(v))
    if pts is None:
        raise SqlError("geoToH3 needs point geometries")
    return _cells.lat_lng_to_cell(pts[1], pts[0], r).astype(np.int64)
