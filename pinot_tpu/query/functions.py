"""Scalar (transform) function registry: name -> vectorized numpy impl.

Reference parity: pinot-common/.../function/FunctionRegistry.java:43
(annotation-scanned @ScalarFunction registry shared by both engines) plus
pinot-core/.../operator/transform/function/ (the 71 transform-function
classes). TPU-native stance: every function is a vectorized numpy ufunc
over whole columns (no per-row evaluation loop); dictionary-encoded string
columns evaluate once per dictionary value and gather (host_eval applies
that). Device (XLA) lowering exists separately for the arithmetic subset
in ops/kernels.py; everything else rides the host path.

Functions are looked up lowercased (the SQL parser lowercases call names).
"""
from __future__ import annotations

import hashlib
import re
import zlib
from typing import Any, Callable, Dict, Optional

import numpy as np

from .sql import SqlError


class FunctionDef:
    __slots__ = ("name", "fn", "min_args", "max_args", "elementwise")

    def __init__(self, name: str, fn: Callable, min_args: int,
                 max_args: Optional[int], elementwise: bool = True):
        self.name = name
        self.fn = fn
        self.min_args = min_args
        self.max_args = max_args
        self.elementwise = elementwise  # safe to eval over dict values+gather


REGISTRY: Dict[str, FunctionDef] = {}


def register(name: str, min_args: int = 1, max_args: Optional[int] = None,
             elementwise: bool = True):
    if max_args is None:
        max_args = min_args

    def deco(fn):
        REGISTRY[name] = FunctionDef(name, fn, min_args, max_args,
                                     elementwise)
        return fn
    return deco


def register_alias(alias: str, name: str) -> None:
    REGISTRY[alias] = REGISTRY[name]


def canonical(name: str) -> str:
    """FunctionRegistry.canonicalize analog: case-insensitive and
    underscore-insensitive (ST_DISTANCE == stDistance == stdistance)."""
    return name.replace("_", "").lower()


def lookup(name: str) -> Optional[FunctionDef]:
    fd = REGISTRY.get(name)
    if fd is None:
        fd = REGISTRY.get(canonical(name))
    return fd


def call(name: str, *args: Any) -> np.ndarray:
    fd = lookup(name)
    if fd is None:
        raise SqlError(f"unknown function {name!r}")
    n = len(args)
    if n < fd.min_args or (fd.max_args is not None and n > fd.max_args):
        raise SqlError(f"{name} expects {fd.min_args}"
                       + (f"..{fd.max_args}" if fd.max_args != fd.min_args
                          else "") + f" args, got {n}")
    return fd.fn(*args)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _f(v: Any) -> np.ndarray:
    return np.asarray(v, dtype=np.float64)


def _i(v: Any) -> np.ndarray:
    return np.asarray(v).astype(np.int64)


def _s(v: Any) -> np.ndarray:
    a = np.asarray(v)
    if a.dtype == object or a.dtype.kind in "US":
        return a.astype(str)
    if a.dtype.kind == "f":
        # render integral floats without the trailing .0 (Pinot prints
        # string casts of longs without decimals)
        flat = a.reshape(-1)
        out = np.asarray([_num_str(x) for x in flat], dtype=object)
        return out.reshape(a.shape).astype(str)
    return a.astype(str)


def _num_str(x) -> str:
    xf = float(x)
    return str(int(xf)) if xf.is_integer() else str(xf)


def _vec_str(fn: Callable[[str], Any], v: Any, dtype=None) -> np.ndarray:
    a = _s(v)
    if a.ndim == 0:
        r = fn(str(a))
        return np.asarray(r, dtype=dtype) if dtype else np.asarray(r)
    out = [fn(x) for x in a]
    return np.asarray(out, dtype=dtype) if dtype else np.asarray(out,
                                                                 dtype=object)


# ---------------------------------------------------------------------------
# math (ArithmeticFunctions.java / transform function analogs)
# ---------------------------------------------------------------------------

register("abs")(lambda v: np.abs(np.asarray(v)))
register("ceil")(lambda v: np.ceil(_f(v)))
register_alias("ceiling", "ceil")
register("floor")(lambda v: np.floor(_f(v)))
register("exp")(lambda v: np.exp(_f(v)))
register("ln")(lambda v: np.log(_f(v)))
register("log")(lambda v: np.log(_f(v)))
register("log2")(lambda v: np.log2(_f(v)))
register("log10")(lambda v: np.log10(_f(v)))
register("sqrt")(lambda v: np.sqrt(_f(v)))
register("cbrt")(lambda v: np.cbrt(_f(v)))
register("sign")(lambda v: np.sign(_f(v)))
register("power", 2)(lambda a, b: np.power(_f(a), _f(b)))
register_alias("pow", "power")
register("mod", 2)(lambda a, b: np.mod(_f(a), _f(b)))


@register("round", 1, 2)
def _round(v, scale=0):
    s = int(np.asarray(scale))
    return np.round(_f(v), s)


register_alias("rounddecimal", "round")


@register("truncate", 1, 2)
def _truncate(v, scale=0):
    s = int(np.asarray(scale))
    m = 10.0 ** s
    return np.trunc(_f(v) * m) / m


register_alias("trunc", "truncate")
def _reduce_pair(op, args):
    out = _f(args[0])
    for x in args[1:]:
        out = op(out, _f(x))
    return out


register("least", 2, 16)(lambda *a: _reduce_pair(np.minimum, a))
register("greatest", 2, 16)(lambda *a: _reduce_pair(np.maximum, a))

# trig (TrigonometricFunctions.java analog)
for _name, _fn in (("sin", np.sin), ("cos", np.cos), ("tan", np.tan),
                   ("asin", np.arcsin), ("acos", np.arccos),
                   ("atan", np.arctan), ("sinh", np.sinh),
                   ("cosh", np.cosh), ("tanh", np.tanh),
                   ("degrees", np.degrees), ("radians", np.radians)):
    register(_name)(lambda v, _fn=_fn: _fn(_f(v)))
register("cot")(lambda v: 1.0 / np.tan(_f(v)))
register("atan2", 2)(lambda a, b: np.arctan2(_f(a), _f(b)))
register("e", 0, 0)(lambda: np.float64(np.e))
register("pi", 0, 0)(lambda: np.float64(np.pi))


# ---------------------------------------------------------------------------
# string (StringFunctions.java analog; substr is 0-based with exclusive end,
# -1 meaning end-of-string, matching the reference's substr contract)
# ---------------------------------------------------------------------------

register("upper")(lambda v: _vec_str(str.upper, v))
register("lower")(lambda v: _vec_str(str.lower, v))
register("trim")(lambda v: _vec_str(str.strip, v))
register("ltrim")(lambda v: _vec_str(str.lstrip, v))
register("rtrim")(lambda v: _vec_str(str.rstrip, v))
register("length")(lambda v: _vec_str(len, v, dtype=np.int64))
register_alias("strlen", "length")
register("reverse")(lambda v: _vec_str(lambda x: x[::-1], v))


@register("substr", 2, 3)
def _substr(v, start, end=None):
    st = int(np.asarray(start))
    en = None if end is None else int(np.asarray(end))
    if en is not None and en == -1:
        en = None
    return _vec_str(lambda x: x[st:en], v)


@register("substring", 2, 3)
def _substring(v, start, ln=None):
    # SQL-style: 1-based start, optional length
    st = max(int(np.asarray(start)) - 1, 0)
    if ln is None:
        return _vec_str(lambda x: x[st:], v)
    n = int(np.asarray(ln))
    return _vec_str(lambda x: x[st:st + n], v)


@register("concat", 2, 16)
def _concat(*args):
    parts = [_s(a) for a in args]
    if len(parts) == 3 and parts[2].ndim == 0:
        sep = str(parts[2])   # concat(col1, col2, separator) — ref semantics
        parts = [parts[0], parts[1]]
    else:
        sep = ""
    shp = None
    for p in parts:
        if p.ndim > 0:
            shp = p.shape
    if shp is None:
        return np.asarray(sep.join(str(p) for p in parts))
    cols = [np.broadcast_to(p, shp) for p in parts]
    out = [sep.join(str(c[i]) for c in cols) for i in range(shp[0])]
    return np.asarray(out, dtype=object)


@register("replace", 3)
def _replace(v, find, sub):
    f, s = str(np.asarray(find)), str(np.asarray(sub))
    return _vec_str(lambda x: x.replace(f, s), v)


@register("startswith", 2)
def _startswith(v, p):
    pp = str(np.asarray(p))
    return _vec_str(lambda x: x.startswith(pp), v, dtype=bool)


@register("endswith", 2)
def _endswith(v, p):
    pp = str(np.asarray(p))
    return _vec_str(lambda x: x.endswith(pp), v, dtype=bool)


@register("contains", 2)
def _contains(v, p):
    pp = str(np.asarray(p))
    return _vec_str(lambda x: pp in x, v, dtype=bool)


@register("strpos", 2, 3)
def _strpos(v, sub, occurrence=1):
    s = str(np.asarray(sub))
    occ = int(np.asarray(occurrence))

    def find(x: str) -> int:
        pos = -1
        for _ in range(max(occ, 1)):
            pos = x.find(s, pos + 1)
            if pos < 0:
                return -1
        return pos
    return _vec_str(find, v, dtype=np.int64)


@register("lpad", 3)
def _lpad(v, size, pad):
    n, p = int(np.asarray(size)), str(np.asarray(pad))
    return _vec_str(
        lambda x: (p * n + x)[-n:] if len(x) < n else x[:n], v)


@register("rpad", 3)
def _rpad(v, size, pad):
    n, p = int(np.asarray(size)), str(np.asarray(pad))
    return _vec_str(
        lambda x: (x + p * n)[:n] if len(x) < n else x[:n], v)


@register("repeat", 2, 3)
def _repeat(v, times, sep=None):
    n = int(np.asarray(times))
    s = "" if sep is None else str(np.asarray(sep))
    return _vec_str(lambda x: s.join([x] * n), v)


@register("remove", 2)
def _remove(v, sub):
    s = str(np.asarray(sub))
    return _vec_str(lambda x: x.replace(s, ""), v)


register("codepoint")(lambda v: _vec_str(lambda x: ord(x[0]) if x else 0, v,
                                         dtype=np.int64))
register("chr")(lambda v: np.asarray(
    [chr(int(x)) for x in np.atleast_1d(_i(v))], dtype=object)
    if np.asarray(v).ndim else np.asarray(chr(int(np.asarray(v)))))


@register("splitpart", 3)
def _splitpart(v, delim, index):
    d, idx = str(np.asarray(delim)), int(np.asarray(index))

    def part(x: str) -> str:
        ps = x.split(d)
        return ps[idx] if 0 <= idx < len(ps) else "null"
    return _vec_str(part, v)


@register("regexpextract", 2, 4, elementwise=True)
def _regexp_extract(v, pattern, group=0, default=""):
    rx = re.compile(str(np.asarray(pattern)))
    g = int(np.asarray(group))
    dflt = str(np.asarray(default))

    def ex(x: str) -> str:
        m = rx.search(x)
        return m.group(g) if m else dflt
    return _vec_str(ex, v)


@register("regexpreplace", 3)
def _regexp_replace(v, pattern, sub):
    rx = re.compile(str(np.asarray(pattern)))
    s = str(np.asarray(sub))
    return _vec_str(lambda x: rx.sub(s, x), v)


@register("regexplike", 2)
def _regexp_like(v, pattern):
    rx = re.compile(str(np.asarray(pattern)))
    return _vec_str(lambda x: bool(rx.search(x)), v, dtype=bool)


# hash (HashFunctions.java analog)
register("md5")(lambda v: _vec_str(
    lambda x: hashlib.md5(x.encode()).hexdigest(), v))
register("sha")(lambda v: _vec_str(
    lambda x: hashlib.sha1(x.encode()).hexdigest(), v))
register("sha256")(lambda v: _vec_str(
    lambda x: hashlib.sha256(x.encode()).hexdigest(), v))
register("sha512")(lambda v: _vec_str(
    lambda x: hashlib.sha512(x.encode()).hexdigest(), v))
register("crc32")(lambda v: _vec_str(
    lambda x: zlib.crc32(x.encode()), v, dtype=np.int64))
register("adler32")(lambda v: _vec_str(
    lambda x: zlib.adler32(x.encode()), v, dtype=np.int64))


# ---------------------------------------------------------------------------
# datetime (DateTimeFunctions.java analog; epoch millis, UTC)
# ---------------------------------------------------------------------------

_MS = {"milliseconds": 1, "seconds": 1000, "minutes": 60_000,
       "hours": 3_600_000, "days": 86_400_000}


def _dt64(millis) -> np.ndarray:
    return _i(millis).astype("datetime64[ms]")


def _field(millis, unit: str) -> np.ndarray:
    d = _dt64(millis)
    y = d.astype("datetime64[Y]")
    if unit == "year":
        return y.astype(np.int64) + 1970
    mo = d.astype("datetime64[M]")
    if unit == "month":
        return (mo - y).astype(np.int64) + 1
    day = d.astype("datetime64[D]")
    if unit == "day":
        return (day - mo).astype(np.int64) + 1
    if unit == "dayofweek":
        # 1=Monday..7=Sunday (ISO, matches the reference's dayOfWeek)
        return (day.astype(np.int64) + 3) % 7 + 1
    if unit == "dayofyear":
        return (day - y).astype(np.int64) + 1
    h = d.astype("datetime64[h]")
    if unit == "hour":
        return (h - day).astype(np.int64)
    mi = d.astype("datetime64[m]")
    if unit == "minute":
        return (mi - h).astype(np.int64)
    s = d.astype("datetime64[s]")
    if unit == "second":
        return (s - mi).astype(np.int64)
    if unit == "millisecond":
        return (d - s).astype(np.int64)
    if unit == "quarter":
        return ((mo - y).astype(np.int64)) // 3 + 1
    if unit == "week":
        # ISO week number
        dow = (day.astype(np.int64) + 3) % 7          # 0=Monday
        thursday = day - dow.astype("timedelta64[D]") \
            + np.timedelta64(3, "D")
        ty = thursday.astype("datetime64[Y]")
        return ((thursday - ty).astype(np.int64)) // 7 + 1
    raise SqlError(f"unknown datetime field {unit}")


for _u in ("year", "month", "hour", "minute", "second", "millisecond",
           "quarter", "week", "dayofweek", "dayofyear"):
    register(_u)(lambda v, _u=_u: _field(v, _u))
register("day")(lambda v: _field(v, "day"))
register_alias("dayofmonth", "day")
register_alias("weekofyear", "week")

for _unit, _mul in (("seconds", 1000), ("minutes", 60_000),
                    ("hours", 3_600_000), ("days", 86_400_000)):
    register(f"toepoch{_unit}")(
        lambda v, _m=_mul: _i(v) // _m)
    register(f"fromepoch{_unit}")(
        lambda v, _m=_mul: _i(v) * _m)
    register(f"toepoch{_unit}rounded", 2)(
        lambda v, b, _m=_mul: (_i(v) // _m) // _i(b) * _i(b))
register("toepochmillis")(lambda v: _i(v))


@register("datetrunc", 2, 3)
def _datetrunc(unit, millis, out_unit=None):
    u = str(np.asarray(unit)).lower()
    d = _dt64(millis)
    trunc_map = {"year": "Y", "month": "M", "day": "D",
                 "hour": "h", "minute": "m", "second": "s",
                 "millisecond": "ms", "quarter": None, "week": None}
    if u == "week":
        # ISO Monday anchor (java.time/joda semantics the reference
        # uses); numpy datetime64[W] anchors on the Thursday epoch and
        # would disagree with the device lowering
        days = np.floor_divide(_i(millis), 86_400_000)
        res = (np.floor_divide(days + 3, 7) * 7 - 3) * 86_400_000
    elif u == "quarter":
        y = d.astype("datetime64[Y]")
        mo = (d.astype("datetime64[M]") - y).astype(np.int64) // 3 * 3
        out = (y.astype("datetime64[M]") + mo.astype("timedelta64[M]"))
        res = out.astype("datetime64[ms]").astype(np.int64)
    else:
        code = trunc_map.get(u)
        if code is None:
            raise SqlError(f"dateTrunc: unknown unit {u!r}")
        res = d.astype(f"datetime64[{code}]").astype("datetime64[ms]") \
            .astype(np.int64)
    if out_unit is not None:
        ou = str(np.asarray(out_unit)).lower()
        res = res // _MS.get(ou, 1)
    return res


@register("timestampadd", 3)
def _timestampadd(unit, count, millis):
    u = str(np.asarray(unit)).lower()
    c = _i(count)
    m = _i(millis)
    if u in _MS:
        return m + c * _MS[u]
    unit_ms = {"second": 1000, "minute": 60_000, "hour": 3_600_000,
               "day": 86_400_000, "week": 7 * 86_400_000}
    if u in unit_ms:
        return m + c * unit_ms[u]
    d = m.astype("datetime64[ms]").astype("datetime64[M]")
    rem = m - d.astype("datetime64[ms]").astype(np.int64)
    if u == "month":
        nd = d + c.astype("timedelta64[M]")
    elif u in ("year",):
        nd = d + (c * 12).astype("timedelta64[M]")
    elif u == "quarter":
        nd = d + (c * 3).astype("timedelta64[M]")
    else:
        raise SqlError(f"timestampAdd: unknown unit {u!r}")
    return nd.astype("datetime64[ms]").astype(np.int64) + rem


@register("timestampdiff", 3)
def _timestampdiff(unit, a, b):
    u = str(np.asarray(unit)).lower()
    diff = _i(b) - _i(a)
    unit_ms = {"millisecond": 1, "second": 1000, "minute": 60_000,
               "hour": 3_600_000, "day": 86_400_000, "week": 7 * 86_400_000}
    if u in unit_ms:
        return diff // unit_ms[u]
    if u in ("month", "year", "quarter"):
        ma = _dt64(a).astype("datetime64[M]").astype(np.int64)
        mb = _dt64(b).astype("datetime64[M]").astype(np.int64)
        months = mb - ma
        if u == "month":
            return months
        return months // (12 if u == "year" else 3)
    raise SqlError(f"timestampDiff: unknown unit {u!r}")


_JODA_MAP = [("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"),
             ("HH", "%H"), ("mm", "%M"), ("ss", "%S"), ("SSS", "%f")]


def _joda_to_strftime(fmt: str) -> str:
    out = fmt
    for j, s in _JODA_MAP:
        out = out.replace(j, s)
    return out


@register("todatetime", 2)
def _todatetime(millis, fmt):
    import datetime as _dt
    f = _joda_to_strftime(str(np.asarray(fmt)))
    # millisecond precision: format %f out-of-band so trailing literals
    # (e.g. a 'Z' after SSS) survive
    f_ms = f.replace("%f", "\x00")

    def conv(ms: int) -> str:
        t = _dt.datetime.fromtimestamp(ms / 1000.0, tz=_dt.timezone.utc)
        s = t.strftime(f_ms)
        return s.replace("\x00", f"{t.microsecond // 1000:03d}")
    m = _i(millis)
    if m.ndim == 0:
        return np.asarray(conv(int(m)))
    return np.asarray([conv(int(x)) for x in m], dtype=object)


@register("fromdatetime", 2)
def _fromdatetime(s, fmt):
    import calendar
    import datetime as _dt
    f = _joda_to_strftime(str(np.asarray(fmt)))

    def conv(x: str) -> int:
        t = _dt.datetime.strptime(x, f)
        return calendar.timegm(t.timetuple()) * 1000 + t.microsecond // 1000
    return _vec_str(conv, s, dtype=np.int64)


@register("now", 0, 0, elementwise=False)
def _now():
    import time
    return np.int64(int(time.time() * 1000))


@register("ago", 1, 1, elementwise=False)
def _ago(period):
    import time
    p = str(np.asarray(period))
    m = re.fullmatch(
        r"PT?(?:(\d+)D)?(?:(\d+)H)?(?:(\d+)M)?(?:(\d+(?:\.\d+)?)S)?", p,
        re.IGNORECASE)
    if not m:
        raise SqlError(f"ago: cannot parse ISO-8601 period {p!r}")
    days, hours, mins, secs = (float(g) if g else 0.0 for g in m.groups())
    delta_ms = int(((days * 24 + hours) * 60 + mins) * 60_000 + secs * 1000)
    return np.int64(int(time.time() * 1000) - delta_ms)


# ---------------------------------------------------------------------------
# json (JsonFunctions.java analog — host-side; '$.a.b[0]' paths)
# ---------------------------------------------------------------------------

_JSON_PATH_RE = re.compile(r"\.([A-Za-z_][A-Za-z_0-9]*)|\[(\d+)\]")


def _json_path_steps(path: str):
    if not path.startswith("$"):
        raise SqlError(f"json path must start with $: {path!r}")
    steps = []
    for m in _JSON_PATH_RE.finditer(path, 1):
        steps.append(m.group(1) if m.group(1) is not None
                     else int(m.group(2)))
    return steps


def _json_get(obj: Any, steps) -> Any:
    for s in steps:
        if obj is None:
            return None
        try:
            obj = obj[s]
        except (KeyError, IndexError, TypeError):
            return None
    return obj


@register("jsonextractscalar", 2, 4)
def _jsonextractscalar(v, path, result_type="STRING", default=None):
    import json as _json
    steps = _json_path_steps(str(np.asarray(path)))
    rt = str(np.asarray(result_type)).upper()
    dflt = None if default is None else np.asarray(default).item()

    def ex(x: str):
        try:
            val = _json_get(_json.loads(x), steps)
        except (ValueError, TypeError):
            val = None
        if val is None:
            return dflt
        return val
    raw = _vec_str(ex, v)
    flat = raw.reshape(-1) if raw.ndim else raw
    if rt in ("INT", "LONG"):
        conv = [int(float(x)) if x is not None else
                (int(dflt) if dflt is not None else -(2 ** 31))
                for x in np.atleast_1d(flat)]
        out = np.asarray(conv, dtype=np.int64)
    elif rt in ("FLOAT", "DOUBLE"):
        conv = [float(x) if x is not None else
                (float(dflt) if dflt is not None else np.nan)
                for x in np.atleast_1d(flat)]
        out = np.asarray(conv, dtype=np.float64)
    else:
        out = np.asarray(["null" if x is None else str(x)
                          for x in np.atleast_1d(flat)], dtype=object)
    return out.reshape(raw.shape) if raw.ndim else out[0]


@register("jsonformat", 1)
def _jsonformat(v):
    import json as _json
    return _vec_str(lambda x: _json.dumps(_json.loads(x),
                                          separators=(",", ":")), v)


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------

_CAST_TARGETS = {
    "int": np.int32, "integer": np.int32, "long": np.int64,
    "bigint": np.int64, "float": np.float32, "double": np.float64,
    "boolean": np.bool_, "timestamp": np.int64,
    "string": None, "varchar": None, "json": None,
}


def cast_value(v: Any, type_name: str) -> np.ndarray:
    t = type_name.lower()
    if t not in _CAST_TARGETS:
        raise SqlError(f"CAST: unknown type {type_name!r}")
    a = np.asarray(v)
    tgt = _CAST_TARGETS[t]
    if tgt is None:
        return _s(a)
    if a.dtype == object or a.dtype.kind in "US":
        a = a.astype(str)
        if tgt in (np.int32, np.int64):
            return np.asarray([int(float(x)) for x in np.atleast_1d(a)],
                              dtype=tgt).reshape(a.shape)
        if tgt is np.bool_:
            return np.asarray([x.lower() == "true"
                               for x in np.atleast_1d(a)],
                              dtype=bool).reshape(a.shape)
        return a.astype(np.float64).astype(tgt)
    if tgt in (np.int32, np.int64) and a.dtype.kind == "f":
        return a.astype(tgt)  # C-style truncation toward zero via astype
    return a.astype(tgt)


register("cast", 2)(lambda v, t: cast_value(v, str(np.asarray(t))))

# geospatial ST_* family (query/geo_functions.py) registers on import
from . import geo_functions as _geo_functions  # noqa: E402,F401
