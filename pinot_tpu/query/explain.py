"""EXPLAIN PLAN / EXPLAIN ANALYZE rendering.

Reference parity: pinot-core explain support (ExplainPlanQueriesTest
pattern): rows of (Operator, Operator_Id, Parent_Id) describing the
physical tree. The TPU plan is flatter than Pinot's pull-based tree — one
fused kernel per segment — so the explain shows the broker reduce, the
combine, and the per-segment plan kinds with their predicate/aggregation
structure (and which segments pruned / answered from rollups / fast paths).

EXPLAIN ANALYZE (round-7 tentpole) executes the query under the span
tracer (utils/spans.py) and renders the resulting tree: per-phase wall
ms (plan / kernel build / device execute / transfer / reduce), the cost
model's strategy decision trace, plan-cache hit/miss, retrace flags, and
estimated vs measured selectivity per segment kernel.
"""
from __future__ import annotations

import json
from collections import Counter
from typing import Any, List, Tuple

from ..ops.ir import (And, Bin, Cmp, Col, EqId, FalseP, IdRange, InBitmap,
                      InSet,
                      KernelPlan, Lit, MaskParam, Not, Or, Pred, TrueP,
                      ValueExpr)
from ..query.planner import CompiledPlan


def _ve(v: ValueExpr, cols: List[str]) -> str:
    if isinstance(v, Col):
        base = cols[v.col]
        return f"{base}" if v.dict_param is None else f"dictGet({base})"
    if isinstance(v, Lit):
        return "literal"
    if isinstance(v, Bin):
        return f"({_ve(v.lhs, cols)}{v.op}{_ve(v.rhs, cols)})"
    return "?"


def _pred(p: Pred, cols: List[str]) -> str:
    if isinstance(p, TrueP):
        return "MATCH_ALL"
    if isinstance(p, FalseP):
        return "MATCH_NONE"
    if isinstance(p, EqId):
        return f"EQ_DICT({cols[p.col]})"
    if isinstance(p, IdRange):
        return f"RANGE_DICT({cols[p.col]})"
    if isinstance(p, InSet):
        return f"IN_SET({cols[p.col]},n={p.n})"
    if isinstance(p, InBitmap):
        return f"IN_BITMAP({cols[p.col]})"
    if isinstance(p, Cmp):
        return f"CMP({_ve(p.lhs, cols)}{p.op})"
    if isinstance(p, MaskParam):
        return "MASK_PARAM"
    if isinstance(p, And):
        return "AND(" + ",".join(_pred(c, cols) for c in p.children) + ")"
    if isinstance(p, Or):
        return "OR(" + ",".join(_pred(c, cols) for c in p.children) + ")"
    if isinstance(p, Not):
        return f"NOT({_pred(p.child, cols)})"
    return "?"


def explain_rows(ctx, plans: List[CompiledPlan], rollup_count: int = 0
                 ) -> Tuple[List[str], List[tuple]]:
    """-> (columns, rows) for the explain result table."""
    rows: List[tuple] = []
    rid = 0

    def emit(op: str, parent: int) -> int:
        nonlocal rid
        rows.append((op, rid, parent))
        rid += 1
        return rid - 1

    root = emit("BROKER_REDUCE"
                + ("(HAVING)" if ctx.having is not None else "")
                + (f"(ORDER_BY:{len(ctx.order_by)})" if ctx.order_by else "")
                + (f"(LIMIT:{ctx.limit})" if ctx.limit is not None else ""),
                -1)
    combine = emit("COMBINE(vmap_batched)", root)
    if rollup_count:
        emit(f"STARTREE_ROLLUP(segments:{rollup_count})", combine)

    kinds = Counter(p.kind for p in plans)
    if kinds.get("pruned"):
        emit(f"SEGMENT_PRUNED(segments:{kinds['pruned']})", combine)
    if kinds.get("fast"):
        emit(f"METADATA_FAST_PATH(segments:{kinds['fast']})", combine)
    if kinds.get("host"):
        emit(f"HOST_VECTORIZED(segments:{kinds['host']})", combine)

    kernel_plans = [p for p in plans if p.kind == "kernel"]
    if kernel_plans:
        p = kernel_plans[0]
        kp: KernelPlan = p.kernel_plan
        node = emit(f"TPU_KERNEL(segments:{len(kernel_plans)},"
                    f"bucket:{p.segment.bucket})", combine)
        emit(f"FILTER_MASK:{_pred(kp.pred, p.col_names)}", node)
        if kp.is_group_by:
            keys = ",".join(p.col_names[i] for i, _ in kp.group_keys)
            emit(f"GROUP_BY_ONEHOT_DOT(keys:[{keys}],"
                 f"space:{kp.group_space})", node)
        for i, spec in enumerate(kp.aggs):
            desc = spec.kind.upper()
            if spec.value is not None:
                desc += f"({_ve(spec.value, p.col_names)})"
            emit(f"AGGREGATE:{desc}", node)
    return ["Operator", "Operator_Id", "Parent_Id"], rows


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE: span-tree rendering
# ---------------------------------------------------------------------------

ANALYZE_COLUMNS = ["Node", "Node_Id", "Parent_Id", "Time_Ms", "Detail"]

# attribute rendering order: the decision-relevant fields first (what the
# cost model estimated vs what the kernel measured), everything else
# alphabetical after
_ATTR_ORDER = ["strategy", "cache", "est_sel", "meas_sel", "slots_cap",
               "matched", "retrace", "compiled",
               # compile lane (staged build_kernel spans, ISSUE 15):
               # trigger taxonomy + executable memory/flops as Detail
               "trigger", "memory_bytes", "flops", "site",
               # cluster plane (scatter_call / server_query spans)
               "server", "attempt", "status", "net_ms", "error"]


def _fmt_val(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    if isinstance(v, (dict, list, tuple)):
        return json.dumps(v, sort_keys=True, default=str)
    return str(v)


def _fmt_attrs(attrs: dict) -> str:
    keys = [k for k in _ATTR_ORDER if k in attrs and attrs[k] is not None]
    keys += sorted(k for k in attrs
                   if k not in _ATTR_ORDER and attrs[k] is not None)
    return " ".join(f"{k}={_fmt_val(attrs[k])}" for k in keys)


def explain_analyze_rows(root) -> Tuple[List[str], List[tuple]]:
    """utils/spans.Span tree -> (columns, rows) of
    (Node, Node_Id, Parent_Id, Time_Ms, Detail) in pre-order — the same
    parent-pointer table shape EXPLAIN PLAN uses, plus timings."""
    rows: List[tuple] = []

    def walk(s, parent: int) -> None:
        rid = len(rows)
        rows.append((s.name, rid, parent, round(s.duration_ms, 3),
                     _fmt_attrs(s.attrs)))
        for c in s.children:
            walk(c, rid)

    walk(root, -1)
    return list(ANALYZE_COLUMNS), rows


def finalize_analyze(root) -> Tuple[List[str], List[tuple], dict]:
    """The shared EXPLAIN ANALYZE render tail: attach the explicit
    ``broker_overhead`` self-time child (so root-child timings sum to
    the query's wall time — the 10% gate both brokers share), render
    the rows, and build the trace envelope. ONE implementation for the
    in-process broker (broker/broker.py) and the cluster broker
    (cluster/broker_node.py): a change here changes what the timing
    gate means everywhere at once."""
    from ..utils import phases as ph
    from ..utils.spans import Span

    overhead = root.duration_ms - root.children_ms()
    if overhead > 0:
        s = Span(ph.BROKER_OVERHEAD)
        s.duration_ms = overhead
        root.children.append(s)
    cols, rows = explain_analyze_rows(root)
    return cols, rows, {"spans": root.to_dict()}
