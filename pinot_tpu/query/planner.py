"""Physical planner: QueryContext + segment -> executable plan.

Reference parity: pinot-core/.../plan/maker/InstancePlanMakerImplV2.java:137
(makeInstancePlan) / :234 (makeSegmentPlanNode) chooses Aggregation /
GroupBy / Selection plans per segment; AggregationPlanNode.java:98-112
installs non-scan fast paths (metadata COUNT, dictionary MIN/MAX);
ColumnValueSegmentPruner drops segments whose min/max can't match.

TPU-native differences:
- literals resolve to dict ids / typed scalars that become runtime kernel
  params (plan structure is literal-free -> one XLA compile per shape);
- dictionary-resolved predicates constant-fold (absent value -> FalseP),
  and folding a segment's root predicate to FalseP IS the pruner;
- range predicates on sorted dictionaries become id-range masks — the
  sorted-dictionary trick replaces the RangeIndex;
- LIKE/REGEXP evaluate host-side over the (small) dictionary and ship the
  matching-id set to the device — the TPU analog of Pinot's
  dictionary-based predicate evaluators.
"""
from __future__ import annotations

import fnmatch
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops.ir import (AggSpec, And, Bin, Case as CaseIR, Cmp, Col, EqId,
                      FalseP, Func as FuncIR, IdRange,
                      InBitmap, InSet, IsNull as IsNullIR, KernelPlan, Lit,
                      MaskParam as MaskParamP, Not, Or, Pred, TrueP,
                      ValueExpr)
from ..segment.immutable import ImmutableSegment
from ..spi.schema import DataType
from .context import AggExpr, QueryContext, _expr_label as _expr_label_of
from .sql import (Between, BinaryOp, BoolAnd, BoolNot, BoolOr, CaseWhen,
                  Cast, Comparison, collect_identifiers, FuncCall,
                  Identifier, InList, IsNull, Like, Literal, SqlError, Star)

MAX_DENSE_GROUPS = 1 << 21          # beyond this, host hash group-by
MAX_DISTINCT_MATRIX = 1 << 24       # group_space * card gate for on-device
# small spaces stay on the dense one-hot kernel (one fused pass, vmap- and
# mesh-friendly); larger spaces compact matched rows first (ops/compact.py)
DENSE_SMALL_GROUPS = 512
# dense one-hot materializes an (bucket, space) int8 operand in HBM; cap its
# size so big segments route to compact even for small spaces (a 134M-row
# segment with 175 groups would otherwise stage a 23GB operand)
DENSE_ONEHOT_BUDGET = 1 << 28


class PlanError(SqlError):
    pass


def _truthy(v: Any) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes")
    return bool(v)


# ---------------------------------------------------------------------------
# plan kinds
# ---------------------------------------------------------------------------

@dataclass
class CompiledPlan:
    kind: str  # 'pruned' | 'fast' | 'kernel' | 'host'
    segment: ImmutableSegment
    ctx: QueryContext
    # kernel path
    col_names: List[str] = field(default_factory=list)
    kernel_plan: Optional[KernelPlan] = None
    params: List[Any] = field(default_factory=list)
    agg_bindings: List["AggBinding"] = field(default_factory=list)
    group_cols: List[str] = field(default_factory=list)   # group key columns
    # per-key decode recipe for extract_partial: ("dict", col, card) |
    # ("int", lo, stride, card) — expression keys (GROUP BY YEAR(ts))
    # have no dictionary; their ids decode as lo + id*stride
    group_decoders: List[tuple] = field(default_factory=list)
    # fast path: precomputed states per agg
    fast_states: Optional[List[Any]] = None
    # kselect path (device selection/order-by)
    select_plan: Optional[Any] = None
    select_names: List[str] = field(default_factory=list)
    # cost model (multistage/costs.py): IR-derived selectivity estimate,
    # the compaction capacity it implies for the compact strategy (None =
    # kernel-default caps), and the strategy decision trace (EXPLAIN /
    # profile tooling)
    est_selectivity: Optional[float] = None
    slots_cap: Optional[int] = None
    strategy_trace: Optional[dict] = None
    # round-12 feedback loop: the plan cache's measured selectivity
    # drifted past the threshold and slots_cap was re-quantized from the
    # measurement — the executor brackets the resulting kernel compile
    # with RetraceDetector.expected() (a deliberate recompile, not a
    # retrace)
    drift_requantized: bool = False


@dataclass
class AggBinding:
    """Maps a logical AggExpr to kernel output names + finalize metadata."""
    agg: AggExpr
    index: int            # position in kernel plan aggs
    integral: bool
    dict_col: Optional[str] = None   # distinct_count id-space column


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

class _Binder:
    def __init__(self, segment: ImmutableSegment):
        self.segment = segment
        self.cols: List[str] = []
        self.params: List[Any] = []

    def bind_col(self, name: str) -> int:
        if name not in self.segment.columns:
            raise PlanError(f"unknown column {name!r} in segment "
                            f"{self.segment.name!r}")
        if name in self.cols:
            return self.cols.index(name)
        self.cols.append(name)
        return len(self.cols) - 1

    def add_param(self, value: Any) -> int:
        self.params.append(value)
        return len(self.params) - 1


def _pad_dup(vals: np.ndarray) -> np.ndarray:
    """Pad a sorted set to pow2 with copies of the LAST element (duplicates
    change neither `any(==)` semantics nor sortedness — the kernel's
    sorted-membership path needs ascending order) to bound recompiles on
    IN-list size."""
    n = len(vals)
    p = 1
    while p < n:
        p <<= 1
    if p == n:
        return vals
    return np.concatenate([vals, np.repeat(vals[-1:], p - n)])


def _simplify(p: Pred) -> Pred:
    if isinstance(p, And):
        kids = []
        for c in (_simplify(c) for c in p.children):
            if isinstance(c, FalseP):
                return FalseP()
            if isinstance(c, TrueP):
                continue
            if isinstance(c, And):
                kids.extend(c.children)
            else:
                kids.append(c)
        if not kids:
            return TrueP()
        return kids[0] if len(kids) == 1 else And(tuple(kids))
    if isinstance(p, Or):
        kids = []
        for c in (_simplify(c) for c in p.children):
            if isinstance(c, TrueP):
                return TrueP()
            if isinstance(c, FalseP):
                continue
            if isinstance(c, Or):
                kids.extend(c.children)
            else:
                kids.append(c)
        if not kids:
            return FalseP()
        return kids[0] if len(kids) == 1 else Or(tuple(kids))
    if isinstance(p, Not):
        c = _simplify(p.child)
        if isinstance(c, TrueP):
            return FalseP()
        if isinstance(c, FalseP):
            return TrueP()
        if isinstance(c, Not):
            return c.child
        return Not(c)
    return p


def _like_to_regex(pattern: str) -> "re.Pattern":
    # SQL LIKE: % = any run, _ = any one char (LikePredicate semantics)
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


# ---------------------------------------------------------------------------
# the planner
# ---------------------------------------------------------------------------

class SegmentPlanner:
    def __init__(self, ctx: QueryContext, segment: ImmutableSegment):
        self.ctx = ctx
        self.seg = segment
        self.b = _Binder(segment)
        self.null_aware = _truthy(ctx.options.get("enableNullHandling"))

    # -- value expressions -------------------------------------------------
    def resolve_value(self, e: Any) -> Tuple[ValueExpr, bool]:
        """-> (ir, integral)."""
        if isinstance(e, Identifier):
            m = self.seg.columns.get(e.name)
            if m is None:
                raise PlanError(f"unknown column {e.name!r}")
            if not getattr(m, "single_value", True):
                raise PlanError(f"column {e.name!r} is multi-value; use "
                                "the MV aggregation forms (SUMMV, ...)")
            if not m.data_type.is_numeric:
                raise PlanError(f"column {e.name!r} ({m.data_type.value}) "
                                "is not numeric in a value context")
            idx = self.b.bind_col(e.name)
            if m.has_dict:
                # marker resolved by the executor against the segment's
                # device cache (dictionaries upload once, not per query)
                dp = self.b.add_param(("dictvals", e.name))
                return Col(idx, dp), m.data_type.is_integral
            return Col(idx), m.data_type.is_integral
        if isinstance(e, Literal):
            v = e.value
            integral = isinstance(v, (int, np.integer)) and not isinstance(v, bool)
            p = self.b.add_param(
                np.int64(v) if integral else np.float64(float(v)))
            return Lit(p), integral
        if isinstance(e, BinaryOp):
            l, li = self.resolve_value(e.lhs)
            r, ri = self.resolve_value(e.rhs)
            integral = li and ri and e.op != "/"
            return Bin(e.op, l, r), integral
        if isinstance(e, FuncCall):
            return self._device_func(e)
        if isinstance(e, Cast):
            return self._device_cast(e)
        if isinstance(e, CaseWhen):
            return self._device_case(e)
        raise PlanError(f"unsupported value expression {e!r}")

    # datetime/math scalar functions with closed-form device lowerings
    # (DateTimeTransformFunction / CastTransformFunction analogs; full
    # registry stays host-side in query/functions.py — PlanError here
    # means the host path evaluates instead)
    _DEVICE_FUNCS = {
        "year": True, "month": True, "day": True, "dayofmonth": True,
        "quarter": True, "dayofweek": True, "hour": True, "minute": True,
        "second": True, "millisecond": True,
        "abs": None, "floor": False, "ceil": False, "sqrt": False,
        "exp": False, "ln": False,
    }

    # constant output ranges of datetime field extractors (lo, hi)
    _FIELD_RANGES = {"month": (1, 12), "day": (1, 31), "quarter": (1, 4),
                     "dayofweek": (1, 7), "hour": (0, 23),
                     "minute": (0, 59), "second": (0, 59),
                     "millisecond": (0, 999)}
    _TRUNC_STRIDES = {"second": 1000, "minute": 60_000,
                      "hour": 3_600_000, "day": 86_400_000,
                      "week": 7 * 86_400_000}

    def _expr_key_range(self, g: Any):
        """GROUP BY expression -> (lo, stride, cardinality) when the
        expression has a device lowering AND a bounded integer range
        derivable from column metadata; None -> host path. The device
        answer to expression group keys (the reference evaluates a
        transform function then runs NoDictionaryGroupKeyGenerator;
        here the key arithmetic fuses into the kernel)."""
        from .functions import canonical
        if not isinstance(g, FuncCall):
            return None
        name = canonical(g.name)
        name = "day" if name == "dayofmonth" else name
        if name in self._FIELD_RANGES and len(g.args) == 1:
            lo, hi = self._FIELD_RANGES[name]
            return lo, 1, hi - lo + 1
        arg_rng = None
        if name == "year" and len(g.args) == 1:
            arg_rng = self._range_of(g.args[0])
            if arg_rng is None:
                return None
            import numpy as _np
            y_lo = int(_np.datetime64(int(arg_rng[0]), "ms")
                       .astype("datetime64[Y]").astype(_np.int64)) + 1970
            y_hi = int(_np.datetime64(int(arg_rng[1]), "ms")
                       .astype("datetime64[Y]").astype(_np.int64)) + 1970
            return y_lo, 1, y_hi - y_lo + 1
        if name == "datetrunc" and len(g.args) == 2 \
                and isinstance(g.args[0], Literal):
            unit = str(g.args[0].value).lower()
            stride = self._TRUNC_STRIDES.get(unit)
            if stride is None:
                return None
            arg_rng = self._range_of(g.args[1])
            if arg_rng is None:
                return None
            ms_lo, ms_hi = int(arg_rng[0]), int(arg_rng[1])
            if unit == "week":
                import math as _math
                d_lo = _math.floor(ms_lo / 86_400_000)
                d_hi = _math.floor(ms_hi / 86_400_000)
                t_lo = ((d_lo + 3) // 7 * 7 - 3) * 86_400_000
                t_hi = ((d_hi + 3) // 7 * 7 - 3) * 86_400_000
            else:
                import math as _math
                t_lo = _math.floor(ms_lo / stride) * stride
                t_hi = _math.floor(ms_hi / stride) * stride
            return t_lo, stride, (t_hi - t_lo) // stride + 1

        return None

    def _expr_key_ir(self, g: FuncCall, lo: int, stride: int) -> ValueExpr:
        """The [0, card) key expression for a ranged group expression."""
        from .functions import canonical
        name = canonical(g.name)
        name = "day" if name == "dayofmonth" else name
        if name == "datetrunc":
            unit = str(g.args[0].value).lower()
            v, vi = self.resolve_value(g.args[1])
            if not vi:
                raise PlanError("dateTrunc key over non-integer (host)")
            f = FuncIR(f"trunc_{unit}", (v,))
        else:
            v, vi = self.resolve_value(g.args[0])
            if not vi:
                raise PlanError(f"{g.name} key over non-integer (host)")
            f = FuncIR(name, (v,))
        out: ValueExpr = f
        if lo:
            out = Bin("-", out, Lit(self.b.add_param(np.int64(lo))))
        if stride != 1:
            out = Bin("//", out, Lit(self.b.add_param(np.int64(stride))))
        return out

    def _device_func(self, e: FuncCall) -> Tuple[ValueExpr, bool]:
        from .functions import canonical
        name = canonical(e.name)
        if name == "datetrunc" and len(e.args) == 2 and                 isinstance(e.args[0], Literal):
            unit = str(e.args[0].value).lower()
            if unit in ("second", "minute", "hour", "day", "week",
                        "month", "quarter", "year"):
                v, vi = self.resolve_value(e.args[1])
                if not vi:
                    raise PlanError("dateTrunc over non-integer (host)")
                return FuncIR(f"trunc_{unit}", (v,)), True
            raise PlanError(f"dateTrunc unit {unit!r} (host fallback)")
        integral = self._DEVICE_FUNCS.get("day" if name == "dayofmonth"
                                          else name, "missing")
        if integral == "missing" or len(e.args) != 1 or e.distinct:
            raise PlanError(f"no device lowering for {e.name!r} "
                            "(host fallback)")
        v, vi = self.resolve_value(e.args[0])
        if integral is True and not vi:
            raise PlanError(f"{e.name} over non-integer input (host)")
        name = "day" if name == "dayofmonth" else name
        out_integral = vi if integral is None else integral
        return FuncIR(name, (v,)), out_integral

    _DEVICE_CASTS = {"long": "cast_long", "bigint": "cast_long",
                     "int": "cast_int", "integer": "cast_int",
                     "double": "cast_double", "float": "cast_float"}

    def _device_cast(self, e: Cast) -> Tuple[ValueExpr, bool]:
        fn = self._DEVICE_CASTS.get(e.type_name.lower())
        if fn is None:
            raise PlanError(f"CAST to {e.type_name!r} (host fallback)")
        v, _vi = self.resolve_value(e.expr)
        return FuncIR(fn, (v,)), fn in ("cast_long", "cast_int")

    def _device_case(self, e: CaseWhen) -> Tuple[ValueExpr, bool]:
        if e.else_ is None:
            # CASE with no ELSE yields NULL for unmatched rows — null
            # result semantics live on the host path
            raise PlanError("CASE without ELSE (host fallback)")
        whens = []
        integral = True
        for cond, res in e.whens:
            pred = _simplify(self._pred(cond))
            v, vi = self.resolve_value(res)
            integral = integral and vi
            whens.append((pred, v))
        ev, ei = self.resolve_value(e.else_)
        return CaseIR(tuple(whens), ev), integral and ei

    # -- predicates --------------------------------------------------------
    def resolve_filter(self, e: Any) -> Pred:
        if e is None:
            return TrueP()
        if self.null_aware and self._nullable_refs(e):
            # enableNullHandling: a row passes only when the predicate is
            # TRUE under three-valued logic. The T/F pair propagates
            # through the tree as ordinary 2VL predicates (host peer:
            # engine/host_eval.eval_filter_3vl), so the kernel stays
            # mask-in mask-out
            t, _f = self._pred_3vl(e)
            return _simplify(t)
        return _simplify(self._pred(e))

    def _nullable_refs(self, e: Any) -> List[str]:
        refs: set = set()
        collect_identifiers(e, refs)
        return [r for r in sorted(refs)
                if getattr(self.seg.columns.get(r), "has_nulls", False)]

    def _null_any_pred(self, e: Any) -> Optional[Pred]:
        """Pred true where ANY input column of e is null (SQL null
        propagation: one null input makes the comparison UNKNOWN)."""
        parts = [MaskParamP(self.b.add_param(("nullmask", r)))
                 for r in self._nullable_refs(e)]
        if not parts:
            return None
        return parts[0] if len(parts) == 1 else Or(tuple(parts))

    def _pred_3vl(self, e: Any) -> Tuple[Pred, Pred]:
        """-> (T, F) preds under Kleene logic (rows not in T and not in F
        are UNKNOWN — filtered out, since only TRUE passes)."""
        if isinstance(e, BoolAnd):
            ts, fs = zip(*(self._pred_3vl(c) for c in e.children))
            return And(ts), Or(fs)
        if isinstance(e, BoolOr):
            ts, fs = zip(*(self._pred_3vl(c) for c in e.children))
            return Or(ts), And(fs)
        if isinstance(e, BoolNot):
            t, f = self._pred_3vl(e.child)
            return f, t
        if isinstance(e, IsNull):
            t = self._pred(e)  # IS [NOT] NULL never yields UNKNOWN
            return t, Not(t)
        # leaf predicate: 2VL result, demoted to UNKNOWN on null inputs
        # (negated leaves included — host_eval.eval_filter_3vl contract)
        p = self._pred(e)
        nm = self._null_any_pred(e)
        if nm is None:
            return p, Not(p)
        valid = Not(nm)
        return _simplify(And((p, valid))), _simplify(And((Not(p), valid)))

    def _pred(self, e: Any) -> Pred:
        if isinstance(e, BoolAnd):
            return And(tuple(self._pred(c) for c in e.children))
        if isinstance(e, BoolOr):
            return Or(tuple(self._pred(c) for c in e.children))
        if isinstance(e, BoolNot):
            return Not(self._pred(e.child))
        if isinstance(e, Comparison):
            return self._comparison(e)
        if isinstance(e, Between):
            p = self._range(e.expr, e.lo, e.hi, True, True)
            if e.negated:
                name = e.expr.name if isinstance(e.expr, Identifier) \
                    else None
                return self._value_negate(p, name)
            return p
        if isinstance(e, InList):
            return self._in_list(e)
        if isinstance(e, Like):
            return self._like(e)
        if isinstance(e, IsNull):
            return self._is_null(e)
        if isinstance(e, Literal) and isinstance(e.value, bool):
            return TrueP() if e.value else FalseP()
        from ..index.predicates import is_index_predicate, index_filter_mask
        if is_index_predicate(e):
            # TEXT_MATCH / JSON_MATCH / VECTOR_SIMILARITY: the index
            # evaluates host-side into a doc mask shipped as a kernel param
            # (SqlError propagates when the index is missing — user error,
            # not host fallback)
            return self._mask_pred(index_filter_mask(self.seg, e))
        from ..index.predicates import try_geo_inclusion_mask
        gmask = try_geo_inclusion_mask(self.seg, e) \
            if isinstance(e, FuncCall) else None
        if gmask is not None:
            # bare boolean ST_Contains/ST_Within over an indexed column
            return self._mask_pred(gmask)
        if isinstance(e, FuncCall):
            p = self._dict_transform_bool(e)
            if p is not None:
                return p
        raise PlanError(f"unsupported filter expression {e!r}")

    def _comparison(self, e: Comparison) -> Pred:
        lhs, rhs, op = e.lhs, e.rhs, e.op
        # normalize literal to the right
        if isinstance(lhs, Literal) and not isinstance(rhs, Literal):
            lhs, rhs = rhs, lhs
            op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
        if isinstance(lhs, Identifier) and isinstance(rhs, Literal):
            name, v = lhs.name, rhs.value
            m = self.seg.columns.get(name)
            if m is None:
                raise PlanError(f"unknown column {name!r}")
            if m.has_dict:
                d = self.seg.dictionary(name)
                if op == "==":
                    i = d.index_of(self._cast_for(m, v))
                    if i < 0:
                        return FalseP()
                    return EqId(self.b.bind_col(name),
                                self.b.add_param(np.int32(i)))
                if op == "!=":
                    i = d.index_of(self._cast_for(m, v))
                    if i < 0:
                        return self._value_negate(FalseP(), name)
                    return self._value_negate(
                        EqId(self.b.bind_col(name),
                             self.b.add_param(np.int32(i))), name)
                lo, hi, il, ih = {
                    "<": (None, v, True, False),
                    "<=": (None, v, True, True),
                    ">": (v, None, False, True),
                    ">=": (v, None, True, True),
                }[op]
                return self._dict_range(name, lo, hi, il, ih)
            # raw column
            return self._raw_cmp(name, m, op, v)
        geo = self._geo_comparison(lhs, op, rhs)
        if geo is not None:
            return geo
        # generic: expr vs expr -> compare difference against zero
        try:
            l, li = self.resolve_value(lhs)
            r, ri = self.resolve_value(rhs)
        except PlanError:
            # no device lowering (string functions etc.): a transform of
            # ONE dict column still plans on-device by evaluating the
            # expression over the DICTIONARY host-side and shipping the
            # matching-id set — the dictionary-based predicate evaluator
            # trick LIKE already uses (reference:
            # predicate/EqualsPredicateEvaluatorFactory dictionary path)
            p = self._dict_transform_cmp(lhs, op, rhs)
            if p is not None:
                return p
            raise
        zero = self.b.add_param(np.int64(0) if (li and ri) else np.float64(0))
        return Cmp(Bin("-", l, r), op, zero)

    # dictionary cardinality above which per-query host evaluation over
    # the dictionary stops paying for itself
    DICT_EVAL_LIMIT = 1 << 17

    def _dict_transform_cmp(self, lhs: Any, op: str,
                            rhs: Any) -> Optional[Pred]:
        if not isinstance(rhs, Literal):
            return None
        out, name = self._eval_over_dict(lhs)
        if out is None:
            return None
        v = rhs.value
        try:
            with np.errstate(all="ignore"):
                if op == "==":
                    hit = out == v
                elif op == "!=":
                    hit = out != v
                else:
                    cmpf = {"<": np.less, "<=": np.less_equal,
                            ">": np.greater,
                            ">=": np.greater_equal}[op]
                    hit = cmpf(out, v)
        except (TypeError, ValueError):
            return None
        return self._ids_pred(name, np.nonzero(np.asarray(hit))[0])

    def _dict_transform_bool(self, e: Any) -> Optional[Pred]:
        """Bare boolean transform (startsWith(city, 'x')) over one dict
        column -> matching-id pred."""
        out, name = self._eval_over_dict(e)
        if out is None:
            return None
        try:
            hit = np.asarray(out).astype(bool)
        except (TypeError, ValueError):
            return None
        return self._ids_pred(name, np.nonzero(hit)[0])

    def _ids_pred(self, name: str, ids: np.ndarray) -> Pred:
        m = self.seg.columns[name]
        if len(ids) == 0:
            return FalseP()
        if len(ids) == m.cardinality:
            # full coverage folds to "has any value": empty MV rows must
            # still NOT match (the direct dictionary path's semantics)
            return self._mv_has_value(name) if self._is_mv(name) \
                else TrueP()
        from ..ops.kernels import INSET_BITMAP_MIN
        if m.cardinality >= INSET_BITMAP_MIN * 4 \
                and len(ids) > m.cardinality // 8:
            table = np.zeros(m.cardinality, dtype=bool)
            table[ids] = True
            return InBitmap(self.b.bind_col(name), self.b.add_param(table))
        arr = _pad_dup(np.sort(ids).astype(np.int32))
        return InSet(self.b.bind_col(name), self.b.add_param(arr),
                     len(arr))

    def _eval_over_dict(self, e: Any):
        """Evaluate an elementwise single-column transform expression
        over the column's dictionary -> (values per dict id, col name);
        (None, None) when the shape doesn't qualify."""
        refs: set = set()
        collect_identifiers(e, refs)
        if len(refs) != 1:
            return None, None
        name = next(iter(refs))
        m = self.seg.columns.get(name)
        if m is None or not m.has_dict or m.cardinality == 0 \
                or m.cardinality > self.DICT_EVAL_LIMIT:
            return None, None
        vals = np.asarray(self.seg.dictionary(name).values)

        from . import functions as F

        def ev(node: Any):
            if isinstance(node, Identifier):
                return vals
            if isinstance(node, Literal):
                return node.value
            if isinstance(node, FuncCall) and not node.distinct:
                fd = F.lookup(node.name)
                if fd is None or not fd.elementwise:
                    raise PlanError(f"non-elementwise {node.name!r}")
                return fd.fn(*[ev(a) for a in node.args])
            if isinstance(node, BinaryOp):
                l, r = ev(node.lhs), ev(node.rhs)
                return {"+": lambda: l + r, "-": lambda: l - r,
                        "*": lambda: l * r,
                        "/": lambda: np.asarray(l, dtype=np.float64)
                        / np.asarray(r, dtype=np.float64),
                        "%": lambda: l % r}[node.op]()
            if isinstance(node, Cast):
                return F.cast_value(ev(node.expr), node.type_name)
            raise PlanError(f"no dictionary evaluation for {node!r}")

        try:
            out = ev(e)
        except (PlanError, SqlError, TypeError, ValueError, KeyError):
            return None, None
        out = np.asarray(out)
        if out.shape != (m.cardinality,):
            return None, None
        return out, name

    def _geo_comparison(self, lhs, op: str, rhs) -> Optional[Pred]:
        """Index-backed geospatial comparisons (H3IndexFilterOperator /
        H3InclusionIndexFilterOperator analogs): ST_Distance(col, point)
        <op> r, and ST_Contains/ST_Within(...) = 0|1. None when the shape
        doesn't match or the column has no geo index (host path then
        evaluates the ST_* scalar row-wise, like the reference's scan
        filter fallback)."""
        from ..index.predicates import (try_geo_distance_mask,
                                        try_geo_inclusion_mask)
        mask = try_geo_distance_mask(self.seg, lhs, op, rhs)
        if mask is None and isinstance(rhs, Literal) and op in ("==", "!=") \
                and isinstance(rhs.value, (bool, int)) \
                and rhs.value in (0, 1, True, False):
            positive = bool(rhs.value) == (op == "==")
            mask = try_geo_inclusion_mask(self.seg, lhs, positive=positive)
        if mask is None:
            return None
        return self._mask_pred(mask)

    def _mask_pred(self, mask) -> Pred:
        """Host-computed doc mask -> constant-folded pred or docmask
        kernel param (shared by index, geo, and bare-boolean filters)."""
        if not mask.any():
            return FalseP()
        if mask.all():
            return TrueP()
        return MaskParamP(self.b.add_param(("docmask", mask)))

    def _cast_for(self, m, v: Any) -> Any:
        if m.data_type == DataType.STRING or not m.data_type.is_numeric:
            return str(v)
        if isinstance(v, str):
            # BadQueryRequestException analog: literal must coerce to the
            # column's numeric type
            try:
                return float(v) if "." in v or "e" in v.lower() else int(v)
            except ValueError:
                raise PlanError(
                    f"cannot compare numeric column with {v!r}") from None
        return v

    def _raw_cmp(self, name: str, m, op: str, v: Any) -> Pred:
        v = self._cast_for(m, v)  # coerce string literals; PlanError if not
        if op == "==" and "bloom" in getattr(m, "indexes", {}):
            # BloomFilterSegmentPruner analog: a definite miss folds the
            # predicate (and possibly the whole segment plan) to FalseP.
            # Coerce the literal to the column dtype first so its string
            # hash matches how the build stringified the typed array
            # (int literal 5 vs stored float "5.0" must not false-prune).
            reader = self.seg.index_reader(name, "bloom")
            probe = (np.asarray(v, dtype=m.data_type.np_dtype)
                     if m.data_type.is_numeric else v)
            if reader is not None and not reader.might_contain(probe):
                return FalseP()
        # min/max constant folding = ColumnValueSegmentPruner for raw columns
        mn, mx = m.min, m.max
        if mn is not None and mx is not None and isinstance(v, (int, float)):
            if op == "==" and (v < mn or v > mx):
                return FalseP()
            if op in ("<", "<=") and v < mn:
                return FalseP()
            if op in (">", ">=") and v > mx:
                return FalseP()
            if op == "<=" and v >= mx:
                return TrueP()
            if op == ">=" and v <= mn:
                return TrueP()
            if op == "<" and v > mx:
                return TrueP()
            if op == ">" and v < mn:
                return TrueP()
        idx = self.b.bind_col(name)
        dt = m.data_type.np_dtype
        if np.issubdtype(dt, np.integer) and isinstance(v, float) \
                and v != int(v):
            # fractional literal vs int column: rewrite to exact int bound
            if op == "==":
                return FalseP()
            if op == "!=":
                return TrueP()
            import math
            if op in ("<", "<="):
                v2 = math.floor(v)
                return Cmp(Col(idx), "<=", self.b.add_param(np.asarray(v2, dt)))
            v2 = math.ceil(v)
            return Cmp(Col(idx), ">=", self.b.add_param(np.asarray(v2, dt)))
        p = self.b.add_param(np.asarray(v, dt) if m.data_type.is_numeric
                             else np.float64(v))
        return Cmp(Col(idx), op, p)

    def _generic_cmp(self, lhs_ast: Any, op: str, rhs_ast: Any) -> Pred:
        """expr-vs-expr comparison: compare the difference against zero."""
        l, li = self.resolve_value(lhs_ast)
        r, ri = self.resolve_value(rhs_ast)
        zero = self.b.add_param(np.int64(0) if (li and ri) else np.float64(0))
        return Cmp(Bin("-", l, r), op, zero)

    def _range(self, expr: Any, lo: Any, hi: Any, il: bool, ih: bool) -> Pred:
        # non-literal bounds (column/expression BETWEEN bounds) or a
        # non-column subject: generic expression comparisons
        lo_lit = lo is None or isinstance(lo, Literal)
        hi_lit = hi is None or isinstance(hi, Literal)
        if not isinstance(expr, Identifier) or not (lo_lit and hi_lit):
            kids: List[Pred] = []
            if lo is not None:
                kids.append(self._generic_cmp(expr, ">=" if il else ">", lo))
            if hi is not None:
                kids.append(self._generic_cmp(expr, "<=" if ih else "<", hi))
            return And(tuple(kids)) if kids else TrueP()
        name = expr.name
        m = self.seg.columns.get(name)
        if m is None:
            raise PlanError(f"unknown column {name!r}")
        lo_v = lo.value if isinstance(lo, Literal) else None
        hi_v = hi.value if isinstance(hi, Literal) else None
        if m.has_dict:
            return self._dict_range(name, lo_v, hi_v, il, ih)
        kids = []
        if lo_v is not None:
            kids.append(self._raw_cmp(name, m, ">=" if il else ">", lo_v))
        if hi_v is not None:
            kids.append(self._raw_cmp(name, m, "<=" if ih else "<", hi_v))
        return _simplify(And(tuple(kids))) if kids else TrueP()

    def _is_mv(self, name: Optional[str]) -> bool:
        if name is None:
            return False
        m = self.seg.columns.get(name)
        return m is not None and not getattr(m, "single_value", True)

    def _mv_has_value(self, name: str) -> Pred:
        """Matches rows with at least one value: value-level negation of a
        nothing-matches predicate on an MV column (empty arrays match
        nothing). -2 equals no dict id, so negated-EqId flips every real
        value true while pads stay excluded."""
        return EqId(self.b.bind_col(name), self.b.add_param(np.int32(-2)),
                    negated=True)

    def _value_negate(self, p: Pred, name: Optional[str]) -> Pred:
        """!=, NOT IN, NOT BETWEEN negate per VALUE: an MV row matches when
        ANY value fails the base predicate (reference NotEquals/NotIn/
        NotBetween applyMV semantics) — different from doc-level Not().
        Identical for single-value columns."""
        from dataclasses import replace as dc_replace
        if isinstance(p, (EqId, IdRange, InSet, InBitmap)):
            return dc_replace(p, negated=not p.negated)
        if self._is_mv(name):
            if isinstance(p, FalseP):   # base matched no value
                return self._mv_has_value(name)
            if isinstance(p, TrueP):    # base matched every value
                return FalseP()
        return Not(p)

    def _dict_range(self, name: str, lo: Any, hi: Any, il: bool, ih: bool
                    ) -> Pred:
        m = self.seg.columns[name]
        d = self.seg.dictionary(name)
        if lo is not None:
            lo = self._cast_for(m, lo)
        if hi is not None:
            hi = self._cast_for(m, hi)
        lo_id, hi_id = d.id_range(lo, hi, il, ih)
        if lo_id > hi_id:
            return FalseP()
        if lo_id == 0 and hi_id == d.cardinality - 1:
            return TrueP()
        idx = self.b.bind_col(name)
        lo_p = self.b.add_param(np.int32(lo_id)) if lo_id > 0 else None
        hi_p = (self.b.add_param(np.int32(hi_id))
                if hi_id < d.cardinality - 1 else None)
        return IdRange(idx, lo_p, hi_p)

    def _in_list(self, e: InList) -> Pred:
        if not isinstance(e.expr, Identifier):
            raise PlanError("IN over expressions not supported yet")
        name = e.expr.name
        m = self.seg.columns.get(name)
        if m is None:
            raise PlanError(f"unknown column {name!r}")
        vals = [v.value for v in e.values]
        if not vals:  # empty IN list (e.g. an empty IN-subquery result)
            return self._value_negate(FalseP(), name) if e.negated \
                else FalseP()
        from ..ops.kernels import INSET_BITMAP_MIN
        if m.has_dict:
            d = self.seg.dictionary(name)
            ids = [d.index_of(self._cast_for(m, v)) for v in vals]
            ids = sorted({i for i in ids if i >= 0})
            if not ids:
                return self._value_negate(FalseP(), name) if e.negated \
                    else FalseP()
            if len(ids) > INSET_BITMAP_MIN:
                # big IN list on a dict column: one presence-table gather
                # per value (InBitmap) instead of a broadcast compare
                table = np.zeros(m.cardinality, dtype=bool)
                table[np.asarray(ids)] = True
                p: Pred = InBitmap(self.b.bind_col(name),
                                   self.b.add_param(table))
            else:
                arr = _pad_dup(np.asarray(ids, dtype=np.int32))
                p = InSet(self.b.bind_col(name), self.b.add_param(arr),
                          len(arr))
        else:
            vals = sorted(self._cast_for(m, v) for v in vals)
            arr = _pad_dup(np.asarray(vals, dtype=m.data_type.np_dtype))
            p = InSet(self.b.bind_col(name), self.b.add_param(arr), len(arr))
        return self._value_negate(p, name) if e.negated else p

    def _like(self, e: Like) -> Pred:
        if not isinstance(e.expr, Identifier):
            raise PlanError("LIKE over expressions not supported")
        name = e.expr.name
        m = self.seg.columns.get(name)
        if m is None or not m.has_dict:
            raise PlanError(f"LIKE needs a dictionary column, got {name!r}")
        d = self.seg.dictionary(name)
        rx = _like_to_regex(e.pattern)
        ids = [i for i, v in enumerate(d.values) if rx.match(str(v))]
        if not ids:
            return TrueP() if e.negated else FalseP()
        if len(ids) == d.cardinality:
            return FalseP() if e.negated else TrueP()
        arr = _pad_dup(np.asarray(ids, dtype=np.int32))
        p = InSet(self.b.bind_col(name), self.b.add_param(arr), len(arr))
        return Not(p) if e.negated else p

    def _is_null(self, e: IsNull) -> Pred:
        if not isinstance(e.expr, Identifier):
            raise PlanError("IS NULL over expressions not supported")
        name = e.expr.name
        m = self.seg.columns.get(name)
        if m is None:
            raise PlanError(f"unknown column {name!r}")
        if not m.has_nulls:
            return TrueP() if e.negated else FalseP()
        p = IsNullIR(self.b.add_param(("nullmask", name)))
        return Not(p) if e.negated else p

    # -- value range analysis (sizes the exact int8-limb MXU group sums) ---
    def _range_of(self, e: Any) -> Optional[Tuple[float, float]]:
        if isinstance(e, Identifier):
            m = self.seg.columns.get(e.name)
            if m is None or not m.data_type.is_numeric:
                return None
            if m.min is None or m.max is None:
                return None
            return (float(m.min), float(m.max))
        if isinstance(e, Literal) and isinstance(e.value, (int, float)) \
                and not isinstance(e.value, bool):
            return (float(e.value), float(e.value))
        if isinstance(e, BinaryOp):
            lr = self._range_of(e.lhs)
            rr = self._range_of(e.rhs)
            if lr is None or rr is None:
                return None
            (a, b), (c, d) = lr, rr
            if e.op == "+":
                return (a + c, b + d)
            if e.op == "-":
                return (a - d, b - c)
            if e.op == "*":
                corners = (a * c, a * d, b * c, b * d)
                return (min(corners), max(corners))
            return None
        return None

    @staticmethod
    def _bits_for(rng: Optional[Tuple[float, float]]) -> Tuple[int, bool]:
        if rng is None:
            return 63, True
        lo, hi = rng
        mag = max(abs(lo), abs(hi))
        bits = max(1, int(mag).bit_length()) if mag < 2 ** 62 else 63
        return min(bits, 63), lo < 0

    # -- aggregations ------------------------------------------------------
    def resolve_agg(self, i: int, agg: AggExpr) -> Tuple[AggSpec, AggBinding]:
        if agg.kind == "count" and agg.arg is None:
            return (AggSpec("count", None, True),
                    AggBinding(agg, i, True))
        if agg.kind == "distinct_count":
            if isinstance(agg.arg, Identifier):
                m = self.seg.columns.get(agg.arg.name)
                if m is not None and m.has_dict \
                        and getattr(m, "single_value", True):
                    idx = self.b.bind_col(agg.arg.name)
                    spec = AggSpec("distinct_count", Col(idx), True,
                                   card=m.cardinality,
                                   null_param=self._agg_null_param(agg))
                    return spec, AggBinding(agg, i, True,
                                            dict_col=agg.arg.name)
            raise PlanError("DISTINCTCOUNT needs a dictionary column "
                            "(host fallback handles the rest)")
        if agg.kind == "count":  # COUNT(col): Pinot counts all rows when
            # null handling is disabled (NullableSingleInputAggregationFunction)
            # — and skips null inputs when it is enabled
            return (AggSpec("count", None, True,
                            null_param=self._agg_null_param(agg)),
                    AggBinding(agg, i, True))
        if agg.kind in ("sum_mv", "count_mv", "min_mv", "max_mv"):
            if self.null_aware and isinstance(agg.arg, Identifier) and \
                    getattr(self.seg.columns.get(agg.arg.name),
                            "has_nulls", False):
                raise PlanError("null-aware MV aggregation (host fallback)")
            return self._resolve_mv_agg(i, agg)
        if agg.kind in ("distinct_count_hll", "distinct_count_theta",
                        "percentile_sketch", "raw_hll", "raw_theta",
                        "percentile_raw_sketch"):
            return self._resolve_sketch_agg(i, agg)
        if agg.kind not in ("sum", "min", "max", "avg"):
            raise PlanError(f"no device lowering for {agg.kind} "
                            "(host fallback)")
        ve, integral = self.resolve_value(agg.arg)
        bits, signed = self._bits_for(self._range_of(agg.arg))
        return (AggSpec(agg.kind, ve, integral, bits=bits, signed=signed,
                        null_param=self._agg_null_param(agg)),
                AggBinding(agg, i, integral))

    def _resolve_sketch_agg(self, i: int, agg: AggExpr
                            ) -> Tuple[AggSpec, AggBinding]:
        """Device lowerings for the flagship sketches (round-5, VERDICT
        r4 next-step #2): DISTINCTCOUNTHLL (register presence bitmap),
        DISTINCTCOUNTTHETASKETCH (k smallest distinct hashes), and the
        PERCENTILEKLL/EST/TDIGEST family (sorted equal-count centroids).
        Partial states match ops/aggregations' host AggImpl formats, so
        kernel and host partials merge interchangeably at the broker.
        Scalar plans only — grouped sketches keep the host registry."""
        if self.ctx.is_group_by and agg.kind not in ("distinct_count_hll",
                                                     "raw_hll"):
            # grouped HLL has a device lowering (presence bitmap, OR-
            # mergeable); theta/percentile group states keep the host
            # registry
            raise PlanError("grouped sketch aggregations use the host "
                            "registry")
        if not isinstance(agg.arg, Identifier):
            raise PlanError("sketch device lowering needs a plain column")
        m = self.seg.columns.get(agg.arg.name)
        if m is None or not getattr(m, "single_value", True):
            raise PlanError("sketch device lowering needs an SV column")
        null_param = self._agg_null_param(agg)

        if agg.kind in ("percentile_sketch", "percentile_raw_sketch"):
            ve, _integral = self.resolve_value(agg.arg)
            from ..ops.aggregations import TDIGEST_MAX_CENTROIDS
            return (AggSpec(agg.kind, ve, False,
                            card=TDIGEST_MAX_CENTROIDS,
                            null_param=null_param),
                    AggBinding(agg, i, False))

        # HLL / theta hash sources: dict columns gather a precomputed
        # per-id hash table (host _hash64 covers strings via md5); raw
        # numeric columns hash on device (splitmix64, bit-identical).
        idx = self.b.bind_col(agg.arg.name)
        if m.has_dict:
            hp = self.b.add_param(("hash64", agg.arg.name))
            ve = Col(idx, hp)
        else:
            if not m.data_type.is_numeric:
                raise PlanError("raw non-numeric sketch input needs the "
                                "host path")
            if not m.data_type.is_integral:
                from ..ops.compact import f64_bitcast_ok
                if not f64_bitcast_ok():
                    # hashing a raw float needs an f64 bit view, which
                    # XLA:TPU cannot lower
                    raise PlanError("raw float sketch input needs the "
                                    "host path on this backend")
            ve = Col(idx)
        from ..ops.aggregations import HLL_DEFAULT_LOG2M
        from ..ops.sketches import THETA_DEFAULT_NOMINAL
        if agg.kind in ("distinct_count_hll", "raw_hll"):
            card = int(agg.params[0]) if agg.params else HLL_DEFAULT_LOG2M
            if not 4 <= card <= 16:
                raise PlanError(f"log2m {card} outside the device range")
        else:
            card = int(agg.params[0]) if agg.params \
                else THETA_DEFAULT_NOMINAL
            if not 1 <= card <= (1 << 16):
                raise PlanError(f"theta k {card} outside the device range")
        return (AggSpec(agg.kind, ve, False, card=card,
                        null_param=null_param),
                AggBinding(agg, i, False))

    def _agg_null_param(self, agg: AggExpr) -> Optional[int]:
        """Null-mask param for a null-aware aggregation's input (skip-null
        semantics, NullableSingleInputAggregationFunction). Host fallback
        for shapes the kernel can't mask per-agg: multi-column nullable
        inputs and group-by plans (the group machinery applies one shared
        mask)."""
        if not self.null_aware:
            return None
        refs: set = set()
        for arg in (agg.arg, agg.arg2):
            if arg is not None:
                collect_identifiers(arg, refs)
        nullable = [r for r in sorted(refs)
                    if getattr(self.seg.columns.get(r), "has_nulls", False)]
        if not nullable:
            return None
        if len(nullable) > 1 or self.ctx.is_group_by:
            raise PlanError("null-aware aggregation shape needs the host "
                            "path")
        return self.b.add_param(("nullmask", nullable[0]))

    SELECT_K_CAP = 1 << 14

    def _plan_selection(self) -> Optional[CompiledPlan]:
        """Device selection: SELECT cols [WHERE ...] [ORDER BY cols]
        LIMIT k -> filter mask + composite order key + lax.top_k + gather
        (ops/kernels.build_select_kernel). Returns None when the shape
        needs the host path (expressions, MV/null cells, non-integral raw
        order keys, unbounded limit)."""
        from ..ops.ir import SelectPlan
        ctx, seg = self.ctx, self.seg
        if ctx.limit is None:
            return None
        # a segment contributes at most bucket rows; lax.top_k also
        # requires k <= operand length
        k = min(ctx.offset + ctx.limit, seg.bucket)
        if not 0 < ctx.offset + ctx.limit <= self.SELECT_K_CAP:
            return None

        names: List[str] = []
        for item in ctx.select_items:
            if isinstance(item, Star):
                names.extend(seg.columns)
            elif isinstance(item, Identifier):
                names.append(item.name)
            else:
                return None
        nh = self.null_aware

        def col_ok(name: str) -> bool:
            m = seg.columns.get(name)
            return (m is not None and getattr(m, "single_value", True)
                    and not (nh and getattr(m, "has_nulls", False)))

        if not all(col_ok(n) for n in names):
            return None

        order: List[Tuple[str, bool, int]] = []
        span = 1
        for o in ctx.order_by:
            if not isinstance(o.expr, Identifier) or not col_ok(o.expr.name):
                return None
            m = seg.columns[o.expr.name]
            if m.has_dict:
                card = max(m.cardinality, 1)
                span *= card
                order.append((o.expr.name, not o.ascending, card))
            else:
                # raw keys can't radix-pack: only a single integral one,
                # with bounds well inside int64 so negation can't wrap
                # into (or past) the unmatched-row sentinel
                if len(ctx.order_by) != 1 or not m.data_type.is_numeric \
                        or m.data_type.np_dtype.kind not in "iu" \
                        or m.min is None or m.max is None \
                        or max(abs(int(m.min)), abs(int(m.max))) >= 1 << 61:
                    return None
                order.append((o.expr.name, not o.ascending, 0))
        if span >= 1 << 62:
            return None

        pred = self.resolve_filter(ctx.filter)  # PlanError -> host (caller)
        if isinstance(pred, FalseP):
            # select_names preserves expanded star labels in the empty
            # result (the host path expands them even for 0 rows)
            return CompiledPlan("pruned", seg, ctx, select_names=names)
        if getattr(seg, "valid_docs", None) is not None and \
                not _truthy(ctx.options.get("skipUpsert")):
            pred = _simplify(And((pred, MaskParamP(
                self.b.add_param(("validdocs", None))))))

        sel_idx = tuple(self.b.bind_col(n) for n in names)
        order_idx = tuple((self.b.bind_col(n), d, c) for n, d, c in order)
        sp = SelectPlan(pred=pred, select_cols=sel_idx, order=order_idx,
                        k=k)
        return CompiledPlan("kselect", seg, ctx, col_names=self.b.cols,
                            params=self.b.params, select_plan=sp,
                            select_names=names)

    def _resolve_mv_agg(self, i: int, agg: AggExpr
                        ) -> Tuple[AggSpec, AggBinding]:
        """SUMMV/COUNTMV/MINMV/MAXMV lower to the base kind over a per-row
        MvReduce (ops/ir.py); AVGMV and DISTINCTCOUNTMV stay host-side
        (their device states need a values-count column pair / 2-D
        presence)."""
        from ..ops.aggregations import base_kind
        from ..ops.ir import MvReduce

        if not isinstance(agg.arg, Identifier):
            raise PlanError("MV aggregations take a column argument")
        name = agg.arg.name
        m = self.seg.columns.get(name)
        if m is None or getattr(m, "single_value", True) \
                or not m.has_dict:
            raise PlanError(f"{agg.kind} needs a multi-value dictionary "
                            f"column (host fallback)")
        idx = self.b.bind_col(name)
        base = base_kind(agg.kind)
        if agg.kind == "count_mv":
            # per-row value count <= maxValues: tiny exact int sums
            bits = max(1, int(m.max_values or 1).bit_length())
            spec = AggSpec("sum", MvReduce(idx, "count"), True,
                           bits=bits, signed=False)
            return spec, AggBinding(agg, i, True)
        if not m.data_type.is_numeric:
            raise PlanError(f"{agg.kind} over a non-numeric MV column "
                            "(host fallback)")
        integral = m.data_type.np_dtype.kind in "iu"
        dict_param = self.b.add_param(("dictvals", name))
        mode = agg.kind.split("_")[0]  # sum | min | max
        ve = MvReduce(idx, mode, dict_param)
        if m.min is None or m.max is None:
            rng = None
        elif mode == "sum":
            # per-row sum bound: maxValues * max magnitude
            mv = float(m.max_values or 1)
            rng = (min(0.0, float(m.min) * mv), float(m.max) * mv)
        else:
            rng = (float(m.min), float(m.max))
        bits, signed = self._bits_for(rng)
        spec = AggSpec(base, ve, integral, bits=bits, signed=signed)
        return spec, AggBinding(agg, i, integral)

    # -- validation --------------------------------------------------------
    def _validate_columns(self) -> None:
        """Unknown columns are user errors everywhere (including host-path
        queries), not host-fallback surprises."""
        ctx = self.ctx
        names: List[str] = []

        from .sql import ast_children

        def walk(e: Any) -> None:
            if isinstance(e, Identifier):
                names.append(e.name)
            for c in ast_children(e):
                walk(c)

        walk(ctx.filter)
        for g in ctx.group_by:
            walk(g)
        for agg in ctx.aggregations:
            if agg.arg is not None:
                walk(agg.arg)
        for item in ctx.select_items:
            if not isinstance(item, (Star,)) and not hasattr(item, "kind"):
                walk(item)
        # virtual columns synthesize host-side (host_eval.virtual_column)
        virtual = {"$docId", "$segmentName", "$hostName"}
        for n in names:
            if n not in self.seg.columns and n not in virtual:
                raise PlanError(f"unknown column {n!r}; segment has "
                                f"{list(self.seg.columns)}")
        self._validate_vector_calls()

    def _validate_vector_calls(self) -> None:
        """VECTOR_SIMILARITY fail-fast validation over the filter,
        select list AND order-by (the order-by isn't part of the column
        walk above): malformed calls — missing index, dim mismatch,
        k <= 0, non-numeric ARRAY — are structured user errors (plain
        SqlError, HTTP 400), raised at plan time on every path.
        Deliberately NOT PlanError: a bad call must never demote to a
        host-path surprise."""
        from ..engine.vector_exec import validate_call, vector_calls
        ctx = self.ctx
        calls = vector_calls(
            ctx.filter,
            *[i for i in ctx.select_items if not hasattr(i, "kind")],
            *[o.expr for o in ctx.order_by])
        for call in calls:
            validate_call(self.seg, call)

    # -- top-level ---------------------------------------------------------
    def plan(self) -> CompiledPlan:
        """Plan this segment, recording the outcome (plan kind, strategy,
        cost-model trace) as a child span of the query's planning span
        when a trace is active (utils/spans.py — no-op otherwise)."""
        from ..utils.spans import span
        with span("plan_segment", segment=self.seg.name) as sp:
            plan = self._plan()
            if plan.kind in ("kernel", "kselect"):
                # fail-fast static verification (analysis/plan_verify):
                # a plan violating a kernel invariant must die HERE with
                # a rule id, not corrupt results or retrace downstream.
                # Deliberately outside the PlanError host-fallback nets —
                # a broken plan is a bug, not a host-path candidate.
                # PINOT_PLAN_VERIFY=0 disables (tools/check_static.py
                # collects diagnostics instead of raising).
                from ..analysis.plan_verify import check_compiled_plan
                check_compiled_plan(plan)
            if sp is not None:
                sp.annotate(kind=plan.kind)
                if plan.kind == "kernel":
                    sp.annotate(strategy=plan.kernel_plan.strategy,
                                est_sel=plan.est_selectivity,
                                slots_cap=plan.slots_cap,
                                cost_trace=plan.strategy_trace)
                    if plan.drift_requantized:
                        sp.annotate(drift_requantized=True)
            return plan

    def _plan(self) -> CompiledPlan:
        ctx, seg = self.ctx, self.seg
        self._validate_columns()
        if _truthy(ctx.options.get("forceHostExecution")):
            # kernel-vs-host differential testing hook (the fuzzer diffs
            # both paths against a numpy oracle; reference analog:
            # QueryGenerator runs against H2)
            return CompiledPlan("host", seg, ctx)
        if self.null_aware:
            # null-aware execution stays on the device: 3VL filters via
            # resolve_filter's T-tree, per-agg null skip via
            # AggSpec.null_param. Null group KEYS form their own group —
            # a representation the dense cartesian id key lacks -> host
            refs: set = set()
            for g in ctx.group_by:
                collect_identifiers(g, refs)
            if any(getattr(seg.columns.get(r), "has_nulls", False)
                   for r in refs):
                return CompiledPlan("host", seg, ctx)
        if getattr(seg, "is_mutable", False):
            # consuming snapshot: vectorized host path (MutableSegmentImpl's
            # realtime read path analog; rows become device-resident on seal)
            return CompiledPlan("host", seg, ctx)
        if not ctx.is_aggregation:
            try:
                ksel = self._plan_selection()
            except PlanError:
                ksel = None
            if ksel is not None:
                return ksel
            return CompiledPlan("host", seg, ctx)  # general selection: host

        try:
            pred = self.resolve_filter(ctx.filter)
        except PlanError:
            # filter uses expressions without a device lowering (scalar
            # functions, CASE, ...) -> vectorized host path
            return CompiledPlan("host", seg, ctx)
        if isinstance(pred, FalseP) :
            return CompiledPlan("pruned", seg, ctx)

        # upsert validDocIds: fold the segment's valid mask into the filter
        # (queryableDocIds in the reference; OPTION(skipUpsert=true) bypasses)
        if getattr(seg, "valid_docs", None) is not None and \
                not _truthy(ctx.options.get("skipUpsert")):
            from ..ops.ir import MaskParam
            pred = _simplify(And((pred, MaskParam(
                self.b.add_param(("validdocs", None))))))

        # group-by feasibility: column keys (dict ids) or expression
        # keys with a metadata-derivable bounded integer range
        group_cols: List[str] = []
        group_keys: List[Tuple[int, int]] = []
        gspecs: List[tuple] = []   # ("col", name, card)|("expr", g, lo, stride, card)
        if ctx.is_group_by:
            dense_ok = True
            space = 1
            for g in ctx.group_by:
                if isinstance(g, Identifier):
                    m = seg.columns.get(g.name)
                    if m is None or not m.has_dict or m.cardinality == 0 \
                            or not getattr(m, "single_value", True):
                        # virtual / raw / MV keys stay host-side
                        dense_ok = False
                        break
                    gspecs.append(("col", g.name, m.cardinality))
                    space *= max(m.cardinality, 1)
                    continue
                rng = self._expr_key_range(g)
                if rng is None:
                    dense_ok = False
                    break
                lo, stride, card = rng
                gspecs.append(("expr", g, lo, stride, card))
                space *= max(card, 1)
            from ..ops.kernels import COMPACT_GROUP_LIMIT
            space_cap = max(MAX_DENSE_GROUPS, COMPACT_GROUP_LIMIT)
            if not dense_ok or space > space_cap:
                return CompiledPlan("host", seg, ctx)

        # fast path: no filter, metadata/dictionary-answerable aggs, no group
        if isinstance(pred, TrueP) and not ctx.is_group_by:
            fast = self._try_fast_path()
            if fast is not None:
                return fast

        try:
            specs: List[AggSpec] = []
            bindings: List[AggBinding] = []
            for i, agg in enumerate(ctx.aggregations):
                spec, binding = self.resolve_agg(i, agg)
                specs.append(spec)
                bindings.append(binding)
        except PlanError:
            return CompiledPlan("host", seg, ctx)

        if not ctx.is_group_by:
            # scalar DISTINCTCOUNT: the sort-boundary path (kernels.
            # DISTINCT_ONEHOT_CARD) removes the card-sized matmul, so the
            # gate is only the (card,) presence-bitmap transfer size
            for s in specs:
                if s.kind == "distinct_count" and s.card is not None \
                        and s.card > MAX_DISTINCT_MATRIX:
                    return CompiledPlan("host", seg, ctx)

        strategy = "dense"
        est_sel: Optional[float] = None
        slots_cap: Optional[int] = None
        strat_trace: Optional[dict] = None
        key_exprs: List[Any] = []
        group_decoders: List[tuple] = []
        if ctx.is_group_by:
            try:
                for spec in gspecs:
                    if spec[0] == "col":
                        _tag, name, card = spec
                        idx = self.b.bind_col(name)
                        group_keys.append((idx, card))
                        key_exprs.append(None)
                        group_cols.append(name)
                        group_decoders.append(("dict", name, card))
                    else:
                        _tag, g, lo, stride, card = spec
                        ve = self._expr_key_ir(g, lo, stride)
                        group_keys.append((0, card))
                        key_exprs.append(ve)
                        group_cols.append(_expr_label_of(g))
                        group_decoders.append(("int", lo, stride, card))
            except PlanError:
                return CompiledPlan("host", seg, ctx)
            space = 1
            for _, c in group_keys:
                space *= max(c, 1)
            import jax as _jax

            from ..ops.kernels import COMPACT_GROUP_LIMIT
            slow_scatter = _jax.default_backend() != "cpu"
            # compact strategy: Pallas row compaction + factorized/sorted
            # aggregation (ops/kernels._compact_group_aggs); covers every
            # core numeric agg (min/max ride an exact int64 orderable in a
            # lexicographic sort)
            from ..ops.ir import MvReduce as _MvR
            compact_ok = (
                not any(e is not None for e in key_exprs)
                and space <= COMPACT_GROUP_LIMIT
                and all(s.kind in ("count", "sum", "avg", "min", "max")
                        for s in specs)
                # MV value columns are (bucket, maxValues) matrices; the
                # row compaction primitive is 1-D — dense handles them
                and not any(isinstance(s.value, _MvR) for s in specs))
            # dense-strategy viability (one-hot over all rows)
            dense_viable = space <= MAX_DENSE_GROUPS
            has_expr_keys = any(e is not None for e in key_exprs)
            if (slow_scatter or has_expr_keys) and \
                    seg.bucket * (space + 1) > DENSE_ONEHOT_BUDGET:
                # the (bucket, space) int8 one-hot operand would not fit /
                # would dominate HBM traffic; matched-row compaction first
                # is strictly better at any real selectivity. Expression
                # keys can't compact (no key column to gather), so the
                # budget gates them to host on every backend.
                dense_viable = False
            for s in specs:
                if s.kind == "distinct_count" and s.card is not None \
                        and space * s.card > MAX_DISTINCT_MATRIX:
                    dense_viable = False
                if s.kind in ("distinct_count_hll", "raw_hll"):
                    from ..ops.kernels import GROUPED_HLL_LIMIT
                    r_levels = 64 - s.card + 1
                    if space * (1 << s.card) * r_levels \
                            > GROUPED_HLL_LIMIT:
                        return CompiledPlan("host", seg, ctx)
                if s.kind in ("min", "max") and slow_scatter and space > 64:
                    # no matmul form for min/max; TPU scatter is
                    # pathological (kernels.MINMAX_UNROLL_GROUPS)
                    dense_viable = False
            if not dense_viable and not compact_ok:
                return CompiledPlan("host", seg, ctx)
            # cost-model strategy choice (round-6 tentpole): dense vs
            # compact driven by IR-measured selectivity x group-space
            # (multistage/costs.py), not the old space>512 heuristic.
            # OPTION(groupByStrategy=dense|compact) pins it when a
            # structurally-possible strategy is forced (hardware gates,
            # differential tests).
            from ..multistage import costs as _costs
            from ..ops.kernels import (FACTORIZED_GROUP_LIMIT,
                                       cpu_scatter_default)
            col_cards = {
                i: int(getattr(seg.columns.get(nm), "cardinality", 0) or 0)
                for i, nm in enumerate(self.b.cols)}
            est_sel = _costs.ir_selectivity(pred, self.b.params, col_cards)
            platform = _jax.default_backend()
            scatter_fast = cpu_scatter_default(platform)
            needs_sort_flag = (space > FACTORIZED_GROUP_LIMIT
                               or any(s.kind in ("min", "max")
                                      for s in specs))
            n_payloads = sum(1 for s in specs if s.kind != "count")
            force = str(ctx.options.get("groupByStrategy", "")).lower() \
                or None
            strategy, strat_trace = _costs.choose_group_strategy(
                seg.n_docs, space, est_sel, platform, scatter_fast,
                needs_sort_flag, n_payloads, dense_viable, compact_ok,
                force)

        plan = KernelPlan(pred=pred, aggs=tuple(specs),
                          group_keys=tuple(group_keys),
                          strategy=strategy,
                          key_exprs=(tuple(key_exprs)
                                     if any(e is not None
                                            for e in key_exprs) else ()))
        drift_requant = False
        if strategy == "compact":
            # size from the LIVE row count (n_docs), not the padded
            # bucket — the pad rows are mask-false and consume no
            # compaction slots
            from ..multistage import costs as _costs
            slots_cap = _costs.compact_slots_cap(
                seg.n_docs, est_sel, platform, scatter_fast)
            # selectivity-drift self-tuning (round-12 feedback loop):
            # when the warm plan-cache entry's MEASURED matched fraction
            # drifts past the threshold from the IR estimate, re-derive
            # the capacity from the measurement. The plan cache brackets
            # the resulting compile (the actual miss, not warm hits)
            # with expected() so it counts as a deliberate recompile;
            # the re-quantized cap is itself a stable cache key, so the
            # recompile happens exactly once.
            from ..ops.plan_cache import global_plan_cache
            meas = global_plan_cache.measured_for(
                plan, seg.bucket, segment=seg, params=self.b.params)
            if meas is not None and _costs.selectivity_drift(est_sel,
                                                             meas):
                from ..utils.metrics import global_metrics
                global_metrics.count("selectivity_drift_detected")
                meas_f = max(meas, _costs.MIN_SEL)
                new_cap = _costs.compact_slots_cap(
                    seg.n_docs, meas_f, platform, scatter_fast)
                if strat_trace is not None:
                    strat_trace["drift"] = {
                        "est_sel": round(est_sel, 8),
                        "meas_sel": round(meas_f, 8),
                        "slots_cap": slots_cap, "new_cap": new_cap}
                if new_cap != slots_cap:
                    global_metrics.count("selectivity_drift_requantized")
                    slots_cap = new_cap
                    drift_requant = True
                # the measurement replaces the estimate either way so
                # every derived capacity (PV106 consistency, the fused/
                # mesh scaled_compact_cap) agrees with the cap in force
                est_sel = meas_f
        return CompiledPlan("kernel", seg, ctx,
                            col_names=list(self.b.cols),
                            kernel_plan=plan,
                            params=list(self.b.params),
                            agg_bindings=bindings,
                            group_cols=group_cols,
                            group_decoders=group_decoders,
                            est_selectivity=est_sel,
                            slots_cap=slots_cap,
                            strategy_trace=strat_trace,
                            drift_requantized=drift_requant)

    def _try_fast_path(self) -> Optional[CompiledPlan]:
        """Metadata/dictionary-only answers (AggregationPlanNode.java:98-112
        NonScanBasedAggregationOperator analog)."""
        seg, ctx = self.seg, self.ctx
        states: List[Any] = []
        for agg in ctx.aggregations:
            if agg.kind == "count":
                if self.null_aware and agg.arg is not None and any(
                        getattr(seg.columns.get(r), "has_nulls", False)
                        for r in collect_identifiers(agg.arg)):
                    # COUNT(col) skips nulls under enableNullHandling;
                    # n_docs would overcount
                    return None
                states.append(seg.n_docs)
                continue
            if agg.kind in ("min", "max") and isinstance(agg.arg, Identifier):
                m = seg.columns.get(agg.arg.name)
                if m is None or m.min is None or m.has_nulls:
                    return None
                if not m.data_type.is_numeric:
                    return None
                states.append(float(m.min if agg.kind == "min" else m.max))
                continue
            if agg.kind == "distinct_count" and isinstance(agg.arg, Identifier):
                m = seg.columns.get(agg.arg.name)
                if m is None or not m.has_dict or m.has_nulls:
                    return None
                # mergeable across segments: the value set, not its size
                states.append(set(seg.dictionary(agg.arg.name).values))
                continue
            return None
        return CompiledPlan("fast", seg, ctx, fast_states=states)
