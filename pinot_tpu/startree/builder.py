"""Star-tree analog: materialized pre-aggregation rollups.

Reference parity: pinot-segment-local/.../startree/v2/builder/
{OffHeapSingleTreeBuilder, MultipleTreesBuilder}.java — Pinot's star-tree
pre-aggregates metrics over dimension subsets and stores a tree whose
star-nodes skip dimensions at query time. TPU-native rethink: the tree is
pointer-chasing (bad fit); the same speedup comes from materializing the
FULL group-by over the configured split dimensions as a tiny regular
segment (one row per distinct dimension combination, pre-aggregated metric
columns). Queries whose filters/group-bys stay within the rollup
dimensions rewrite onto the rollup (query.py) and scan orders of magnitude
fewer rows through the exact same dense MXU kernels — the rollup IS a
segment, so every engine feature (pruning, batching, distribution) applies
unchanged. Multiple rollups per segment = MultipleTreesBuilder.

Rollup column naming: dims keep their names; each (func, metric) pair
becomes "<metric>__<func>", plus "__count" (star-tree's implicit COUNT)."""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..segment.builder import SegmentBuilder
from ..segment.immutable import ImmutableSegment
from ..spi.config import TableConfig
from ..spi.schema import DataType, FieldSpec, FieldType, Schema

ROLLUP_META_KEY = "rollups"
SUPPORTED_FUNCS = ("sum", "min", "max")


@dataclass
class RollupConfig:
    """StarTreeIndexConfig analog: dimensionsSplitOrder +
    functionColumnPairs."""
    dims: List[str]
    metrics: List[Tuple[str, str]] = field(default_factory=list)  # (func,col)

    def name(self, index: int) -> str:
        return f"startree{index}"


def build_rollup(seg: ImmutableSegment, config: RollupConfig,
                 index: int = 0) -> str:
    """Build one rollup under the segment dir; registers it in the segment
    metadata. Returns the rollup directory."""
    for func, col in config.metrics:
        if func not in SUPPORTED_FUNCS:
            raise ValueError(f"unsupported rollup function {func!r}")

    for d in config.dims:
        if seg.null_mask(d) is not None:
            raise ValueError(
                f"rollup dimension {d!r} has nulls; null identity does not "
                "survive materialization — exclude it or disable nulls")

    n = seg.n_docs
    # factorize the dimension tuple
    codes = np.zeros(n, dtype=np.int64)
    dim_vals: List[np.ndarray] = []
    uniques: List[np.ndarray] = []
    for d in config.dims:
        v = np.asarray(seg.raw_values(d))
        if v.dtype == object:
            v = v.astype(str)
        u, inv = np.unique(v, return_inverse=True)
        codes = codes * len(u) + inv
        uniques.append(u)
        dim_vals.append(v)
    ucodes, inv = np.unique(codes, return_inverse=True)
    n_groups = len(ucodes)

    out_cols: Dict[str, np.ndarray] = {}
    fields: List[FieldSpec] = []
    rem = ucodes.copy()
    decoded: List[np.ndarray] = []
    for u in reversed(uniques):
        decoded.append(u[rem % len(u)])
        rem //= len(u)
    decoded.reverse()
    for d, vals in zip(config.dims, decoded):
        spec = seg.schema.field(d)
        out_cols[d] = vals if vals.dtype != object else vals.astype(object)
        fields.append(FieldSpec(d, spec.data_type, FieldType.DIMENSION))

    counts = np.bincount(inv, minlength=n_groups)
    out_cols["__count"] = counts.astype(np.int64)
    fields.append(FieldSpec("__count", DataType.LONG, FieldType.METRIC))

    for func, col in config.metrics:
        v = np.asarray(seg.raw_values(col))
        spec = seg.schema.field(col)
        name = f"{col}__{func}"
        if func == "sum":
            if np.issubdtype(v.dtype, np.integer):
                acc = np.zeros(n_groups, dtype=np.int64)
                np.add.at(acc, inv, v.astype(np.int64))
                out_cols[name] = acc
                fields.append(FieldSpec(name, DataType.LONG,
                                        FieldType.METRIC))
            else:
                acc = np.zeros(n_groups, dtype=np.float64)
                np.add.at(acc, inv, v.astype(np.float64))
                out_cols[name] = acc
                fields.append(FieldSpec(name, DataType.DOUBLE,
                                        FieldType.METRIC))
        elif func in ("min", "max"):
            if np.issubdtype(v.dtype, np.integer):
                init = (np.iinfo(np.int64).max if func == "min"
                        else np.iinfo(np.int64).min)
                acc = np.full(n_groups, init, dtype=np.int64)
                (np.minimum if func == "min" else np.maximum).at(
                    acc, inv, v.astype(np.int64))
                out_cols[name] = acc
                fields.append(FieldSpec(name, DataType.LONG,
                                        FieldType.METRIC))
            else:
                init = np.inf if func == "min" else -np.inf
                acc = np.full(n_groups, init, dtype=np.float64)
                (np.minimum if func == "min" else np.maximum).at(
                    acc, inv, v.astype(np.float64))
                out_cols[name] = acc
                fields.append(FieldSpec(name, DataType.DOUBLE,
                                        FieldType.METRIC))

    rollup_schema = Schema(f"{seg.name}_{config.name(index)}", fields)
    builder = SegmentBuilder(rollup_schema, TableConfig(rollup_schema.name))
    rollup_dir = builder.build(out_cols, seg.dir, config.name(index))

    # register in segment metadata
    meta_path = os.path.join(seg.dir, "metadata.json")
    with open(meta_path) as fh:
        meta = json.load(fh)
    entry = {
        "name": config.name(index),
        "dims": list(config.dims),
        "metrics": [[f, c] for f, c in config.metrics],
    }
    meta.setdefault(ROLLUP_META_KEY, [])
    meta[ROLLUP_META_KEY] = [e for e in meta[ROLLUP_META_KEY]
                             if e["name"] != entry["name"]] + [entry]
    with open(meta_path, "w") as fh:
        json.dump(meta, fh, indent=1)
    seg.metadata = meta
    return rollup_dir
