"""Rollup query rewriting: swap a matching aggregation onto the rollup.

Reference parity: pinot-core/.../startree/{StarTreeUtils.java,
plan/StarTreeProjectionPlanNode...} — AggregationPlanNode swaps in the
star-tree executor when every predicate/group-by column is a tree
dimension and every aggregation has a pre-aggregated column pair. Same
matching rules here; the "tree traversal" is just the dense kernel over
the (tiny) rollup segment with rewritten aggregations:

    COUNT(*)   -> SUM(__count)
    SUM(m)     -> SUM(m__sum)
    MIN(m)     -> MIN(m__min)
    MAX(m)     -> MAX(m__max)
    AVG(m)     -> (SUM(m__sum), SUM(__count)) recombined into the avg state
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from ..query.context import AggExpr, QueryContext
from ..query.sql import Identifier
from ..segment.immutable import ImmutableSegment
from .builder import ROLLUP_META_KEY


def _filter_refs(e: Any) -> Optional[set]:
    """Referenced column names, or None if the filter shape is unsupported
    for rewriting (expressions over metrics etc. stay on the raw path)."""
    from ..query.sql import (Between, BinaryOp, BoolAnd, BoolNot, BoolOr,
                             Comparison, InList, IsNull, Like, Literal)
    if e is None:
        return set()
    if isinstance(e, (BoolAnd, BoolOr)):
        out: set = set()
        for c in e.children:
            r = _filter_refs(c)
            if r is None:
                return None
            out |= r
        return out
    if isinstance(e, BoolNot):
        return _filter_refs(e.child)
    if isinstance(e, Comparison):
        sides = [e.lhs, e.rhs]
        out = set()
        for s in sides:
            if isinstance(s, Identifier):
                out.add(s.name)
            elif not isinstance(s, Literal):
                return None
        return out
    if isinstance(e, Between):
        if isinstance(e.expr, Identifier) and \
                isinstance(e.lo, Literal) and isinstance(e.hi, Literal):
            return {e.expr.name}
        return None
    if isinstance(e, IsNull):
        # rollup dims lose null identity (builder refuses null-bearing dims,
        # but reject defensively)
        return None
    if isinstance(e, (InList, Like)):
        if isinstance(e.expr, Identifier):
            return {e.expr.name}
        return None
    return None


def _rollup_cols(agg: AggExpr, metrics: set) -> Optional[List[Tuple[str,
                                                                    str]]]:
    """-> [(rewritten_kind, rollup_col)] building blocks, or None."""
    if agg.kind == "count" :
        return [("sum", "__count")]
    if not isinstance(agg.arg, Identifier):
        return None
    col = agg.arg.name
    if agg.kind == "sum" and ("sum", col) in metrics:
        return [("sum", f"{col}__sum")]
    if agg.kind == "min" and ("min", col) in metrics:
        return [("min", f"{col}__min")]
    if agg.kind == "max" and ("max", col) in metrics:
        return [("max", f"{col}__max")]
    if agg.kind == "avg" and ("sum", col) in metrics:
        return [("sum", f"{col}__sum"), ("sum", "__count")]
    return None


def try_rollup_execute(ctx: QueryContext, seg: ImmutableSegment):
    """Partial via a matching rollup, or None (raw-segment path)."""
    entries = seg.metadata.get(ROLLUP_META_KEY) if hasattr(seg, "metadata") \
        else None
    if not entries or not ctx.is_aggregation:
        return None
    if getattr(seg, "valid_docs", None) is not None:
        # upsert-invalidated docs are baked into the rollup's pre-aggregates;
        # only the per-doc path can mask them out
        return None
    refs = _filter_refs(ctx.filter)
    if refs is None:
        return None
    group_cols = []
    for g in ctx.group_by:
        if not isinstance(g, Identifier):
            return None
        group_cols.append(g.name)

    for entry in entries:
        dims = set(entry["dims"])
        metrics = {(f, c) for f, c in entry["metrics"]}
        if not refs <= dims or not set(group_cols) <= dims:
            continue
        mapping: List[List[Tuple[str, str]]] = []
        ok = True
        for agg in ctx.aggregations:
            m = _rollup_cols(agg, metrics)
            if m is None:
                ok = False
                break
            mapping.append(m)
        if not ok:
            continue
        return _execute_on_rollup(ctx, seg, entry, mapping)
    return None


def _execute_on_rollup(ctx: QueryContext, seg: ImmutableSegment, entry,
                       mapping):
    from ..engine.executor import (AggPartial, GroupByPartial,
                                   execute_segment)
    rollup_dir = os.path.join(seg.dir, entry["name"])
    rollup = _load_rollup(seg, rollup_dir)

    # rewritten context: flat list of (kind, col) aggs, dedup'd
    flat: List[Tuple[str, str]] = []
    for m in mapping:
        for pair in m:
            if pair not in flat:
                flat.append(pair)
    rewritten = QueryContext(
        table=ctx.table,
        select_items=[],
        labels=[],
        aggregations=[AggExpr(kind, Identifier(col), f"{kind}({col})")
                      for kind, col in flat],
        group_by=list(ctx.group_by),
        filter=ctx.filter,
        having=None,
        order_by=[],
        limit=None,
        offset=0,
    )
    partial = execute_segment(rewritten, rollup)

    def remap(states: List[Any]) -> List[Any]:
        by_pair = dict(zip(flat, states))
        out: List[Any] = []
        for agg, m in zip(ctx.aggregations, mapping):
            if agg.kind == "avg":
                out.append((by_pair[m[0]], by_pair[m[1]]))
            else:
                out.append(by_pair[m[0]])
        return out

    if isinstance(partial, AggPartial):
        return AggPartial(remap(partial.states))
    assert isinstance(partial, GroupByPartial)
    return GroupByPartial({k: remap(v) for k, v in partial.groups.items()})


def _load_rollup(seg: ImmutableSegment, rollup_dir: str) -> ImmutableSegment:
    cache = getattr(seg, "_rollup_cache", None)
    if cache is None:
        cache = {}
        seg._rollup_cache = cache
    if rollup_dir not in cache:
        cache[rollup_dir] = ImmutableSegment.load(rollup_dir)
    return cache[rollup_dir]
