from .builder import RollupConfig, build_rollup  # noqa: F401
from .query import try_rollup_execute  # noqa: F401
