"""Extended input formats: protobuf, thrift, CLP, ORC (round-4,
VERDICT r3 missing #9 — reference: pinot-plugins/pinot-input-format/
{pinot-protobuf, pinot-thrift, pinot-clp-log, pinot-orc}).

- protobuf: real wire-format reader — a FileDescriptorSet (protoc
  --descriptor_set_out) names the message type; records are
  varint-delimited on disk (java writeDelimitedTo framing, the
  reference ProtoBufRecordReader's layout).
- thrift: from-scratch TBinaryProtocol struct decoder (no thrift lib in
  the environment): records are concatenated structs; field ids map to
  column names through the caller-provided schema, unknown fields skip.
- CLP: from-scratch CLP-style log encoding (reference
  CLPLogRecordReader): each configured message field becomes three
  columns — <f>_logtype (the message with variables replaced by
  placeholder bytes), <f>_dictionaryVars (word-like variables),
  <f>_encodedVars (numeric variables) — and clp_decode() reassembles
  the original string (tested round-trip).
- ORC: served through pyarrow.orc when present (it is in this image),
  with a clear gating error otherwise — same contract as parquet.
"""
from __future__ import annotations

import json
import re
import struct
from typing import Any, Dict, Iterable, List, Optional, Tuple

# ---------------------------------------------------------------------------
# protobuf
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        out |= (b & 0x7F) << shift
        pos += 1
        if not b & 0x80:
            return out, pos
        shift += 7


def write_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _message_class(descriptor_file: str, message_type: str):
    from google.protobuf import (descriptor_pb2, descriptor_pool,
                                 message_factory)

    with open(descriptor_file, "rb") as fh:
        fds = descriptor_pb2.FileDescriptorSet.FromString(fh.read())
    pool = descriptor_pool.DescriptorPool()
    for f in fds.file:
        pool.Add(f)
    return message_factory.GetMessageClass(
        pool.FindMessageTypeByName(message_type))


def _msg_to_row(msg) -> Dict[str, Any]:
    row: Dict[str, Any] = {}
    for f in msg.DESCRIPTOR.fields:
        v = getattr(msg, f.name)
        repeated = f.is_repeated if hasattr(f, "is_repeated") \
            else f.label == f.LABEL_REPEATED  # protobuf<5 fallback
        if f.message_type is not None and \
                f.message_type.GetOptions().map_entry:
            # map fields iterate as keys — materialize the mapping
            val_f = f.message_type.fields_by_name["value"]
            row[f.name] = {k: (_msg_to_row(v[k])
                               if val_f.message_type else v[k])
                           for k in v}
        elif repeated:
            row[f.name] = [(_msg_to_row(x) if f.message_type else x)
                           for x in v]
        elif f.message_type is not None:
            row[f.name] = _msg_to_row(v)
        elif f.type == f.TYPE_BYTES:
            row[f.name] = bytes(v)
        else:
            row[f.name] = v
    return row


def read_protobuf(path: str, descriptor_file: str,
                  message_type: str) -> List[Dict[str, Any]]:
    """Varint-delimited protobuf records -> row dicts."""
    cls = _message_class(descriptor_file, message_type)
    with open(path, "rb") as fh:
        data = fh.read()
    rows: List[Dict[str, Any]] = []
    pos = 0
    while pos < len(data):
        ln, pos = _read_varint(data, pos)
        rows.append(_msg_to_row(cls.FromString(data[pos:pos + ln])))
        pos += ln
    return rows


def write_protobuf(path: str, messages: Iterable[Any]) -> None:
    """Varint-delimited writer (the producing side of the contract)."""
    with open(path, "wb") as fh:
        for m in messages:
            b = m.SerializeToString()
            fh.write(write_varint(len(b)) + b)


# ---------------------------------------------------------------------------
# thrift (TBinaryProtocol)
# ---------------------------------------------------------------------------

_T_STOP, _T_BOOL, _T_BYTE, _T_DOUBLE = 0, 2, 3, 4
_T_I16, _T_I32, _T_I64, _T_STRING = 6, 8, 10, 11
_T_STRUCT, _T_MAP, _T_SET, _T_LIST = 12, 13, 14, 15


def _thrift_value(buf: bytes, pos: int, ttype: int) -> Tuple[Any, int]:
    if ttype == _T_BOOL:
        return buf[pos] != 0, pos + 1
    if ttype == _T_BYTE:
        return struct.unpack_from(">b", buf, pos)[0], pos + 1
    if ttype == _T_DOUBLE:
        return struct.unpack_from(">d", buf, pos)[0], pos + 8
    if ttype == _T_I16:
        return struct.unpack_from(">h", buf, pos)[0], pos + 2
    if ttype == _T_I32:
        return struct.unpack_from(">i", buf, pos)[0], pos + 4
    if ttype == _T_I64:
        return struct.unpack_from(">q", buf, pos)[0], pos + 8
    if ttype == _T_STRING:
        (ln,) = struct.unpack_from(">i", buf, pos)
        raw = buf[pos + 4:pos + 4 + ln]
        try:
            return raw.decode("utf-8"), pos + 4 + ln
        except UnicodeDecodeError:
            return raw, pos + 4 + ln
    if ttype == _T_STRUCT:
        return _thrift_struct(buf, pos)
    if ttype in (_T_LIST, _T_SET):
        etype = buf[pos]
        (n,) = struct.unpack_from(">i", buf, pos + 1)
        pos += 5
        out = []
        for _ in range(n):
            v, pos = _thrift_value(buf, pos, etype)
            out.append(v)
        return out, pos
    if ttype == _T_MAP:
        ktype, vtype = buf[pos], buf[pos + 1]
        (n,) = struct.unpack_from(">i", buf, pos + 2)
        pos += 6
        out: Dict[Any, Any] = {}
        for _ in range(n):
            k, pos = _thrift_value(buf, pos, ktype)
            v, pos = _thrift_value(buf, pos, vtype)
            out[k] = v
        return out, pos
    raise ValueError(f"unsupported thrift type {ttype}")


def _thrift_struct(buf: bytes, pos: int
                   ) -> Tuple[Dict[int, Any], int]:
    """-> ({field_id: value}, next_pos); TBinaryProtocol field layout:
    u8 type | i16 field_id | value, terminated by T_STOP."""
    out: Dict[int, Any] = {}
    while True:
        ttype = buf[pos]
        pos += 1
        if ttype == _T_STOP:
            return out, pos
        (fid,) = struct.unpack_from(">h", buf, pos)
        pos += 2
        v, pos = _thrift_value(buf, pos, ttype)
        out[fid] = v


def read_thrift(path: str,
                field_names: Dict[int, str]) -> List[Dict[str, Any]]:
    """Concatenated TBinaryProtocol structs -> row dicts. field_names
    maps thrift field ids to column names (the role the generated
    thrift class plays for ThriftRecordReader); unmapped fields drop."""
    with open(path, "rb") as fh:
        data = fh.read()
    rows: List[Dict[str, Any]] = []
    pos = 0
    while pos < len(data):
        fields, pos = _thrift_struct(data, pos)
        rows.append({field_names[fid]: v for fid, v in fields.items()
                     if fid in field_names})
    return rows


# ---------------------------------------------------------------------------
# CLP-style log encoding
# ---------------------------------------------------------------------------

# placeholders (CLP's scheme: logtype keeps structure, vars extracted)
_PH_INT = "\x11"
_PH_FLOAT = "\x12"
_PH_DICT = "\x13"
_ESC = "\x1b"   # literal 0x11-0x13 (or 0x1b) bytes in the message are
# escaped in the logtype so they can never be misread as var slots

_VAR_TOKEN = re.compile(
    r"(?P<float>-?\d+\.\d+)|(?P<int>-?\d+)|(?P<dict>[A-Za-z0-9_./:\-]*"
    r"\d[A-Za-z0-9_./:\-]*)")


def clp_encode(message: str) -> Tuple[str, List[str], List[int]]:
    """-> (logtype, dictionary_vars, encoded_vars). Numeric tokens
    become encoded vars (floats bit-cast to int64 like CLP), tokens
    containing digits become dictionary vars, everything else stays in
    the logtype."""
    dict_vars: List[str] = []
    enc_vars: List[int] = []
    for ch in (_ESC, _PH_INT, _PH_FLOAT, _PH_DICT):
        message = message.replace(ch, _ESC + ch)

    def sub(m: re.Match) -> str:
        tok = m.group()
        # losslessness gate (real CLP does the same): tokens whose
        # numeric form does not reproduce the exact text — leading-zero
        # ints, trailing-zero floats — go to the dictionary instead
        if m.group("float") is not None:
            if repr(float(tok)) == tok:
                enc_vars.append(struct.unpack(
                    ">q", struct.pack(">d", float(tok)))[0])
                return _PH_FLOAT
            dict_vars.append(tok)
            return _PH_DICT
        if m.group("int") is not None:
            if str(int(tok)) == tok:
                enc_vars.append(int(tok))
                return _PH_INT
            dict_vars.append(tok)
            return _PH_DICT
        dict_vars.append(tok)
        return _PH_DICT

    return _VAR_TOKEN.sub(sub, message), dict_vars, enc_vars


def clp_decode(logtype: str, dict_vars: List[str],
               enc_vars: List[int]) -> str:
    di = iter(dict_vars)
    ei = iter(enc_vars)
    out: List[str] = []
    it = iter(logtype)
    for ch in it:
        if ch == _ESC:
            out.append(next(it))          # escaped literal byte
        elif ch == _PH_INT:
            out.append(str(next(ei)))
        elif ch == _PH_FLOAT:
            out.append(repr(struct.unpack(
                ">d", struct.pack(">q", next(ei)))[0]))  # exact: the
            # encoder only takes floats whose repr matches the token
        elif ch == _PH_DICT:
            out.append(next(di))
        else:
            out.append(ch)
    return "".join(out)


def read_clp(path: str, fields: Tuple[str, ...] = ("message",)
             ) -> List[Dict[str, Any]]:
    """JSON-lines log events; each configured field is CLP-encoded into
    <f>_logtype / <f>_dictionaryVars / <f>_encodedVars columns
    (CLPLogRecordReader's output shape), other fields pass through."""
    rows: List[Dict[str, Any]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            ev = json.loads(line)
            row: Dict[str, Any] = {}
            for k, v in ev.items():
                if k in fields and isinstance(v, str):
                    lt, dv, evars = clp_encode(v)
                    row[f"{k}_logtype"] = lt
                    row[f"{k}_dictionaryVars"] = dv
                    row[f"{k}_encodedVars"] = evars
                else:
                    row[k] = v
            rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# ORC (gated)
# ---------------------------------------------------------------------------

def read_orc(path: str) -> List[Dict[str, Any]]:
    try:
        from pyarrow import orc  # type: ignore[import-not-found]
    except ImportError:
        raise RuntimeError(
            "orc input needs the 'pyarrow' package, which is not "
            "installed in this environment") from None
    return orc.read_table(path).to_pylist()
