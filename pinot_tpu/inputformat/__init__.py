"""Input-format record readers (batch ingestion sources).

Reference parity: pinot-plugins/pinot-input-format/ — RecordReader SPI
implementations for csv, json, avro, parquet, orc, protobuf, thrift.
Python-native: csv/json(l) read with the stdlib; avro container files
decode through the from-scratch binary codec (inputformat/avro.py — no
fastavro dependency); parquet loads through pyarrow when present and
raises a clear gating error when not (the environment does not allow
installing it).
"""
from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, List

FORMATS = ("csv", "json", "avro", "parquet", "orc", "protobuf",
           "thrift", "clp")


def _infer(v: str) -> Any:
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        return v


def read_csv(path: str) -> List[Dict[str, Any]]:
    with open(path, newline="") as fh:
        return [{k: _infer(v) if v != "" else None for k, v in row.items()}
                for row in csv.DictReader(fh)]


def read_json(path: str) -> List[Dict[str, Any]]:
    """A JSON array file, or JSON-lines (one object per line)."""
    with open(path) as fh:
        text = fh.read().strip()
    if text.startswith("["):
        rows = json.loads(text)
    else:
        rows = [json.loads(line) for line in text.splitlines() if line]
    if not isinstance(rows, list):
        raise ValueError(f"{path}: expected a JSON array or JSON lines")
    return rows


def read_avro(path: str) -> List[Dict[str, Any]]:
    """Object-container-file reader — from-scratch binary codec
    (inputformat/avro.py), no fastavro dependency (round-5)."""
    from .avro import read_container
    return read_container(path)


def read_parquet(path: str) -> List[Dict[str, Any]]:
    try:
        import pyarrow.parquet as pq  # type: ignore[import-not-found]
    except ImportError:
        raise RuntimeError(
            "parquet input needs the 'pyarrow' package, which is not "
            "installed in this environment") from None
    return pq.read_table(path).to_pylist()


_READERS = {"csv": read_csv, "json": read_json, "avro": read_avro,
            "parquet": read_parquet}


def read_records(path: str, fmt: str = "",
                 **format_args: Any) -> List[Dict[str, Any]]:
    """Read a file into row dicts; format inferred from the extension when
    not given. protobuf needs (descriptor_file, message_type), thrift
    needs field_names={id: name}, clp accepts fields=(...) — see
    inputformat/extended.py."""
    fmt = (fmt or os.path.splitext(path)[1].lstrip(".")).lower()
    if fmt == "jsonl":
        fmt = "json"
    if fmt in ("orc", "protobuf", "thrift", "clp") \
            and fmt not in _READERS:
        from . import extended
        _READERS.update(orc=extended.read_orc,
                        protobuf=extended.read_protobuf,
                        thrift=extended.read_thrift,
                        clp=extended.read_clp)
    reader = _READERS.get(fmt)
    if reader is None:
        raise ValueError(f"unknown input format {fmt!r}; have {FORMATS}")
    return reader(path, **format_args)
