"""Avro from scratch: binary codec, object-container files, and the
Confluent schema-registry wire format.

Reference parity: pinot-plugins/pinot-input-format/pinot-avro(-base)
(container-file ingestion) and pinot-confluent-avro/
.../KafkaConfluentSchemaRegistryAvroMessageDecoder.java:53 (round-5;
VERDICT r4 minor). The environment has no fastavro/confluent libraries,
and the Avro binary encoding + Confluent framing are small, stable
public specs — implemented here directly:

- binary codec: zigzag-varint int/long, IEEE float/double (LE),
  length-prefixed bytes/string, enum index, fixed, union branch index,
  records in field order, block-encoded arrays/maps (negative block
  counts carry a byte size to skip);
- object container files: 'Obj\\x01' magic, metadata map with
  avro.schema / avro.codec (null + deflate via zlib), 16-byte sync
  marker, counted blocks;
- Confluent wire format: magic 0x00 | 4-byte big-endian schema id |
  Avro binary body; schemas fetched from a registry REST endpoint
  (GET /schemas/ids/{id}) and cached per id. SchemaRegistryStub is the
  in-process registry for tests (POST /subjects/{s}/versions assigns
  ids like the real service).

logicalType handling: decimal (bytes- OR fixed-backed) decodes to
decimal.Decimal (unscaled big-endian two's complement / 10^scale) and
Decimal values re-encode symmetrically; date / time-* / timestamp-* /
uuid deliberately pass through as their underlying int/long/string —
the ingestion pipeline consumes epoch numbers natively (dateTime field
specs), so no datetime objects are fabricated.
"""
from __future__ import annotations

import io
import json
import struct
import threading
import urllib.request
import zlib
from typing import Any, Dict, List, Optional, Tuple


class AvroError(ValueError):
    pass


# ---------------------------------------------------------------------------
# binary codec
# ---------------------------------------------------------------------------

def _zigzag_encode(n: int) -> bytes:
    u = (n << 1) ^ (n >> 63)
    u &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag_decode(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise AvroError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return (result >> 1) ^ -(result & 1), pos
        shift += 7
        if shift > 63:
            raise AvroError("varint too long")


_PRIMITIVES = {"null", "boolean", "int", "long", "float", "double",
               "bytes", "string"}


def _is_decimal_schema(s: Any) -> bool:
    return isinstance(s, dict) and s.get("logicalType") == "decimal" \
        and s.get("type") in ("bytes", "fixed")


def _decimal_from_bytes(raw: bytes, s: Dict[str, Any]):
    import decimal
    unscaled = int.from_bytes(raw, "big", signed=True)
    return decimal.Decimal(unscaled).scaleb(-int(s.get("scale", 0)))


def _decimal_to_bytes(v, s: Dict[str, Any]) -> bytes:
    import decimal
    if isinstance(v, float):
        # floats normalize through their shortest repr: 1.23 means the
        # written "1.23" (fits scale 2), not its binary expansion
        # 1.2299999999999999822... (which would reject every non-dyadic)
        v = decimal.Decimal(str(v))
    scaled = decimal.Decimal(v).scaleb(int(s.get("scale", 0)))
    unscaled = int(scaled)
    if unscaled != scaled:
        # reference Avro writers reject scale mismatches; silently
        # rounding would corrupt (monetary) values on ingest
        raise AvroError(
            f"decimal {v} does not fit scale {s.get('scale', 0)}")
    if s.get("type") == "fixed":
        try:
            return unscaled.to_bytes(s["size"], "big", signed=True)
        except OverflowError:
            raise AvroError(
                f"decimal {v} overflows fixed size {s['size']}") from None
    n = max((unscaled.bit_length() + 8) // 8, 1)   # minimal two's compl.
    return unscaled.to_bytes(n, "big", signed=True)


def _type_name(schema: Any) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    return schema["type"]


class AvroCodec:
    """Encoder/decoder for one schema (JSON string or parsed)."""

    def __init__(self, schema: Any):
        if isinstance(schema, str) and schema not in _PRIMITIVES:
            schema = json.loads(schema)
        self.schema = schema
        self._named: Dict[str, Any] = {}
        self._index_names(schema)

    def _index_names(self, s: Any, enclosing_ns: str = "") -> None:
        """Register named types under BOTH the short name and the
        namespaced fullname (Java-written schemas reference reused
        types by fullname; child types inherit the enclosing namespace
        per the spec)."""
        if isinstance(s, dict):
            ns = s.get("namespace", enclosing_ns)
            if s.get("name") and s.get("type") in ("record", "enum",
                                                   "fixed"):
                self._named[s["name"]] = s
                if ns:
                    self._named[f"{ns}.{s['name']}"] = s
            for f in s.get("fields", []):
                self._index_names(f["type"], ns)
            for k in ("items", "values"):
                if k in s:
                    self._index_names(s[k], ns)
        elif isinstance(s, list):
            for b in s:
                self._index_names(b, enclosing_ns)

    def _resolve(self, s: Any) -> Any:
        if isinstance(s, str) and s in self._named:
            return self._named[s]
        return s

    # -- decode -----------------------------------------------------------
    def decode(self, buf: bytes, pos: int = 0) -> Tuple[Any, int]:
        return self._dec(self.schema, buf, pos)

    def _dec(self, s: Any, buf: bytes, pos: int) -> Tuple[Any, int]:
        s = self._resolve(s)
        t = _type_name(s)
        if t == "null":
            return None, pos
        if t == "boolean":
            if pos >= len(buf):
                raise AvroError("truncated boolean")
            return buf[pos] != 0, pos + 1
        if t in ("int", "long"):
            return _zigzag_decode(buf, pos)
        if t == "float":
            if pos + 4 > len(buf):
                raise AvroError("truncated float")
            return struct.unpack("<f", buf[pos:pos + 4])[0], pos + 4
        if t == "double":
            if pos + 8 > len(buf):
                raise AvroError("truncated double")
            return struct.unpack("<d", buf[pos:pos + 8])[0], pos + 8
        if t in ("bytes", "string"):
            n, pos = _zigzag_decode(buf, pos)
            raw = buf[pos:pos + n]
            if len(raw) != n:
                raise AvroError("truncated bytes/string")
            if t == "bytes" and isinstance(s, dict) \
                    and s.get("logicalType") == "decimal":
                return _decimal_from_bytes(raw, s), pos + n
            return (raw.decode() if t == "string" else raw), pos + n
        if t == "fixed":
            n = s["size"]
            raw = buf[pos:pos + n]
            if len(raw) != n:
                raise AvroError("truncated fixed")
            if s.get("logicalType") == "decimal":
                return _decimal_from_bytes(raw, s), pos + n
            return raw, pos + n
        if t == "enum":
            i, pos = _zigzag_decode(buf, pos)
            try:
                return s["symbols"][i], pos
            except IndexError:
                raise AvroError(f"enum index {i} out of range")
        if t == "union":
            i, pos = _zigzag_decode(buf, pos)
            if not 0 <= i < len(s):
                raise AvroError(f"union branch {i} out of range")
            return self._dec(s[i], buf, pos)
        if t == "record":
            out = {}
            for f in s["fields"]:
                out[f["name"]], pos = self._dec(f["type"], buf, pos)
            return out, pos
        if t == "array":
            out_l: List[Any] = []
            while True:
                cnt, pos = _zigzag_decode(buf, pos)
                if cnt == 0:
                    return out_l, pos
                if cnt < 0:
                    cnt = -cnt
                    _size, pos = _zigzag_decode(buf, pos)
                for _ in range(cnt):
                    v, pos = self._dec(s["items"], buf, pos)
                    out_l.append(v)
        if t == "map":
            out_m: Dict[str, Any] = {}
            while True:
                cnt, pos = _zigzag_decode(buf, pos)
                if cnt == 0:
                    return out_m, pos
                if cnt < 0:
                    cnt = -cnt
                    _size, pos = _zigzag_decode(buf, pos)
                for _ in range(cnt):
                    k, pos = self._dec("string", buf, pos)
                    out_m[k], pos = self._dec(s["values"], buf, pos)
        raise AvroError(f"unsupported schema type {t!r}")

    # -- encode -----------------------------------------------------------
    def encode(self, value: Any) -> bytes:
        out = bytearray()
        self._enc(self.schema, value, out)
        return bytes(out)

    def _enc(self, s: Any, v: Any, out: bytearray) -> None:
        s = self._resolve(s)
        t = _type_name(s)
        if t == "null":
            return
        if t == "boolean":
            out.append(1 if v else 0)
        elif t == "int":
            # encoder-level int32 bound (not just union matching): an
            # out-of-range value must raise, never emit an invalid varint
            if not -(1 << 31) <= int(v) < (1 << 31):
                raise AvroError(f"value {v!r} out of int32 range")
            out += _zigzag_encode(int(v))
        elif t == "long":
            out += _zigzag_encode(int(v))
        elif t == "float":
            out += struct.pack("<f", float(v))
        elif t == "double":
            out += struct.pack("<d", float(v))
        elif t == "string":
            b = str(v).encode()
            out += _zigzag_encode(len(b)) + b
        elif t == "bytes":
            if _is_decimal_schema(s) and not isinstance(v, (bytes,
                                                            bytearray)):
                v = _decimal_to_bytes(v, s)    # round-trippable decimals
            out += _zigzag_encode(len(v)) + bytes(v)
        elif t == "fixed":
            if _is_decimal_schema(s) and not isinstance(v, (bytes,
                                                            bytearray)):
                v = _decimal_to_bytes(v, s)
            if len(v) != s["size"]:
                raise AvroError("fixed size mismatch")
            out += bytes(v)
        elif t == "enum":
            out += _zigzag_encode(s["symbols"].index(v))
        elif t == "union":
            for i, branch in enumerate(s):
                if self._matches(branch, v):
                    out += _zigzag_encode(i)
                    self._enc(branch, v, out)
                    return
            raise AvroError(f"no union branch for {v!r}")
        elif t == "record":
            for f in s["fields"]:
                self._enc(f["type"], v[f["name"]], out)
        elif t == "array":
            if v:
                out += _zigzag_encode(len(v))
                for item in v:
                    self._enc(s["items"], item, out)
            out += _zigzag_encode(0)
        elif t == "map":
            if v:
                out += _zigzag_encode(len(v))
                for k, mv in v.items():
                    self._enc("string", k, out)
                    self._enc(s["values"], mv, out)
            out += _zigzag_encode(0)
        else:
            raise AvroError(f"unsupported schema type {t!r}")

    def _matches(self, s: Any, v: Any) -> bool:
        t = _type_name(self._resolve(s))
        if t == "null":
            return v is None
        if v is None:
            return False
        if t == "boolean":
            return isinstance(v, bool)
        if t == "int":
            # int32-bounded: a 2^40 value must NOT be written into an
            # int branch (conformant readers would overflow/reject)
            return isinstance(v, int) and not isinstance(v, bool) \
                and -(1 << 31) <= v < (1 << 31)
        if t == "long":
            return isinstance(v, int) and not isinstance(v, bool)
        if t in ("float", "double"):
            # int promotes to float/double (every standard Avro writer
            # accepts it; earlier int/long branches win on order)
            return isinstance(v, (int, float)) and not isinstance(v, bool)
        if t == "string":
            return isinstance(v, str)
        if t in ("bytes", "fixed"):
            if _is_decimal_schema(self._resolve(s)):
                import decimal
                return isinstance(v, (bytes, bytearray, decimal.Decimal))
            return isinstance(v, (bytes, bytearray))
        if t == "record":
            return isinstance(v, dict)
        if t == "array":
            return isinstance(v, list)
        if t == "map":
            return isinstance(v, dict)
        if t == "enum":
            return isinstance(v, str)
        return False


# ---------------------------------------------------------------------------
# object container files
# ---------------------------------------------------------------------------

_MAGIC = b"Obj\x01"
_META_SCHEMA = {"type": "map", "values": "bytes"}


def read_container(path: str) -> List[Dict[str, Any]]:
    with open(path, "rb") as fh:
        data = fh.read()
    if data[:4] != _MAGIC:
        raise AvroError(f"{path!r} is not an Avro container file")
    meta_codec = AvroCodec(_META_SCHEMA)
    meta, pos = meta_codec.decode(data, 4)   # str keys, bytes values
    raw_schema = meta["avro.schema"]
    schema = json.loads(raw_schema.decode()
                        if isinstance(raw_schema, bytes) else raw_schema)
    codec_name = meta.get("avro.codec", b"null")
    if isinstance(codec_name, bytes):
        codec_name = codec_name.decode()
    if codec_name not in ("null", "deflate"):
        raise AvroError(f"unsupported container codec {codec_name!r}")
    sync = data[pos:pos + 16]
    pos += 16
    codec = AvroCodec(schema)
    rows: List[Dict[str, Any]] = []
    while pos < len(data):
        count, pos = _zigzag_decode(data, pos)
        size, pos = _zigzag_decode(data, pos)
        block = data[pos:pos + size]
        pos += size
        if data[pos:pos + 16] != sync:
            raise AvroError("container sync marker mismatch")
        pos += 16
        if codec_name == "deflate":
            block = zlib.decompress(block, -15)
        bp = 0
        for _ in range(count):
            row, bp = codec.decode(block, bp)
            rows.append(row)
    return rows


def write_container(path: str, schema: Any,
                    rows: List[Dict[str, Any]],
                    codec_name: str = "null") -> None:
    codec = AvroCodec(schema)
    meta_codec = AvroCodec(_META_SCHEMA)
    sync = b"\x13" * 16
    body = b"".join(codec.encode(r) for r in rows)
    if codec_name == "deflate":
        c = zlib.compressobj(9, zlib.DEFLATED, -15)
        body = c.compress(body) + c.flush()
    with open(path, "wb") as fh:
        fh.write(_MAGIC)
        fh.write(meta_codec.encode({
            "avro.schema": json.dumps(
                schema if not isinstance(schema, str) else
                json.loads(schema)).encode(),
            "avro.codec": codec_name.encode()}))
        fh.write(sync)
        fh.write(_zigzag_encode(len(rows)) + _zigzag_encode(len(body)))
        fh.write(body)
        fh.write(sync)


# ---------------------------------------------------------------------------
# Confluent schema-registry wire format
# ---------------------------------------------------------------------------

class ConfluentAvroDecoder:
    """KafkaConfluentSchemaRegistryAvroMessageDecoder.java:53 analog:
    decode `0x00 | schema_id:i32be | avro binary` messages, fetching and
    caching writer schemas from the registry REST API. Callable, so it
    plugs straight into stream consumers as the value decoder."""

    def __init__(self, registry_url: str, timeout: float = 10.0):
        self.registry_url = registry_url.rstrip("/")
        self.timeout = timeout
        self._codecs: Dict[int, AvroCodec] = {}
        self._lock = threading.Lock()

    def _codec(self, schema_id: int) -> AvroCodec:
        with self._lock:
            codec = self._codecs.get(schema_id)
        if codec is not None:
            return codec
        with urllib.request.urlopen(
                f"{self.registry_url}/schemas/ids/{schema_id}",
                timeout=self.timeout) as resp:
            payload = json.loads(resp.read())
        codec = AvroCodec(payload["schema"])
        with self._lock:
            self._codecs[schema_id] = codec
        return codec

    def __call__(self, message: bytes) -> Dict[str, Any]:
        if not message or message[0] != 0:
            raise AvroError(
                "not a Confluent-framed message (magic byte != 0)")
        if len(message) < 5:
            raise AvroError("truncated Confluent frame header")
        (schema_id,) = struct.unpack(">i", message[1:5])
        value, _pos = self._codec(schema_id).decode(message, 5)
        return value


def confluent_encode(schema_id: int, codec: AvroCodec,
                     value: Dict[str, Any]) -> bytes:
    return b"\x00" + struct.pack(">i", schema_id) + codec.encode(value)


class SchemaRegistryStub:
    """In-process schema registry speaking the two endpoints the decoder
    and producers need: POST /subjects/{s}/versions (register, returns
    {'id': n}) and GET /schemas/ids/{n} (returns {'schema': json})."""

    def __init__(self, port: int = 0):
        import http.server

        stub = self
        self.schemas: Dict[int, str] = {}
        self._next = 0
        self._lock = threading.Lock()

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, status: int, obj: Any) -> None:
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type",
                                 "application/vnd.schemaregistry.v1+json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:
                parts = self.path.strip("/").split("/")
                if len(parts) == 3 and parts[:2] == ["schemas", "ids"]:
                    sid = int(parts[2])
                    schema = stub.schemas.get(sid)
                    if schema is None:
                        return self._send(404, {
                            "error_code": 40403,
                            "message": "Schema not found"})
                    return self._send(200, {"schema": schema})
                self._send(404, {"error_code": 404, "message": "nope"})

            def do_POST(self) -> None:
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n)) if n else {}
                parts = self.path.strip("/").split("/")
                if len(parts) == 3 and parts[0] == "subjects" \
                        and parts[2] == "versions":
                    sid = stub.register(body["schema"])
                    return self._send(200, {"id": sid})
                self._send(404, {"error_code": 404, "message": "nope"})

        import http.server as hs
        self._server = hs.ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def register(self, schema: str) -> int:
        with self._lock:
            for sid, s in self.schemas.items():
                if s == schema:
                    return sid
            self._next += 1
            self.schemas[self._next] = schema
            return self._next

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
