"""Client library: connections to in-process brokers and broker HTTP
endpoints.

Reference parity: pinot-clients/pinot-java-client (Connection /
ResultSetGroup over broker REST) and pinot-jdbc-client's
cursor-flavoured access. `connect()` (re-exported from broker.broker)
wraps an in-process Broker; HttpConnection speaks /query/sql to a
BrokerNode.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..broker.broker import Broker, Connection, connect  # noqa: F401
from ..engine.reduce import ResultTable
from ..query.sql import SqlError


class HttpConnection:
    """SQL over a broker's REST endpoint (java-client Connection
    analog). execute() returns the same ResultTable the in-process path
    yields; errors surface as SqlError."""

    def __init__(self, broker_url: str, timeout: float = 60.0):
        self.broker_url = broker_url.rstrip("/")
        self.timeout = timeout

    def execute(self, sql: str) -> ResultTable:
        import urllib.error

        from ..cluster.http_util import http_json
        try:
            resp = http_json("POST", f"{self.broker_url}/query/sql",
                             {"sql": sql}, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            try:
                detail = e.read().decode()
            except Exception:
                detail = str(e)
            raise SqlError(f"broker rejected query: {detail[:300]}") \
                from None
        return result_table_from_response(resp)

    __call__ = execute

    # cursor-style access (jdbc-client analog)
    def cursor(self) -> "Cursor":
        return Cursor(self)


class Cursor:
    """Minimal DB-API-shaped cursor over HttpConnection/Connection."""

    def __init__(self, conn):
        self._conn = conn
        self._result: Optional[ResultTable] = None
        self._pos = 0

    @property
    def description(self):
        if self._result is None:
            return None
        return [(c, None, None, None, None, None, None)
                for c in self._result.columns]

    def execute(self, sql: str) -> "Cursor":
        self._result = self._conn.execute(sql)
        self._pos = 0
        return self

    def fetchone(self):
        if self._result is None or self._pos >= len(self._result.rows):
            return None
        row = self._result.rows[self._pos]
        self._pos += 1
        return row

    def fetchall(self) -> List[tuple]:
        if self._result is None:
            return []
        rows = self._result.rows[self._pos:]
        self._pos = len(self._result.rows)
        return rows

    def close(self) -> None:
        self._result = None


def result_table_from_response(resp: Dict[str, Any]) -> ResultTable:
    rt = resp.get("resultTable") or {}
    out = ResultTable(
        columns=list((rt.get("dataSchema") or {}).get("columnNames", [])),
        rows=[tuple(r) for r in rt.get("rows", [])])
    out.num_segments = resp.get("numSegmentsQueried", 0)
    out.num_segments_pruned = resp.get("numSegmentsPruned", 0)
    out.num_docs_scanned = resp.get("numDocsScanned", 0)
    out.time_ms = resp.get("timeUsedMs", 0.0)
    return out


def connect_url(broker_url: str, timeout: float = 60.0) -> HttpConnection:
    return HttpConnection(broker_url, timeout)
