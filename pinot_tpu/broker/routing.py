"""Broker routing: segment pruning, instance selection, time boundary.

Reference parity: pinot-broker/.../broker/routing/ —
- segment pruners (segmentpruner/{TimeSegmentPruner,PartitionSegment
  Pruner}.java): drop segments a query cannot match using broker-held
  segment metadata (per-column min/max, partition ids);
- instance selectors (instanceselector/{Balanced,ReplicaGroup,
  StrictReplicaGroup}InstanceSelector.java): which replica serves each
  segment;
- adaptive server selection (adaptiveserverselector/): latency/in-flight
  aware replica choice;
- TimeBoundaryManager (timeboundary/TimeBoundaryManager.java): the
  offline/realtime split point for hybrid tables.

All pure logic over the routing snapshot — shared by the in-process
broker and the HTTP BrokerNode.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Set, Tuple

from ..query.sql import (Between, BoolAnd, Comparison, Identifier, InList,
                         Literal)
from ..spi.partition import partition_of

# ---------------------------------------------------------------------------
# filter analysis: per-column value constraints from the WHERE conjuncts
# ---------------------------------------------------------------------------


class ColumnBounds:
    """Interval + optional equality-set constraint for one column."""

    def __init__(self):
        self.lo: Optional[Any] = None
        self.hi: Optional[Any] = None
        self.values: Optional[Set[Any]] = None  # None = unconstrained

    def add_range(self, lo: Optional[Any], hi: Optional[Any]) -> None:
        if lo is not None and (self.lo is None or lo > self.lo):
            self.lo = lo
        if hi is not None and (self.hi is None or hi < self.hi):
            self.hi = hi

    def add_values(self, vals: Set[Any]) -> None:
        self.values = vals if self.values is None else (self.values & vals)


def filter_bounds(e: Any) -> Dict[str, ColumnBounds]:
    """Top-level AND conjunct analysis (same scope the reference's pruners
    use — OR branches are not analyzed)."""
    out: Dict[str, ColumnBounds] = {}

    def bound(name: str) -> ColumnBounds:
        return out.setdefault(name, ColumnBounds())

    def visit(conj: Any) -> None:
        if isinstance(conj, BoolAnd):
            for c in conj.children:
                visit(c)
            return
        if isinstance(conj, Comparison) and \
                isinstance(conj.lhs, Identifier) and \
                isinstance(conj.rhs, Literal):
            name, v = conj.lhs.name, conj.rhs.value
            if conj.op == "==":
                bound(name).add_range(v, v)
                bound(name).add_values({v})
            elif conj.op in (">", ">="):
                bound(name).add_range(v, None)
            elif conj.op in ("<", "<="):
                bound(name).add_range(None, v)
        elif isinstance(conj, Comparison) and \
                isinstance(conj.rhs, Identifier) and \
                isinstance(conj.lhs, Literal):
            flipped = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(
                conj.op, conj.op)
            visit(Comparison(flipped, conj.rhs, conj.lhs))
        elif isinstance(conj, Between) and not conj.negated and \
                isinstance(conj.expr, Identifier) and \
                isinstance(conj.lo, Literal) and isinstance(conj.hi, Literal):
            bound(conj.expr.name).add_range(conj.lo.value, conj.hi.value)
        elif isinstance(conj, InList) and not conj.negated and \
                isinstance(conj.expr, Identifier):
            bound(conj.expr.name).add_values(
                {v.value for v in conj.values})

    if e is not None:
        visit(e)
    return out


# ---------------------------------------------------------------------------
# segment pruning over broker-held metadata
# ---------------------------------------------------------------------------

def _cmp_overlap(lo, hi, smin, smax) -> bool:
    """Does [lo,hi] (None = open) intersect the segment's [smin,smax]?"""
    try:
        if lo is not None and smax is not None and smax < lo:
            return False
        if hi is not None and smin is not None and smin > hi:
            return False
    except TypeError:
        return True  # incomparable types: cannot prune
    return True


def prune_segments(segment_meta: Dict[str, Dict[str, Any]], where: Any,
                   table_cfg: Optional[Dict[str, Any]] = None
                   ) -> Tuple[List[str], int]:
    """(segments to query, pruned count). segment_meta: name ->
    {"columns": {col: {"min","max","partitions"}}, "numPartitions": N}.
    Segments without metadata are never pruned."""
    bounds = filter_bounds(where)
    keep: List[str] = []
    pruned = 0
    pc = (table_cfg or {}).get("partitionColumn")
    for name, meta in segment_meta.items():
        cols = (meta or {}).get("columns") or {}
        drop = False
        for col, b in bounds.items():
            cm = cols.get(col)
            if cm is None:
                continue
            smin, smax = cm.get("min"), cm.get("max")
            # value-range pruning (ColumnValueSegmentPruner / time pruner)
            if (b.lo is not None or b.hi is not None) and \
                    not _cmp_overlap(b.lo, b.hi, smin, smax):
                drop = True
                break
            # partition pruning: equality values all outside this
            # segment's partitions
            parts = cm.get("partitions")
            if parts is not None and col == pc and b.values:
                n = int(meta.get("numPartitions") or
                        (table_cfg or {}).get("numPartitions") or 1)
                pset = set(parts)
                if not any(partition_of(v, n) in pset for v in b.values):
                    drop = True
                    break
        if drop:
            pruned += 1
        else:
            keep.append(name)
    return keep, pruned


# ---------------------------------------------------------------------------
# instance selection
# ---------------------------------------------------------------------------

# placement-affinity multipliers (HBM tier, engine/tier.py): a replica
# already holding a segment's columns hot — or a warm ragged cube for
# the plan key — answers without paying the upload, so its adaptive
# score shrinks by the factor. Warm (padded host arrays) still skips
# the mmap re-pad, a weaker but real preference. Unknown/cold = 1.0.
PLACEMENT_AFFINITY = {"hot": 0.3, "cube": 0.45, "warm": 0.6}

# every selector's select() accepts ``placement`` ({segment: {server:
# tier}} from the residency heartbeats); only the adaptive selector
# uses it — the deterministic selectors keep their reference semantics


class BalancedInstanceSelector:
    """Round-robin across healthy replicas per segment (the default)."""

    def __init__(self):
        self._rr = 0

    def select(self, assignment: Dict[str, List[str]],
               healthy, placement=None) -> Dict[str, Optional[str]]:
        out: Dict[str, Optional[str]] = {}
        for seg, holders in assignment.items():
            cands = [h for h in holders if healthy(h)] or list(holders)
            if not cands:
                out[seg] = None
                continue
            self._rr += 1
            out[seg] = cands[self._rr % len(cands)]
        return out


class ReplicaGroupInstanceSelector:
    """One replica index per query: every segment served by the same
    replica position, minimizing the number of servers a query fans out
    to (ReplicaGroupInstanceSelector semantics). Falls back per segment
    when that replica is unhealthy."""

    def __init__(self):
        self._rr = 0

    def select(self, assignment: Dict[str, List[str]],
               healthy, placement=None) -> Dict[str, Optional[str]]:
        self._rr += 1
        r = self._rr
        out: Dict[str, Optional[str]] = {}
        for seg, holders in assignment.items():
            if not holders:
                out[seg] = None
                continue
            pick = holders[r % len(holders)]
            if not healthy(pick):
                cands = [h for h in holders if healthy(h)] or list(holders)
                pick = cands[r % len(cands)] if cands else None
            out[seg] = pick
        return out


class StrictReplicaGroupInstanceSelector(ReplicaGroupInstanceSelector):
    """Like ReplicaGroup but refuses to mix replica positions: if the
    chosen replica of any segment is unhealthy, the whole query errors
    (strict consistency for partial-upsert routing)."""

    def select(self, assignment: Dict[str, List[str]],
               healthy, placement=None) -> Dict[str, Optional[str]]:
        self._rr += 1
        r = self._rr
        out: Dict[str, Optional[str]] = {}
        for seg, holders in assignment.items():
            pick = holders[r % len(holders)] if holders else None
            out[seg] = pick if (pick is not None and healthy(pick)) \
                else None
        return out


class AdaptiveServerSelector:
    """Latency-EWMA + in-flight aware replica choice
    (adaptiveserverselector/ NumInFlightReqSelector + LatencySelector
    hybrid): score = ewma_latency_ms * (1 + in_flight), scaled by the
    placement-affinity factor when tier residency is known — a replica
    already holding the segment hot (or a warm cube) wins unless its
    latency/in-flight picture is badly worse, and the server-name
    tiebreak keeps repeated picks sticky instead of ping-ponging
    uploads across replicas."""

    ALPHA = 0.3

    def __init__(self):
        self._lock = threading.Lock()
        self._lat: Dict[str, float] = {}
        self._inflight: Dict[str, int] = {}

    def record_start(self, server: str) -> None:
        with self._lock:
            self._inflight[server] = self._inflight.get(server, 0) + 1

    def record_end(self, server: str, latency_ms: float) -> None:
        with self._lock:
            self._inflight[server] = max(
                0, self._inflight.get(server, 1) - 1)
            prev = self._lat.get(server)
            self._lat[server] = latency_ms if prev is None else \
                (1 - self.ALPHA) * prev + self.ALPHA * latency_ms

    def score(self, server: str) -> float:
        return self._score_default(server, 1.0)

    def estimate_ms(self, server: str) -> Optional[float]:
        """Latency EWMA for hedging decisions (None until the first
        completed call establishes an estimate)."""
        with self._lock:
            return self._lat.get(server)

    def _score_default(self, server: str, default: float) -> float:
        """score() with an explicit unknown-latency default (the
        placement-aware path): the stock 1.0 ms optimism makes a
        never-measured replica out-bid a measured one holding the
        segment HOT, ping-ponging uploads across replicas — with
        residency in hand, an unknown server scores like the average
        known one instead."""
        with self._lock:
            return self._lat.get(server, default) * \
                (1 + self._inflight.get(server, 0))

    def select(self, assignment: Dict[str, List[str]],
               healthy, placement=None) -> Dict[str, Optional[str]]:
        with self._lock:
            lats = list(self._lat.values())
        mean_lat = (sum(lats) / len(lats)) if lats else 1.0
        out: Dict[str, Optional[str]] = {}
        for seg, holders in assignment.items():
            cands = [h for h in holders if healthy(h)] or list(holders)
            if not cands:
                out[seg] = None
                continue
            tiers = (placement or {}).get(seg) or {}
            default = mean_lat if tiers else 1.0
            out[seg] = min(
                cands,
                key=lambda h: (self._score_default(h, default)
                               * PLACEMENT_AFFINITY.get(tiers.get(h),
                                                        1.0), h))
        return out


SELECTORS = {
    "balanced": BalancedInstanceSelector,
    "replicaGroup": ReplicaGroupInstanceSelector,
    "strictReplicaGroup": StrictReplicaGroupInstanceSelector,
    "adaptive": AdaptiveServerSelector,
}


def make_selector(kind: str):
    cls = SELECTORS.get(kind)
    if cls is None:
        raise ValueError(f"unknown instance selector {kind!r}; "
                         f"have {sorted(SELECTORS)}")
    return cls()


# ---------------------------------------------------------------------------
# hybrid-table time boundary
# ---------------------------------------------------------------------------

def resolve_time_column(config: Optional[Dict[str, Any]], schema: Any
                        ) -> Optional[str]:
    """Table time column: explicit timeColumn config, else the schema's
    first DATE_TIME field. Accepts a schema dict ({"fields": [...]}) or a
    Schema object — shared by the in-process and HTTP brokers."""
    if config and config.get("timeColumn"):
        return config["timeColumn"]
    fields = (schema or {}).get("fields", []) if isinstance(schema, dict) \
        else getattr(schema, "fields", [])
    for f in fields:
        if isinstance(f, dict):
            if f.get("fieldType") == "DATE_TIME":
                return f.get("name")
        elif getattr(getattr(f, "field_type", None), "value", None) \
                == "DATE_TIME":
            return f.name
    return None


def time_boundary(offline_segment_meta: Dict[str, Dict[str, Any]],
                  time_col: str) -> Optional[Any]:
    """Max end time across offline segments (TimeBoundaryManager: the
    offline side answers time <= boundary, realtime time > boundary)."""
    best = None
    for meta in offline_segment_meta.values():
        cm = ((meta or {}).get("columns") or {}).get(time_col)
        if cm is None or cm.get("max") is None:
            return None  # a segment without time metadata: no boundary
        if best is None or cm["max"] > best:
            best = cm["max"]
    return best


def split_hybrid(stmt, time_col: str, boundary: Any):
    """Rewrite one logical-table statement into (offline, realtime)
    statements with the boundary conjuncts applied."""
    import copy
    from ..query.sql import SelectStmt  # noqa: F401

    def with_conjunct(s, conj):
        s = copy.copy(s)
        s.options = dict(s.options)
        s.where = conj if s.where is None else BoolAnd((s.where, conj))
        return s

    off = with_conjunct(stmt, Comparison(
        "<=", Identifier(time_col), Literal(boundary)))
    off.table = stmt.table + "_OFFLINE"
    rt = with_conjunct(stmt, Comparison(
        ">", Identifier(time_col), Literal(boundary)))
    rt.table = stmt.table + "_REALTIME"
    return off, rt
