"""Per-table query quotas (QPS rate limiting at the broker).

Reference parity: pinot-broker/.../queryquota/
HelixExternalViewBasedQueryQuotaManager.java — per-table max QPS from
table config, enforced with a token bucket at each broker; queries over
quota are rejected up front (BrokerMeter.QUERY_QUOTA_EXCEEDED). The
reference divides the table quota by the number of live brokers; here
each broker enforces the configured rate directly (single-broker default)
with an optional divisor for multi-broker deployments.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..query.sql import SqlError


class QuotaExceededError(SqlError):
    pass


class _TokenBucket:
    def __init__(self, qps: float, burst: Optional[float] = None):
        self.qps = float(qps)
        self.capacity = burst if burst is not None else max(self.qps, 1.0)
        self.tokens = self.capacity
        self.t0 = time.monotonic()

    def try_acquire(self) -> bool:
        now = time.monotonic()
        self.tokens = min(self.capacity,
                          self.tokens + (now - self.t0) * self.qps)
        self.t0 = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class QueryQuotaManager:
    """table -> token bucket, built from table config quotaQps."""

    def __init__(self, num_brokers: int = 1):
        self._lock = threading.Lock()
        self._buckets: Dict[str, _TokenBucket] = {}
        self._qps: Dict[str, float] = {}
        self.num_brokers = max(num_brokers, 1)

    def set_quota(self, table: str, qps: Optional[float]) -> None:
        with self._lock:
            if qps is None or qps <= 0:
                self._buckets.pop(table, None)
                self._qps.pop(table, None)
                return
            per_broker = qps / self.num_brokers
            if self._qps.get(table) != per_broker:
                self._qps[table] = per_broker
                self._buckets[table] = _TokenBucket(per_broker)

    def check(self, table: str) -> None:
        """Raise QuotaExceededError when the table is over its QPS."""
        with self._lock:
            bucket = self._buckets.get(table)
            if bucket is not None and not bucket.try_acquire():
                raise QuotaExceededError(
                    f"table {table!r} exceeded its query quota "
                    f"({self._qps[table]:g} qps/broker)")
