"""Per-table query quotas (QPS rate limiting at the broker).

Reference parity: pinot-broker/.../queryquota/
HelixExternalViewBasedQueryQuotaManager.java — per-table max QPS from
table config, enforced with a token bucket at each broker; queries over
quota are rejected up front (BrokerMeter.QUERY_QUOTA_EXCEEDED). The
reference divides the table quota by the number of LIVE brokers (its
``processQueryRateLimitingExternalViewChange`` recomputes the per-broker
rate whenever the broker resource's external view changes); here the
divisor is refreshed the same way from the controller's heartbeat-fresh
broker list (``routing_snapshot()["liveBrokers"]`` — round 14 made
brokers register+heartbeat like servers), via ``set_num_brokers`` on
every quota check. A standalone in-process broker keeps the divisor at
its default of 1.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..query.sql import SqlError


class QuotaExceededError(SqlError):
    pass


class _TokenBucket:
    def __init__(self, qps: float, burst: Optional[float] = None):
        self.qps = float(qps)
        self.capacity = burst if burst is not None else max(self.qps, 1.0)
        self.tokens = self.capacity
        self.t0 = time.monotonic()

    def try_acquire(self) -> bool:
        now = time.monotonic()
        self.tokens = min(self.capacity,
                          self.tokens + (now - self.t0) * self.qps)
        self.t0 = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def rescale(self, qps: float) -> None:
        """Change the rate IN PLACE, preserving the spent fraction of
        the burst. A live-broker-count change must not mint a fresh
        full burst — heartbeat flapping would otherwise let a client
        sustain a multiple of the configured QPS by cashing a new
        bucket on every flip."""
        now = time.monotonic()
        self.tokens = min(self.capacity,
                          self.tokens + (now - self.t0) * self.qps)
        self.t0 = now
        frac = self.tokens / self.capacity if self.capacity else 0.0
        self.qps = float(qps)
        self.capacity = max(self.qps, 1.0)
        self.tokens = frac * self.capacity


class QueryQuotaManager:
    """table -> token bucket, built from table config quotaQps divided
    by the live broker count."""

    def __init__(self, num_brokers: int = 1):
        self._lock = threading.Lock()
        self._buckets: Dict[str, _TokenBucket] = {}
        self._qps: Dict[str, float] = {}       # effective (per-broker)
        self._raw: Dict[str, float] = {}       # configured table rate
        self.num_brokers = max(num_brokers, 1)

    def set_num_brokers(self, n: int) -> None:
        """Refresh the live-broker divisor (the external-view-change
        analog). Existing buckets re-divide only when the count
        actually changed — a broker joining/leaving the fleet rescales
        every table's per-broker rate."""
        n = max(int(n), 1)
        with self._lock:
            if n == self.num_brokers:
                return
            self.num_brokers = n
            for table, raw in self._raw.items():
                per_broker = raw / n
                self._qps[table] = per_broker
                # rescale in place (spent-burst fraction preserved):
                # a fresh bucket per membership flip would mint a full
                # burst each flip and bypass the quota
                self._buckets[table].rescale(per_broker)

    def set_quota(self, table: str, qps: Optional[float]) -> None:
        with self._lock:
            if qps is None or qps <= 0:
                self._buckets.pop(table, None)
                self._qps.pop(table, None)
                self._raw.pop(table, None)
                return
            self._raw[table] = float(qps)
            per_broker = qps / self.num_brokers
            if self._qps.get(table) != per_broker:
                self._qps[table] = per_broker
                self._buckets[table] = _TokenBucket(per_broker)

    def effective_qps(self, table: str) -> Optional[float]:
        """The per-broker rate currently enforced (tests + consoles)."""
        with self._lock:
            return self._qps.get(table)

    def check(self, table: str) -> None:
        """Raise QuotaExceededError when the table is over its QPS."""
        with self._lock:
            bucket = self._buckets.get(table)
            if bucket is not None and not bucket.try_acquire():
                raise QuotaExceededError(
                    f"table {table!r} exceeded its query quota "
                    f"({self._qps[table]:g} qps/broker across "
                    f"{self.num_brokers} live broker(s))")
