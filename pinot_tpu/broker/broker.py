"""Broker: SQL in, ResultTable out — compile, route, scatter, reduce.

Reference parity: pinot-broker/.../requesthandler/
BaseSingleStageBrokerRequestHandler.java (compile :256, optimize :492-521,
route :560-577) + SingleConnectionBrokerRequestHandler.java:141-151
(scatter-gather + reduce) + BrokerRequestHandlerDelegate (engine pick) +
query options (QueryOptionsUtils: timeoutMs, trace, skipUpsert) + EXPLAIN.
In-process execution over local TableDataManagers; the HTTP cluster roles
(cluster/broker_node.py) reuse the same reduce over remote partials, and
ICI collectives (parallel/distributed.py) replace the Netty data plane for
mesh-resident tables.
"""
from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional

from ..engine.executor import execute_plan
from ..engine.reduce import ResultTable, reduce_partials
from ..engine.setops import combine_setop, order_limit_rows
from ..query.context import build_query_context
from ..query.planner import SegmentPlanner, _truthy
from ..query.sql import (Comparison, CteDef, DdlStmt, Exists, InList,
                         InSubquery, Literal, ScalarSubquery, SelectStmt,
                         SetOpStmt, SqlError, map_expr, parse_sql)
from ..server.data_manager import TableDataManager
from ..utils import phases as ph
from ..utils.metrics import global_metrics
from ..utils.trace import Tracing

DEFAULT_TIMEOUT_MS = 10_000


def _cte_table(name: str, columns: List[str], rows: List[tuple],
               tmpdirs: List[str]) -> TableDataManager:
    """Materialize a CTE result as a single-segment table. Types are
    inferred per column (all-int -> LONG, numeric -> DOUBLE, else
    STRING); an empty result registers a segment-less manager."""
    import tempfile

    import numpy as np

    from ..segment import SegmentBuilder
    from ..spi import DataType, FieldSpec, FieldType, Schema, TableConfig

    dm = TableDataManager(name)
    if not rows:
        return dm
    cols: Dict[str, Any] = {}
    fields: List[FieldSpec] = []
    for j, cname in enumerate(columns):
        vals = [r[j] for r in rows]
        if any(v is None for v in vals):
            raise SqlError(f"CTE {name!r} column {cname!r} produced NULL "
                           "values; filter them in the CTE query")
        if all(isinstance(v, (int, np.integer))
               and not isinstance(v, (bool, np.bool_)) for v in vals):
            cols[cname] = np.asarray(vals, dtype=np.int64)
            dt = DataType.LONG
        elif all(isinstance(v, (int, float, np.integer, np.floating))
                 and not isinstance(v, (bool, np.bool_)) for v in vals):
            cols[cname] = np.asarray(vals, dtype=np.float64)
            dt = DataType.DOUBLE
        else:
            cols[cname] = np.asarray([str(v) for v in vals])
            dt = DataType.STRING
        fields.append(FieldSpec(cname, dt, FieldType.DIMENSION))
    out = tempfile.mkdtemp(prefix="ptpu_cte_")
    tmpdirs.append(out)
    seg_dir = SegmentBuilder(Schema(name, fields),
                             TableConfig(name)).build(cols, out, "cte_0")
    dm.add_segment_dir(seg_dir)
    return dm


class QueryTimeoutError(SqlError):
    pass


class Broker:
    def __init__(self, trace_ratio: Optional[float] = None,
                 trace_ledger_path: Optional[str] = None,
                 micro_batch: Optional[bool] = None,
                 micro_batch_window_ms: Optional[float] = None):
        from .quota import QueryQuotaManager
        self._tables: Dict[str, TableDataManager] = {}
        # cross-query micro-batching (PR 8): concurrent queries sharing
        # a plan structure fuse into one ragged device dispatch
        # (engine/ragged.py). The dispatcher is engine-global (fusion
        # happens below the broker), so the flag configures the shared
        # batcher; None leaves the PINOT_MICROBATCH env default alone.
        if micro_batch is not None or micro_batch_window_ms is not None:
            from ..engine.ragged import global_batcher
            global_batcher.configure(enabled=micro_batch,
                                     window_ms=micro_batch_window_ms)
        # name -> view body statement (CREATE VIEW ... AS <select>);
        # expanded into CTEs at reference time (_expand_views)
        self._views: Dict[str, Any] = {}
        self.quota = QueryQuotaManager()
        # overload protection (ISSUE 12, broker/workload.py): per-tenant
        # budgets + the watermark degradation ladder. Process-global
        # like the accountant — tenant isolation is a per-process
        # property, and in-process clusters run several broker roles in
        # one interpreter
        from .workload import global_workload
        self.workload = global_workload
        # traceRatio production sampling (round 12): constructor wins,
        # then PINOT_TRACE_RATIO, then off (the shared
        # forensics.default_trace_ratio chain). OPTION(traceRatio=...)
        # overrides per query; sampled queries land validated
        # query_trace ledger records without EXPLAIN ANALYZE.
        from ..cluster.forensics import default_trace_ratio
        self._trace_ratio = default_trace_ratio(trace_ratio)
        self._trace_ledger_path = trace_ledger_path
        # compile-plane forensics (ISSUE 15): a broker with a trace
        # ledger and no explicit PINOT_COMPILE_LEDGER lands compile
        # events in the same file, so span_diff captures double as
        # warmup-debt corpora (tools/warmup_report.py --gate)
        if trace_ledger_path:
            from ..utils.compileplane import global_compile_log
            global_compile_log.configure_path_if_unset(trace_ledger_path)

    # -- table registry (ideal-state analog) -------------------------------
    def register_table(self, dm: TableDataManager) -> None:
        self._tables[dm.table_name] = dm
        cfg = getattr(dm, "table_config", None)
        if cfg is not None and getattr(cfg, "quota_qps", None):
            self.quota.set_quota(dm.table_name, cfg.quota_qps)
        if cfg is not None:
            # workload tenant from the TableConfig tenant field; tables
            # without one charge the default tenant
            self.workload.set_table_tenant(
                dm.table_name, getattr(cfg, "tenant", None))

    def table(self, name: str) -> TableDataManager:
        if name not in self._tables:
            raise SqlError(f"table {name!r} not found; "
                           f"have {list(self._tables)}")
        return self._tables[name]

    @property
    def table_names(self) -> List[str]:
        return list(self._tables)

    # -- query path --------------------------------------------------------
    def query(self, sql: str) -> ResultTable:
        global_metrics.count("broker_queries")
        with global_metrics.timer("broker_query"):
            try:
                return self._query(sql)
            except SqlError:
                global_metrics.count("broker_query_exceptions")
                raise

    def _query(self, sql: str) -> ResultTable:
        t0 = time.perf_counter()
        stmt = parse_sql(sql)
        if isinstance(stmt, DdlStmt):
            return self._execute_ddl(stmt, t0)
        stmt._raw_sql = sql  # for the EXPLAIN ANALYZE ledger record
        opts = getattr(stmt, "options", {}) or {}
        # OPTION(queryId=...) lets replicas/retries of the same logical
        # query agree on the sampling AND shed decisions; otherwise a
        # fresh uuid draws independently per broker
        qid = str(opts.get("queryId") or uuid.uuid4().hex[:12])[:64]
        # overload admission (broker/workload.py), once per USER query —
        # nested CTE/subquery/set-op statements recurse through
        # _execute_stmt under this ticket. Plan-only EXPLAIN never
        # admits (no execution to protect); EXPLAIN ANALYZE does.
        # A shed raises the 429-shaped OverloadShedError here, before
        # any planning/dispatch work.
        from .workload import (clamp_brownout, leaf_table,
                               parse_retry_attempt)
        ticket = None
        if not getattr(stmt, "explain", False) or \
                getattr(stmt, "analyze", False):
            ticket = self.workload.admit(
                qid, leaf_table(stmt),
                retry_attempt=parse_retry_attempt(opts))
            if ticket.brownout:
                # rung-3 brownout: clamp to the floor deadline and
                # force partial-result semantics — degraded answers
                # beat a metastable queue
                clamp_brownout(stmt.options, DEFAULT_TIMEOUT_MS)
        try:
            # traceRatio production sampling: plan-only (EXPLAIN) and
            # analyze statements never sample; the decision is
            # deterministic in the query id (utils/spans.
            # sample_decision) and costs nothing when unsampled. Rung
            # >= 1 sheds this speculative work entirely.
            if not getattr(stmt, "analyze", False) and \
                    not getattr(stmt, "explain", False) and \
                    not (ticket is not None and ticket.degraded):
                from ..cluster.forensics import parse_trace_ratio
                ratio = parse_trace_ratio(opts, self._trace_ratio)
                if ratio > 0:
                    from ..utils.spans import sample_decision
                    if sample_decision(qid, ratio):
                        return self._execute_sampled(stmt, sql, t0, qid)
            return self._execute_stmt(stmt, t0)
        finally:
            self.workload.release(ticket)

    def _execute_sampled(self, stmt, sql: str, t0: float,
                         qid: str) -> ResultTable:
        """A traceRatio-sampled production query: execute under the span
        tracer (the EXPLAIN ANALYZE machinery, minus the rendered rows)
        and append a validated ``query_trace`` ledger record cross-linked
        by qid. Subqueries/CTEs/set-op branches recurse through
        _execute_stmt, so the whole statement lands in ONE tree."""
        from ..utils.spans import span_tracer
        root = span_tracer.start(ph.QUERY,
                                 table=getattr(stmt, "table", None),
                                 query_id=qid, sampled=True)
        try:
            try:
                result = self._execute_stmt(stmt, t0)
            finally:
                root = span_tracer.stop() or root
        except SqlError as e:
            # a failed sampled query still lands its (partial) tree —
            # those are exactly the spans forensics wants
            root.annotate(error=str(e)[:200])
            self._append_trace(root, stmt, sql, qid)
            raise
        root.annotate(rows=len(result.rows))
        self._append_trace(root, stmt, sql, qid)
        return result

    def _append_trace(self, root, stmt, sql: str, qid: str) -> None:
        global_metrics.count("sampled_traces")
        import os

        from ..utils import ledger as uledger
        # explicit-ledger-only, like QueryForensics.record_trace: no
        # configured path means the trace is counted but not persisted —
        # an implicit CWD PERF_LEDGER.jsonl write would pollute the repo
        # bench ledger (and the span-diff gate reading it) with traces
        # from whatever code version happens to be running
        path = (getattr(stmt, "options", {}).get("ledgerPath")
                or self._trace_ledger_path
                or os.environ.get("PINOT_TPU_LEDGER_PATH"))
        if not path:
            return
        try:
            uledger.append_record(
                uledger.trace_record(root, sql, qid=qid, sampled=True),
                path)
        except OSError:
            # observability must never fail the data path
            global_metrics.count("query_trace_write_errors")

    # -- views (QueryEnvironment view catalog analog) ----------------------
    def _execute_ddl(self, stmt: DdlStmt, t0: float) -> ResultTable:
        if stmt.kind == "create_view":
            if stmt.name in self._tables or self._is_hybrid(stmt.name):
                raise SqlError(
                    f"cannot create view {stmt.name!r}: a table with "
                    "that name exists")
            if stmt.name in self._views and not stmt.or_replace:
                raise SqlError(
                    f"view {stmt.name!r} already exists; use CREATE OR "
                    "REPLACE VIEW")
            self._views[stmt.name] = stmt.stmt
            status = "CREATED"
        else:
            if stmt.name not in self._views:
                if stmt.if_exists:
                    status = "NOT_FOUND"
                else:
                    raise SqlError(f"view {stmt.name!r} not found; "
                                   f"have {sorted(self._views)}")
            else:
                del self._views[stmt.name]
                status = "DROPPED"
        result = ResultTable(["view", "status"], [(stmt.name, status)])
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result

    @property
    def view_names(self) -> List[str]:
        return sorted(self._views)

    def _referenced_tables(self, stmt, out: set) -> None:
        """Every table name a statement tree references (main, joins,
        set-op branches, subqueries, CTE bodies)."""
        from ..query.sql import ast_children

        if isinstance(stmt, SetOpStmt):
            self._referenced_tables(stmt.left, out)
            self._referenced_tables(stmt.right, out)
            return
        out.add(stmt.table)
        for j in stmt.joins:
            out.add(j.table.name)
        for cte in getattr(stmt, "ctes", []) or []:
            self._referenced_tables(cte.stmt, out)

        def walk_expr(e):
            if isinstance(e, (InSubquery, Exists, ScalarSubquery)):
                self._referenced_tables(e.stmt, out)
            for c in ast_children(e):
                walk_expr(c)

        for e in (stmt.where, stmt.having):
            if e is not None:
                walk_expr(e)

    def _expand_views(self, stmt):
        """Prepend referenced views (transitively, dependencies first) as
        CTEs — the CTE machinery then materializes and scopes them. Names
        already registered as tables (including a scoped CTE broker's)
        or defined as explicit CTEs are never expanded."""
        if not self._views or isinstance(stmt, DdlStmt):
            return stmt
        defined = {c.name for c in getattr(stmt, "ctes", []) or []}
        order: List[str] = []

        def visit(name: str, stack: tuple) -> None:
            if name in defined or name in self._tables or name in order \
                    or name not in self._views:
                return
            if name in stack:
                raise SqlError(
                    "view cycle: " + " -> ".join(stack + (name,)))
            refs: set = set()
            self._referenced_tables(self._views[name], refs)
            for r in sorted(refs):
                visit(r, stack + (name,))
            order.append(name)

        refs: set = set()
        self._referenced_tables(stmt, refs)
        for r in sorted(refs):
            visit(r, ())
        if not order:
            return stmt
        import copy
        new_ctes = [CteDef(n, None, copy.deepcopy(self._views[n]))
                    for n in order]
        stmt.ctes = new_ctes + (stmt.ctes or [])
        return stmt

    def _is_hybrid(self, table: str) -> bool:
        return table not in self._tables and \
            f"{table}_OFFLINE" in self._tables and \
            f"{table}_REALTIME" in self._tables

    def _execute_stmt(self, stmt, t0: float) -> ResultTable:
        if getattr(stmt, "analyze", False):
            return self._execute_analyze(stmt, t0)
        stmt = self._expand_views(stmt)
        if getattr(stmt, "ctes", None):
            return self._execute_with_ctes(stmt, t0)
        if isinstance(stmt, SetOpStmt):
            return self._execute_setop(stmt, t0)
        stmt = self._resolve_subqueries(stmt)
        from ..engine.accounting import global_accountant
        from ..multistage.window import has_window
        # OPTION(queryId=...) names the accountant registration too (not
        # just the round-12 sampling decision): chaos tooling needs the
        # per-query fault streams (utils/faults.py) keyed by a
        # DETERMINISTIC id so same-seed runs reproduce p<1 draws.
        # Collisions are the caller's contract — two concurrent queries
        # sharing a name would share accounting and fault streams.
        query_id = str(getattr(stmt, "options", {}).get("queryId")
                       or uuid.uuid4().hex[:12])[:64]
        timeout_ms = int(stmt.options.get("timeoutMs", DEFAULT_TIMEOUT_MS))
        deadline = t0 + timeout_ms / 1e3
        # tenant attribution rides the accountant registration: the
        # watcher's tier-aware kill ordering and the post-paid tenant
        # budgets (workload.observe at unregister) both read it there
        tenant, tier = self.workload.resolve(stmt.table)
        if self._is_hybrid(stmt.table):
            if stmt.joins or has_window(stmt):
                raise SqlError("joins/window functions over hybrid "
                               "tables are not supported yet; query the "
                               "_OFFLINE/_REALTIME tables directly")
            global_accountant.register(query_id, deadline=deadline,
                                       tenant=tenant, tier=tier,
                                       sql=getattr(stmt, "_raw_sql", None))
            try:
                return self._execute_hybrid(stmt, t0, query_id)
            finally:
                global_accountant.unregister(query_id)
        self.quota.check(stmt.table)
        if stmt.joins or has_window(stmt):
            # v2 engine (BrokerRequestHandlerDelegate picks the multi-stage
            # handler when the query needs it); registered with the
            # accountant like any query so kills/deadlines reach its leaf
            # scans' sample points
            from ..multistage import execute_multistage
            from ..multistage.executor import explain_multistage
            if stmt.explain:
                return explain_multistage(self, stmt)
            global_accountant.register(query_id, deadline=deadline,
                                       tenant=tenant, tier=tier,
                                       sql=getattr(stmt, "_raw_sql", None))
            try:
                return execute_multistage(self, stmt)
            finally:
                global_accountant.unregister(query_id)
        ctx = build_query_context(stmt)
        trace_on = _truthy(ctx.options.get("trace"))
        scope = Tracing.register(query_id, trace_on)
        global_accountant.register(query_id, deadline=deadline,
                                   tenant=tenant, tier=tier,
                                   sql=getattr(stmt, "_raw_sql", None))
        try:
            result = self._execute_ctx(ctx, stmt, t0, deadline,
                                       query_id=query_id)
        finally:
            global_accountant.unregister(query_id)
            Tracing.unregister()
        if trace_on:
            result.trace = scope.to_dict()
        return result

    # -- EXPLAIN ANALYZE (round-7 observability tentpole) ------------------
    def _execute_analyze(self, stmt, t0: float) -> ResultTable:
        """Execute the statement for real under the span tracer and
        return the rendered span tree: per-phase wall ms (planning /
        kernel build / device execute / transfer / reduce), the
        cost-model strategy trace, plan-cache hit/miss + retrace flags,
        and estimated vs measured selectivity. OPTION(ledgerTrace=true)
        additionally appends the tree as a v2 ``query_trace`` ledger
        record (utils/ledger.py)."""
        from ..ops.plan_cache import global_plan_cache
        from ..query.explain import finalize_analyze
        from ..utils.spans import span_tracer

        stmt.analyze = False  # the re-entrant call executes normally
        cache0 = global_plan_cache.stats()
        root = span_tracer.start(ph.QUERY,
                                 table=getattr(stmt, "table", None))
        try:
            inner = self._execute_stmt(stmt, t0)
        finally:
            root = span_tracer.stop() or root
        cache1 = global_plan_cache.stats()
        root.annotate(
            rows=len(inner.rows),
            num_segments=inner.num_segments,
            num_docs_scanned=inner.num_docs_scanned,
            cache_hits=cache1["hits"] - cache0["hits"],
            cache_misses=cache1["misses"] - cache0["misses"],
            retraces=cache1["retraces"] - cache0["retraces"])
        # finalize_analyze attaches the explicit broker_overhead
        # self-time child (context build, quota, accountant
        # registration) so phase timings sum to the query's wall time —
        # shared with the cluster broker's _query_analyze
        cols, rows, trace = finalize_analyze(root)
        result = ResultTable(cols, rows,
                             num_segments=inner.num_segments,
                             num_docs_scanned=inner.num_docs_scanned)
        result.trace = trace
        if _truthy(stmt.options.get("ledgerTrace")):
            import os

            from ..utils import ledger as uledger
            path = (stmt.options.get("ledgerPath")
                    or os.environ.get("PINOT_TPU_LEDGER_PATH")
                    or "PERF_LEDGER.jsonl")
            uledger.append_record(uledger.trace_record(
                root, getattr(stmt, "_raw_sql", str(stmt.table))), path)
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result

    # -- hybrid offline+realtime tables (TimeBoundaryManager analog) -------
    def _execute_hybrid(self, stmt: SelectStmt, t0: float,
                        query_id: str = "") -> ResultTable:
        """Logical table = T_OFFLINE + T_REALTIME: the offline side answers
        time <= boundary, the realtime side time > boundary, partials merge
        in one reduce (BaseBrokerRequestHandler hybrid scatter)."""
        from ..engine.accounting import QueryKilledError
        from ..engine.serving import execute_planned, plan_segments
        from .routing import (resolve_time_column, split_hybrid,
                              time_boundary)
        logical = stmt.table
        off_dm = self.table(f"{logical}_OFFLINE")

        cfg = getattr(off_dm, "table_config", None)
        time_col = resolve_time_column(
            {"timeColumn": getattr(cfg, "time_column", None)}
            if cfg is not None else None, off_dm.schema)
        if time_col is None:
            raise SqlError(
                f"hybrid table {logical!r} needs a timeColumn in its "
                f"config or a DATE_TIME schema field")

        boundary = time_boundary(
            {seg.name: {"columns": {time_col: {
                "max": getattr(seg.columns.get(time_col), "max", None)}}}
             for seg in off_dm.acquire_segments()}, time_col)
        if boundary is None:
            raise SqlError(
                f"hybrid table {logical!r}: no offline segments, or "
                f"offline segments lack {time_col!r} metadata for the "
                f"time boundary")

        off_stmt, rt_stmt = split_hybrid(stmt, time_col, boundary)
        if stmt.explain:
            # _execute_stmt charges the quota for the explain itself
            return self._execute_stmt(off_stmt, t0)
        self.quota.check(f"{logical}_OFFLINE")
        partials: List[Any] = []
        n_segments = pruned = docs = 0
        try:
            for part_stmt in (off_stmt, rt_stmt):
                ctx_p = build_query_context(part_stmt)
                dm = self.table(ctx_p.table)
                segments = dm.acquire_segments()
                ex = plan_segments(ctx_p, segments, use_rollups=True)
                partials.extend(execute_planned(ex))
                n_segments += len(segments)
                pruned += ex.pruned
                docs += ex.docs_scanned
        except QueryKilledError as e:
            if e.is_deadline:
                global_metrics.count("broker_query_timeouts")
                raise QueryTimeoutError(str(e)) from None
            raise
        result = reduce_partials(build_query_context(off_stmt), partials)
        result.num_segments = n_segments
        result.num_segments_pruned = pruned
        result.num_docs_scanned = docs
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result

    # -- set operations (v2 set operators; combine at the broker) ----------
    _BRANCH_LIMIT = 1 << 31  # branches run unlimited; compound LIMIT caps

    def _execute_setop(self, stmt: SetOpStmt, t0: float) -> ResultTable:
        if stmt.explain:
            return self._explain_setop(stmt)
        left = self._run_branch(stmt.left, stmt.options)
        right = self._run_branch(stmt.right, stmt.options)
        result = combine_setop(stmt.op, stmt.all, left, right)
        from ..engine.reduce import DEFAULT_LIMIT
        limit = stmt.limit if stmt.limit is not None else DEFAULT_LIMIT
        result = order_limit_rows(result, stmt.order_by, limit, stmt.offset)
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result

    def _run_branch(self, stmt, options: Optional[dict] = None
                    ) -> ResultTable:
        if isinstance(stmt, SetOpStmt):
            left = self._run_branch(stmt.left, options)
            right = self._run_branch(stmt.right, options)
            return combine_setop(stmt.op, stmt.all, left, right)
        if options:
            # compound-level OPTION(...) applies to every branch
            # (branch-specific keys win)
            stmt.options = {**options, **stmt.options}
        if stmt.limit is None:
            stmt.limit = self._BRANCH_LIMIT
        return self._execute_stmt(stmt, time.perf_counter())

    def _explain_setop(self, stmt: SetOpStmt) -> ResultTable:
        rows: List[tuple] = []

        def emit(node, parent: int) -> None:
            rid = len(rows)
            if isinstance(node, SetOpStmt):
                tag = node.op.upper() + ("_ALL" if node.all else "")
                rows.append((f"SET_OP({tag})", rid, parent))
                emit(node.left, rid)
                emit(node.right, rid)
            else:
                rows.append((f"SELECT({node.table})", rid, parent))

        rows.append(("BROKER_REDUCE", 0, -1))
        emit(stmt, 0)
        return ResultTable(["Operator", "Operator_Id", "Parent_Id"], rows)

    # -- WITH / common table expressions -----------------------------------
    def _execute_with_ctes(self, stmt, t0: float) -> ResultTable:
        """Materialize each CTE (in order — later CTEs may reference
        earlier ones) into an in-memory segment registered under a
        SCOPED broker copy, then run the main statement against it.
        The scope shadows real tables for this query only and is torn
        down afterwards. Reference:
        pinot-query-planner/.../QueryEnvironment.java:126 (Calcite CTE
        planning); materialization-first is the TPU-friendly stance —
        the CTE result becomes a real segment every engine path (joins,
        windows, group-by kernels) already handles."""
        import copy
        import dataclasses
        import shutil

        scoped = copy.copy(self)
        scoped._tables = dict(self._tables)
        tmpdirs: List[str] = []
        try:
            cap = int(stmt.options.get("cteLimit", 1_000_000))
            for cte in stmt.ctes:
                if stmt.explain and not stmt.joins:
                    # EXPLAIN must not execute CTE/view bodies (same
                    # contract as _resolve_subqueries): register a
                    # zero-row placeholder carrying the output columns
                    # so the outer plan still builds. SELECT * bodies
                    # have no static column list, and the multistage
                    # join path needs real (typed) segments —
                    # materialize those the normal way.
                    names = self._static_output_columns(cte.stmt)
                    if names is not None:
                        if cte.columns and \
                                len(cte.columns) != len(names):
                            raise SqlError(
                                f"CTE {cte.name!r} declares "
                                f"{len(cte.columns)} columns but its "
                                f"query produces {len(names)}")
                        scoped._tables[cte.name] = _cte_table(
                            cte.name, list(cte.columns or names), [],
                            tmpdirs)
                        continue
                # keep the body's OWN ctes (a view defined with a WITH
                # clause): the recursive _execute_stmt materializes them
                # in a further scope; replace() still copies the node so
                # option/limit mutations never touch the stored body
                sub = dataclasses.replace(cte.stmt)
                if "timeoutMs" in stmt.options:
                    sub.options.setdefault("timeoutMs",
                                           stmt.options["timeoutMs"])
                # a CTE materializes its FULL result (no engine default
                # LIMIT 10), bounded by the cteLimit resource guard the
                # same way IN-subqueries are: an explicit LIMIT within
                # the cap is honored, anything else gets the cap+1
                # probe + error so the guard stays enforceable
                user_limit = sub.limit
                honored = user_limit is not None and user_limit <= cap
                if not honored:
                    sub.limit = cap + 1
                res = scoped._execute_stmt(sub, time.perf_counter())
                if not honored and len(res.rows) > cap:
                    over = (f" (its LIMIT {user_limit} exceeds the cap "
                            "and was not applied)"
                            if user_limit is not None else "")
                    raise SqlError(
                        f"CTE {cte.name!r} produced more than {cap} "
                        f"rows{over}; add a LIMIT <= {cap} or raise "
                        "OPTION(cteLimit=...)")
                names = cte.columns or res.columns
                if len(names) != len(res.columns):
                    raise SqlError(
                        f"CTE {cte.name!r} declares {len(cte.columns)} "
                        f"columns but its query produces "
                        f"{len(res.columns)}")
                scoped._tables[cte.name] = _cte_table(
                    cte.name, list(names), res.rows, tmpdirs)
            inner = dataclasses.replace(stmt, ctes=[])
            return scoped._execute_stmt(inner, t0)
        finally:
            for d in tmpdirs:
                shutil.rmtree(d, ignore_errors=True)

    @staticmethod
    def _static_output_columns(stmt) -> Optional[List[str]]:
        """Output column names of a statement WITHOUT executing it, or
        None when they aren't statically known (SELECT *)."""
        if isinstance(stmt, SetOpStmt):
            return Broker._static_output_columns(stmt.left)
        try:
            labels = build_query_context(stmt).labels
        except SqlError:
            return None
        if any(lb == "*" for lb in labels):
            return None
        return list(labels)

    # -- subqueries (IN_SUBQUERY / scalar / EXISTS rewrite at the broker) --
    _TRUE = Comparison("==", Literal(1), Literal(1))
    _FALSE = Comparison("==", Literal(1), Literal(0))

    def _decorrelate_exists(self, e: "Exists", stmt: SelectStmt):
        """Rewrite EXISTS to something the existing machinery executes.

        Uncorrelated: the subquery runs once with LIMIT 1 and folds to a
        constant predicate. Equality-correlated (the decorrelatable
        class Calcite's SubQueryRemoveRule handles as a semi-join):
        exactly one top-level AND-ed `inner.col = outer.col` conjunct —
        rewritten to `outer.col IN (SELECT inner.col FROM ... WHERE
        <remaining conjuncts>)`, which the IN-subquery (IdSet) path then
        materializes. Returns the replacement predicate node, or raises
        SqlError for correlation shapes outside that class."""
        import dataclasses

        from ..query.sql import BoolAnd, Comparison as Cmp, Identifier, \
            IsNull, SelectItem, collect_identifiers

        sub = e.stmt
        # standard SQL scoping: an alias REPLACES the table name as the
        # qualifier (so a self-table subquery with an alias still sees
        # the outer name as a correlation, not as itself)
        outer_labels = {(stmt.table_alias or stmt.table).lower()}
        inner_labels = {(sub.table_alias or sub.table).lower()}

        def cols_of(table: str) -> set:
            # tolerant: hybrid logical names (ev -> ev_OFFLINE/_REALTIME)
            # aren't in _tables; qualified correlation still classifies
            # by label, and a misjudged bare identifier surfaces as an
            # unknown-column error at execution, never a wrong result
            try:
                schema = self.table(table).schema
            except SqlError:
                return set()
            return {f.name for f in schema.fields} if schema else set()

        outer_cols = cols_of(stmt.table)
        inner_cols = cols_of(sub.table)

        def side(ident: str):
            """'inner' | 'outer' for an identifier in the subquery."""
            if "." in ident:
                qual, col = ident.split(".", 1)
                if qual.lower() in inner_labels:
                    return "inner", col
                if qual.lower() in outer_labels:
                    return "outer", col
                raise SqlError(
                    f"unknown qualifier {qual!r} in EXISTS subquery "
                    f"(tables in scope: {sorted(inner_labels)} inner, "
                    f"{sorted(outer_labels)} outer)")
            if ident in inner_cols:
                return "inner", ident
            if ident in outer_cols:
                return "outer", ident
            return "inner", ident   # let execution raise unknown-column

        conjuncts = (list(sub.where.children)
                     if isinstance(sub.where, BoolAnd)
                     else [sub.where] if sub.where is not None else [])
        corr, local = [], []
        for c in conjuncts:
            sides = {side(i)[0] for i in collect_identifiers(c)}
            (corr if "outer" in sides else local).append(c)
        if not corr:
            probe = dataclasses.replace(
                sub, limit=1, ctes=[],
                options={**stmt.options, **sub.options})
            res = self._execute_stmt(probe, time.perf_counter())
            return self._TRUE if res.rows else self._FALSE

        if len(corr) != 1 or sub.joins or sub.group_by or sub.having:
            raise SqlError(
                "correlated EXISTS is supported with exactly one "
                "top-level `inner.col = outer.col` equality and no "
                "joins/GROUP BY/HAVING in the subquery; rewrite the "
                "query as an explicit JOIN instead")
        c = corr[0]
        if not (isinstance(c, Cmp) and c.op == "=="
                and isinstance(c.lhs, Identifier)
                and isinstance(c.rhs, Identifier)):
            raise SqlError(
                "correlated EXISTS predicate must be a plain equality "
                f"between one inner and one outer column, got "
                f"{type(c).__name__}")
        s1, col1 = side(c.lhs.name)
        s2, col2 = side(c.rhs.name)
        if {s1, s2} != {"inner", "outer"}:
            raise SqlError(
                "correlated EXISTS equality must reference exactly one "
                "inner and one outer column")
        inner_col = col1 if s1 == "inner" else col2
        outer_col = col2 if s1 == "inner" else col1

        def strip(expr):
            from ..query.sql import map_expr

            def unqualify(x):
                if isinstance(x, Identifier) and "." in x.name:
                    qual, col = x.name.split(".", 1)
                    if qual.lower() in inner_labels:
                        return Identifier(col)
                return x
            return map_expr(expr, unqualify)

        remaining = [strip(x) for x in local]
        # inner NULLs can never witness the equality; filtering them keeps
        # the materialized IN list clean for the NOT EXISTS (BoolNot) form
        remaining.append(IsNull(Identifier(inner_col), negated=True))
        where = remaining[0] if len(remaining) == 1 \
            else BoolAnd(tuple(remaining))
        sub2 = dataclasses.replace(
            sub, select=[SelectItem(Identifier(inner_col))],
            distinct=True, where=where, limit=None, order_by=[],
            table_alias=None,
            options={**stmt.options, **sub.options})
        return InSubquery(Identifier(outer_col), sub2, negated=False)

    def _resolve_subqueries(self, stmt: SelectStmt) -> SelectStmt:
        if stmt.explain:
            # EXPLAIN must not execute the subquery scan; substitute
            # placeholder shapes so the plan still builds
            def placeholder(e):
                if isinstance(e, InSubquery):
                    return InList(e.expr, (Literal(0),), e.negated)
                if isinstance(e, ScalarSubquery):
                    return Literal(0)
                if isinstance(e, Exists):
                    return self._TRUE
                return e
            if stmt.where is not None:
                stmt.where = map_expr(stmt.where, placeholder)
            if stmt.having is not None:
                stmt.having = map_expr(stmt.having, placeholder)
            return stmt

        def rw(e):
            if isinstance(e, Exists):
                # decorrelate/fold, then resolve the InSubquery it may
                # produce through the same materialization below
                return rw(self._decorrelate_exists(e, stmt))
            if isinstance(e, InSubquery):
                # bounded materialization (VERDICT r3 weak #7; the
                # reference bounds IdSet size the same way): the broker
                # fetches cap+1 rows and ERRORS past the cap instead of
                # silently truncating to a wrong answer
                cap = int(stmt.options.get("inSubqueryLimit", 100_000))
                sub = e.stmt
                # an explicit user LIMIT within the cap is honored as-is
                # (bounded materialization with the documented
                # deterministic-truncation LIMIT contract); anything else
                # — no LIMIT, or a LIMIT above the cap — keeps the cap+1
                # probe + error so the resource guard stays enforceable
                user_limit = sub.limit
                honored = user_limit is not None and user_limit <= cap
                if not honored:
                    sub.limit = cap + 1
                res = self._execute_stmt(sub, time.perf_counter())
                if len(res.columns) != 1:
                    raise SqlError(
                        f"IN subquery must select exactly 1 column, "
                        f"got {len(res.columns)}")
                if not honored and len(res.rows) > cap:
                    over = (f" (its LIMIT {user_limit} exceeds the cap "
                            "and was not applied)"
                            if user_limit is not None else "")
                    raise SqlError(
                        f"IN subquery produced more than {cap} rows"
                        f"{over}; add a LIMIT <= {cap}, narrow it, or "
                        "raise OPTION(inSubqueryLimit=...)")
                vals = tuple(Literal(r[0].item() if hasattr(r[0], "item")
                                     else r[0]) for r in res.rows)
                return InList(e.expr, vals, e.negated)
            if isinstance(e, ScalarSubquery):
                res = self._execute_stmt(e.stmt, time.perf_counter())
                if len(res.rows) != 1 or len(res.rows[0]) != 1:
                    raise SqlError(
                        f"scalar subquery must return 1 row x 1 column, "
                        f"got {len(res.rows)} rows")
                v = res.rows[0][0]
                return Literal(v.item() if hasattr(v, "item") else v)
            return e

        if stmt.where is not None:
            stmt.where = map_expr(stmt.where, rw)
        if stmt.having is not None:
            stmt.having = map_expr(stmt.having, rw)
        return stmt

    def _execute_ctx(self, ctx, stmt, t0: float, deadline: float,
                     query_id: str = "") -> ResultTable:
        dm = self.table(ctx.table)
        segments = dm.acquire_segments()

        # mesh-resident table: one shard_map program + ICI combine replaces
        # the per-segment scatter-gather entirely
        from ..utils.spans import span
        if dm.distributed is not None and ctx.is_aggregation \
                and not stmt.explain:
            with Tracing.phase(ph.DISTRIBUTED_EXECUTE), \
                    span(ph.DISTRIBUTED_EXECUTE):
                partial = dm.distributed.try_execute(ctx)
            if partial is not None:
                result = reduce_partials(ctx, [partial])
                result.num_segments = len(dm.distributed.segments)
                result.num_docs_scanned = sum(
                    s.n_docs for s in dm.distributed.segments)
                result.time_ms = (time.perf_counter() - t0) * 1e3
                return result

        # shared plan + rollup + batched-dispatch loop (engine/serving.py)
        from ..engine.serving import execute_planned, plan_segments
        ex = plan_segments(ctx, segments, use_rollups=not stmt.explain)

        if stmt.explain:
            from ..query.explain import explain_rows
            cols, rows = explain_rows(ctx, ex.real_plans, ex.rollup_segments)
            return ResultTable(cols, rows, num_segments=len(segments))

        # Planning includes XLA compilation on a cold chip (20-40s once,
        # cached thereafter) — exclude it from the query budget, which
        # covers execution + reduce, or every cold-start query would blow
        # the default 10s timeout (ServerQueryExecutorV1Impl's timeout
        # covers execution; Java has no compile phase to exclude).
        plan_elapsed = time.perf_counter() - t0
        deadline += plan_elapsed
        from ..engine.accounting import global_accountant
        global_accountant.set_deadline(query_id, deadline)

        Tracing.count("numSegmentsQueried", len(segments))
        Tracing.count("numSegmentsPruned", ex.pruned)
        Tracing.count("numDocsScanned", ex.docs_scanned)

        from ..engine.accounting import QueryKilledError
        try:
            partials = execute_planned(ex)
        except QueryKilledError as e:
            if e.is_deadline:
                global_metrics.count("broker_query_timeouts")
                raise QueryTimeoutError(str(e)) from None
            raise

        if time.perf_counter() > deadline:
            global_metrics.count("broker_query_timeouts")
            raise QueryTimeoutError(
                f"query timed out (>{int((deadline - t0) * 1e3)}ms)")

        with Tracing.phase(ph.REDUCE), span(ph.REDUCE,
                                          partials=len(partials)):
            result = reduce_partials(ctx, partials)
        result.num_segments = len(segments)
        result.num_segments_pruned = ex.pruned
        result.num_docs_scanned = ex.docs_scanned
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result


class Connection:
    """Client-facing handle (pinot-clients java-client analog)."""

    def __init__(self, broker: Broker):
        self.broker = broker

    def execute(self, sql: str) -> ResultTable:
        return self.broker.query(sql)

    __call__ = execute


def connect(broker: Broker) -> Connection:
    return Connection(broker)
