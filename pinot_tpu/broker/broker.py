"""Broker: SQL in, ResultTable out — compile, route, scatter, reduce.

Reference parity: pinot-broker/.../requesthandler/
BaseSingleStageBrokerRequestHandler.java (compile :256, optimize :492-521,
route :560-577) + SingleConnectionBrokerRequestHandler.java:141-151
(scatter-gather + reduce) + BrokerRequestHandlerDelegate (engine pick) +
query options (QueryOptionsUtils: timeoutMs, trace, skipUpsert) + EXPLAIN.
In-process execution over local TableDataManagers; the HTTP cluster roles
(cluster/broker_node.py) reuse the same reduce over remote partials, and
ICI collectives (parallel/distributed.py) replace the Netty data plane for
mesh-resident tables.
"""
from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional

from ..engine.executor import execute_plan
from ..engine.reduce import ResultTable, reduce_partials
from ..query.context import build_query_context
from ..query.planner import SegmentPlanner, _truthy
from ..query.sql import SqlError, parse_sql
from ..server.data_manager import TableDataManager
from ..utils.metrics import global_metrics
from ..utils.trace import Tracing

DEFAULT_TIMEOUT_MS = 10_000


class QueryTimeoutError(SqlError):
    pass


class Broker:
    def __init__(self):
        self._tables: Dict[str, TableDataManager] = {}

    # -- table registry (ideal-state analog) -------------------------------
    def register_table(self, dm: TableDataManager) -> None:
        self._tables[dm.table_name] = dm

    def table(self, name: str) -> TableDataManager:
        if name not in self._tables:
            raise SqlError(f"table {name!r} not found; "
                           f"have {list(self._tables)}")
        return self._tables[name]

    @property
    def table_names(self) -> List[str]:
        return list(self._tables)

    # -- query path --------------------------------------------------------
    def query(self, sql: str) -> ResultTable:
        global_metrics.count("broker_queries")
        with global_metrics.timer("broker_query"):
            try:
                return self._query(sql)
            except SqlError:
                global_metrics.count("broker_query_exceptions")
                raise

    def _query(self, sql: str) -> ResultTable:
        t0 = time.perf_counter()
        stmt = parse_sql(sql)
        from ..engine.accounting import global_accountant
        query_id = uuid.uuid4().hex[:12]
        timeout_ms = int(stmt.options.get("timeoutMs", DEFAULT_TIMEOUT_MS))
        deadline = t0 + timeout_ms / 1e3
        if stmt.joins:
            # v2 engine (BrokerRequestHandlerDelegate picks the multi-stage
            # handler when the query needs it); registered with the
            # accountant like any query so kills/deadlines reach its leaf
            # scans' sample points
            from ..multistage import execute_multistage
            from ..multistage.executor import explain_multistage
            if stmt.explain:
                return explain_multistage(self, stmt)
            global_accountant.register(query_id, deadline=deadline)
            try:
                return execute_multistage(self, stmt)
            finally:
                global_accountant.unregister(query_id)
        ctx = build_query_context(stmt)
        trace_on = _truthy(ctx.options.get("trace"))
        scope = Tracing.register(query_id, trace_on)
        global_accountant.register(query_id, deadline=deadline)
        try:
            result = self._execute_ctx(ctx, stmt, t0, deadline)
        finally:
            global_accountant.unregister(query_id)
            Tracing.unregister()
        if trace_on:
            result.trace = scope.to_dict()
        return result

    def _execute_ctx(self, ctx, stmt, t0: float, deadline: float
                     ) -> ResultTable:
        dm = self.table(ctx.table)
        segments = dm.acquire_segments()

        # mesh-resident table: one shard_map program + ICI combine replaces
        # the per-segment scatter-gather entirely
        if dm.distributed is not None and ctx.is_aggregation \
                and not stmt.explain:
            with Tracing.phase("distributed_execute"):
                partial = dm.distributed.try_execute(ctx)
            if partial is not None:
                result = reduce_partials(ctx, [partial])
                result.num_segments = len(dm.distributed.segments)
                result.num_docs_scanned = sum(
                    s.n_docs for s in dm.distributed.segments)
                result.time_ms = (time.perf_counter() - t0) * 1e3
                return result

        # shared plan + rollup + batched-dispatch loop (engine/serving.py)
        from ..engine.serving import execute_planned, plan_segments
        ex = plan_segments(ctx, segments, use_rollups=not stmt.explain)

        if stmt.explain:
            from ..query.explain import explain_rows
            cols, rows = explain_rows(ctx, ex.real_plans, ex.rollup_segments)
            return ResultTable(cols, rows, num_segments=len(segments))

        if time.perf_counter() > deadline:
            global_metrics.count("broker_query_timeouts")
            raise QueryTimeoutError(
                f"query timed out during planning "
                f"(>{int((deadline - t0) * 1e3)}ms)")

        Tracing.count("numSegmentsQueried", len(segments))
        Tracing.count("numSegmentsPruned", ex.pruned)
        Tracing.count("numDocsScanned", ex.docs_scanned)

        partials = execute_planned(ex)

        if time.perf_counter() > deadline:
            global_metrics.count("broker_query_timeouts")
            raise QueryTimeoutError(
                f"query timed out (>{int((deadline - t0) * 1e3)}ms)")

        with Tracing.phase("reduce"):
            result = reduce_partials(ctx, partials)
        result.num_segments = len(segments)
        result.num_segments_pruned = ex.pruned
        result.num_docs_scanned = ex.docs_scanned
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result


class Connection:
    """Client-facing handle (pinot-clients java-client analog)."""

    def __init__(self, broker: Broker):
        self.broker = broker

    def execute(self, sql: str) -> ResultTable:
        return self.broker.query(sql)

    __call__ = execute


def connect(broker: Broker) -> Connection:
    return Connection(broker)
