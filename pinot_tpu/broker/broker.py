"""Broker: SQL in, ResultTable out — compile, route, scatter, reduce.

Reference parity: pinot-broker/.../requesthandler/
BaseSingleStageBrokerRequestHandler.java (compile :256, optimize :492-521,
route :560-577) + SingleConnectionBrokerRequestHandler.java:141-151
(scatter-gather + reduce). Round-1 scope: in-process execution over local
TableDataManagers (the Netty data plane of the reference is replaced by
direct calls here and by ICI collectives in parallel/distributed.py; a
multi-host gRPC/DCN dispatch layer arrives with the cluster roles).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ..engine.executor import execute_plan
from ..engine.reduce import ResultTable, reduce_partials
from ..query.context import build_query_context
from ..query.planner import SegmentPlanner
from ..query.sql import SqlError, parse_sql
from ..server.data_manager import TableDataManager


class Broker:
    def __init__(self):
        self._tables: Dict[str, TableDataManager] = {}

    # -- table registry (ideal-state analog) -------------------------------
    def register_table(self, dm: TableDataManager) -> None:
        self._tables[dm.table_name] = dm

    def table(self, name: str) -> TableDataManager:
        if name not in self._tables:
            raise SqlError(f"table {name!r} not found; "
                           f"have {list(self._tables)}")
        return self._tables[name]

    @property
    def table_names(self) -> List[str]:
        return list(self._tables)

    # -- query path --------------------------------------------------------
    def query(self, sql: str) -> ResultTable:
        t0 = time.perf_counter()
        stmt = parse_sql(sql)
        if stmt.joins:
            # v2 engine (BrokerRequestHandlerDelegate picks the multi-stage
            # handler when the query needs it)
            from ..multistage import execute_multistage
            return execute_multistage(self, stmt)
        ctx = build_query_context(stmt)
        dm = self.table(ctx.table)
        segments = dm.acquire_segments()

        # mesh-resident table: one shard_map program + ICI combine replaces
        # the per-segment scatter-gather entirely
        if dm.distributed is not None and ctx.is_aggregation:
            partial = dm.distributed.try_execute(ctx)
            if partial is not None:
                result = reduce_partials(ctx, [partial])
                result.num_segments = len(dm.distributed.segments)
                result.num_docs_scanned = sum(
                    s.n_docs for s in dm.distributed.segments)
                result.time_ms = (time.perf_counter() - t0) * 1e3
                return result

        # star-tree analog: segments with a matching rollup answer from the
        # pre-aggregation (StarTreeUtils swap-in)
        from ..startree.query import try_rollup_execute
        plans = []
        precomputed = {}
        for i, seg in enumerate(segments):
            partial = (try_rollup_execute(ctx, seg)
                       if hasattr(seg, "metadata") else None)
            if partial is not None:
                precomputed[i] = partial
                plans.append(None)
            else:
                plans.append(SegmentPlanner(ctx, seg).plan())
        real_plans = [p for p in plans if p is not None]
        pruned = sum(1 for p in real_plans if p.kind == "pruned")
        docs_scanned = sum(p.segment.n_docs for p in real_plans
                           if p.kind in ("kernel", "host"))
        # one vmapped device dispatch per plan shape (combine-operator analog)
        from ..engine.batch import execute_plans_batched
        executed = iter(execute_plans_batched(real_plans))
        partials = [precomputed[i] if p is None else next(executed)
                    for i, p in enumerate(plans)]

        result = reduce_partials(ctx, partials)
        result.num_segments = len(segments)
        result.num_segments_pruned = pruned
        result.num_docs_scanned = docs_scanned
        result.time_ms = (time.perf_counter() - t0) * 1e3
        return result


class Connection:
    """Client-facing handle (pinot-clients java-client analog)."""

    def __init__(self, broker: Broker):
        self.broker = broker

    def execute(self, sql: str) -> ResultTable:
        return self.broker.query(sql)

    __call__ = execute


def connect(broker: Broker) -> Connection:
    return Connection(broker)
