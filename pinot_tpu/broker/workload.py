"""Per-tenant workload isolation + watermark load shedding (ISSUE 12).

Reference parity: Pinot layers exactly this defense-in-depth —
``HelixExternalViewBasedQueryQuotaManager`` (per-table QPS, broker/
quota.py here), ``PerQueryCPUMemAccountantFactory`` (watcher kills,
engine/accounting.py) and scheduler admission (engine/scheduler.py).
What none of them answer is *what happens at 4x capacity*: a sustained
spike needs tenant isolation (one tenant's burst must not starve the
others), a graceful degradation ladder (shed speculative work before
shedding queries, shed best-effort tenants before paying ones), and a
retry contract that cannot amplify the overload. This module is that
plane, shared by BOTH brokers (broker/broker.py in-process,
cluster/broker_node.py HTTP).

Three pieces:

**WorkloadManager** — tenant registry + budgets. A table's tenant comes
from its TableConfig ``tenant`` field (``DEFAULT_TENANT`` when
unconfigured); each tenant carries a priority tier (``protected`` /
``standard`` / ``besteffort``) and optional budgets: max concurrent
in-flight queries, CPU-ms/s and result-bytes/s token buckets (post-paid:
the accountant's existing ``track_result`` fence feeds actual usage back
through ``observe()`` at unregister time, so a tenant that overdraws its
bucket is shed until the debt refills — usage-shaped isolation without
per-launch metering), and a retries/s budget so client retries during
overload cannot amplify it. Shed queries raise ``OverloadShedError`` —
a 429-shaped ``SqlError`` carrying ``retryAfterMs`` — never a 500.

**OverloadGovernor** — the watermark degradation ladder, driven by
signals the repo already exports (registered as (name, fn, capacity)
pairs — broker in-flight count, scheduler queue depth, accountant RSS
pressure, utils/devmem pool bytes). ``pressure`` = max normalized
signal; watermarks map it to a rung with hysteresis:

==== ======================================================
rung effect
==== ======================================================
0    normal service
1    shed speculative work: hedged re-dispatch off,
     traceRatio sampling off, micro-batch admission window
     widened (fewer, fuller fused launches)
2    shed ``besteffort`` tenants outright and ``standard``
     tenants by a deterministic per-(qid, tenant) draw,
     with a structured 429 + ``retryAfterMs``
3    brownout: ``besteffort``/``standard`` shed entirely;
     every admitted query is clamped to a floor deadline
     and forced to ``allowPartialResults`` semantics
==== ======================================================

**Determinism** (the round-16 stream-keying discipline): given a rung,
the shed decision and ``retryAfterMs`` for a (qid, tenant) are pure
hash draws — same qids shed identically across same-seed runs. The
traffic-replay harness (tools/traffic_replay.py) pins the rung per
replayed qid from the offered-load schedule (``pin_rungs``), so its
whole shed stream is a pure function of (ledger, multiple, seed) and
two same-seed replays produce byte-identical shed streams; live
deployments drive the same ladder from live signals instead.

Every shed/degrade decision is counted in ``global_metrics``
(``overload_shed`` + per-rung/reason/tenant counters), annotated on the
active span, appended to the bounded ``shed_log`` (the chaos-gate
comparison stream), and — on the cluster broker — lands in the
``query_stats`` ledger row so the fleet rollup trends shed rates per
table/tenant.

Default state is inert: no tenants configured + no signals armed + no
pins => rung 0 and unlimited budgets, so the plane costs two dict reads
per query until an operator arms it.
"""
from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..query.sql import SqlError
from ..utils.metrics import global_metrics

DEFAULT_TENANT = "default"

TIER_PROTECTED = "protected"
TIER_STANDARD = "standard"
TIER_BESTEFFORT = "besteffort"
TIERS = (TIER_PROTECTED, TIER_STANDARD, TIER_BESTEFFORT)

# shed order: lower rank sheds (and OOM-kills) first
_TIER_SHED_RANK = {TIER_BESTEFFORT: 0, TIER_STANDARD: 1,
                   TIER_PROTECTED: 2}

# rung-2 partial shed of the standard tier (deterministic per qid draw);
# rung 3 sheds standard entirely — "besteffort then standard"
STANDARD_SHED_P = 0.5

# retryAfterMs = base * rung + deterministic per-(qid, tenant) jitter
RETRY_AFTER_BASE_MS = 100
RETRY_AFTER_SPREAD_MS = 150

# brownout (rung 3): every admitted query's deadline clamps to this
# floor unless the broker was configured tighter
BROWNOUT_DEADLINE_MS = 1_000.0

SHED_LOG_CAP = 8192

# Pinot-common QueryException analogs: 429 is the tenant-shed shape the
# webapp/console render with retryAfterMs; 211 is the scheduler's
# "server out of capacity" rejection (engine/scheduler.py reuses it)
ERR_TOO_MANY_REQUESTS = 429
ERR_SERVER_OUT_OF_CAPACITY = 211


def tier_shed_rank(tier: Optional[str]) -> int:
    """Shed/kill ordering rank (besteffort first, protected last);
    unknown/missing tiers rank with standard."""
    return _TIER_SHED_RANK.get(tier or TIER_STANDARD, 1)


def _unit(key: str) -> float:
    """Deterministic uniform [0, 1) — the utils/faults._unit discipline
    (md5 keeps parity with utils/spans.sample_decision)."""
    h = hashlib.md5(key.encode()).digest()
    return int.from_bytes(h[:8], "big") / 2.0 ** 64


def retry_after_ms(qid: str, tenant: str, rung: int) -> int:
    """Deterministic per-(qid, tenant) retry-after: a rung-scaled base
    plus a hash-spread jitter so a shed wave's retries don't stampede
    back in one synchronized burst — and so same-seed chaos replays see
    identical values."""
    base = RETRY_AFTER_BASE_MS * max(rung, 1)
    jitter = int(_unit(f"retry|{qid}|{tenant}") * RETRY_AFTER_SPREAD_MS)
    return base + jitter


def shed_decision(qid: str, tenant: str, tier: str,
                  rung: int) -> Optional[str]:
    """The PURE rung-shed ladder: -> shed reason or None (admit).

    Pure in (qid, tenant, tier, rung) — no clocks, no counters — which
    is what makes the replay gate's shed stream reproducible: the same
    pinned rung schedule sheds the same qids every run."""
    if rung < 2 or tier == TIER_PROTECTED:
        return None
    if tier == TIER_BESTEFFORT:
        return "tier_besteffort"
    # standard: partial at rung 2 (deterministic draw), full at rung 3+
    if rung >= 3:
        return "tier_standard"
    if _unit(f"shed|{qid}|{tenant}") < STANDARD_SHED_P:
        return "tier_standard"
    return None


class OverloadShedError(SqlError):
    """A load-shed query: the 429-shaped structured rejection. Both
    brokers render it as JSON carrying ``errorCode`` 429 and
    ``retryAfterMs`` — never a 500/stack trace (cluster/http_util.py
    renders any escaping exception with these attrs the same way)."""

    error_code = ERR_TOO_MANY_REQUESTS

    def __init__(self, msg: str, retry_after_ms: int, tenant: str,
                 rung: int, reason: str, tier: str = TIER_STANDARD):
        super().__init__(msg)
        self.retry_after_ms = int(retry_after_ms)
        self.tenant = tenant
        self.rung = int(rung)
        self.reason = reason
        self.tier = tier

    def payload(self) -> Dict[str, Any]:
        """The structured response body (HTTP 429)."""
        return {"error": str(self), "errorCode": self.error_code,
                "retryAfterMs": self.retry_after_ms,
                "tenant": self.tenant, "tier": self.tier,
                "rung": self.rung, "reason": self.reason}


def clamp_brownout(options: Dict[str, Any],
                   default_timeout_ms: int) -> None:
    """Rung-3 brownout effects on a statement's options, shared by both
    brokers so the ladder can never drift between them: clamp the query
    deadline to the floor and force partial-result semantics. Validates
    timeoutMs (a bad value is a 400-class SqlError, never a ValueError
    escaping mid-clamp)."""
    raw = options.get("timeoutMs", default_timeout_ms)
    try:
        cur = int(raw)
    except (TypeError, ValueError):
        raise SqlError(f"invalid timeoutMs value {raw!r}; "
                       "expected an integer of milliseconds") from None
    options["timeoutMs"] = min(cur, int(BROWNOUT_DEADLINE_MS))
    options.setdefault("allowPartialResults", "true")
    global_metrics.count("overload_brownout_clamped")


def leaf_table(stmt: Any) -> Optional[str]:
    """Left-most leaf table of a statement tree — the tenant anchor for
    compound set operations (shared by both brokers)."""
    while hasattr(stmt, "left") and not hasattr(stmt, "table"):
        stmt = stmt.left
    return getattr(stmt, "table", None)


def parse_retry_attempt(options: Dict[str, Any]) -> int:
    """Validate ``OPTION(retryAttempt=N)`` pre-dispatch (the client-side
    retry contract: a client resubmitting a shed query marks the
    attempt so the broker can charge the tenant's retry budget). A bad
    value is a 400-class SqlError, never a ValueError escaping as a
    500."""
    raw = (options or {}).get("retryAttempt")
    if raw is None:
        return 0
    try:
        v = int(raw)
    except (TypeError, ValueError):
        raise SqlError(f"invalid retryAttempt value {raw!r}; "
                       "expected a non-negative integer") from None
    if v < 0:
        raise SqlError(f"invalid retryAttempt value {raw!r}; "
                       "expected a non-negative integer")
    return v


@dataclass
class TenantSpec:
    """One tenant's tier + budgets (None = unlimited)."""
    tier: str = TIER_STANDARD
    max_inflight: Optional[int] = None
    cpu_ms_per_s: Optional[float] = None
    result_bytes_per_s: Optional[float] = None
    retries_per_s: Optional[float] = None


class _PostPaidBucket:
    """Post-paid token bucket: admission only requires a non-negative
    balance; actual usage debits afterwards (and may drive the balance
    negative — the debt then blocks new admissions until it refills).
    This matches how the accountant meters: usage is only known at the
    post-execute ``track_result`` fence, never up front. ``now`` is
    injectable so the replay/tests can drive virtual time."""

    def __init__(self, rate_per_s: float, burst_s: float = 1.0):
        self.rate = float(rate_per_s)
        self.balance = self.rate * burst_s
        self.cap = self.balance
        self._t0: Optional[float] = None

    def _refill(self, now: float) -> None:
        if self._t0 is None:
            self._t0 = now
        self.balance = min(self.cap,
                           self.balance + (now - self._t0) * self.rate)
        self._t0 = now

    def ok(self, now: Optional[float] = None) -> bool:
        self._refill(time.monotonic() if now is None else now)
        return self.balance > 0.0

    def debit(self, amount: float,
              now: Optional[float] = None) -> None:
        self._refill(time.monotonic() if now is None else now)
        self.balance -= max(float(amount), 0.0)

    def retry_after_ms(self) -> int:
        """Time until the debt refills past zero (the budget-shed
        retryAfterMs)."""
        if self.balance > 0 or self.rate <= 0:
            return RETRY_AFTER_BASE_MS
        return int(-self.balance / self.rate * 1e3) + RETRY_AFTER_BASE_MS


@dataclass
class AdmissionTicket:
    """One admitted query: carried from admit() to release()."""
    qid: str
    table: Optional[str]
    tenant: str
    tier: str
    rung: int
    brownout: bool = False
    degraded: bool = False
    # False on the inert fast path: nothing was counted at admit, so
    # release() must not touch inflight state or gauges either
    counted: bool = field(default=True, repr=False)
    released: bool = field(default=False, repr=False)


class WorkloadManager:
    """Tenant registry + budget admission (module docstring). All state
    mutates under one lock; nothing blocking runs inside it."""

    def __init__(self, governor: Optional["OverloadGovernor"] = None):
        self._lock = threading.Lock()
        self._tenants: Dict[str, TenantSpec] = {}
        self._table_tenant: Dict[str, str] = {}
        self._inflight: Dict[str, int] = {}
        self._cpu: Dict[str, _PostPaidBucket] = {}
        self._bytes: Dict[str, _PostPaidBucket] = {}
        self._retries: Dict[str, _PostPaidBucket] = {}
        self.shed_log: List[Tuple[str, str, int, str, int]] = []
        self.governor = governor or OverloadGovernor()

    # -- configuration -----------------------------------------------------
    def set_tenant(self, name: str, tier: str = TIER_STANDARD,
                   max_inflight: Optional[int] = None,
                   cpu_ms_per_s: Optional[float] = None,
                   result_bytes_per_s: Optional[float] = None,
                   retries_per_s: Optional[float] = None) -> TenantSpec:
        if tier not in TIERS:
            raise ValueError(f"unknown tier {tier!r}; have {list(TIERS)}")
        spec = TenantSpec(tier, max_inflight, cpu_ms_per_s,
                          result_bytes_per_s, retries_per_s)
        with self._lock:
            self._tenants[name] = spec
            self._cpu.pop(name, None)
            self._bytes.pop(name, None)
            self._retries.pop(name, None)
            if cpu_ms_per_s:
                self._cpu[name] = _PostPaidBucket(cpu_ms_per_s)
            if result_bytes_per_s:
                self._bytes[name] = _PostPaidBucket(result_bytes_per_s)
            if retries_per_s:
                self._retries[name] = _PostPaidBucket(retries_per_s)
        return spec

    def set_table_tenant(self, table: str,
                         tenant: Optional[str]) -> None:
        with self._lock:
            if tenant:
                self._table_tenant[table] = tenant
            else:
                self._table_tenant.pop(table, None)

    def resolve(self, table: Optional[str]) -> Tuple[str, str]:
        """-> (tenant, tier) for a table; unconfigured tables map to the
        default tenant at standard tier."""
        with self._lock:
            tenant = self._table_tenant.get(table or "", DEFAULT_TENANT)
            spec = self._tenants.get(tenant)
        return tenant, spec.tier if spec else TIER_STANDARD

    def tenant_names(self) -> List[str]:
        with self._lock:
            return sorted(set(self._tenants)
                          | set(self._table_tenant.values()))

    def inflight(self, tenant: Optional[str] = None) -> int:
        with self._lock:
            if tenant is not None:
                return self._inflight.get(tenant, 0)
            return sum(self._inflight.values())

    # -- admission ---------------------------------------------------------
    def admit(self, qid: str, table: Optional[str],
              retry_attempt: int = 0,
              now: Optional[float] = None) -> AdmissionTicket:
        """Admission-or-shed for one user query. Raises
        ``OverloadShedError`` (429-shaped, retryAfterMs) on a shed;
        otherwise returns the ticket the broker must ``release()``.

        Order of checks (cheapest/purest first): rung ladder (pure),
        retry budget (a retry during overload charges it), concurrency
        budget, then the post-paid cpu/bytes buckets."""
        with self._lock:
            inert = not self._tenants and not self._table_tenant
        rung = self.governor.rung_for(qid)
        if inert and rung == 0:
            # the process default: no tenants configured, nothing
            # armed — two lock reads per query, no metrics churn
            return AdmissionTicket(qid, table, DEFAULT_TENANT,
                                   TIER_STANDARD, 0, counted=False)
        tenant, tier = self.resolve(table)
        reason = shed_decision(qid, tenant, tier, rung)
        retry_ms: Optional[int] = None
        if reason is None and retry_attempt > 0 and rung >= 2:
            # the retry amplification guard: during overload a tenant's
            # retries draw a separate budget, so a shed wave's
            # re-submissions cannot multiply the offered load
            with self._lock:
                bucket = self._retries.get(tenant)
                if bucket is not None:
                    if bucket.ok(now):
                        bucket.debit(1.0, now)
                    else:
                        reason = "retry_budget"
                        retry_ms = 2 * retry_after_ms(qid, tenant, rung)
                        global_metrics.count(
                            "overload_retries_suppressed")
        if reason is None:
            with self._lock:
                spec = self._tenants.get(tenant)
                if spec is not None and spec.max_inflight is not None \
                        and self._inflight.get(tenant, 0) \
                        >= spec.max_inflight:
                    reason = "inflight_budget"
                elif not self._cpu.get(tenant,
                                       _ALWAYS_OK).ok(now):
                    reason = "cpu_budget"
                    retry_ms = self._cpu[tenant].retry_after_ms()
                elif not self._bytes.get(tenant,
                                         _ALWAYS_OK).ok(now):
                    reason = "bytes_budget"
                    retry_ms = self._bytes[tenant].retry_after_ms()
                else:
                    self._inflight[tenant] = \
                        self._inflight.get(tenant, 0) + 1
        if reason is not None:
            self._shed(qid, table, tenant, tier, rung, reason,
                       retry_ms)
        ticket = AdmissionTicket(qid, table, tenant, tier, rung,
                                 brownout=rung >= 3,
                                 degraded=rung >= 1)
        global_metrics.count(f"tenant_admitted_{tenant}")
        global_metrics.gauge(f"tenant_inflight_{tenant}",
                             self.inflight(tenant))
        if ticket.degraded:
            from ..utils.spans import annotate
            annotate(overload_rung=rung)
        return ticket

    def _shed(self, qid: str, table: Optional[str], tenant: str,
              tier: str, rung: int, reason: str,
              retry_ms: Optional[int]) -> None:
        if retry_ms is None:
            retry_ms = retry_after_ms(qid, tenant, rung)
        global_metrics.count("overload_shed")
        global_metrics.count(f"overload_shed_rung_{rung}")
        global_metrics.count(f"overload_shed_{reason}")
        global_metrics.count(f"tenant_shed_{tenant}")
        from ..utils.spans import annotate
        annotate(shed=True, shed_rung=rung, shed_reason=reason)
        with self._lock:
            self.shed_log.append((qid, tenant, rung, reason, retry_ms))
            if len(self.shed_log) > SHED_LOG_CAP:
                del self.shed_log[: SHED_LOG_CAP // 2]
        raise OverloadShedError(
            f"query {qid} shed under overload (tenant {tenant!r} tier "
            f"{tier}, rung {rung}, {reason}); retry after "
            f"{retry_ms}ms", retry_ms, tenant, rung, reason, tier)

    def release(self, ticket: Optional[AdmissionTicket],
                cpu_ms: Optional[float] = None,
                result_bytes: Optional[float] = None,
                now: Optional[float] = None) -> None:
        """End of one admitted query: decrement in-flight and debit any
        explicitly-measured usage (the cluster broker's result-size
        estimate; the in-process path debits through ``observe()``
        instead). Idempotent per ticket."""
        if ticket is None or ticket.released:
            return
        ticket.released = True
        if not ticket.counted:
            return  # inert fast path: nothing to undo
        with self._lock:
            n = self._inflight.get(ticket.tenant, 0)
            if n > 1:
                self._inflight[ticket.tenant] = n - 1
            else:
                self._inflight.pop(ticket.tenant, None)
            if cpu_ms and ticket.tenant in self._cpu:
                self._cpu[ticket.tenant].debit(cpu_ms, now)
            if result_bytes and ticket.tenant in self._bytes:
                self._bytes[ticket.tenant].debit(result_bytes, now)
        global_metrics.gauge(f"tenant_inflight_{ticket.tenant}",
                             self.inflight(ticket.tenant))

    def observe(self, usage: Any) -> None:
        """The accountant's unregister hook (engine/accounting.py): a
        QueryUsage carrying a tenant debits its actual CPU-ms and
        tracked result bytes — the post-paid feed off the existing
        ``track_result`` fence, no extra metering on the hot path."""
        tenant = getattr(usage, "tenant", None)
        if not tenant:
            return
        with self._lock:
            if tenant in self._cpu:
                self._cpu[tenant].debit(usage.cpu_s * 1e3)
            if tenant in self._bytes:
                self._bytes[tenant].debit(usage.mem_bytes)

    def clear_shed_log(self) -> None:
        """Reset the comparison stream (the replay gate clears it at
        the spike boundary so only spike decisions are compared)."""
        with self._lock:
            self.shed_log.clear()

    def shed_stream(self) -> List[Tuple[str, str, int, str, int]]:
        """Order-independent copy of the shed log (qid, tenant, rung,
        reason, retryAfterMs) — the chaos-gate comparison stream, the
        ``FaultPlan.fired_summary`` discipline."""
        with self._lock:
            return sorted(self.shed_log)

    def reset(self) -> None:
        """Back to the inert default (tests + harness teardown)."""
        with self._lock:
            self._tenants.clear()
            self._table_tenant.clear()
            self._inflight.clear()
            self._cpu.clear()
            self._bytes.clear()
            self._retries.clear()
            self.shed_log.clear()
        self.governor.reset()

    def health(self) -> Dict[str, Any]:
        """The per-tenant block for /metrics consoles."""
        with self._lock:
            tenants = sorted(set(self._tenants)
                             | set(self._inflight))
            out = {t: {
                "tier": (self._tenants.get(t) or TenantSpec()).tier,
                "inflight": self._inflight.get(t, 0),
            } for t in tenants}
        return out


class _AlwaysOk:
    """Null bucket for tenants without a budget."""

    @staticmethod
    def ok(now: Optional[float] = None) -> bool:
        return True


_ALWAYS_OK = _AlwaysOk()


class OverloadGovernor:
    """Watermark ladder over registered pressure signals (module
    docstring). Signals are (fn, capacity) pairs: pressure is the MAX
    of fn()/capacity over all signals — overload is whichever resource
    saturates first, never an average that hides it."""

    #: pressure thresholds per rung (>= threshold enters the rung)
    WATERMARKS: Dict[int, float] = {1: 0.5, 2: 0.75, 3: 0.9}
    HYSTERESIS = 0.05
    # live pressure is re-sampled at most this often (signal fns may
    # read /proc); pins bypass the cache entirely
    POLL_S = 0.05

    def __init__(self):
        self._lock = threading.Lock()
        self._signals: Dict[str, Tuple[Callable[[], float], float]] = {}
        self._pins: Optional[Dict[str, int]] = None
        self._pin_default: int = 0
        self._rung = 0
        self._pressure = 0.0
        self._t_sample = 0.0

    # -- configuration -----------------------------------------------------
    def add_signal(self, name: str, fn: Callable[[], float],
                   capacity: float) -> None:
        """Register a pressure source: fn() in the same unit as
        ``capacity`` (e.g. in-flight queries vs a capacity of 32)."""
        if capacity <= 0:
            raise ValueError(f"signal {name!r} needs capacity > 0")
        with self._lock:
            self._signals[name] = (fn, float(capacity))

    def remove_signal(self, name: str) -> None:
        with self._lock:
            self._signals.pop(name, None)
            disarmed = not self._signals and self._pins is None
        if disarmed:
            # back to inert: the cached rung must not stick elevated
            # forever once nothing can ever lower it again
            self._apply(0)

    def pin_rungs(self, by_qid: Dict[str, int],
                  default: int = 0) -> None:
        """Replay-harness mode: the rung per qid is a precomputed pure
        schedule (tools/traffic_replay.py derives it from the offered-
        load curve through ``rung_for_pressure`` — the same ladder live
        signals drive), so shed streams are reproducible. ``default``
        applies to qids outside the map."""
        with self._lock:
            self._pins = dict(by_qid)
            self._pin_default = int(default)
        self._apply(max([default] + list(by_qid.values()))
                    if by_qid or default else 0)

    def unpin(self) -> None:
        with self._lock:
            self._pins = None
            self._pin_default = 0
        self._apply(0)

    @classmethod
    def rung_for_pressure(cls, pressure: float) -> int:
        """The PURE watermark map (no hysteresis, no state) — shared by
        the live path and the replay planner's schedule computation."""
        rung = 0
        for r, w in sorted(cls.WATERMARKS.items()):
            if pressure >= w:
                rung = r
        return rung

    # -- live evaluation ---------------------------------------------------
    def pressure(self) -> float:
        with self._lock:
            signals = list(self._signals.values())
        if not signals:
            return 0.0
        p = 0.0
        for fn, cap in signals:
            try:
                p = max(p, float(fn()) / cap)
            except Exception:
                continue  # a broken signal must never fail admission
        return p

    def rung(self, now: Optional[float] = None) -> int:
        """Current rung from live signals, with hysteresis (a rung only
        drops once pressure falls HYSTERESIS below its watermark — no
        flapping at the boundary). ``now`` injects the poll clock for
        replay/tests; pinned and inert governors return before any
        clock read, so replay-mode admission never touches wall time.
        """
        with self._lock:
            pinned = self._pins is not None
            inert = not self._signals
            current = self._rung
            t_sample = self._t_sample
        if pinned or inert:
            # inert (nothing armed) is the process default: zero work,
            # zero metric churn, zero clock reads on every
            # admission/hedge check
            return current
        t = now if now is not None else time.monotonic()
        if (t - t_sample) < self.POLL_S:
            return current
        p = self.pressure()
        rung = self.rung_for_pressure(p)
        if rung < current:
            # hysteresis: stay on the higher rung until clearly below it
            w = self.WATERMARKS.get(current, 1.0)
            if p >= w - self.HYSTERESIS:
                rung = current
        with self._lock:
            self._pressure = p
            self._t_sample = t if now is not None else time.monotonic()
        if rung != current:
            self._apply(rung)
        global_metrics.gauge("overload_pressure", round(p, 4))
        return rung

    def rung_for(self, qid: str, now: Optional[float] = None) -> int:
        """The admission rung for one query: the pinned schedule when
        one is installed (replay), else the live rung."""
        with self._lock:
            if self._pins is not None:
                return self._pins.get(qid, self._pin_default)
        return self.rung(now)

    def _apply(self, rung: int) -> None:
        """Rung transition side effects: the speculative-work ladder
        (rung >= 1 widens the micro-batch admission window so fused
        launches get fuller while hedging/tracing pause — the brokers
        consult ``rung()`` for those directly)."""
        with self._lock:
            prev, self._rung = self._rung, rung
        if prev == rung:
            return
        global_metrics.count(f"overload_rung_enter_{rung}")
        global_metrics.gauge("overload_rung", rung)
        try:
            from ..engine.ragged import global_batcher
            global_batcher.window_scale = 4.0 if rung >= 1 else 1.0
        except Exception:
            pass  # stripped installs without the engine

    # -- degradation queries (brokers consult these) -----------------------
    def shed_speculative(self) -> bool:
        """rung >= 1: hedging + traceRatio sampling pause."""
        return self.rung() >= 1

    def brownout_deadline_ms(self) -> Optional[float]:
        """rung >= 3: the floor deadline every admitted query clamps
        to (None below rung 3)."""
        return BROWNOUT_DEADLINE_MS if self.rung() >= 3 else None

    def reset(self) -> None:
        with self._lock:
            self._signals.clear()
            self._pins = None
            self._pin_default = 0
            self._pressure = 0.0
            self._t_sample = 0.0
        self._apply(0)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"rung": self._rung,
                    "pressure": round(self._pressure, 4),
                    "pinned": self._pins is not None,
                    "signals": sorted(self._signals)}


def arm_default_signals(workload: "WorkloadManager",
                        inflight_capacity: int = 64,
                        rss_limit_bytes: Optional[int] = None,
                        devmem_budget_bytes: Optional[int] = None,
                        queue_depth_fn: Optional[Callable[[], float]]
                        = None,
                        queue_capacity: int = 64) -> None:
    """Wire the repo's existing signals into a governor: broker
    in-flight count, accountant RSS pressure, utils/devmem pool bytes,
    and (when provided) a scheduler/batch queue-depth callable. Called
    by operators/harnesses that want live overload protection —
    NOT armed by default (the ladder stays inert until configured)."""
    gov = workload.governor
    gov.add_signal("inflight", workload.inflight,
                   float(inflight_capacity))
    if rss_limit_bytes is None:
        from ..engine.accounting import system_memory_bytes
        rss_limit_bytes = int(system_memory_bytes() * 0.9) or None
    if rss_limit_bytes:
        from ..engine.accounting import process_rss_bytes
        gov.add_signal("rss", process_rss_bytes, float(rss_limit_bytes))
    if devmem_budget_bytes:
        from ..utils.devmem import global_device_memory

        def _dev_bytes() -> float:
            snap = global_device_memory.snapshot()
            return float((snap.get("total") or {}).get("bytes", 0))
        gov.add_signal("devmem", _dev_bytes, float(devmem_budget_bytes))
    if queue_depth_fn is not None:
        gov.add_signal("queue", queue_depth_fn, float(queue_capacity))


# process-global instances, the global_accountant/global_batcher idiom:
# in-process clusters run several broker roles in one interpreter and
# tenant budgets must be enforced once per process, not per role
global_governor = OverloadGovernor()
global_workload = WorkloadManager(global_governor)
