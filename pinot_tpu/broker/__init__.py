from .broker import (Broker, Connection, QueryTimeoutError,  # noqa: F401
                     connect)
