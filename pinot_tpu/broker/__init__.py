from .broker import Broker, Connection, connect  # noqa: F401
