"""Stream ingestion SPI: partitioned consumption with integer offsets.

Reference parity: pinot-spi/.../spi/stream/{StreamConsumerFactory.java,
PartitionGroupConsumer.java, MessageBatch.java, StreamPartitionMsgOffset
.java, StreamConfig.java} (33 files). The TPU-native SPI keeps the same
shape at Python scale: a factory creates per-partition consumers; a
consumer fetches MessageBatch(rows, next_offset) from a start offset;
offsets are opaque-but-ordered ints persisted in the segment checkpoint
state (the ZK segment-metadata analog, manager.py).

InMemoryStream is the FakeStreamConsumerFactory analog (pinot-core test
fixture pattern, SURVEY.md section 4.6) and doubles as the bridge for any
in-process producer. Kafka/Kinesis-shaped plugins implement the same two
classes against their client libraries.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence


class OffsetOutOfRange(Exception):
    """A consumer's start offset no longer exists on the stream (log
    truncation/retention, shard reshard, consumer-group rebalance):
    retrying the same fetch can never succeed. Consumers raise this (or
    a subclass) instead of their generic transport error so the realtime
    manager can snap the partition back to its durable checkpoint
    (manager._rebalance_reset) rather than retry forever."""


def consume_faults(key: str) -> None:
    """The one named ingest-read fault hook (``stream.error``): every
    consumer's fetch() passes through here before touching its
    transport, so a seeded plan can fail kafka/kinesis/pulsar/in-memory
    reads identically. Zero-cost ``is None`` check when no plan is
    installed (utils/faults.py contract)."""
    from ..utils import faults
    if faults.active():
        faults.fault_point("stream.error", key)


@dataclass
class StreamConfig:
    topic: str
    num_partitions: int = 1
    # segment sealing thresholds (realtime.segment.flush.threshold.* analog)
    flush_threshold_rows: int = 100_000
    flush_threshold_seconds: float = 3600.0
    # bounded retry-with-backoff around consumer reads (the manager's
    # recovery muscle for stream.error-class transport failures)
    fetch_retries: int = 3
    fetch_backoff_s: float = 0.02
    consumer_factory: Optional["StreamConsumerFactory"] = None
    # config-named factory (stream.<type>.consumer.factory.class.name
    # analog): resolved via the plugin loader (spi/plugin.py) when no
    # factory instance was injected; args pass to its constructor
    consumer_factory_class: Optional[str] = None
    consumer_factory_args: Dict[str, Any] = field(default_factory=dict)

    def make_consumer_factory(self) -> "StreamConsumerFactory":
        if self.consumer_factory is not None:
            return self.consumer_factory
        if self.consumer_factory_class is None:
            raise ValueError("StreamConfig needs consumer_factory or "
                             "consumer_factory_class")
        from ..spi.plugin import create_instance
        self.consumer_factory = create_instance(
            self.consumer_factory_class, **self.consumer_factory_args)
        return self.consumer_factory


@dataclass
class MessageBatch:
    rows: List[Mapping[str, Any]]
    next_offset: int
    # per-row stream offsets for NON-DENSE streams (Kinesis sequence
    # numbers have gaps): row_offsets[i] is the offset of rows[i], and
    # the offset "after" it is row_offsets[i] + 1. None = dense stream
    # (offset arithmetic is checkpoint + row count).
    row_offsets: Optional[List[int]] = None

    @property
    def message_count(self) -> int:
        return len(self.rows)


class PartitionGroupConsumer:
    """One partition's consumer (PartitionGroupConsumer.java)."""

    def fetch(self, start_offset: int, max_messages: int) -> MessageBatch:
        raise NotImplementedError

    def latest_offset(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass


class StreamConsumerFactory:
    """Creates per-partition consumers (StreamConsumerFactory.java)."""

    def num_partitions(self) -> int:
        raise NotImplementedError

    def create_consumer(self, partition: int) -> PartitionGroupConsumer:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# in-memory stream (FakeStream analog + in-process producer bridge)
# ---------------------------------------------------------------------------

class _Partition:
    def __init__(self):
        self.rows: List[Mapping[str, Any]] = []
        self.lock = threading.Lock()


class InMemoryStream(StreamConsumerFactory):
    def __init__(self, num_partitions: int = 1,
                 partitioner: Optional[Callable[[Mapping[str, Any]], int]]
                 = None, name: str = "mem"):
        """``name`` scopes the stream.error fault site key
        (``<name>/<partition>``) — give distinct streams distinct names
        when several consume concurrently in one process, or they share
        one per-key decision stream (faults.py purity contract)."""
        self._partitions = [_Partition() for _ in range(num_partitions)]
        self._partitioner = partitioner
        self.name = name

    def num_partitions(self) -> int:
        return len(self._partitions)

    def produce(self, row: Mapping[str, Any],
                partition: Optional[int] = None) -> int:
        if partition is None:
            if self._partitioner is not None:
                partition = self._partitioner(row) % len(self._partitions)
            else:
                partition = 0
        p = self._partitions[partition]
        with p.lock:
            p.rows.append(dict(row))
            return len(p.rows) - 1

    def produce_many(self, rows: Sequence[Mapping[str, Any]],
                     partition: Optional[int] = None) -> None:
        for r in rows:
            self.produce(r, partition)

    def create_consumer(self, partition: int) -> "_InMemoryConsumer":
        return _InMemoryConsumer(self._partitions[partition], partition,
                                 self.name)


class _InMemoryConsumer(PartitionGroupConsumer):
    def __init__(self, partition: _Partition, index: int = 0,
                 name: str = "mem"):
        self._p = partition
        self._key = f"{name}/{index}"

    def fetch(self, start_offset: int, max_messages: int) -> MessageBatch:
        consume_faults(self._key)
        with self._p.lock:
            rows = self._p.rows[start_offset: start_offset + max_messages]
            return MessageBatch(list(rows), start_offset + len(rows))

    def latest_offset(self) -> int:
        with self._p.lock:
            return len(self._p.rows)
