"""Kafka wire-protocol stream plugin: a real-protocol consumer client +
an in-process fake broker speaking the same bytes.

Round-5 (VERDICT r4 missing #2 / next-step #5): the wirestream module
plays the Kafka *role* over a private protocol; this module speaks the
actual Kafka protocol so the consumer could point at a real cluster.
Reference analog: pinot-plugins/pinot-stream-ingestion/pinot-kafka-2.0/
.../KafkaPartitionLevelConsumer.java:42 (consumer),
KafkaConsumerFactory / KafkaStreamMetadataProvider (metadata + offsets).

Implemented (enough of) the protocol, from the public Kafka protocol
spec, all from scratch:

- primitives: big-endian INT8/16/32/64, STRING (i16 len), NULLABLE
  bytes/arrays (len -1), ARRAY (i32 count), zigzag varint/varlong
- request header v1 (api_key, api_version, correlation_id, client_id),
  response header v0 (correlation_id)
- ApiVersions v0 (key 18), Metadata v1 (key 3), ListOffsets v1 (key 2,
  timestamp -1 latest / -2 earliest), Fetch v4 (key 1), Produce v3
  (key 0)
- RecordBatch magic v2: batch header (base offset, leader epoch, magic,
  CRC32C over attributes..end, attributes, lastOffsetDelta, timestamps,
  producer id/epoch/sequence, record count) + per-record zigzag-varint
  records (attributes, timestampDelta, offsetDelta, key, value, headers)
- CRC32C (Castagnoli, reflected poly 0x82F63B78) — table-based, checked
  on every consumed batch

`FakeKafkaBroker` is the embedded-Kafka test fixture analog (reference:
pinot-integration-tests embedded kafka): a TCP server holding
partitioned logs, decoding Produce record batches and encoding Fetch
record batches with the real wire format. `KafkaStream` /
`KafkaPartitionConsumer` are the stream-SPI clients; messages are JSON
values (the decoder contract shared with wirestream)."""
from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple

from .stream import MessageBatch, OffsetOutOfRange, \
    PartitionGroupConsumer, StreamConsumerFactory, consume_faults

API_PRODUCE, API_FETCH, API_LIST_OFFSETS, API_METADATA = 0, 1, 2, 3
API_VERSIONS = 18
ERR_NONE = 0
ERR_OFFSET_OUT_OF_RANGE = 1
ERR_UNKNOWN_TOPIC_OR_PARTITION = 3
ERR_CORRUPT_MESSAGE = 2

_MAX_FRAME = 64 << 20


class KafkaError(Exception):
    """Protocol-level error (broker error code or malformed bytes)."""


class KafkaOffsetOutOfRange(KafkaError, OffsetOutOfRange):
    """ERR_OFFSET_OUT_OF_RANGE from the broker: the requested offset is
    gone (log truncation/retention). Subclasses the stream SPI's
    OffsetOutOfRange so the realtime manager snaps the partition back to
    its checkpoint instead of retrying a fetch that can never succeed."""


# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) — required by RecordBatch v2; zlib.crc32 is IEEE
# ---------------------------------------------------------------------------

def _make_crc32c_table() -> List[int]:
    poly = 0x82F63B78
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_CRC32C_TABLE = _make_crc32c_table()


def crc32c(data: bytes) -> int:
    c = 0xFFFFFFFF
    for b in data:
        c = _CRC32C_TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


# ---------------------------------------------------------------------------
# primitive codecs
# ---------------------------------------------------------------------------

class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise KafkaError("truncated message")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def i8(self) -> int:
        return struct.unpack(">b", self.take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.take(8))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self.take(4))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        return None if n < 0 else self.take(n).decode()

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        return None if n < 0 else self.take(n)

    def varint(self) -> int:
        # zigzag LEB128
        shift = 0
        result = 0
        while True:
            b = self.take(1)[0]
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 63:
                raise KafkaError("varint too long")
        return (result >> 1) ^ -(result & 1)


def _i8(v: int) -> bytes:
    return struct.pack(">b", v)


def _i16(v: int) -> bytes:
    return struct.pack(">h", v)


def _i32(v: int) -> bytes:
    return struct.pack(">i", v)


def _i64(v: int) -> bytes:
    return struct.pack(">q", v)


def _u32(v: int) -> bytes:
    return struct.pack(">I", v)


def _string(s: Optional[str]) -> bytes:
    if s is None:
        return _i16(-1)
    b = s.encode()
    return _i16(len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return _i32(-1)
    return _i32(len(b)) + b


def _varint(v: int) -> bytes:
    u = (v << 1) ^ (v >> 63)  # zigzag (python ints: arithmetic shift ok)
    u &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


# ---------------------------------------------------------------------------
# RecordBatch v2 encode/decode
# ---------------------------------------------------------------------------

def encode_record_batch(base_offset: int,
                        records: List[Tuple[Optional[bytes], bytes]],
                        base_timestamp: int) -> bytes:
    """records: list of (key, value). One batch, magic 2, no compression."""
    recs = []
    for i, (key, value) in enumerate(records):
        body = (_i8(0)                       # record attributes
                + _varint(0)                 # timestampDelta
                + _varint(i)                 # offsetDelta
                + (_varint(-1) if key is None
                   else _varint(len(key)) + key)
                + _varint(len(value)) + value
                + _varint(0))                # headers count
        recs.append(_varint(len(body)) + body)
    records_bytes = b"".join(recs)
    n = len(records)
    after_crc = (_i16(0)                     # batch attributes (no codec)
                 + _i32(n - 1)               # lastOffsetDelta
                 + _i64(base_timestamp)      # baseTimestamp
                 + _i64(base_timestamp)      # maxTimestamp
                 + _i64(-1)                  # producerId
                 + _i16(-1)                  # producerEpoch
                 + _i32(-1)                  # baseSequence
                 + _i32(n)                   # record count
                 + records_bytes)
    body = (_i32(0)                          # partitionLeaderEpoch
            + _i8(2)                         # magic
            + _u32(crc32c(after_crc))
            + after_crc)
    return _i64(base_offset) + _i32(len(body)) + body


def decode_record_batches(data: bytes
                          ) -> List[Tuple[int, Optional[bytes], bytes]]:
    """-> [(offset, key, value)] across all batches in the record set.
    Verifies magic and CRC32C; raises KafkaError on corruption."""
    out: List[Tuple[int, Optional[bytes], bytes]] = []
    r = _Reader(data)
    while r.pos + 12 <= len(r.data):
        base_offset = r.i64()
        batch_len = r.i32()
        if r.pos + batch_len > len(r.data):
            break  # partial trailing batch (Kafka permits; client retries)
        body = _Reader(r.take(batch_len))
        body.i32()                           # partitionLeaderEpoch
        magic = body.i8()
        if magic != 2:
            raise KafkaError(f"unsupported record batch magic {magic}")
        crc = body.u32()
        rest = body.data[body.pos:]
        if crc32c(rest) != crc:
            raise KafkaError("record batch CRC32C mismatch")
        body.i16()                           # attributes
        body.i32()                           # lastOffsetDelta
        body.i64()                           # baseTimestamp
        body.i64()                           # maxTimestamp
        body.i64()                           # producerId
        body.i16()                           # producerEpoch
        body.i32()                           # baseSequence
        count = body.i32()
        for _ in range(count):
            ln = body.varint()
            rec = _Reader(body.take(ln))
            rec.i8()                         # record attributes
            rec.varint()                     # timestampDelta
            off_delta = rec.varint()
            klen = rec.varint()
            key = None if klen < 0 else rec.take(klen)
            vlen = rec.varint()
            value = b"" if vlen < 0 else rec.take(vlen)
            hdrs = rec.varint()
            for _h in range(hdrs):
                hk = rec.varint()
                rec.take(max(hk, 0))
                hv = rec.varint()
                rec.take(max(hv, 0))
            out.append((base_offset + off_delta, key, value))
    return out


# ---------------------------------------------------------------------------
# fake broker (embedded-kafka test fixture analog)
# ---------------------------------------------------------------------------

class _PartLog:
    def __init__(self):
        self.records: List[Tuple[Optional[bytes], bytes, int]] = []
        self.lock = threading.Lock()


class _KafkaHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        broker: "FakeKafkaBroker" = self.server.broker  # type: ignore
        try:
            while True:
                raw = _recv_exact(self.request, 4)
                (size,) = struct.unpack(">i", raw)
                if not 0 < size <= _MAX_FRAME:
                    return
                req = _Reader(_recv_exact(self.request, size))
                api_key = req.i16()
                api_version = req.i16()
                corr = req.i32()
                req.string()                 # client_id
                body = broker._dispatch(api_key, api_version, req)
                resp = _i32(corr) + body
                self.request.sendall(_i32(len(resp)) + resp)
        except (ConnectionError, OSError, KafkaError):
            return


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


class FakeKafkaBroker:
    """Single-node broker speaking the Kafka wire protocol over TCP.

    Supports ApiVersions v0, Metadata v0-v1, ListOffsets v0-v1, Fetch
    v4, Produce v3 — the set the consumer + producer clients use. Logs
    are in-memory (durability is wirestream's job; this fixture's job is
    the PROTOCOL boundary)."""

    def __init__(self, topics: Dict[str, int], port: int = 0):
        # topics: name -> partition count
        self.topics = {t: [_PartLog() for _ in range(n)]
                       for t, n in topics.items()}

        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True

        self._server = _Srv(("127.0.0.1", port), _KafkaHandler)
        self._server.daemon_threads = True
        self._server.broker = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    # direct in-process append (tests that don't exercise Produce)
    def append(self, topic: str, partition: int,
               rows: List[Mapping[str, Any]]) -> int:
        log = self.topics[topic][partition]
        ts = int(time.time() * 1000)
        with log.lock:
            base = len(log.records)
            log.records.extend(
                (None, json.dumps(dict(r)).encode(), ts) for r in rows)
            return base

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- dispatch ---------------------------------------------------------

    def _dispatch(self, api_key: int, version: int, req: _Reader) -> bytes:
        if api_key == API_VERSIONS:
            supported = [(API_PRODUCE, 0, 3), (API_FETCH, 0, 4),
                         (API_LIST_OFFSETS, 0, 1), (API_METADATA, 0, 1),
                         (API_VERSIONS, 0, 0)]
            return (_i16(ERR_NONE) + _i32(len(supported))
                    + b"".join(_i16(k) + _i16(lo) + _i16(hi)
                               for k, lo, hi in supported))
        if api_key == API_METADATA:
            return self._metadata(version, req)
        if api_key == API_LIST_OFFSETS:
            return self._list_offsets(version, req)
        if api_key == API_FETCH:
            return self._fetch(version, req)
        if api_key == API_PRODUCE:
            return self._produce(version, req)
        raise KafkaError(f"unsupported api key {api_key}")

    def _metadata(self, version: int, req: _Reader) -> bytes:
        n = req.i32()
        names = (list(self.topics) if n < 0
                 else [req.string() for _ in range(n)])
        out = [_i32(1),                      # brokers
               _i32(0), _string("127.0.0.1"), _i32(self.port)]
        if version >= 1:
            out.append(_string(None))        # rack
            out.append(_i32(0))              # controller_id
        out.append(_i32(len(names)))
        for t in names:
            logs = self.topics.get(t)
            err = ERR_NONE if logs is not None \
                else ERR_UNKNOWN_TOPIC_OR_PARTITION
            out.append(_i16(err) + _string(t))
            if version >= 1:
                out.append(_i8(0))           # is_internal
            parts = logs or []
            out.append(_i32(len(parts)))
            for p in range(len(parts)):
                out.append(_i16(ERR_NONE) + _i32(p) + _i32(0)
                           + _i32(1) + _i32(0)      # replicas [0]
                           + _i32(1) + _i32(0))     # isr [0]
        return b"".join(out)

    def _list_offsets(self, version: int, req: _Reader) -> bytes:
        req.i32()                            # replica_id
        n_topics = req.i32()
        out = [_i32(n_topics)]
        for _ in range(n_topics):
            topic = req.string()
            n_parts = req.i32()
            out.append(_string(topic) + _i32(n_parts))
            for _p in range(n_parts):
                part = req.i32()
                ts = req.i64()
                if version == 0:
                    req.i32()                # max_num_offsets
                logs = self.topics.get(topic)
                if logs is None or not 0 <= part < len(logs):
                    err, off = ERR_UNKNOWN_TOPIC_OR_PARTITION, -1
                else:
                    with logs[part].lock:
                        end = len(logs[part].records)
                    off = 0 if ts == -2 else end
                    err = ERR_NONE
                if version == 0:
                    out.append(_i32(part) + _i16(err) + _i32(1)
                               + _i64(off))
                else:
                    out.append(_i32(part) + _i16(err) + _i64(-1)
                               + _i64(off))
        return b"".join(out)

    def _fetch(self, version: int, req: _Reader) -> bytes:
        req.i32()                            # replica_id
        req.i32()                            # max_wait_ms
        req.i32()                            # min_bytes
        if version >= 3:
            req.i32()                        # max_bytes
        if version >= 4:
            req.i8()                         # isolation_level
        n_topics = req.i32()
        out = [_i32(0)] if version >= 1 else []   # throttle_time
        out.append(_i32(n_topics))
        for _ in range(n_topics):
            topic = req.string()
            n_parts = req.i32()
            out.append(_string(topic) + _i32(n_parts))
            for _p in range(n_parts):
                part = req.i32()
                offset = req.i64()
                max_bytes = req.i32()
                logs = self.topics.get(topic)
                if logs is None or not 0 <= part < len(logs):
                    out.append(_i32(part)
                               + _i16(ERR_UNKNOWN_TOPIC_OR_PARTITION)
                               + _i64(-1) + _i64(-1) + _i32(-1)
                               + _bytes(b""))
                    continue
                log = logs[part]
                with log.lock:
                    end = len(log.records)
                    if offset < 0 or offset > end:
                        out.append(_i32(part)
                                   + _i16(ERR_OFFSET_OUT_OF_RANGE)
                                   + _i64(end) + _i64(end) + _i32(-1)
                                   + _bytes(b""))
                        continue
                    # bound the batch by max_bytes (approx: value sizes)
                    take = []
                    size = 0
                    for rec in log.records[offset:]:
                        size += len(rec[1]) + 32
                        if take and size > max(max_bytes, 1):
                            break
                        take.append(rec)
                if take:
                    batch = encode_record_batch(
                        offset, [(k, v) for k, v, _t in take], take[0][2])
                else:
                    batch = b""
                out.append(_i32(part) + _i16(ERR_NONE) + _i64(end)
                           + _i64(end) + _i32(-1)   # no aborted txns
                           + _bytes(batch))
        return b"".join(out)

    def _produce(self, version: int, req: _Reader) -> bytes:
        if version >= 3:
            req.string()                     # transactional_id
        req.i16()                            # acks
        req.i32()                            # timeout
        n_topics = req.i32()
        out_topics = []
        for _ in range(n_topics):
            topic = req.string()
            n_parts = req.i32()
            parts_out = []
            for _p in range(n_parts):
                part = req.i32()
                record_set = req.bytes_() or b""
                logs = self.topics.get(topic)
                if logs is None or not 0 <= part < len(logs):
                    parts_out.append(
                        _i32(part) + _i16(ERR_UNKNOWN_TOPIC_OR_PARTITION)
                        + _i64(-1) + _i64(-1))
                    continue
                try:
                    recs = decode_record_batches(record_set)
                except KafkaError:
                    parts_out.append(_i32(part) + _i16(ERR_CORRUPT_MESSAGE)
                                     + _i64(-1) + _i64(-1))
                    continue
                log = logs[part]
                ts = int(time.time() * 1000)
                with log.lock:
                    base = len(log.records)
                    log.records.extend((k, v, ts) for _o, k, v in recs)
                parts_out.append(_i32(part) + _i16(ERR_NONE) + _i64(base)
                                 + _i64(ts))
            out_topics.append(_string(topic) + _i32(n_parts)
                              + b"".join(parts_out))
        return (_i32(n_topics) + b"".join(out_topics)
                + _i32(0))                   # throttle_time (v1+)


# ---------------------------------------------------------------------------
# client connection
# ---------------------------------------------------------------------------

class _KafkaConn:
    def __init__(self, host: str, port: int, timeout: float,
                 client_id: str = "pinot-tpu"):
        self.host, self.port, self.timeout = host, port, timeout
        self.client_id = client_id
        self.sock: Optional[socket.socket] = None
        self._corr = 0
        self.api_versions: Optional[Dict[int, Tuple[int, int]]] = None

    def _ensure(self) -> socket.socket:
        if self.sock is None:
            self.sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout)
        return self.sock

    def call(self, api_key: int, version: int, body: bytes,
             retries: int = 1) -> _Reader:
        for attempt in range(retries + 1):
            try:
                sock = self._ensure()
                self._corr += 1
                header = (_i16(api_key) + _i16(version) + _i32(self._corr)
                          + _string(self.client_id))
                msg = header + body
                sock.sendall(_i32(len(msg)) + msg)
                (size,) = struct.unpack(">i", _recv_exact(sock, 4))
                if not 0 < size <= _MAX_FRAME:
                    raise KafkaError(f"bad response size {size}")
                resp = _Reader(_recv_exact(sock, size))
                corr = resp.i32()
                if corr != self._corr:
                    raise KafkaError(
                        f"correlation id mismatch {corr} != {self._corr}")
                return resp
            except (ConnectionError, OSError, socket.timeout):
                self.close()
                if attempt == retries:
                    raise
        raise AssertionError("unreachable")

    def handshake(self) -> Dict[int, Tuple[int, int]]:
        """ApiVersions exchange; caches the broker's supported ranges."""
        if self.api_versions is None:
            r = self.call(API_VERSIONS, 0, b"")
            err = r.i16()
            if err != ERR_NONE:
                raise KafkaError(f"ApiVersions error {err}")
            n = r.i32()
            vers = {}
            for _ in range(n):
                k, lo, hi = r.i16(), r.i16(), r.i16()
                vers[k] = (lo, hi)
            self.api_versions = vers
            for k, need in ((API_FETCH, 4), (API_LIST_OFFSETS, 1),
                            (API_METADATA, 1)):
                lo, hi = vers.get(k, (0, -1))
                if not lo <= need <= hi:
                    raise KafkaError(
                        f"broker does not support api {k} v{need} "
                        f"(range {lo}..{hi})")
        return self.api_versions

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None
                self.api_versions = None


# ---------------------------------------------------------------------------
# stream SPI plugin (consumer) + producer
# ---------------------------------------------------------------------------

class KafkaStream(StreamConsumerFactory):
    """Stream SPI factory over the Kafka protocol (KafkaConsumerFactory
    analog; config-addressable via
    consumer_factory_class='pinot_tpu.realtime.kafka.KafkaStream')."""

    def __init__(self, topic: str, host: str = "127.0.0.1", port: int = 0,
                 timeout: float = 10.0, value_decoder=None):
        """value_decoder: bytes -> row dict (default JSON). Pass a
        pinot_tpu.inputformat.avro.ConfluentAvroDecoder for
        schema-registry-framed Avro messages (the
        KafkaConfluentSchemaRegistryAvroMessageDecoder analog)."""
        self.topic = topic
        self.host = host
        self.port = int(port)
        self.timeout = timeout
        self.value_decoder = value_decoder
        self._n_parts: Optional[int] = None

    def num_partitions(self) -> int:
        """Metadata round-trip (KafkaStreamMetadataProvider analog)."""
        if self._n_parts is None:
            conn = _KafkaConn(self.host, self.port, self.timeout)
            try:
                conn.handshake()
                body = _i32(1) + _string(self.topic)
                r = conn.call(API_METADATA, 1, body)
                n_brokers = r.i32()
                for _ in range(n_brokers):
                    r.i32()
                    r.string()
                    r.i32()
                    r.string()               # rack (v1)
                r.i32()                      # controller_id
                n_topics = r.i32()
                for _ in range(n_topics):
                    err = r.i16()
                    name = r.string()
                    r.i8()                   # is_internal
                    n_parts = r.i32()
                    for _p in range(n_parts):
                        r.i16()
                        r.i32()
                        r.i32()
                        for _x in range(r.i32()):
                            r.i32()
                        for _x in range(r.i32()):
                            r.i32()
                    if name == self.topic:
                        if err != ERR_NONE:
                            raise KafkaError(
                                f"metadata error {err} for {name!r}")
                        self._n_parts = n_parts
                if self._n_parts is None:
                    raise KafkaError(f"topic {self.topic!r} not in "
                                     "metadata response")
            finally:
                conn.close()
        return self._n_parts

    def create_consumer(self, partition: int) -> "KafkaPartitionConsumer":
        return KafkaPartitionConsumer(self.topic, self.host, self.port,
                                      partition, self.timeout,
                                      self.value_decoder)


class KafkaPartitionConsumer(PartitionGroupConsumer):
    """Per-partition consumer speaking Fetch v4 / ListOffsets v1
    (KafkaPartitionLevelConsumer.java:42 analog). Message values are
    JSON rows; offsets are the Kafka long offsets."""

    FETCH_MAX_BYTES = 4 << 20

    def __init__(self, topic: str, host: str, port: int, partition: int,
                 timeout: float, value_decoder=None):
        self.topic = topic
        self.partition = partition
        self._decode = value_decoder or (lambda v: json.loads(v))
        self._conn = _KafkaConn(host, port, timeout)

    def fetch(self, start_offset: int, max_messages: int) -> MessageBatch:
        consume_faults(f"kafka/{self.topic}/{self.partition}")
        self._conn.handshake()
        body = (_i32(-1)                     # replica_id
                + _i32(100)                  # max_wait_ms
                + _i32(1)                    # min_bytes
                + _i32(self.FETCH_MAX_BYTES)
                + _i8(0)                     # isolation: read_uncommitted
                + _i32(1) + _string(self.topic) + _i32(1)
                + _i32(self.partition) + _i64(start_offset)
                + _i32(self.FETCH_MAX_BYTES))
        r = self._conn.call(API_FETCH, 4, body)
        r.i32()                              # throttle_time
        rows: List[Mapping[str, Any]] = []
        next_offset = start_offset
        n_topics = r.i32()
        for _ in range(n_topics):
            r.string()
            n_parts = r.i32()
            for _p in range(n_parts):
                r.i32()                      # partition
                err = r.i16()
                r.i64()                      # high_watermark
                r.i64()                      # last_stable_offset
                n_aborted = r.i32()
                for _a in range(max(n_aborted, 0)):
                    r.i64()
                    r.i64()
                record_set = r.bytes_() or b""
                if err == ERR_OFFSET_OUT_OF_RANGE:
                    raise KafkaOffsetOutOfRange(
                        f"offset {start_offset} out of range for "
                        f"{self.topic}/{self.partition}")
                if err != ERR_NONE:
                    raise KafkaError(f"fetch error code {err}")
                for off, _key, value in decode_record_batches(record_set):
                    if off < start_offset:
                        continue             # batch may start earlier
                    if len(rows) >= max_messages:
                        break
                    rows.append(self._decode(value))
                    next_offset = off + 1
        return MessageBatch(rows, next_offset)

    def latest_offset(self) -> int:
        self._conn.handshake()
        body = (_i32(-1) + _i32(1) + _string(self.topic) + _i32(1)
                + _i32(self.partition) + _i64(-1))   # ts -1 = latest
        r = self._conn.call(API_LIST_OFFSETS, 1, body)
        n_topics = r.i32()
        for _ in range(n_topics):
            r.string()
            n_parts = r.i32()
            for _p in range(n_parts):
                r.i32()                      # partition
                err = r.i16()
                r.i64()                      # timestamp
                off = r.i64()
                if err != ERR_NONE:
                    raise KafkaError(f"ListOffsets error {err}")
                return int(off)
        raise KafkaError("empty ListOffsets response")

    def close(self) -> None:
        self._conn.close()


class KafkaProducer:
    """Minimal Produce v3 client: encodes real record batches so the
    broker's decode path is exercised from a true client."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self._conn = _KafkaConn(host, port, timeout)

    def produce_many(self, topic: str, partition: int,
                     rows: List[Mapping[str, Any]]) -> int:
        self._conn.handshake()
        batch = encode_record_batch(
            0, [(None, json.dumps(dict(r)).encode()) for r in rows],
            int(time.time() * 1000))
        body = (_string(None)                # transactional_id
                + _i16(-1)                   # acks: full ISR
                + _i32(int(self._conn.timeout * 1000))
                + _i32(1) + _string(topic) + _i32(1)
                + _i32(partition) + _bytes(batch))
        # retries=0: Produce is not idempotent at this protocol level
        r = self._conn.call(API_PRODUCE, 3, body, retries=0)
        n_topics = r.i32()
        base = -1
        for _ in range(n_topics):
            r.string()
            n_parts = r.i32()
            for _p in range(n_parts):
                r.i32()
                err = r.i16()
                base = r.i64()
                r.i64()                      # log_append_time
                if err != ERR_NONE:
                    raise KafkaError(f"produce error code {err}")
        r.i32()                              # throttle_time
        return int(base)

    def close(self) -> None:
        self._conn.close()
