from .stream import (InMemoryStream, MessageBatch,  # noqa: F401
                     OffsetOutOfRange, PartitionGroupConsumer,
                     StreamConfig, StreamConsumerFactory)
from .manager import RealtimeTableDataManager  # noqa: F401
