from .stream import (InMemoryStream, MessageBatch, PartitionGroupConsumer,
                     StreamConfig, StreamConsumerFactory)  # noqa: F401
from .manager import RealtimeTableDataManager  # noqa: F401
