"""Kinesis wire-protocol stream plugin: a real-API consumer client + an
in-process fake Kinesis endpoint speaking the same JSON.

Reference analog: pinot-plugins/pinot-stream-ingestion/pinot-kinesis/
.../KinesisConsumer.java:45 (consumer), KinesisConsumerFactory,
KinesisStreamMetadataProvider (shards), KinesisPartitionGroupOffset
(sequence-number offsets). The AWS SDK v2 client is replaced by a
from-scratch client for the public Kinesis Data Streams API: JSON over
HTTP with `X-Amz-Target: Kinesis_20131202.<Op>` +
`Content-Type: application/x-amz-json-1.1`, signed with AWS SigV4
(service "kinesis" — the same signer as fs/s3.py).

Operations: ListShards, GetShardIterator, GetRecords, PutRecord
(producer for tests). Record Data is base64; messages are JSON rows
(the decoder contract shared with the Kafka/wirestream plugins).

Offset mapping (KinesisPartitionGroupOffset analog): Kinesis sequence
numbers are decimal strings of unbounded integers, NOT dense. The SPI's
integer offset is defined as `last consumed sequence number + 1`; a
fetch at offset 0 uses a TRIM_HORIZON iterator, any other offset uses
AFTER_SEQUENCE_NUMBER(offset - 1). The fake server assigns sequence
numbers with gaps so nothing can quietly assume density. Shards map to
SPI partitions by sorted ShardId.
"""
from __future__ import annotations

import base64
import datetime
import hashlib
import json
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple

from ..fs.rest import RestClient
from ..fs.s3 import sigv4_headers
from .stream import MessageBatch, OffsetOutOfRange, \
    PartitionGroupConsumer, StreamConsumerFactory, consume_faults

_TARGET_PREFIX = "Kinesis_20131202."
_CT = "application/x-amz-json-1.1"


class KinesisError(Exception):
    def __init__(self, status: int, type_: str, message: str):
        super().__init__(f"Kinesis {status} {type_}: {message}")
        self.status = status
        self.type = type_


class KinesisOffsetOutOfRange(KinesisError, OffsetOutOfRange):
    """The shard position can't be resumed: the sequence number aged out
    past retention (InvalidArgumentException) or the shard is gone after
    a reshard (ResourceNotFoundException). Subclasses the stream SPI's
    OffsetOutOfRange so the realtime manager snaps the partition back to
    its checkpoint instead of retrying an iterator mint that can never
    succeed."""


# GetShardIterator error types that mean "this position is gone", not
# "try again" — the snap-back classification above
_GONE_TYPES = ("InvalidArgumentException", "ResourceNotFoundException")


class KinesisClient:
    """Minimal Kinesis Data Streams API client with SigV4."""

    def __init__(self, endpoint_url: str, access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1",
                 timeout: float = 10.0, max_retries: int = 3,
                 backoff: float = 0.2):
        # retries live HERE (per-attempt re-signing keeps x-amz-date
        # fresh); the transport itself never retries
        self.rest = RestClient(endpoint_url, timeout=timeout,
                               max_retries=0)
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.max_retries = max_retries
        self.backoff = backoff

    def call(self, op: str, payload: Dict[str, Any],
             retriable: bool = True) -> Dict[str, Any]:
        body = json.dumps(payload).encode()
        host = self.rest.host if self.rest.port in (80, 443) \
            else f"{self.rest.host}:{self.rest.port}"
        attempts = self.max_retries if retriable else 0
        for attempt in range(attempts + 1):
            amz_date = datetime.datetime.now(datetime.timezone.utc)\
                .strftime("%Y%m%dT%H%M%SZ")
            hdrs = sigv4_headers(
                "POST", host, "/", {},
                {"content-type": _CT,
                 "x-amz-target": _TARGET_PREFIX + op},
                hashlib.sha256(body).hexdigest(), self.access_key,
                self.secret_key, self.region, amz_date,
                service="kinesis")
            try:
                st, _h, resp = self.rest.request(
                    "POST", "/", headers=hdrs, body=body)
            except (ConnectionError, OSError) as e:
                if attempt == attempts:
                    raise
                time.sleep(self.backoff * (2 ** attempt))
                continue
            if st >= 500 and attempt < attempts:
                time.sleep(self.backoff * (2 ** attempt))
                continue
            if st != 200:
                try:
                    err = json.loads(resp.decode())
                    t = (err.get("__type") or "Unknown").split("#")[-1]
                    msg = err.get("message") or err.get("Message") or ""
                except ValueError:
                    t, msg = "Unknown", resp.decode(errors="replace")
                raise KinesisError(st, t, msg)
            return json.loads(resp.decode())
        raise AssertionError("unreachable")

    # -- operations -------------------------------------------------------

    def list_shards(self, stream: str) -> List[dict]:
        shards: List[dict] = []
        token: Optional[str] = None
        while True:
            payload: Dict[str, Any] = {"NextToken": token} if token \
                else {"StreamName": stream}
            res = self.call("ListShards", payload)
            shards.extend(res.get("Shards", []))
            token = res.get("NextToken")
            if not token:
                return shards

    def get_shard_iterator(self, stream: str, shard_id: str,
                           iterator_type: str,
                           sequence_number: Optional[str] = None) -> str:
        payload: Dict[str, Any] = {"StreamName": stream,
                                   "ShardId": shard_id,
                                   "ShardIteratorType": iterator_type}
        if sequence_number is not None:
            payload["StartingSequenceNumber"] = sequence_number
        return self.call("GetShardIterator", payload)["ShardIterator"]

    def get_records(self, iterator: str, limit: int) -> dict:
        return self.call("GetRecords",
                         {"ShardIterator": iterator, "Limit": limit})

    def put_record(self, stream: str, data: bytes,
                   partition_key: str) -> Tuple[str, str]:
        res = self.call("PutRecord", {
            "StreamName": stream,
            "Data": base64.b64encode(data).decode(),
            "PartitionKey": partition_key}, retriable=False)
        return res["ShardId"], res["SequenceNumber"]


class KinesisStream(StreamConsumerFactory):
    """StreamConsumerFactory over Kinesis (KinesisConsumerFactory
    analog). Shards (sorted by ShardId) are the SPI partitions."""

    def __init__(self, stream: str, endpoint_url: str,
                 access_key: str = "", secret_key: str = "",
                 region: str = "us-east-1", value_decoder=None,
                 **client_kw):
        self.stream = stream
        self.client = KinesisClient(endpoint_url, access_key, secret_key,
                                    region, **client_kw)
        self.value_decoder = value_decoder
        self._shard_cache: Optional[List[str]] = None

    def _shard_ids(self, refresh: bool = False) -> List[str]:
        """Sorted shard ids, cached after the first ListShards (the
        reference's metadata-provider caching) — steady-state consumption
        performs zero ListShards calls. refresh_shards() re-lists after
        a reshard."""
        if self._shard_cache is None or refresh:
            self._shard_cache = sorted(
                s["ShardId"] for s in self.client.list_shards(self.stream))
        return self._shard_cache

    def refresh_shards(self) -> List[str]:
        return self._shard_ids(refresh=True)

    def num_partitions(self) -> int:
        return len(self._shard_ids())

    def create_consumer(self, partition: int) -> "KinesisShardConsumer":
        shard_ids = self._shard_ids()
        if partition >= len(shard_ids):
            raise KinesisError(
                404, "ResourceNotFoundException",
                f"partition {partition} but only {len(shard_ids)} shards")
        return KinesisShardConsumer(self.client, self.stream,
                                    shard_ids[partition],
                                    self.value_decoder)


class KinesisShardConsumer(PartitionGroupConsumer):
    """One shard's consumer (KinesisConsumer.java:45 analog).

    Caches the NextShardIterator between contiguous fetches so steady
    consumption costs one GetRecords per batch, not an extra
    GetShardIterator (the reference caches the same way)."""

    def __init__(self, client: KinesisClient, stream: str, shard_id: str,
                 value_decoder=None):
        self.client = client
        self.stream = stream
        self.shard_id = shard_id
        self._decode = value_decoder or (lambda v: json.loads(v))
        self._cached: Optional[Tuple[int, str]] = None  # (offset, iter)

    def _iterator_for(self, start_offset: int) -> str:
        if self._cached is not None and self._cached[0] == start_offset:
            return self._cached[1]
        if start_offset <= 0:
            return self.client.get_shard_iterator(
                self.stream, self.shard_id, "TRIM_HORIZON")
        try:
            return self.client.get_shard_iterator(
                self.stream, self.shard_id, "AFTER_SEQUENCE_NUMBER",
                str(start_offset - 1))
        except KinesisError as e:
            if e.type in _GONE_TYPES:
                raise KinesisOffsetOutOfRange(
                    e.status, e.type,
                    f"cannot resume {self.stream}/{self.shard_id} at "
                    f"{start_offset}: {e}") from e
            raise

    def fetch(self, start_offset: int, max_messages: int) -> MessageBatch:
        consume_faults(f"kinesis/{self.stream}/{self.shard_id}")
        it = self._iterator_for(start_offset)
        try:
            res = self.client.get_records(it, max_messages)
        except KinesisError as e:
            # a cached iterator can expire (5-minute service TTL);
            # re-mint once from the sequence number and retry — without
            # this, a quiet partition wedges permanently on one token
            self._cached = None
            if e.type != "ExpiredIteratorException":
                raise
            res = self.client.get_records(
                self._iterator_for(start_offset), max_messages)
        rows: List[Mapping[str, Any]] = []
        row_offsets: List[int] = []
        next_offset = start_offset
        for rec in res.get("Records", []):
            rows.append(self._decode(base64.b64decode(rec["Data"])))
            row_offsets.append(int(rec["SequenceNumber"]))
            next_offset = row_offsets[-1] + 1
        nxt = res.get("NextShardIterator")
        self._cached = (next_offset, nxt) if nxt else None
        # publish per-row sequence numbers: they are NOT dense, and the
        # realtime manager needs the exact offset after any row count
        return MessageBatch(rows, next_offset, row_offsets)

    def latest_offset(self) -> int:
        """Kinesis has no 'latest sequence' API; walk forward from
        TRIM_HORIZON (test/diagnostic use only — the realtime manager
        checkpoints consumed offsets, never this)."""
        off = 0
        while True:
            batch = self.fetch(off, 10_000)
            if not batch.rows:
                return off
            off = batch.next_offset

    def close(self) -> None:
        self._cached = None


# ---------------------------------------------------------------------------
# fake Kinesis endpoint (embedded test fixture, localstack-of-the-suite)
# ---------------------------------------------------------------------------

class FakeKinesisServer:
    """In-process Kinesis API endpoint. Sequence numbers increase with
    GAPS (step 3) so clients can't assume density; iterators are opaque
    one-shot tokens renewed by every GetRecords, like the real service.
    Verifies SigV4 when credentials are configured. `inject_failures(n)`
    makes the next n requests 500 (retry-path testing)."""

    def __init__(self, streams: Dict[str, int], port: int = 0,
                 access_key: Optional[str] = None, secret_key: str = ""):
        import http.server

        self.access_key = access_key
        self.secret_key = secret_key
        # stream -> [shard records]; record = (seq:int, pkey, data bytes)
        self.shards: Dict[str, List[List[Tuple[int, str, bytes]]]] = {
            s: [[] for _ in range(n)] for s, n in streams.items()}
        self.next_seq = 7                      # arbitrary non-zero start
        self.iterators: Dict[str, Tuple[str, int, int]] = {}
        self.next_iter = 0
        self.fail_next = 0
        self._lock = threading.Lock()
        stub = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reply(self, status: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", _CT)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self) -> None:
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n)
                with stub._lock:
                    if stub.fail_next > 0:
                        stub.fail_next -= 1
                        return self._reply(500, {
                            "__type": "InternalFailure",
                            "message": "injected"})
                if not stub._auth_ok(self.headers):
                    return self._reply(403, {
                        "__type": "IncompleteSignatureException",
                        "message": "bad signature"})
                op = (self.headers.get("X-Amz-Target") or "")\
                    .split(".")[-1]
                try:
                    payload = json.loads(body.decode() or "{}")
                    st, out = stub._dispatch(op, payload)
                except KeyError as e:
                    st, out = 400, {"__type": "ValidationException",
                                    "message": f"missing {e}"}
                self._reply(st, out)

        class _Srv(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Srv(("127.0.0.1", port), _Handler)
        self.port = self._server.server_address[1]
        self.endpoint_url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    # -- handler core -----------------------------------------------------

    def _auth_ok(self, headers) -> bool:
        if self.access_key is None:
            return True
        auth = headers.get("Authorization") or ""
        return (auth.startswith("AWS4-HMAC-SHA256")
                and f"Credential={self.access_key}/" in auth
                and "Signature=" in auth)

    def _shard_list(self, stream: str) -> List[List]:
        if stream not in self.shards:
            raise _NotFound(f"stream {stream!r} not found")
        return self.shards[stream]

    def _dispatch(self, op: str, p: dict) -> Tuple[int, dict]:
        try:
            with self._lock:
                if op == "ListShards":
                    stream = p.get("StreamName") or p["NextToken"]
                    shards = self._shard_list(stream)
                    return 200, {"Shards": [
                        {"ShardId": f"shardId-{i:012d}",
                         "SequenceNumberRange": {
                             "StartingSequenceNumber":
                                 str(recs[0][0]) if recs else "0"}}
                        for i, recs in enumerate(shards)]}
                if op == "GetShardIterator":
                    stream = p["StreamName"]
                    sid = p["ShardId"]
                    idx = int(sid.rsplit("-", 1)[-1])
                    shards = self._shard_list(stream)
                    if idx >= len(shards):
                        raise _NotFound(f"no shard {sid}")
                    t = p["ShardIteratorType"]
                    if t == "TRIM_HORIZON":
                        pos = 0
                    elif t == "LATEST":
                        pos = 1 << 62
                    elif t == "AFTER_SEQUENCE_NUMBER":
                        pos = int(p["StartingSequenceNumber"]) + 1
                    elif t == "AT_SEQUENCE_NUMBER":
                        pos = int(p["StartingSequenceNumber"])
                    else:
                        return 400, {"__type": "ValidationException",
                                     "message": f"bad type {t}"}
                    return 200, {"ShardIterator":
                                 self._mint(stream, idx, pos)}
                if op == "GetRecords":
                    it = p["ShardIterator"]
                    tok = self.iterators.pop(it, None)
                    if tok is None:
                        return 400, {"__type": "ExpiredIteratorException",
                                     "message": "unknown iterator"}
                    stream, idx, pos = tok
                    recs = self.shards[stream][idx]
                    limit = int(p.get("Limit", 10_000))
                    out = [r for r in recs if r[0] >= pos][:limit]
                    new_pos = out[-1][0] + 1 if out else pos
                    return 200, {
                        "Records": [{
                            "SequenceNumber": str(seq),
                            "PartitionKey": pk,
                            "ApproximateArrivalTimestamp": 0,
                            "Data": base64.b64encode(data).decode()}
                            for seq, pk, data in out],
                        "NextShardIterator":
                            self._mint(stream, idx, new_pos),
                        "MillisBehindLatest": 0}
                if op == "PutRecord":
                    stream = p["StreamName"]
                    shards = self._shard_list(stream)
                    pk = p["PartitionKey"]
                    data = base64.b64decode(p["Data"])
                    idx = int(hashlib.md5(pk.encode()).hexdigest(),
                              16) % len(shards)
                    seq = self.next_seq
                    self.next_seq += 3       # gaps: density is a lie
                    shards[idx].append((seq, pk, data))
                    return 200, {"ShardId": f"shardId-{idx:012d}",
                                 "SequenceNumber": str(seq)}
            return 400, {"__type": "UnknownOperationException",
                         "message": op}
        except _NotFound as e:
            return 400, {"__type": "ResourceNotFoundException",
                         "message": str(e)}

    def _mint(self, stream: str, idx: int, pos: int) -> str:
        # called only from the API dispatch, which already holds _lock
        self.next_iter += 1  # jaxlint: ok unlocked-mutation
        it = f"it-{self.next_iter}"
        self.iterators[it] = (stream, idx, pos)  # jaxlint: ok unlocked-mutation
        return it

    # -- test hooks -------------------------------------------------------

    def put(self, stream: str, shard: int,
            rows: List[Mapping[str, Any]]) -> None:
        """Direct append for fixtures (bypasses the API, keeps gaps)."""
        with self._lock:
            for r in rows:
                seq = self.next_seq
                self.next_seq += 3
                self.shards[stream][shard].append(
                    (seq, "fixture", json.dumps(r).encode()))

    def inject_failures(self, n: int) -> None:
        with self._lock:
            self.fail_next = n

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class _NotFound(Exception):
    pass
