"""Pulsar binary-protocol stream plugin: a real-protocol reader client +
an in-process fake broker speaking the same bytes.

Reference analog: pinot-plugins/pinot-stream-ingestion/pinot-pulsar/
.../PulsarPartitionLevelConsumer.java (the pulsar-client library is
replaced by a from-scratch client for the public Pulsar binary
protocol). Like the Kafka plugin (realtime/kafka.py), the client and
the FakePulsarBroker share only the wire contract, never code.

Implemented from the public protocol spec (PulsarApi.proto + the
binary-protocol docs), all from scratch:

- protobuf wire codec: varint, length-delimited submessages — enough to
  encode/decode the BaseCommand envelope and the sub-commands below
- simple command frame: [totalSize][commandSize][BaseCommand]
- payload command frame: [totalSize][commandSize][BaseCommand]
  [0x0e01 magic][CRC32C over metadata+payload][metadataSize]
  [MessageMetadata][payload] — checksum verified on every frame
- commands: CONNECT/CONNECTED, PRODUCER/PRODUCER_SUCCESS,
  SEND/SEND_RECEIPT, SUBSCRIBE (Reader-style: Exclusive,
  initial position), FLOW (permit-based delivery), MESSAGE,
  SEEK/SUCCESS, CLOSE_CONSUMER, PING/PONG, ERROR

Offsets (MessageId): Pulsar ids are (ledgerId, entryId) pairs — NOT
dense integers. The SPI offset packs them as (ledgerId << 20) | entryId
(a real BookKeeper ledger holds < 2^20 entries under default rollover)
and the consumer publishes per-row offsets (MessageBatch.row_offsets)
exactly like the Kinesis plugin, so the realtime manager's checkpoints
commit real ids. The fake broker rolls ledgers every few entries so
nothing can quietly assume one ledger or dense entry ids.
"""
from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Any, Dict, List, Mapping, Optional, Tuple

from .kafka import crc32c
from .stream import MessageBatch, PartitionGroupConsumer, \
    StreamConsumerFactory, consume_faults

# BaseCommand.Type values (PulsarApi.proto enum)
CONNECT, CONNECTED = 2, 3
SUBSCRIBE, PRODUCER, SEND, SEND_RECEIPT = 4, 5, 6, 7
MESSAGE, FLOW = 9, 11
SUCCESS, ERROR = 13, 14
CLOSE_PRODUCER, CLOSE_CONSUMER, PRODUCER_SUCCESS = 15, 16, 17
PING, PONG = 18, 19
SEEK = 28
GET_LAST_MESSAGE_ID, GET_LAST_MESSAGE_ID_RESPONSE = 29, 30

_MAGIC = 0x0E01
_ENTRY_BITS = 20          # SPI offset = ledgerId << 20 | entryId
_MAX_FRAME = 16 << 20


class PulsarError(Exception):
    """Protocol-level error (broker ERROR command or malformed bytes)."""


# ---------------------------------------------------------------------------
# minimal protobuf wire codec
# ---------------------------------------------------------------------------

def _pb_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _pb_field(num: int, v: int) -> bytes:
    return _pb_varint(num << 3) + _pb_varint(v)


def _pb_bytes(num: int, data: bytes) -> bytes:
    return _pb_varint((num << 3) | 2) + _pb_varint(len(data)) + data


def _pb_str(num: int, s: str) -> bytes:
    return _pb_bytes(num, s.encode())


def pb_decode(data: bytes) -> Dict[int, List[Any]]:
    """field number -> list of values (ints for varint fields, bytes for
    length-delimited). Unknown wire types are skipped structurally."""
    out: Dict[int, List[Any]] = {}
    pos = 0

    def varint() -> int:
        nonlocal pos
        shift = v = 0
        while True:
            if pos >= len(data):
                raise PulsarError("truncated protobuf")
            b = data[pos]
            pos += 1
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                return v
            shift += 7

    while pos < len(data):
        tag = varint()
        num, wt = tag >> 3, tag & 7
        if wt == 0:
            val: Any = varint()
        elif wt == 2:
            n = varint()
            if pos + n > len(data):
                raise PulsarError("truncated protobuf")
            val = data[pos:pos + n]
            pos += n
        elif wt == 5:
            if pos + 4 > len(data):
                raise PulsarError("truncated protobuf")
            val = struct.unpack_from("<I", data, pos)[0]
            pos += 4
        elif wt == 1:
            if pos + 8 > len(data):
                raise PulsarError("truncated protobuf")
            val = struct.unpack_from("<Q", data, pos)[0]
            pos += 8
        else:
            raise PulsarError(f"unsupported wire type {wt}")
        out.setdefault(num, []).append(val)
    return out


def _one(fields: Dict[int, List[Any]], num: int, default=None):
    vals = fields.get(num)
    return vals[0] if vals else default


# message ids
def _encode_message_id(ledger: int, entry: int) -> bytes:
    return _pb_field(1, ledger) + _pb_field(2, entry)


def _decode_message_id(data: bytes) -> Tuple[int, int]:
    f = pb_decode(data)
    return _one(f, 1, 0), _one(f, 2, 0)


def pack_offset(ledger: int, entry: int) -> int:
    return (ledger << _ENTRY_BITS) | entry


def unpack_offset(offset: int) -> Tuple[int, int]:
    return offset >> _ENTRY_BITS, offset & ((1 << _ENTRY_BITS) - 1)


# ---------------------------------------------------------------------------
# frames
# ---------------------------------------------------------------------------

def encode_frame(cmd: bytes, metadata: Optional[bytes] = None,
                 payload: bytes = b"") -> bytes:
    if metadata is None:
        body = struct.pack(">I", len(cmd)) + cmd
        return struct.pack(">I", len(body)) + body
    blob = struct.pack(">I", len(metadata)) + metadata + payload
    crc = crc32c(blob)
    body = (struct.pack(">I", len(cmd)) + cmd
            + struct.pack(">HI", _MAGIC, crc) + blob)
    return struct.pack(">I", len(body)) + body


def decode_frame(body: bytes) -> Tuple[Dict[int, List[Any]],
                                       Optional[bytes], bytes]:
    """-> (BaseCommand fields, metadata bytes or None, payload)."""
    (cmd_size,) = struct.unpack_from(">I", body, 0)
    cmd = pb_decode(body[4:4 + cmd_size])
    rest = body[4 + cmd_size:]
    if not rest:
        return cmd, None, b""
    magic, crc = struct.unpack_from(">HI", rest, 0)
    if magic != _MAGIC:
        raise PulsarError(f"bad payload magic {magic:#x}")
    blob = rest[6:]
    if crc32c(blob) != crc:
        raise PulsarError("CRC32C mismatch on payload frame")
    (md_size,) = struct.unpack_from(">I", blob, 0)
    metadata = blob[4:4 + md_size]
    return cmd, metadata, blob[4 + md_size:]


class _Conn:
    """One connection: CONNECT handshake + framed send/recv."""

    def __init__(self, host: str, port: int, timeout: float):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(timeout)
        self._buf = b""
        connect = _pb_field(1, CONNECT) + _pb_bytes(
            3, _pb_str(1, "pinot-tpu") + _pb_field(4, 21))
        self.send(encode_frame(connect))
        cmd, _m, _p = self.recv()
        if _one(cmd, 1) != CONNECTED:
            raise PulsarError(f"expected CONNECTED, got {_one(cmd, 1)}")

    def send(self, frame: bytes) -> None:
        self.sock.sendall(frame)

    def recv(self) -> Tuple[Dict[int, List[Any]], Optional[bytes], bytes]:
        while True:
            if len(self._buf) >= 4:
                (total,) = struct.unpack_from(">I", self._buf, 0)
                if total > _MAX_FRAME:
                    raise PulsarError(f"frame too large: {total}")
                if len(self._buf) >= 4 + total:
                    body = self._buf[4:4 + total]
                    self._buf = self._buf[4 + total:]
                    cmd, md, pl = decode_frame(body)
                    t = _one(cmd, 1)
                    if t == PING:       # keepalive: answer and continue
                        self.send(encode_frame(_pb_field(1, PONG)))
                        continue
                    if t == ERROR:
                        err = pb_decode(_one(cmd, 16, b""))
                        msg = _one(err, 3, b"")
                        raise PulsarError(
                            msg.decode() if isinstance(msg, bytes)
                            else str(msg))
                    return cmd, md, pl
                    # noqa: unreachable
            chunk = self.sock.recv(65536)
            if not chunk:
                raise PulsarError("connection closed")
            self._buf += chunk

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# sub-command field numbers (PulsarApi.proto):
# BaseCommand: each command type has its own submessage field — the ones
# used here: connect=3, subscribe=5, producer=7, send=8, send_receipt=9,
# message=11, flow=13, success=15, error=16, close_consumer=19,
# producer_success=20, seek=30


class PulsarStream(StreamConsumerFactory):
    """StreamConsumerFactory over partitioned Pulsar topics: partition i
    is topic '<topic>-partition-<i>' (the Pulsar partitioned-topic
    naming), one reader connection each."""

    def __init__(self, topic: str, host: str = "127.0.0.1",
                 port: int = 6650, partitions: Optional[int] = None,
                 timeout: float = 10.0, value_decoder=None):
        self.topic = topic
        self.host = host
        self.port = port
        self._partitions = partitions
        self.timeout = timeout
        self.value_decoder = value_decoder

    def num_partitions(self) -> int:
        if self._partitions is not None:
            return self._partitions
        raise PulsarError("partitions must be configured (the trimmed "
                          "client implements no LOOKUP/metadata round)")

    def create_consumer(self, partition: int) -> "PulsarReaderConsumer":
        return PulsarReaderConsumer(
            f"{self.topic}-partition-{partition}", self.host, self.port,
            self.timeout, self.value_decoder)


class PulsarReaderConsumer(PartitionGroupConsumer):
    """Reader-style consumer: SUBSCRIBE (Exclusive, earliest), SEEK to
    the fetch offset, FLOW permits, collect MESSAGE frames. Each fetch
    seeks explicitly, so the SPI's stateless fetch(start_offset)
    contract holds across restarts and redeliveries."""

    _next_consumer = [0]

    def __init__(self, topic: str, host: str, port: int, timeout: float,
                 value_decoder=None):
        self.topic = topic
        self._decode = value_decoder or (lambda v: json.loads(v))
        self._conn = _Conn(host, port, timeout)
        PulsarReaderConsumer._next_consumer[0] += 1
        self.consumer_id = PulsarReaderConsumer._next_consumer[0]
        self._req = 0
        sub = (_pb_str(1, topic) + _pb_str(2, "pinot-tpu-reader")
               + _pb_field(3, 0)            # subType Exclusive
               + _pb_field(4, self.consumer_id)
               + _pb_field(5, self._next_req())
               + _pb_field(13, 1))          # initialPosition Earliest
        self._conn.send(encode_frame(_pb_field(1, SUBSCRIBE)
                                     + _pb_bytes(5, sub)))
        cmd, _m, _p = self._conn.recv()
        if _one(cmd, 1) != SUCCESS:
            raise PulsarError(f"subscribe failed: type {_one(cmd, 1)}")

    def _next_req(self) -> int:
        self._req += 1
        return self._req

    def fetch(self, start_offset: int, max_messages: int) -> MessageBatch:
        consume_faults(f"pulsar/{self.topic}")
        ledger, entry = unpack_offset(start_offset)
        seek = (_pb_field(1, self.consumer_id)
                + _pb_field(2, self._next_req())
                + _pb_bytes(3, _encode_message_id(ledger, entry)))
        self._conn.send(encode_frame(_pb_field(1, SEEK)
                                     + _pb_bytes(30, seek)))
        cmd, _m, _p = self._conn.recv()
        if _one(cmd, 1) != SUCCESS:
            raise PulsarError(f"seek failed: type {_one(cmd, 1)}")
        flow = (_pb_field(1, self.consumer_id)
                + _pb_field(2, max_messages))
        self._conn.send(encode_frame(_pb_field(1, FLOW)
                                     + _pb_bytes(13, flow)))

        rows: List[Mapping[str, Any]] = []
        row_offsets: List[int] = []
        next_offset = start_offset
        delivered = 0               # every MESSAGE consumes one permit,
        while delivered < max_messages:   # even ones we skip — counting
            cmd, _md, payload = self._conn.recv()   # rows would hang on
            t = _one(cmd, 1)                        # skipped deliveries
            if t != MESSAGE:
                raise PulsarError(f"unexpected command {t} mid-delivery")
            msg = pb_decode(_one(cmd, 11, b""))
            ledger, entry = _decode_message_id(_one(msg, 2, b""))
            if payload == b"":      # end-of-available marker (see fake)
                break
            delivered += 1
            if _one(msg, 1) != self.consumer_id:
                continue            # stale delivery for an old consumer
            off = pack_offset(ledger, entry)
            if off < start_offset:
                continue            # pre-seek redelivery
            rows.append(self._decode(payload))
            row_offsets.append(off)
            next_offset = off + 1
        return MessageBatch(rows, next_offset, row_offsets)

    def latest_offset(self) -> int:
        """GET_LAST_MESSAGE_ID — the protocol's metadata round for the
        topic end (no payload transfer, unlike a scan-to-end)."""
        req = (_pb_field(1, self.consumer_id)
               + _pb_field(2, self._next_req()))
        self._conn.send(encode_frame(_pb_field(1, GET_LAST_MESSAGE_ID)
                                     + _pb_bytes(32, req)))
        cmd, _m, _p = self._conn.recv()
        if _one(cmd, 1) != GET_LAST_MESSAGE_ID_RESPONSE:
            raise PulsarError(
                f"expected last-message-id response, got {_one(cmd, 1)}")
        resp = pb_decode(_one(cmd, 33, b""))
        ledger, entry = _decode_message_id(_one(resp, 1, b""))
        # a real broker signals an empty topic with entryId = -1
        # (varint-encoded as 2^64-1); (ledger 0, entry 0) is a REAL
        # first message, not a sentinel
        if entry >= 1 << 63 or ledger >= 1 << 63:
            return 0
        return pack_offset(ledger, entry) + 1

    def close(self) -> None:
        close = (_pb_field(1, self.consumer_id)
                 + _pb_field(2, self._next_req()))
        try:
            self._conn.send(encode_frame(_pb_field(1, CLOSE_CONSUMER)
                                         + _pb_bytes(19, close)))
        except OSError:
            pass
        self._conn.close()


class PulsarProducer:
    """Test-side producer speaking PRODUCER/SEND with payload frames."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self._conn = _Conn(host, port, timeout)
        self._producer_ids: Dict[str, int] = {}
        self._next_pid = 0
        self._req = 0
        self._seq = 0

    def _ensure_producer(self, topic: str) -> int:
        if topic in self._producer_ids:
            return self._producer_ids[topic]
        self._next_pid += 1
        pid = self._next_pid
        self._req += 1
        prod = (_pb_str(1, topic) + _pb_field(2, pid)
                + _pb_field(3, self._req))
        self._conn.send(encode_frame(_pb_field(1, PRODUCER)
                                     + _pb_bytes(7, prod)))
        cmd, _m, _p = self._conn.recv()
        if _one(cmd, 1) != PRODUCER_SUCCESS:
            raise PulsarError(f"producer failed: type {_one(cmd, 1)}")
        self._producer_ids[topic] = pid
        return pid

    def send(self, topic: str, row: Mapping[str, Any]) -> int:
        """-> packed (ledgerId, entryId) offset from the SEND_RECEIPT."""
        pid = self._ensure_producer(topic)
        self._seq += 1
        send = _pb_field(1, pid) + _pb_field(2, self._seq)
        metadata = (_pb_str(1, f"producer-{pid}")
                    + _pb_field(2, self._seq)
                    + _pb_field(3, 0))      # publish_time
        payload = json.dumps(row).encode()
        self._conn.send(encode_frame(
            _pb_field(1, SEND) + _pb_bytes(8, send), metadata, payload))
        cmd, _m, _p = self._conn.recv()
        if _one(cmd, 1) != SEND_RECEIPT:
            raise PulsarError(f"expected SEND_RECEIPT, got {_one(cmd, 1)}")
        receipt = pb_decode(_one(cmd, 9, b""))
        ledger, entry = _decode_message_id(_one(receipt, 3, b""))
        return pack_offset(ledger, entry)

    def send_many(self, topic: str, rows: List[Mapping[str, Any]]
                  ) -> List[int]:
        return [self.send(topic, r) for r in rows]

    def close(self) -> None:
        self._conn.close()


# ---------------------------------------------------------------------------
# fake Pulsar broker (embedded test fixture)
# ---------------------------------------------------------------------------

class FakePulsarBroker:
    """In-process TCP broker speaking the protocol subset above. Topics
    hold (ledgerId, entryId, payload) entries; LEDGERS ROLL every
    `ledger_entries` messages (entry ids restart at 0), so consumers
    can't assume one ledger or dense packed offsets. Delivery follows
    the real model: SEEK positions the cursor, FLOW grants permits,
    MESSAGE frames stream until permits or data run out; an empty-
    payload MESSAGE marks end-of-available (the test fixture's stand-in
    for a delivery pause)."""

    def __init__(self, topics: List[str], port: int = 0,
                 ledger_entries: int = 5):
        self.topics: Dict[str, List[Tuple[int, int, bytes]]] = {
            t: [] for t in topics}
        self.ledger_entries = ledger_entries
        self._next_ledger = 11
        self._lock = threading.Lock()
        broker = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                buf = b""
                cursors: Dict[int, Tuple[str, int]] = {}  # cid -> (topic, pos_offset)
                producers: Dict[int, str] = {}
                sock = self.request
                try:
                    while True:
                        while len(buf) < 4:
                            chunk = sock.recv(65536)
                            if not chunk:
                                return
                            buf += chunk
                        (total,) = struct.unpack_from(">I", buf, 0)
                        while len(buf) < 4 + total:
                            chunk = sock.recv(65536)
                            if not chunk:
                                return
                            buf += chunk
                        body = buf[4:4 + total]
                        buf = buf[4 + total:]
                        out = broker._handle(body, cursors, producers)
                        for frame in out:
                            sock.sendall(frame)
                except (ConnectionError, OSError, PulsarError):
                    return

        class Srv(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Srv(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    # -- log --------------------------------------------------------------

    def append(self, topic: str, rows: List[Mapping[str, Any]]
               ) -> List[int]:
        """Direct append for fixtures; returns packed offsets."""
        out = []
        with self._lock:
            for r in rows:
                out.append(self._append_locked(
                    topic, json.dumps(r).encode()))
        return out

    def _append_locked(self, topic: str, payload: bytes) -> int:
        log = self.topics[topic]
        if not log or log[-1][1] + 1 >= self.ledger_entries:
            ledger = self._next_ledger
            self._next_ledger += 2      # gaps between ledger ids too
            entry = 0
        else:
            ledger, entry = log[-1][0], log[-1][1] + 1
        log.append((ledger, entry, payload))
        return pack_offset(ledger, entry)

    # -- protocol ----------------------------------------------------------

    def _handle(self, body: bytes, cursors, producers) -> List[bytes]:
        cmd, metadata, payload = decode_frame(body)
        t = _one(cmd, 1)
        if t == CONNECT:
            return [encode_frame(_pb_field(1, CONNECTED)
                                 + _pb_bytes(4, _pb_field(4, 21)))]
        if t == PRODUCER:
            p = pb_decode(_one(cmd, 7, b""))
            topic = _one(p, 1, b"").decode()
            pid = _one(p, 2, 0)
            if topic not in self.topics:
                return [self._error(f"no topic {topic!r}")]
            producers[pid] = topic
            ps = _pb_field(1, _one(p, 3, 0)) + _pb_str(2, f"p-{pid}")
            return [encode_frame(_pb_field(1, PRODUCER_SUCCESS)
                                 + _pb_bytes(20, ps))]
        if t == SEND:
            s = pb_decode(_one(cmd, 8, b""))
            pid = _one(s, 1, 0)
            topic = producers.get(pid)
            if topic is None:
                return [self._error(f"unknown producer {pid}")]
            with self._lock:
                off = self._append_locked(topic, payload)
            ledger, entry = unpack_offset(off)
            receipt = (_pb_field(1, pid) + _pb_field(2, _one(s, 2, 0))
                       + _pb_bytes(3, _encode_message_id(ledger, entry)))
            return [encode_frame(_pb_field(1, SEND_RECEIPT)
                                 + _pb_bytes(9, receipt))]
        if t == SUBSCRIBE:
            s = pb_decode(_one(cmd, 5, b""))
            topic = _one(s, 1, b"").decode()
            cid = _one(s, 4, 0)
            if topic not in self.topics:
                return [self._error(f"no topic {topic!r}")]
            cursors[cid] = (topic, 0)
            return [encode_frame(
                _pb_field(1, SUCCESS)
                + _pb_bytes(15, _pb_field(1, _one(s, 5, 0))))]
        if t == SEEK:
            s = pb_decode(_one(cmd, 30, b""))
            cid = _one(s, 1, 0)
            if cid not in cursors:
                return [self._error(f"unknown consumer {cid}")]
            ledger, entry = _decode_message_id(_one(s, 3, b""))
            cursors[cid] = (cursors[cid][0], pack_offset(ledger, entry))
            return [encode_frame(
                _pb_field(1, SUCCESS)
                + _pb_bytes(15, _pb_field(1, _one(s, 2, 0))))]
        if t == FLOW:
            f = pb_decode(_one(cmd, 13, b""))
            cid = _one(f, 1, 0)
            permits = _one(f, 2, 0)
            if cid not in cursors:
                return [self._error(f"unknown consumer {cid}")]
            topic, pos = cursors[cid]
            frames = []
            with self._lock:
                entries = [e for e in self.topics[topic]
                           if pack_offset(e[0], e[1]) >= pos][:permits]
            for ledger, entry, pl in entries:
                mid = _encode_message_id(ledger, entry)
                msg = _pb_field(1, cid) + _pb_bytes(2, mid)
                md = _pb_str(1, "p") + _pb_field(2, 1) + _pb_field(3, 0)
                frames.append(encode_frame(
                    _pb_field(1, MESSAGE) + _pb_bytes(11, msg), md, pl))
            if entries:
                last = pack_offset(entries[-1][0], entries[-1][1]) + 1
                cursors[cid] = (topic, last)
            if len(entries) < permits:
                # end-of-available marker (empty payload MESSAGE)
                mid = _encode_message_id(0, 0)
                msg = _pb_field(1, cid) + _pb_bytes(2, mid)
                frames.append(encode_frame(
                    _pb_field(1, MESSAGE) + _pb_bytes(11, msg),
                    _pb_str(1, "p") + _pb_field(2, 1) + _pb_field(3, 0),
                    b""))
            return frames
        if t == GET_LAST_MESSAGE_ID:
            g = pb_decode(_one(cmd, 32, b""))
            cid = _one(g, 1, 0)
            if cid not in cursors:
                return [self._error(f"unknown consumer {cid}")]
            topic = cursors[cid][0]
            neg1 = (1 << 64) - 1          # varint encoding of int64 -1
            with self._lock:
                log = self.topics[topic]
                last = (log[-1][0], log[-1][1]) if log else (neg1, neg1)
            resp = (_pb_bytes(1, _encode_message_id(*last))
                    + _pb_field(2, _one(g, 2, 0)))
            return [encode_frame(
                _pb_field(1, GET_LAST_MESSAGE_ID_RESPONSE)
                + _pb_bytes(33, resp))]
        if t == CLOSE_CONSUMER:
            c = pb_decode(_one(cmd, 19, b""))
            cursors.pop(_one(c, 1, 0), None)
            return [encode_frame(
                _pb_field(1, SUCCESS)
                + _pb_bytes(15, _pb_field(1, _one(c, 2, 0))))]
        return [self._error(f"unsupported command type {t}")]

    @staticmethod
    def _error(msg: str) -> bytes:
        err = _pb_field(1, 0) + _pb_field(2, 0) + _pb_str(3, msg)
        return encode_frame(_pb_field(1, ERROR) + _pb_bytes(16, err))

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
