"""File-backed durable stream: the Kafka-shaped plugin for the stream SPI.

Reference parity: pinot-plugins/pinot-stream-ingestion/pinot-kafka-2.0/
(KafkaConsumerFactory / KafkaPartitionLevelConsumer) against the SPI in
pinot-spi/.../spi/stream/StreamConsumerFactory.java. Kafka's essentials —
a durable partitioned append-only log, independent producer processes,
monotonically increasing per-partition offsets, restart-resume from a
committed offset — are modeled on the filesystem:

    <log_dir>/stream.json          {"numPartitions": N}
    <log_dir>/partition_<k>.log    one JSON object per line (the
                                   StreamDataDecoder analog is json.loads)

Offsets are ROW indexes (Kafka-like logical offsets, and what the
checkpoint accounting in realtime/manager.py expects). Consumers keep a
row->byte cursor so sequential fetches never rescan; a consumer created
at a non-zero offset (restart-resume) scans forward once. A partially
written trailing line (producer mid-append) is never consumed.

Producers may live in OTHER processes — each append is a single
write+flush of one line, and POSIX O_APPEND keeps concurrent producers'
lines intact.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Mapping, Optional

from .stream import MessageBatch, PartitionGroupConsumer, \
    StreamConsumerFactory, consume_faults

META_FILE = "stream.json"


def _log_path(log_dir: str, partition: int) -> str:
    return os.path.join(log_dir, f"partition_{partition}.log")


class FileLogProducer:
    """Appends JSON-line rows to partition logs (KafkaProducer analog;
    safe to run from any process)."""

    def __init__(self, log_dir: str, num_partitions: int = 1,
                 partitioner: Optional[Callable[[Mapping[str, Any]], int]]
                 = None):
        self.log_dir = log_dir
        self.num_partitions = num_partitions
        self._partitioner = partitioner
        os.makedirs(log_dir, exist_ok=True)
        meta = os.path.join(log_dir, META_FILE)
        if os.path.exists(meta):
            # the stream's partition count is fixed at creation (Kafka
            # topics don't silently change width either): adopt it so a
            # second producer process can't write to partitions no
            # consumer will ever read
            with open(meta) as fh:
                self.num_partitions = int(json.load(fh)["numPartitions"])
        else:
            tmp = meta + ".tmp"
            with open(tmp, "w") as fh:
                json.dump({"numPartitions": num_partitions}, fh)
            os.replace(tmp, meta)
        self._files = [open(_log_path(log_dir, p), "ab")
                       for p in range(self.num_partitions)]

    def produce(self, row: Mapping[str, Any],
                partition: Optional[int] = None) -> None:
        if partition is None:
            partition = (self._partitioner(row) % self.num_partitions
                         if self._partitioner else 0)
        line = json.dumps(row, separators=(",", ":")).encode() + b"\n"
        f = self._files[partition]
        f.write(line)
        f.flush()

    def produce_many(self, rows, partition: Optional[int] = None) -> None:
        for r in rows:
            self.produce(r, partition)

    def close(self) -> None:
        for f in self._files:
            f.close()


class FileLogStream(StreamConsumerFactory):
    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        with open(os.path.join(log_dir, META_FILE)) as fh:
            self._num_partitions = int(json.load(fh)["numPartitions"])

    def num_partitions(self) -> int:
        return self._num_partitions

    def create_consumer(self, partition: int) -> "FileLogConsumer":
        return FileLogConsumer(_log_path(self.log_dir, partition))


class FileLogConsumer(PartitionGroupConsumer):
    def __init__(self, path: str):
        self._path = path
        self._row = 0      # cursor: next row index ...
        self._byte = 0     # ... starts at this byte

    def _seek_to(self, fh, start_offset: int) -> None:
        if start_offset == self._row:
            fh.seek(self._byte)
            return
        # non-sequential start (restart-resume): scan forward once
        fh.seek(0)
        row = 0
        pos = 0
        while row < start_offset:
            line = fh.readline()
            if not line or not line.endswith(b"\n"):
                # fewer complete rows than requested: EOF fetch. readline
                # consumed the partial fragment — the cursor must point at
                # its START so the line is re-read once it completes
                fh.seek(pos)
                break
            row += 1
            pos = fh.tell()
        self._row, self._byte = row, pos

    def fetch(self, start_offset: int, max_messages: int) -> MessageBatch:
        consume_faults(f"file/{os.path.basename(self._path)}")
        if not os.path.exists(self._path):
            return MessageBatch([], start_offset)
        rows = []
        with open(self._path, "rb") as fh:
            self._seek_to(fh, start_offset)
            if self._row < start_offset:  # log shorter than start
                return MessageBatch([], start_offset)
            while len(rows) < max_messages:
                pos = fh.tell()
                line = fh.readline()
                if not line or not line.endswith(b"\n"):
                    fh.seek(pos)  # partial trailing line: not ours yet
                    break
                rows.append(json.loads(line))
            self._row += len(rows)
            self._byte = fh.tell()
        return MessageBatch(rows, start_offset + len(rows))

    def latest_offset(self) -> int:
        if not os.path.exists(self._path):
            return 0
        with open(self._path, "rb") as fh:
            return sum(1 for line in fh if line.endswith(b"\n"))
