"""Realtime table data manager: consume -> index -> seal -> commit -> resume.

Reference parity: pinot-core/.../data/manager/realtime/
RealtimeSegmentDataManager.java:121 (the 1863-line consumption state
machine: consumeLoop at :420, threshold state transitions :733-813) plus
the durable-state half of the SegmentCompletionProtocol
(pinot-common/.../protocols/SegmentCompletionProtocol.java:77-122): each
partition's committed segments and next stream offset are checkpointed
atomically, so a restarted server resumes exactly where the last COMMIT
left off — rows land in committed segments exactly once (the consuming
tail is re-consumed from the checkpoint, the at-least-once half Pinot
also has before a commit).

Single-process scope for this layer: the controller-arbitrated multi-
replica commit election lives with the cluster roles; the state machine
and durable checkpoint format here are the same ones that protocol
drives.

Lifecycle per partition (CONSUMING segment):
    state.json holds {partition: {seq, next_offset, segments: [...]}}
    loop: fetch(next_offset) -> MutableSegment.index each row
          row/time threshold reached -> seal:
              MutableSegment.seal -> immutable dir (start/end offsets in
              metadata) -> load + atomic swap into the table -> write
              state.json (tmp+rename) -> fresh MutableSegment at the
              committed offset
    restart: load committed segment dirs from state, resume consuming at
             next_offset.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..segment.immutable import ImmutableSegment
from ..segment.mutable import MutableSegment
from ..server.data_manager import TableDataManager
from ..spi.config import TableConfig
from ..spi.schema import Schema
from .stream import MessageBatch, StreamConfig

STATE_FILE = "state.json"
FETCH_BATCH = 10_000


class RealtimeTableDataManager(TableDataManager):
    def __init__(self, table_name: str, schema: Schema,
                 stream_config: StreamConfig, data_dir: str,
                 table_config: Optional[TableConfig] = None,
                 poll_interval: float = 0.02,
                 upsert_config=None, dedup_config=None,
                 completion_client=None):
        super().__init__(table_name)
        self.schema = schema
        self.stream_config = stream_config
        self.table_config = table_config or TableConfig(table_name)
        self.data_dir = data_dir
        self.poll_interval = poll_interval
        # controller-arbitrated commit (cluster.completion.CompletionClient);
        # None = standalone mode, seal locally without arbitration
        self.completion_client = completion_client
        self._last_report: Dict[int, float] = {}
        self.report_interval_s = 0.05
        os.makedirs(data_dir, exist_ok=True)

        self._mutables: Dict[int, MutableSegment] = {}
        self._mutable_age: Dict[int, float] = {}
        # non-dense stream offsets (Kinesis sequence numbers have gaps):
        # per partition, the stream offset of EVERY row in the consuming
        # mutable (MessageBatch.row_offsets), so the offset after any
        # sealed row count resolves exactly — even when a concurrent
        # external seal captures a row count mid-batch. In-memory only:
        # a restart falls back to the committed checkpoint, which
        # re-consumes the tail exactly like the dense path. Dense streams
        # (kafka/wirestream/file) publish no row_offsets and keep the
        # checkpoint+rows arithmetic unchanged.
        self._row_offsets: Dict[int, List[int]] = {}
        self._state: Dict[str, Dict[str, Any]] = self._load_state()
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._seal_lock = threading.Lock()

        # upsert/dedup metadata, per partition (PKs are partition-local,
        # same contract as the reference's partition managers)
        self._upsert: Dict[int, Any] = {}
        self._dedup: Dict[int, Any] = {}
        if upsert_config is not None and dedup_config is not None:
            raise ValueError("a table is upsert or dedup, not both")
        self.upsert_config = upsert_config
        self.dedup_config = dedup_config

        # pre-indexing row pipeline (CompositeTransformer before
        # MutableSegmentImpl.index, as in RealtimeSegmentDataManager).
        # Split at the filter stage so the filter sees the same rows it
        # would in batch ingestion (raw source columns, pre-coercion);
        # filtered rows are indexed then invalidated so stream-offset ==
        # doc-id accounting stays exact.
        from ..ingestion.transformers import (CompositeTransformer,
                                              FilterTransformer)
        self._pre_transformer = None
        self._row_filter: Optional[FilterTransformer] = None
        self._post_transformer = None
        if getattr(self.table_config, "ingestion", None):
            chain = CompositeTransformer.from_table_config(
                self.table_config, schema).transformers
            fidx = next((i for i, t in enumerate(chain)
                         if isinstance(t, FilterTransformer)), None)
            if fidx is None:
                self._pre_transformer = CompositeTransformer(chain)
            else:
                self._pre_transformer = CompositeTransformer(chain[:fidx])
                self._row_filter = chain[fidx]
                self._post_transformer = CompositeTransformer(
                    chain[fidx + 1:])

        factory = stream_config.make_consumer_factory()
        n_parts = factory.num_partitions()
        if upsert_config is not None:
            from ..upsert import PartitionUpsertMetadataManager
            for p in range(n_parts):
                self._upsert[p] = PartitionUpsertMetadataManager(
                    upsert_config)
        if dedup_config is not None:
            from ..upsert import PartitionDedupMetadataManager
            for p in range(n_parts):
                self._dedup[p] = PartitionDedupMetadataManager(dedup_config)

        # restart path: re-register committed segments from the checkpoint,
        # replaying PK metadata in commit order for upsert/dedup tables
        for pkey, pstate in self._state.items():
            p = int(pkey)
            for seg_name in pstate["segments"]:
                seg_dir = os.path.join(self.data_dir, seg_name)
                if not os.path.isdir(seg_dir):
                    continue
                seg = ImmutableSegment.load(seg_dir)
                self.add_segment(seg)
                self._replay_metadata(p, seg)

        for p in range(n_parts):
            self._partition_state(p)
            self._new_mutable(p)

    def _replay_metadata(self, p: int, seg: ImmutableSegment) -> None:
        if p in self._upsert:
            cfg = self.upsert_config
            pks = self._segment_pks(seg, cfg.pk_columns)
            if cfg.comparison_column is not None:
                cmps = list(np.asarray(
                    seg.raw_values(cfg.comparison_column)))
            else:
                start = seg.metadata.get("startOffset", 0)
                cmps = list(range(start, start + seg.n_docs))
            # the old mask stays visible until replay_segment publishes the
            # rebuilt one — clearing first would transiently expose
            # superseded rows to concurrent queries
            self._upsert[p].replay_segment(seg, pks, cmps)
            seg.persist_valid_docs()
        elif p in self._dedup:
            pks = self._segment_pks(seg, self.dedup_config.pk_columns)
            self._dedup[p].replay_segment(seg, pks)

    @staticmethod
    def _segment_pks(seg: ImmutableSegment, pk_cols) -> List[tuple]:
        arrays = [np.asarray(seg.raw_values(c)) for c in pk_cols]
        return list(zip(*[a.tolist() for a in arrays]))

    # -- durable state (segment ZK metadata analog) ------------------------
    def _state_path(self) -> str:
        return os.path.join(self.data_dir, STATE_FILE)

    def _load_state(self) -> Dict[str, Dict[str, Any]]:
        path = self._state_path()
        if os.path.exists(path):
            with open(path) as fh:
                return json.load(fh)
        return {}

    def _write_state(self) -> None:
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self._state, fh, indent=1)
        os.replace(tmp, self._state_path())  # atomic commit point

    def _partition_state(self, p: int) -> Dict[str, Any]:
        key = str(p)
        if key not in self._state:
            self._state[key] = {"seq": 0, "next_offset": 0, "segments": []}
        return self._state[key]

    # -- consuming segment lifecycle ---------------------------------------
    def _segment_name(self, p: int, seq: int) -> str:
        # <table>__<partition>__<seq> (LLCSegmentName analog)
        return f"{self.table_name}__{p}__{seq}"

    def _new_mutable(self, p: int) -> MutableSegment:
        st = self._partition_state(p)
        m = MutableSegment(self.schema,
                           self._segment_name(p, st["seq"]),
                           self.table_config)
        m.start_offset = st["next_offset"]
        self._mutables[p] = m
        self._mutable_age[p] = time.monotonic()
        self._row_offsets[p] = []
        return m

    def _stream_offset(self, p: int, rows: int) -> int:
        """Stream offset after `rows` rows of the consuming mutable: the
        recorded per-row offset when the stream publishes them (gapped
        Kinesis sequence numbers), else the dense checkpoint+rows
        arithmetic (kafka-style contiguous offsets)."""
        offs = self._row_offsets.get(p)
        if offs and 0 < rows <= len(offs):
            return offs[rows - 1] + 1
        return self._partition_state(p)["next_offset"] + rows

    def consume_once(self, p: int, consumer=None) -> int:
        """Drain currently-available messages for one partition; returns
        rows indexed. Deterministic entry point (tests + the thread loop)."""
        own = consumer is None
        if own:
            consumer = self.stream_config.consumer_factory.create_consumer(p)
        try:
            total = 0
            while True:
                m = self._mutables[p]
                # never overshoot the seal threshold inside one batch
                room = max(1, self.stream_config.flush_threshold_rows
                           - m.n_docs)
                offset = self._stream_offset(p, m.n_docs)
                batch: MessageBatch = consumer.fetch(
                    offset, min(FETCH_BATCH, room))
                if not batch.rows:
                    break
                self._index_rows(p, m, batch.rows, offset)
                if batch.row_offsets is not None:
                    offs = self._row_offsets[p]
                    if len(offs) + len(batch.row_offsets) == m.n_docs:
                        offs.extend(batch.row_offsets)
                    else:
                        # a stream that mixes offset-bearing and dense
                        # batches can't be tracked per-row; drop to the
                        # dense arithmetic (empty list stays empty)
                        self._row_offsets[p] = []
                total += len(batch.rows)
                self._maybe_seal(p)
            return total
        finally:
            if own:
                consumer.close()

    def _index_rows(self, p: int, m: MutableSegment, rows, offset: int
                    ) -> None:
        """Index a batch, maintaining upsert/dedup metadata per row.

        Dedup'd rows are still indexed but immediately invalidated — the
        stream offset accounting stays row = doc (the reference instead
        skips indexing; masks make skipping unnecessary here and keep
        offsets trivially exact)."""
        drop = None
        if self._pre_transformer is not None:
            try:
                rows = self._pre_transformer.transform(
                    [dict(r) for r in rows])
                if self._row_filter is not None:
                    drop = self._row_filter.drop_mask(rows)
                if self._post_transformer is not None:
                    rows = self._post_transformer.transform(rows)
            except Exception:
                # a poison batch must not kill the consumer thread
                # (realtimeRowsWithErrors in the reference): index
                # schema-shaped placeholders and invalidate them so
                # offset == doc accounting still holds
                from ..utils.metrics import global_metrics
                global_metrics.count("realtime_rows_with_errors",
                                    len(rows))
                rows = [{f.name: None for f in self.schema.fields}
                        for _ in rows]
                drop = np.ones(len(rows), dtype=bool)
        upsert = self._upsert.get(p)
        dedup = self._dedup.get(p)
        if upsert is None and dedup is None and drop is None:
            m.index_batch(rows)
            return
        for i, row in enumerate(rows):
            if drop is not None and drop[i]:
                m.invalidate_doc(m.index(row))  # ingestion-filtered row
            elif dedup is not None:
                doc = m.index(row)
                if dedup.should_drop(row):
                    m.invalidate_doc(doc)
            elif upsert is not None:
                # partial mode merges with the current live row BEFORE
                # indexing, so the indexed row is already the merged one
                row = upsert.prepare_row(row)
                doc = m.index(row)
                upsert.add_row(m, doc, row, offset + i)
            else:
                m.index(row)
        if upsert is not None:
            upsert.evict_expired()  # metadata TTL housekeeping per batch

    def _maybe_seal(self, p: int) -> None:
        m = self._mutables[p]
        cfg = self.stream_config
        age = time.monotonic() - self._mutable_age[p]
        if not (m.n_docs >= cfg.flush_threshold_rows or (
                m.n_docs > 0 and age >= cfg.flush_threshold_seconds)):
            return
        if self.completion_client is None:
            self.seal_partition(p)
        else:
            self._protocol_seal(p)

    def _protocol_seal(self, p: int) -> None:
        """Controller-arbitrated commit (SegmentCompletionProtocol client
        side): report the threshold, then act on the controller's verdict
        — COMMIT: build + split-commit; CATCHUP: keep consuming; HOLD:
        wait; COMMITTED: another replica won, download its artifact and
        resume from its end offset."""
        now = time.monotonic()
        if now - self._last_report.get(p, 0.0) < self.report_interval_s:
            return
        self._last_report[p] = now
        cc = self.completion_client
        m = self._mutables[p]
        name = m.name
        offset = self._stream_offset(p, m.n_docs)
        try:
            resp = cc.segment_consumed(self.table_name, name, offset)
        except Exception:
            return  # controller unreachable: report again next poll;
            # a network blip must never kill the consumer thread
        status = resp.get("status")
        if status == "COMMIT":
            # build-then-commit-then-adopt: local durable state advances
            # ONLY after the controller acknowledged the split commit —
            # a failed commit leaves the mutable live for retry/takeover
            with self._seal_lock:
                built = self._build_artifact(p)
                if built is None:
                    return
                mm, seg, sealed = built
                ok = False
                try:
                    from ..cluster.deepstore import pruning_metadata
                    ok = cc.split_commit(self.table_name, name, seg.dir,
                                         pruning_metadata(seg.dir))
                except Exception:
                    ok = False
                if ok:
                    self._commit_local(p, mm, seg, sealed)
                else:
                    import shutil
                    shutil.rmtree(seg.dir, ignore_errors=True)
        elif status == "COMMITTED":
            uri = resp.get("downloadURI")
            if uri is None:
                return  # nothing to adopt from; report again next poll
            off = resp.get("offset")
            try:
                # off may be None (registry fallback without offsets) —
                # _adopt_committed then derives it from the artifact's own
                # endOffset metadata, so the replica never stalls forever
                self._adopt_committed(
                    p, name, uri, None if off is None else int(off))
            except Exception:
                pass  # deep store unreachable: retry on the next poll
        # CATCHUP / HOLD: keep consuming / report again next poll

    def _adopt_committed(self, p: int, name: str, download_uri: str,
                         end_offset: Optional[int]) -> None:
        """A peer replica committed this segment: drop the local consuming
        state, download the canonical artifact, resume after it (the
        non-winner CONSUMING->ONLINE transition with deep-store
        download)."""
        from ..cluster.deepstore import download_segment
        with self._seal_lock:
            st = self._partition_state(p)
            if name in st["segments"]:
                return
            seg_dir = download_segment(download_uri, self.data_dir)
            seg = ImmutableSegment.load(seg_dir)
            if end_offset is None:
                end_offset = seg.metadata.get(
                    "endOffset", st["next_offset"] + seg.n_docs)
            self.add_segment(seg)
            st["next_offset"] = end_offset
            st["seq"] += 1
            st["segments"].append(name)
            self._write_state()
            self._new_mutable(p)
            # the discarded mutable polluted the upsert/dedup metadata
            # with rows past end_offset that will be re-consumed; rebuild
            # the partition's PK state from committed segments only, or
            # re-consumed rows would be dropped as phantom duplicates
            self._rebuild_partition_metadata(p)

    def _rebuild_partition_metadata(self, p: int) -> None:
        if p in self._upsert:
            from ..upsert import PartitionUpsertMetadataManager
            self._upsert[p] = PartitionUpsertMetadataManager(
                self.upsert_config)
        elif p in self._dedup:
            from ..upsert import PartitionDedupMetadataManager
            self._dedup[p] = PartitionDedupMetadataManager(
                self.dedup_config)
        else:
            return
        st = self._partition_state(p)
        by_name = {s.name: s for s in super().acquire_segments()}
        for seg_name in st["segments"]:
            seg = by_name.get(seg_name)
            if seg is not None:
                self._replay_metadata(p, seg)

    def _build_artifact(self, p: int):
        """Build the immutable artifact from the consuming segment WITHOUT
        touching durable state — the commit decision may still fail (split
        commit), and the mutable must stay live until it succeeds.
        Returns (mutable, segment, sealed_docs) or None when empty."""
        m = self._mutables[p]
        if m.n_docs == 0:
            return None
        st = self._partition_state(p)
        seg_dir = m.seal(self.data_dir)
        sealed = m.sealed_docs  # NOT m.n_docs: rows indexed during the
        # build are absent from the artifact and must be re-consumed
        # record offsets in segment metadata for lineage/debug
        meta_path = os.path.join(seg_dir, "metadata.json")
        with open(meta_path) as fh:
            meta = json.load(fh)
        meta["startOffset"] = st["next_offset"]
        meta["endOffset"] = self._stream_offset(p, sealed)
        meta["partition"] = p
        with open(meta_path, "w") as fh:
            json.dump(meta, fh, indent=1)

        seg = ImmutableSegment.load(seg_dir)
        # upsert/dedup: carry the consuming segment's validDocIds into
        # the committed artifact and repoint PK locations at it
        valid = m.valid_mask(sealed)
        if not valid.all():
            seg.set_valid_docs(valid.copy())
            seg.persist_valid_docs()
        return m, seg, sealed

    def _commit_local(self, p: int, m, seg: ImmutableSegment,
                      sealed: int) -> None:
        """Second half of the seal: swap + checkpoint + fresh mutable."""
        st = self._partition_state(p)
        if p in self._upsert:
            self._upsert[p].remap_segment(m, seg, sealed)
        self.add_segment(seg)  # atomic swap: queries see it immediately
        st["next_offset"] = self._stream_offset(p, sealed)
        st["seq"] += 1
        st["segments"].append(m.name)
        self._write_state()
        self._new_mutable(p)

    def seal_partition(self, p: int) -> Optional[ImmutableSegment]:
        """CONSUMING -> ONLINE: build, swap, checkpoint (standalone
        mode — no controller arbitration)."""
        with self._seal_lock:
            built = self._build_artifact(p)
            if built is None:
                return None
            m, seg, sealed = built
            self._commit_local(p, m, seg, sealed)
            return seg

    # -- background consumption (PartitionConsumer.run analog) -------------
    def start(self) -> None:
        factory = self.stream_config.consumer_factory
        for p in range(factory.num_partitions()):
            t = threading.Thread(target=self._consume_loop, args=(p,),
                                 name=f"consumer-{self.table_name}-{p}",
                                 daemon=True)
            self._threads.append(t)
            t.start()

    def _consume_loop(self, p: int) -> None:
        consumer = self.stream_config.consumer_factory.create_consumer(p)
        try:
            while not self._stop.is_set():
                n = self.consume_once(p, consumer)
                self._maybe_seal(p)
                if n == 0:
                    self._stop.wait(self.poll_interval)
        finally:
            consumer.close()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout)
        self._threads.clear()

    # -- query integration --------------------------------------------------
    def acquire_segments(self):
        """Committed immutables + consuming snapshots (hybrid view)."""
        segs = list(super().acquire_segments())
        for m in self._mutables.values():
            view = m.snapshot()
            if view.n_docs > 0:
                segs.append(view)
        return segs

    @property
    def consuming_docs(self) -> int:
        return sum(m.n_docs for m in self._mutables.values())
