"""Realtime table data manager: consume -> index -> seal -> commit -> resume.

Reference parity: pinot-core/.../data/manager/realtime/
RealtimeSegmentDataManager.java:121 (the 1863-line consumption state
machine: consumeLoop at :420, threshold state transitions :733-813) plus
the durable-state half of the SegmentCompletionProtocol
(pinot-common/.../protocols/SegmentCompletionProtocol.java:77-122): each
partition's committed segments and next stream offset are checkpointed
atomically, so a restarted server resumes exactly where the last COMMIT
left off — rows land in committed segments exactly once (the consuming
tail is re-consumed from the checkpoint, the at-least-once half Pinot
also has before a commit).

Single-process scope for this layer: the controller-arbitrated multi-
replica commit election lives with the cluster roles; the state machine
and durable checkpoint format here are the same ones that protocol
drives.

Lifecycle per partition (CONSUMING segment):
    state.json holds {partition: {seq, next_offset, segments: [...]}}
    loop: fetch(next_offset) -> MutableSegment.index each row
          row/time threshold reached -> seal:
              MutableSegment.seal -> immutable dir (start/end offsets in
              metadata) -> load + atomic swap into the table -> write
              state.json (tmp+rename) -> fresh MutableSegment at the
              committed offset
    restart: load committed segment dirs from state, resume consuming at
             next_offset.

Chaos hardening (utils/faults.py ingest family): consumer reads run
under bounded retry-with-backoff (``stream.error``), an injected
rebalance (``stream.rebalance``) snaps the partition back to its
checkpoint exactly like a restart, ``commit.crash`` /
``upsert.compact_crash`` raise IngestCrash (abandon + restart — the
orphan-artifact cleanup at construction makes the restart idempotent),
and completion-protocol RPC failures (``commit.http_error``) re-enter
the HOLD/CATCHUP loop on the next poll. Every recovery event lands in
the per-table ingest stats (write_ingest_stats -> ``ingest_stats``
ledger records) and the ``ingest_*`` global_metrics counters.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..segment.immutable import ImmutableSegment
from ..segment.mutable import MutableSegment
from ..server.data_manager import TableDataManager
from ..spi.config import TableConfig
from ..spi.schema import Schema
from ..utils import faults
from ..utils.metrics import global_metrics
from .stream import MessageBatch, OffsetOutOfRange, StreamConfig

STATE_FILE = "state.json"
FETCH_BATCH = 10_000

# gauge name -> id(manager) of the last writer: several managers of the
# SAME table in one process (replicas) last-writer-wins the shared
# per-table freshness gauge, so stop() must only remove it when this
# manager was the latest writer — never a live replica's reading.
# Module-global state needs a MODULE lock (concur CC201/CC205): each
# replica's _stats_lock is a different object, so it excludes nothing
# across replicas, and stop()'s owner check-then-act raced a live
# replica's write — the stopping manager could still delete the gauge
# the live one had just refreshed.
_FRESHNESS_OWNERS: Dict[str, int] = {}
_FRESHNESS_LOCK = threading.Lock()


class RealtimeTableDataManager(TableDataManager):
    def __init__(self, table_name: str, schema: Schema,
                 stream_config: StreamConfig, data_dir: str,
                 table_config: Optional[TableConfig] = None,
                 poll_interval: float = 0.02,
                 upsert_config=None, dedup_config=None,
                 completion_client=None):
        super().__init__(table_name)
        self.schema = schema
        self.stream_config = stream_config
        self.table_config = table_config or TableConfig(table_name)
        self.data_dir = data_dir
        self.poll_interval = poll_interval
        # controller-arbitrated commit (cluster.completion.CompletionClient);
        # None = standalone mode, seal locally without arbitration
        self.completion_client = completion_client
        self._last_report: Dict[int, float] = {}
        self.report_interval_s = 0.05
        os.makedirs(data_dir, exist_ok=True)

        self._mutables: Dict[int, MutableSegment] = {}
        self._mutable_age: Dict[int, float] = {}
        # non-dense stream offsets (Kinesis sequence numbers have gaps):
        # per partition, the stream offset of EVERY row in the consuming
        # mutable (MessageBatch.row_offsets), so the offset after any
        # sealed row count resolves exactly — even when a concurrent
        # external seal captures a row count mid-batch. In-memory only:
        # a restart falls back to the committed checkpoint, which
        # re-consumes the tail exactly like the dense path. Dense streams
        # (kafka/wirestream/file) publish no row_offsets and keep the
        # checkpoint+rows arithmetic unchanged.
        self._row_offsets: Dict[int, List[int]] = {}
        self._state: Dict[str, Dict[str, Any]] = self._load_state()
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._seal_lock = threading.Lock()

        # ingest stats (freshness ledger writer side + ingest_* counters)
        self._stats_lock = threading.Lock()
        self._stats: Dict[str, int] = {
            "rows": 0, "commits": 0, "commit_retries": 0,
            "commit_failures": 0, "rebalance_resets": 0,
            "stream_retries": 0, "upsert_replays": 0,
            "orphans_cleaned": 0, "handoff_retries": 0}
        self._ingest_t0: Optional[float] = None
        self._freshness_ms: Optional[float] = None
        # commit latency (seal -> durable checkpoint, split-commit RPC
        # included on the protocol path): EWMA for the ledger +
        # a bounded raw history for percentile-grade consumers
        # (engine/loadgen). Both guarded by _stats_lock.
        self._commit_ewma: Optional[float] = None
        self._commit_ms_hist: List[float] = []
        self._clean_orphans()

        # upsert/dedup metadata, per partition (PKs are partition-local,
        # same contract as the reference's partition managers)
        self._upsert: Dict[int, Any] = {}
        self._dedup: Dict[int, Any] = {}
        if upsert_config is not None and dedup_config is not None:
            raise ValueError("a table is upsert or dedup, not both")
        self.upsert_config = upsert_config
        self.dedup_config = dedup_config

        # pre-indexing row pipeline (CompositeTransformer before
        # MutableSegmentImpl.index, as in RealtimeSegmentDataManager).
        # Split at the filter stage so the filter sees the same rows it
        # would in batch ingestion (raw source columns, pre-coercion);
        # filtered rows are indexed then invalidated so stream-offset ==
        # doc-id accounting stays exact.
        from ..ingestion.transformers import (CompositeTransformer,
                                              FilterTransformer)
        self._pre_transformer = None
        self._row_filter: Optional[FilterTransformer] = None
        self._post_transformer = None
        if getattr(self.table_config, "ingestion", None):
            chain = CompositeTransformer.from_table_config(
                self.table_config, schema).transformers
            fidx = next((i for i, t in enumerate(chain)
                         if isinstance(t, FilterTransformer)), None)
            if fidx is None:
                self._pre_transformer = CompositeTransformer(chain)
            else:
                self._pre_transformer = CompositeTransformer(chain[:fidx])
                self._row_filter = chain[fidx]
                self._post_transformer = CompositeTransformer(
                    chain[fidx + 1:])

        factory = stream_config.make_consumer_factory()
        n_parts = factory.num_partitions()
        if upsert_config is not None:
            from ..upsert import PartitionUpsertMetadataManager
            for p in range(n_parts):
                self._upsert[p] = PartitionUpsertMetadataManager(
                    upsert_config, site_key=f"{table_name}/{p}")
        if dedup_config is not None:
            from ..upsert import PartitionDedupMetadataManager
            for p in range(n_parts):
                self._dedup[p] = PartitionDedupMetadataManager(dedup_config)

        # restart path: re-register committed segments from the checkpoint,
        # replaying PK metadata in commit order for upsert/dedup tables
        for pkey, pstate in self._state.items():
            p = int(pkey)
            for seg_name in pstate["segments"]:
                seg_dir = os.path.join(self.data_dir, seg_name)
                if not os.path.isdir(seg_dir):
                    continue
                seg = ImmutableSegment.load(seg_dir)
                self.add_segment(seg)
                self._replay_metadata(p, seg)

        for p in range(n_parts):
            self._partition_state(p)
            self._new_mutable(p)

    def _count_stat(self, name: str, n: int = 1) -> None:
        with self._stats_lock:
            self._stats[name] += n
        global_metrics.count("ingest_" + name, n)

    def _note_commit_ms(self, ms: float) -> None:
        """One committed segment's seal->checkpoint latency."""
        with self._stats_lock:
            e = self._commit_ewma
            self._commit_ewma = ms if e is None else 0.8 * e + 0.2 * ms
            self._commit_ms_hist.append(ms)
            if len(self._commit_ms_hist) > 4096:
                del self._commit_ms_hist[:2048]

    def commit_latencies(self) -> List[float]:
        """Raw per-commit latencies (ms, bounded history) — the
        percentile inputs engine/loadgen aggregates into the
        ``ingest_bench`` ledger record."""
        with self._stats_lock:
            return list(self._commit_ms_hist)

    def _clean_orphans(self) -> None:
        """Idempotent-restart hygiene: a crash between the segment build
        and the checkpoint ``os.replace`` (the commit.crash window)
        leaves a built artifact directory the durable state never
        adopted. Remove it — its rows re-consume from the checkpoint,
        and the next seal reuses the same directory name — plus any torn
        ``state.json.tmp`` whose rename never happened."""
        import shutil
        committed = {s for pstate in self._state.values()
                     for s in pstate["segments"]}
        prefix = f"{self.table_name}__"
        for entry in sorted(os.listdir(self.data_dir)):
            path = os.path.join(self.data_dir, entry)
            if entry.startswith(prefix) and entry not in committed \
                    and os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
                self._count_stat("orphans_cleaned")
        tmp = self._state_path() + ".tmp"
        if os.path.exists(tmp):
            os.remove(tmp)

    def _replay_metadata(self, p: int, seg: ImmutableSegment) -> None:
        if p in self._upsert:
            self._count_stat("upsert_replays")
            cfg = self.upsert_config
            pks = self._segment_pks(seg, cfg.pk_columns)
            if cfg.comparison_column is not None:
                cmps = list(np.asarray(
                    seg.raw_values(cfg.comparison_column)))
            else:
                start = seg.metadata.get("startOffset", 0)
                cmps = list(range(start, start + seg.n_docs))
            # the old mask stays visible until replay_segment publishes the
            # rebuilt one — clearing first would transiently expose
            # superseded rows to concurrent queries
            self._upsert[p].replay_segment(seg, pks, cmps)
            seg.persist_valid_docs()
        elif p in self._dedup:
            pks = self._segment_pks(seg, self.dedup_config.pk_columns)
            self._dedup[p].replay_segment(seg, pks)

    @staticmethod
    def _segment_pks(seg: ImmutableSegment, pk_cols) -> List[tuple]:
        arrays = [np.asarray(seg.raw_values(c)) for c in pk_cols]
        return list(zip(*[a.tolist() for a in arrays]))

    # -- durable state (segment ZK metadata analog) ------------------------
    def _state_path(self) -> str:
        return os.path.join(self.data_dir, STATE_FILE)

    def _load_state(self) -> Dict[str, Dict[str, Any]]:
        path = self._state_path()
        if os.path.exists(path):
            with open(path) as fh:
                return json.load(fh)
        return {}

    def _write_state(self) -> None:
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self._state, fh, indent=1)
        os.replace(tmp, self._state_path())  # atomic commit point

    def _partition_state(self, p: int) -> Dict[str, Any]:
        # single-writer per partition key: only p's consume thread (or
        # the seal/rebalance paths, which hold _seal_lock AND quiesce
        # the partition first) touches str(p)'s entry, and dict element
        # stores are GIL-atomic — no lock needed on the per-row path
        key = str(p)
        if key not in self._state:  # concur: ok CC205
            st = {"seq": 0, "next_offset": 0, "segments": []}
            self._state[key] = st  # concur: ok CC201
        return self._state[key]

    # -- consuming segment lifecycle ---------------------------------------
    def _segment_name(self, p: int, seq: int) -> str:
        # <table>__<partition>__<seq> (LLCSegmentName analog)
        return f"{self.table_name}__{p}__{seq}"

    def _new_mutable(self, p: int) -> MutableSegment:
        st = self._partition_state(p)
        m = MutableSegment(self.schema,
                           self._segment_name(p, st["seq"]),
                           self.table_config)
        m.start_offset = st["next_offset"]
        # same single-writer-per-partition rule as _partition_state
        self._mutables[p] = m  # concur: ok CC201
        self._mutable_age[p] = time.monotonic()  # concur: ok CC201
        self._row_offsets[p] = []  # concur: ok CC201
        return m

    def _stream_offset(self, p: int, rows: int) -> int:
        """Stream offset after `rows` rows of the consuming mutable: the
        recorded per-row offset when the stream publishes them (gapped
        Kinesis sequence numbers), else the dense checkpoint+rows
        arithmetic (kafka-style contiguous offsets)."""
        offs = self._row_offsets.get(p)
        if offs and 0 < rows <= len(offs):
            return offs[rows - 1] + 1
        return self._partition_state(p)["next_offset"] + rows

    def consume_once(self, p: int, consumer=None) -> int:
        """Drain currently-available messages for one partition; returns
        rows indexed. Deterministic entry point (tests + the thread loop)."""
        own = consumer is None
        if own:
            consumer = self.stream_config.consumer_factory.create_consumer(p)
        try:
            total = 0
            snapped_back = False
            while True:
                if faults.active() and faults.fault_fires(
                        "stream.rebalance", f"{self.table_name}/{p}"):
                    self._rebalance_reset(p)
                m = self._mutables[p]
                # never overshoot the seal threshold inside one batch
                room = max(1, self.stream_config.flush_threshold_rows
                           - m.n_docs)
                offset = self._stream_offset(p, m.n_docs)
                t_fetch = time.monotonic()
                try:
                    batch: MessageBatch = self._fetch_with_retry(
                        consumer, offset, min(FETCH_BATCH, room))
                except OffsetOutOfRange:
                    # a REAL offset snap-back (log truncation, expired
                    # iterator): same recovery as the injected
                    # stream.rebalance — resume from the checkpoint. One
                    # reset per drain; if the checkpoint offset is gone
                    # too, propagate to the consume loop's poll backoff
                    if snapped_back:
                        raise
                    snapped_back = True
                    self._rebalance_reset(p)
                    continue
                if not batch.rows:
                    break
                self._index_rows(p, m, batch.rows, offset)
                if batch.row_offsets is not None:
                    offs = self._row_offsets[p]
                    if len(offs) + len(batch.row_offsets) == m.n_docs:
                        offs.extend(batch.row_offsets)
                    else:
                        # a stream that mixes offset-bearing and dense
                        # batches can't be tracked per-row; drop to the
                        # dense arithmetic (empty list stays empty);
                        # single-writer per partition key (see
                        # _partition_state)
                        self._row_offsets[p] = []  # concur: ok CC201
                total += len(batch.rows)
                self._note_batch(len(batch.rows), t_fetch)
                self._maybe_seal(p)
            return total
        finally:
            if own:
                consumer.close()

    def _retry_bounded(self, call: Callable[[], Any], stat: str) -> Any:
        """Bounded retry-with-backoff (StreamConfig.fetch_retries /
        fetch_backoff_s — one tuning pair for the whole ingest plane):
        a transient failure (injected or real) must neither kill the
        consumer thread nor skip work. Each retry bumps ``stat``;
        exhaustion re-raises and the caller falls back to its
        poll-again path. IngestCrash is never retried — it IS the
        process dying."""
        cfg = self.stream_config
        for attempt in range(cfg.fetch_retries + 1):
            try:
                return call()
            except faults.IngestCrash:
                raise
            except OffsetOutOfRange:
                raise  # the offset is gone: retrying can never succeed
            except Exception:
                if attempt == cfg.fetch_retries:
                    raise
                self._count_stat(stat)
                time.sleep(cfg.fetch_backoff_s * (2 ** attempt))
        raise AssertionError("unreachable")

    def _fetch_with_retry(self, consumer, offset: int, limit: int
                          ) -> MessageBatch:
        """One consumer read under bounded retry: the checkpoint
        guarantees an exact re-read after exhaustion."""
        return self._retry_bounded(
            lambda: consumer.fetch(offset, limit), "stream_retries")

    def _note_batch(self, rows: int, t_fetch: float) -> None:
        """Freshness accounting per indexed batch: fetch-start ->
        queryable latency EWMA (rows are queryable the moment they are
        indexed — snapshot views include them) + the rows/sec inputs."""
        self._count_stat("rows", rows)
        lat_ms = (time.monotonic() - t_fetch) * 1e3
        with self._stats_lock:
            if self._ingest_t0 is None:
                self._ingest_t0 = t_fetch
            f = self._freshness_ms
            self._freshness_ms = lat_ms if f is None \
                else 0.8 * f + 0.2 * lat_ms
            # per-table gauge: two TABLES in one process must not
            # last-writer-wins each other's freshness on the consoles
            # (replicas of the same table still share one gauge —
            # _FRESHNESS_OWNERS guards removal, not the readings)
            gname = "ingest_freshness_ms_" + self.table_name
            with _FRESHNESS_LOCK:
                # gauge write + ownership record are atomic vs stop():
                # a stopping replica either sees this manager as owner
                # (and this gauge survives via its next write) or
                # removes strictly older state
                global_metrics.gauge(gname,
                                     round(self._freshness_ms, 3))
                _FRESHNESS_OWNERS[gname] = id(self)

    def _rebalance_reset(self, p: int) -> None:
        """Partition offsets snapped back (consumer-group rebalance /
        OffsetOutOfRange): drop the consuming mutable and resume from
        the durable checkpoint — the restart path minus the process
        death. Upsert/dedup PK state polluted by the discarded rows is
        rebuilt from committed segments only (phantom-duplicate rule,
        same as _adopt_committed)."""
        with self._seal_lock:
            discarded = self._mutables[p].n_docs
            self._new_mutable(p)
            self._rebuild_partition_metadata(p)
        if discarded:
            # the discarded consuming rows re-consume from the
            # checkpoint and would be counted again: back them out so
            # rows / rows_per_s mean DELIVERED rows — the freshness
            # ledger must not overstate throughput exactly on the chaos
            # runs it exists to measure
            self._count_stat("rows", -discarded)
        self._count_stat("rebalance_resets")

    def _index_rows(self, p: int, m: MutableSegment, rows, offset: int
                    ) -> None:
        """Index a batch, maintaining upsert/dedup metadata per row.

        Dedup'd rows are still indexed but immediately invalidated — the
        stream offset accounting stays row = doc (the reference instead
        skips indexing; masks make skipping unnecessary here and keep
        offsets trivially exact)."""
        drop = None
        if self._pre_transformer is not None:
            try:
                rows = self._pre_transformer.transform(
                    [dict(r) for r in rows])
                if self._row_filter is not None:
                    drop = self._row_filter.drop_mask(rows)
                if self._post_transformer is not None:
                    rows = self._post_transformer.transform(rows)
            except Exception:
                # a poison batch must not kill the consumer thread
                # (realtimeRowsWithErrors in the reference): index
                # schema-shaped placeholders and invalidate them so
                # offset == doc accounting still holds
                from ..utils.metrics import global_metrics
                global_metrics.count("realtime_rows_with_errors",
                                    len(rows))
                rows = [{f.name: None for f in self.schema.fields}
                        for _ in rows]
                drop = np.ones(len(rows), dtype=bool)
        upsert = self._upsert.get(p)
        dedup = self._dedup.get(p)
        if upsert is None and dedup is None and drop is None:
            m.index_batch(rows)
            return
        for i, row in enumerate(rows):
            if drop is not None and drop[i]:
                m.invalidate_doc(m.index(row))  # ingestion-filtered row
            elif dedup is not None:
                doc = m.index(row)
                if dedup.should_drop(row):
                    m.invalidate_doc(doc)
            elif upsert is not None:
                # partial mode merges with the current live row BEFORE
                # indexing, so the indexed row is already the merged one
                row = upsert.prepare_row(row)
                doc = m.index(row)
                upsert.add_row(m, doc, row, offset + i)
            else:
                m.index(row)
        if upsert is not None:
            upsert.evict_expired()  # metadata TTL housekeeping per batch

    def _maybe_seal(self, p: int) -> None:
        m = self._mutables[p]
        cfg = self.stream_config
        age = time.monotonic() - self._mutable_age[p]
        if not (m.n_docs >= cfg.flush_threshold_rows or (
                m.n_docs > 0 and age >= cfg.flush_threshold_seconds)):
            return
        if self.completion_client is None:
            self.seal_partition(p)
        else:
            self._protocol_seal(p)

    def _protocol_seal(self, p: int) -> None:
        """Controller-arbitrated commit (SegmentCompletionProtocol client
        side): report the threshold, then act on the controller's verdict
        — COMMIT: build + split-commit; CATCHUP: keep consuming; HOLD:
        wait; COMMITTED: another replica won, download its artifact and
        resume from its end offset."""
        now = time.monotonic()
        if now - self._last_report.get(p, 0.0) < self.report_interval_s:
            return
        self._last_report[p] = now
        cc = self.completion_client
        m = self._mutables[p]
        name = m.name
        offset = self._stream_offset(p, m.n_docs)
        try:
            resp = self._completion_rpc(
                lambda: cc.segment_consumed(self.table_name, name,
                                            offset))
        except faults.IngestCrash:
            raise
        except Exception:
            return  # controller unreachable past the bounded retries:
            # report again next poll (HOLD/CATCHUP re-entry); a network
            # blip must never kill the consumer thread
        status = resp.get("status")
        if status == "COMMIT":
            # build-then-commit-then-adopt: local durable state advances
            # ONLY after the controller acknowledged the split commit —
            # a failed commit leaves the mutable live for retry/takeover
            t_commit = time.monotonic()
            with self._seal_lock:
                built = self._build_artifact(p)
            if built is None:
                return
            mm, seg, sealed = built
            ok = False
            try:
                from ..cluster.deepstore import pruning_metadata
                # the RPC (and its retry-backoff ladder) runs OUTSIDE
                # the table-wide seal lock: a flaky controller must not
                # stall other partitions' seal/adopt. Partition p's
                # state can't move underneath us — only p's own
                # consumer thread seals/adopts/resets p
                ok = self._completion_rpc(
                    lambda: cc.split_commit(self.table_name, name,
                                            seg.dir,
                                            pruning_metadata(seg.dir)))
            except faults.IngestCrash:
                raise
            except Exception:
                ok = False
            if ok:
                with self._seal_lock:
                    self._commit_local(p, mm, seg, sealed)
                self._note_commit_ms(
                    (time.monotonic() - t_commit) * 1e3)
            else:
                # the mutable stays live: the next poll re-reports,
                # the controller re-elects/continues, and the build
                # runs again (split-commit re-entry)
                self._count_stat("commit_failures")
                import shutil
                shutil.rmtree(seg.dir, ignore_errors=True)
        elif status == "COMMITTED":
            uri = resp.get("downloadURI")
            if uri is None:
                return  # nothing to adopt from; report again next poll
            off = resp.get("offset")
            try:
                # off may be None (registry fallback without offsets) —
                # _adopt_committed then derives it from the artifact's own
                # endOffset metadata, so the replica never stalls forever
                self._adopt_committed(
                    p, name, uri, None if off is None else int(off))
            except faults.IngestCrash:
                raise
            except Exception:
                # deep store stalled/corrupt (handoff.stall) or
                # unreachable: retry on the next poll
                self._count_stat("handoff_retries")
        # CATCHUP / HOLD: keep consuming / report again next poll

    def _completion_rpc(self, call: Callable[[], Any]) -> Any:
        """A completion-protocol RPC (injected commit.http_error or a
        real controller blip) under bounded retry; exhaustion falls back
        to report-again-next-poll at the caller."""
        return self._retry_bounded(call, "commit_retries")

    def _adopt_committed(self, p: int, name: str, download_uri: str,
                         end_offset: Optional[int]) -> None:
        """A peer replica committed this segment: drop the local consuming
        state, download the canonical artifact, resume after it (the
        non-winner CONSUMING->ONLINE transition with deep-store
        download)."""
        from ..cluster.deepstore import download_segment
        with self._seal_lock:
            if name in self._partition_state(p)["segments"]:
                return
        # the download (and any handoff stall, injected or real) runs
        # OUTSIDE the table-wide seal lock — same rule as the
        # split-commit RPC: one wedged deep store must not freeze other
        # partitions' seal/adopt. Only p's own consumer thread adopts p,
        # so p's state can't move underneath us
        seg_dir = download_segment(download_uri, self.data_dir)
        seg = ImmutableSegment.load(seg_dir)
        recount = 0
        with self._seal_lock:
            st = self._partition_state(p)
            if name in st["segments"]:
                return
            if end_offset is None:
                end_offset = seg.metadata.get(
                    "endOffset", st["next_offset"] + seg.n_docs)
            # the consuming tail past the adopted artifact's end will be
            # fetched (and counted) again: back it out below so
            # rows/rows_per_s keep meaning DELIVERED rows (approximate
            # under gapped kinesis sequence numbers, exact for dense)
            m = self._mutables[p]
            recount = max(0, self._stream_offset(p, m.n_docs)
                          - int(end_offset))
            self.add_segment(seg)
            st["next_offset"] = end_offset
            st["seq"] += 1
            st["segments"].append(name)
            self._write_state()
            self._new_mutable(p)
            # the discarded mutable polluted the upsert/dedup metadata
            # with rows past end_offset that will be re-consumed; rebuild
            # the partition's PK state from committed segments only, or
            # re-consumed rows would be dropped as phantom duplicates
            self._rebuild_partition_metadata(p)
        if recount:
            self._count_stat("rows", -recount)

    def _rebuild_partition_metadata(self, p: int) -> None:
        if p in self._upsert:
            from ..upsert import PartitionUpsertMetadataManager
            self._upsert[p] = PartitionUpsertMetadataManager(
                self.upsert_config, site_key=f"{self.table_name}/{p}")
        elif p in self._dedup:
            from ..upsert import PartitionDedupMetadataManager
            self._dedup[p] = PartitionDedupMetadataManager(
                self.dedup_config)
        else:
            return
        st = self._partition_state(p)
        by_name = {s.name: s for s in super().acquire_segments()}
        for seg_name in st["segments"]:
            seg = by_name.get(seg_name)
            if seg is not None:
                self._replay_metadata(p, seg)

    def _build_artifact(self, p: int):
        """Build the immutable artifact from the consuming segment WITHOUT
        touching durable state — the commit decision may still fail (split
        commit), and the mutable must stay live until it succeeds.
        Returns (mutable, segment, sealed_docs) or None when empty."""
        m = self._mutables[p]
        if m.n_docs == 0:
            return None
        st = self._partition_state(p)
        seg_dir = m.seal(self.data_dir)
        sealed = m.sealed_docs  # NOT m.n_docs: rows indexed during the
        # build are absent from the artifact and must be re-consumed
        # record offsets in segment metadata for lineage/debug
        meta_path = os.path.join(seg_dir, "metadata.json")
        with open(meta_path) as fh:
            meta = json.load(fh)
        meta["startOffset"] = st["next_offset"]
        meta["endOffset"] = self._stream_offset(p, sealed)
        meta["partition"] = p
        with open(meta_path, "w") as fh:
            json.dump(meta, fh, indent=1)

        seg = ImmutableSegment.load(seg_dir)
        # upsert/dedup: carry the consuming segment's validDocIds into
        # the committed artifact and repoint PK locations at it
        valid = m.valid_mask(sealed)
        if not valid.all():
            seg.set_valid_docs(valid.copy())
            seg.persist_valid_docs()
        return m, seg, sealed

    def _commit_local(self, p: int, m, seg: ImmutableSegment,
                      sealed: int) -> None:
        """Second half of the seal: swap + checkpoint + fresh mutable."""
        if faults.active() and faults.fault_fires("commit.crash", m.name):
            # the commit.crash window: artifact built (and, on the
            # protocol path, split-committed) but the checkpoint
            # os.replace never ran — restart must re-consume the tail
            # exactly once (orphan cleanup + checkpoint replay)
            raise faults.IngestCrash(
                f"injected commit.crash before checkpoint ({m.name})")
        st = self._partition_state(p)
        if p in self._upsert:
            self._upsert[p].remap_segment(m, seg, sealed)
        self.add_segment(seg)  # atomic swap: queries see it immediately
        st["next_offset"] = self._stream_offset(p, sealed)
        st["seq"] += 1
        st["segments"].append(m.name)
        self._write_state()
        self._new_mutable(p)
        self._count_stat("commits")

    def seal_partition(self, p: int) -> Optional[ImmutableSegment]:
        """CONSUMING -> ONLINE: build, swap, checkpoint (standalone
        mode — no controller arbitration)."""
        t_commit = time.monotonic()
        with self._seal_lock:
            built = self._build_artifact(p)
            if built is None:
                return None
            m, seg, sealed = built
            self._commit_local(p, m, seg, sealed)
        self._note_commit_ms((time.monotonic() - t_commit) * 1e3)
        return seg

    # -- background consumption (PartitionConsumer.run analog) -------------
    def start(self) -> None:
        factory = self.stream_config.consumer_factory
        for p in range(factory.num_partitions()):
            t = threading.Thread(target=self._consume_loop, args=(p,),
                                 name=f"consumer-{self.table_name}-{p}",
                                 daemon=True)
            self._threads.append(t)
            t.start()

    def _consume_loop(self, p: int) -> None:
        consumer = self.stream_config.consumer_factory.create_consumer(p)
        try:
            while not self._stop.is_set():
                try:
                    n = self.consume_once(p, consumer)
                    self._maybe_seal(p)
                except faults.IngestCrash:
                    raise  # simulated process death: the loop dies too
                except Exception:
                    # transient trouble past the bounded retries: back
                    # off one poll interval, keep the consumer alive
                    global_metrics.count("ingest_consume_errors")
                    n = 0
                if n == 0:
                    self._stop.wait(self.poll_interval)
        finally:
            consumer.close()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout)
        self._threads.clear()
        # drop this table's freshness gauge: ingest_health rolls up the
        # WORST table, and a dead table's last EWMA would pin it
        # forever. Owner-guarded: a stopped replica must not delete a
        # live replica's reading
        gname = "ingest_freshness_ms_" + self.table_name
        with _FRESHNESS_LOCK:
            if _FRESHNESS_OWNERS.get(gname) == id(self):
                global_metrics.remove_gauge(gname)
                _FRESHNESS_OWNERS.pop(gname, None)

    # -- query integration --------------------------------------------------
    def acquire_segments(self):
        """Committed immutables + consuming snapshots (hybrid view)."""
        segs = list(super().acquire_segments())
        for m in self._mutables.values():
            view = m.snapshot()
            if view.n_docs > 0:
                segs.append(view)
        return segs

    @property
    def consuming_docs(self) -> int:
        return sum(m.n_docs for m in self._mutables.values())

    # -- freshness ledger ---------------------------------------------------
    def ingest_stats(self) -> Dict[str, Any]:
        """The freshness ledger's writer-side view: rows/sec since the
        first consume, end-to-end freshness (fetch-start -> queryable
        EWMA, ms), commit/retry/recovery counters, and the faults fired
        by the installed plan (0 when none). ``faults_fired`` is the
        plan's PROCESS-WIDE total — a fault plan has no per-table
        attribution, so multi-table processes see the same number in
        every table's record; single-table chaos runs that need the
        per-run count pass it explicitly (tools/chaos_smoke.py)."""
        with self._stats_lock:
            stats = dict(self._stats)
            t0 = self._ingest_t0
            fresh = self._freshness_ms
            commit = self._commit_ewma
        elapsed = (time.monotonic() - t0) if t0 is not None else 0.0
        plan = faults.current_plan()
        # every counter in _stats ships under its own name; a new stat
        # must only be added to the _stats initializer + the ledger
        # contract (writer-side validation catches a missed contract)
        return {
            "table": self.table_name,
            **stats,
            "rows_per_s": round(stats["rows"] / elapsed, 3)
            if elapsed > 0 else 0.0,
            "freshness_ms": round(fresh, 3) if fresh is not None else None,
            "commit_ms": round(commit, 3) if commit is not None else None,
            "segments": self.num_segments,
            "consuming_docs": self.consuming_docs,
            "partitions": len(self._mutables),
            "faults_fired": len(plan.fired) if plan is not None else 0,
        }

    def write_ingest_stats(self, path: str, **extra: Any
                           ) -> Dict[str, Any]:
        """Append one validated ``ingest_stats`` v2 record (the
        freshness ledger — utils/ledger.py field contract, enforced
        writer-side like every other kind; tools/check_ledger.py reports
        its per-kind count)."""
        from ..utils import ledger as uledger
        rec = uledger.make_record("ingest_stats",
                                  **{**self.ingest_stats(), **extra})
        uledger.append_record(rec, path)
        return rec
