"""Socket-level stream plugin: a TCP message broker + consumer client.

Round-4 (VERDICT r3 missing #6): the file-log stream was Kafka-*shaped*
but nothing spoke a real broker protocol over a wire. This module is an
honest socket-level implementation: `WireBroker` is a standalone TCP
server holding partitioned append-only logs (the test fixture's
single-node "Kafka"), and `WireStream`/`WireStreamConsumer` are real
network clients speaking its binary protocol through the stream SPI —
the role KafkaPartitionLevelConsumer.java plays against a Kafka cluster
(reference: pinot-plugins/pinot-stream-ingestion/pinot-kafka-2.0/...).

Wire protocol (all integers big-endian):
  request  := u32 frame_len | u8 op | payload
  response := u32 frame_len | u8 status | payload   (status 0=ok, 1=err)
  ops:
    0 METADATA ()                    -> u32 n_partitions
    1 PRODUCE  (u32 part, u32 n, n*(u32 len, bytes json_row))
                                     -> u64 base_offset
    2 FETCH    (u32 part, u64 offset, u32 max)
                                     -> u64 next_offset | u32 n
                                        | n*(u32 len, bytes json_row)
    3 LATEST   (u32 part)            -> u64 latest_offset

Offsets are per-partition message indexes (the Kafka long-offset model;
StreamPartitionMsgOffset analog). The broker optionally persists each
partition's log to disk so a restarted broker serves the same offsets —
which is what lets the consumer's checkpoint/resume contract be tested
against a real process boundary.
"""
from __future__ import annotations

import json
import os
import socket
import socketserver
import struct
import threading
from typing import Any, Dict, List, Mapping, Optional, Tuple

from .stream import MessageBatch, PartitionGroupConsumer, \
    StreamConsumerFactory, consume_faults

OP_METADATA, OP_PRODUCE, OP_FETCH, OP_LATEST = 0, 1, 2, 3
_MAX_FRAME = 64 << 20


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, head: int, payload: bytes) -> None:
    sock.sendall(struct.pack(">IB", len(payload) + 1, head) + payload)


def _recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
    (ln,) = struct.unpack(">I", _recv_exact(sock, 4))
    if not 1 <= ln <= _MAX_FRAME:
        raise ConnectionError(f"bad frame length {ln}")
    body = _recv_exact(sock, ln)
    return body[0], body[1:]


# ---------------------------------------------------------------------------
# broker (server side)
# ---------------------------------------------------------------------------

class _PartitionLog:
    def __init__(self, path: Optional[str]):
        self.messages: List[bytes] = []
        self.lock = threading.Lock()
        self.path = path
        self.fh = None
        if path is not None and os.path.exists(path):
            with open(path, "rb") as f:
                data = f.read()
            pos = 0
            while pos + 4 <= len(data):
                (ln,) = struct.unpack(">I", data[pos:pos + 4])
                if pos + 4 + ln > len(data):
                    break  # torn tail write
                self.messages.append(data[pos + 4:pos + 4 + ln])
                pos += 4 + ln
            if pos != len(data):
                # TRUNCATE the torn tail before appending (Kafka log
                # recovery does the same) — appending behind a torn
                # header would lose or desync every later record
                with open(path, "r+b") as f:
                    f.truncate(pos)
        if path is not None:
            self.fh = open(path, "ab")

    def append(self, msgs: List[bytes]) -> int:
        with self.lock:
            base = len(self.messages)
            self.messages.extend(msgs)
            if self.fh is not None:
                for m in msgs:
                    self.fh.write(struct.pack(">I", len(m)) + m)
                self.fh.flush()
                # PRODUCE acks the base offset and the module contract
                # says a restarted broker serves the same offsets — that
                # must hold across an OS/process crash, not just a clean
                # restart, so fsync before acknowledging
                os.fsync(self.fh.fileno())
            return base

    def read(self, offset: int, max_n: int) -> Tuple[List[bytes], int]:
        with self.lock:
            end = min(len(self.messages), max(offset, 0) + max_n)
            out = self.messages[offset:end]
            return out, (offset + len(out))

    def latest(self) -> int:
        with self.lock:
            return len(self.messages)

    def close(self) -> None:
        if self.fh is not None:
            self.fh.close()
            self.fh = None


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        broker: "WireBroker" = self.server.broker  # type: ignore
        try:
            while True:
                op, payload = _recv_frame(self.request)
                try:
                    resp = broker._dispatch(op, payload)
                    _send_frame(self.request, 0, resp)
                except _ClientError as e:
                    _send_frame(self.request, 1, str(e).encode())
        except (ConnectionError, OSError):
            return


class _ClientError(Exception):
    pass


class WireBroker:
    """Single-node TCP message broker (the test cluster's 'Kafka')."""

    def __init__(self, num_partitions: int = 1, port: int = 0,
                 log_dir: Optional[str] = None):
        self.num_partitions = num_partitions
        if log_dir is not None:
            os.makedirs(log_dir, exist_ok=True)
        self._logs = [
            _PartitionLog(os.path.join(log_dir, f"p{p}.log")
                          if log_dir else None)
            for p in range(num_partitions)]
        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True  # restart on the same port
            # (TIME_WAIT would otherwise block the recovery contract)

        self._server = _Srv(("127.0.0.1", port), _Handler,
                            bind_and_activate=True)
        self._server.daemon_threads = True
        self._server.broker = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def _log(self, part: int) -> _PartitionLog:
        if not 0 <= part < self.num_partitions:
            raise _ClientError(f"unknown partition {part}")
        return self._logs[part]

    def _dispatch(self, op: int, payload: bytes) -> bytes:
        if op == OP_METADATA:
            return struct.pack(">I", self.num_partitions)
        if op == OP_PRODUCE:
            part, n = struct.unpack(">II", payload[:8])
            msgs = []
            pos = 8
            for _ in range(n):
                (ln,) = struct.unpack(">I", payload[pos:pos + 4])
                msgs.append(payload[pos + 4:pos + 4 + ln])
                pos += 4 + ln
            base = self._log(part).append(msgs)
            return struct.pack(">Q", base)
        if op == OP_FETCH:
            part, offset, max_n = struct.unpack(">IQI", payload[:16])
            msgs, nxt = self._log(part).read(offset, max_n)
            out = [struct.pack(">QI", nxt, len(msgs))]
            for m in msgs:
                out.append(struct.pack(">I", len(m)) + m)
            return b"".join(out)
        if op == OP_LATEST:
            (part,) = struct.unpack(">I", payload[:4])
            return struct.pack(">Q", self._log(part).latest())
        raise _ClientError(f"unknown op {op}")

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        for log in self._logs:
            log.close()


# ---------------------------------------------------------------------------
# client side (the stream SPI plugin)
# ---------------------------------------------------------------------------

class _Conn:
    """One broker connection with reconnect-on-failure."""

    def __init__(self, host: str, port: int, timeout: float):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None

    def _ensure(self) -> socket.socket:
        if self.sock is None:
            self.sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout)
        return self.sock

    def call(self, op: int, payload: bytes, retries: int = 1) -> bytes:
        for attempt in range(retries + 1):
            try:
                sock = self._ensure()
                _send_frame(sock, op, payload)
                status, body = _recv_frame(sock)
                if status != 0:
                    raise BrokerError(body.decode())
                return body
            except (ConnectionError, OSError, socket.timeout):
                self.close()
                if attempt == retries:
                    raise
        raise AssertionError("unreachable")

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None


class BrokerError(Exception):
    """Broker-reported protocol error (bad partition, bad op)."""


class WireProducer:
    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self._conn = _Conn(host, port, timeout)
        self._n_parts: Optional[int] = None

    def num_partitions(self) -> int:
        if self._n_parts is None:
            (self._n_parts,) = struct.unpack(
                ">I", self._conn.call(OP_METADATA, b""))
        return self._n_parts

    def produce(self, row: Mapping[str, Any],
                partition: Optional[int] = None) -> int:
        return self.produce_many([row], partition)

    def produce_many(self, rows, partition: Optional[int] = None) -> int:
        part = 0 if partition is None else partition
        msgs = [json.dumps(dict(r)).encode() for r in rows]
        payload = [struct.pack(">II", part, len(msgs))]
        for m in msgs:
            payload.append(struct.pack(">I", len(m)) + m)
        # retries=0: PRODUCE is not idempotent — a retry after a lost
        # response would append the batch twice. The caller sees the
        # connection error and decides (at-least-once is an explicit
        # re-produce, never a silent one).
        (base,) = struct.unpack(">Q", self._conn.call(
            OP_PRODUCE, b"".join(payload), retries=0))
        return base

    def close(self) -> None:
        self._conn.close()


class WireStream(StreamConsumerFactory):
    """Stream SPI factory over the wire protocol (the
    KafkaConsumerFactory analog; config-addressable via
    consumer_factory_class='pinot_tpu.realtime.wirestream.WireStream')."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout: float = 10.0):
        self.host = host
        self.port = int(port)
        self.timeout = timeout
        self._n_parts: Optional[int] = None

    def num_partitions(self) -> int:
        if self._n_parts is None:
            conn = _Conn(self.host, self.port, self.timeout)
            try:
                (self._n_parts,) = struct.unpack(
                    ">I", conn.call(OP_METADATA, b""))
            finally:
                conn.close()
        return self._n_parts

    def create_consumer(self, partition: int) -> "WireStreamConsumer":
        return WireStreamConsumer(self.host, self.port, partition,
                                  self.timeout)


class WireStreamConsumer(PartitionGroupConsumer):
    """Per-partition network consumer (KafkaPartitionLevelConsumer
    analog): fetch(start_offset, max) -> MessageBatch over the socket,
    reconnecting once on connection failure."""

    def __init__(self, host: str, port: int, partition: int,
                 timeout: float):
        self.partition = partition
        self._key = f"wire/{host}:{port}/{partition}"
        self._conn = _Conn(host, port, timeout)

    def fetch(self, start_offset: int, max_messages: int) -> MessageBatch:
        consume_faults(self._key)
        body = self._conn.call(OP_FETCH, struct.pack(
            ">IQI", self.partition, start_offset, max_messages))
        nxt, n = struct.unpack(">QI", body[:12])
        rows = []
        pos = 12
        for _ in range(n):
            (ln,) = struct.unpack(">I", body[pos:pos + 4])
            rows.append(json.loads(body[pos + 4:pos + 4 + ln]))
            pos += 4 + ln
        return MessageBatch(rows, int(nxt))

    def latest_offset(self) -> int:
        (latest,) = struct.unpack(">Q", self._conn.call(
            OP_LATEST, struct.pack(">I", self.partition)))
        return int(latest)

    def close(self) -> None:
        self._conn.close()
