from .mesh import segment_mesh  # noqa: F401
from .distributed import DistributedTable  # noqa: F401
