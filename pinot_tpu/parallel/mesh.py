"""Mesh helpers for segment-parallel execution."""
from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

SEG_AXIS = "seg"


def segment_mesh(n_devices: Optional[int] = None,
                 devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over devices; the axis is segment-parallelism (the analog of
    Pinot's scatter-gather across servers, SURVEY.md section 2.9 table)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    import numpy as np
    return Mesh(np.asarray(devices), (SEG_AXIS,))
