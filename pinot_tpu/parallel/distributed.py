"""Distributed execution: segments sharded over a device mesh, one
shard_map program per query, XLA collectives for the combine.

Reference parity: the broker scatter-gather data plane —
pinot-core/.../transport/QueryRouter.java:89 (Netty fan-out to servers) +
BrokerReduceService.java:61 (merge DataTables) + per-server combine
(BaseCombineOperator.java:99-117, one task per segment). TPU-native
replacement: segments of one table are stacked into (n_segments, bucket)
arrays laid out over a 1-D Mesh axis; each device vmaps the leaf kernel
over its local segments (intra-server combine), then psum/pmin/pmax over
ICI replace the Netty response hop entirely. The result lands replicated on
every device — the "broker" just reads it.

Requirements for the dense on-device combine:
- all segments share table-level dictionaries (SegmentBuilder shared_dicts
  path), so dict ids and group spaces agree across devices;
- plans whose params are per-segment data (null-mask filters) fall back to
  the per-segment host-merge path.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat import shard_map as _shard_map
from ..engine.executor import extract_partial, resolve_params
from ..utils.spans import annotate, device_fence, span
from ..ops.kernels import build_kernel
from ..query.context import QueryContext
from ..query.planner import CompiledPlan, SegmentPlanner
from ..segment.immutable import ImmutableSegment, bucket_for
from .mesh import SEG_AXIS, segment_mesh


def _reduce_op(name: str) -> str:
    if name.endswith("_present"):
        return "or"
    if name.endswith("_min"):
        return "min"
    if name.endswith("_max"):
        return "max"
    return "sum"  # matched, counts, sums, avg parts, group_count


class DistributedTable:
    """A table resident across a device mesh as stacked sharded columns."""

    def __init__(self, segments: List[ImmutableSegment],
                 mesh: Optional[Mesh] = None):
        if not segments:
            raise ValueError("no segments")
        self.segments = segments
        self.mesh = mesh or segment_mesh()
        self.n_dev = self.mesh.devices.size
        self.bucket = max(bucket_for(s.n_docs) for s in segments)
        # pad segment count to a multiple of the mesh (empty segments are
        # inert: n_docs=0 -> all-false validity masks)
        self.n_slots = -(-len(segments) // self.n_dev) * self.n_dev
        self._cols: Dict[str, jax.Array] = {}
        self._n_docs = self._shard_1d(np.array(
            [s.n_docs for s in segments] +
            [0] * (self.n_slots - len(segments)), dtype=np.int32))
        self._check_shared_dicts()

    def _check_shared_dicts(self) -> None:
        s0 = self.segments[0]
        for s in self.segments[1:]:
            for name, m in s0.columns.items():
                m2 = s.columns[name]
                if m.has_dict != m2.has_dict:
                    raise ValueError(
                        f"segment {s.name!r} column {name!r} does not share "
                        "the table dictionary (build with shared_dicts=...)")
                if m.has_dict:
                    v0 = np.asarray(s0.dictionary(name).values)
                    v1 = np.asarray(s.dictionary(name).values)
                    if len(v0) != len(v1) or not np.array_equal(v0, v1):
                        raise ValueError(
                            f"segment {s.name!r} column {name!r} dictionary "
                            "differs from the table dictionary")

    def _plan_view(self):
        """A table-wide planning view: segment 0's shape with min/max/nulls
        WIDENED across every mesh-resident segment. Planning against one
        segment's statistics is wrong table-wide: its min/max would
        constant-fold predicates other segments don't satisfy, and
        AggSpec.bits sized from one segment's value range would silently
        truncate other segments' int8-limb group sums."""
        import copy
        s0 = self.segments[0]
        view = copy.copy(s0)
        view.columns = {}
        for name, m0 in s0.columns.items():
            m = copy.copy(m0)
            for s in self.segments[1:]:
                m2 = s.columns[name]
                if m.min is not None:
                    m.min = (None if m2.min is None
                             else min(m.min, m2.min))
                if m.max is not None:
                    m.max = (None if m2.max is None
                             else max(m.max, m2.max))
                m.has_nulls = m.has_nulls or m2.has_nulls
                m.is_sorted = m.is_sorted and m2.is_sorted
            view.columns[name] = m
        # ANY segment with upsert-invalidated docs forces the validdocs
        # param into the plan (-> try_execute falls back to the per-segment
        # path), not just segment 0
        view.valid_docs = next(
            (s.valid_docs for s in self.segments
             if getattr(s, "valid_docs", None) is not None), None)
        return view

    # -- sharded residency -------------------------------------------------
    def _sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def _shard_1d(self, host: np.ndarray) -> jax.Array:
        return jax.device_put(host, self._sharding(P(SEG_AXIS)))

    def device_col(self, name: str) -> jax.Array:
        if name not in self._cols:
            m = self.segments[0].columns[name]
            stack = np.zeros(
                (self.n_slots, self.bucket),
                dtype=np.int32 if m.has_dict else m.fwd_dtype)
            for i, s in enumerate(self.segments):
                arr = np.asarray(s.fwd(name))
                stack[i, : s.n_docs] = arr.astype(stack.dtype, copy=False)
            self._cols[name] = jax.device_put(
                stack, self._sharding(P(SEG_AXIS, None)))
        return self._cols[name]

    # -- execution ---------------------------------------------------------
    def plan(self, ctx: QueryContext) -> CompiledPlan:
        """Plan against the widened table view; shared dictionaries make the
        dict-id params valid table-wide, and widened min/max keep raw-column
        constant folds and limb sizing correct for every segment. Compact-
        strategy group-bys run flattened per device (local segments
        concatenate along the row axis — _distributed_kernel), so the
        planner chooses strategies exactly as the single-chip path does."""
        return SegmentPlanner(ctx, self._plan_view()).plan()

    def try_execute(self, ctx: QueryContext):
        """Distributed partial, or None when the plan needs the per-segment
        path (host fallbacks, per-segment null masks, metadata fast paths
        whose states differ per segment)."""
        plan = self.plan(ctx)
        if plan.kind != "kernel":
            return None
        if any(isinstance(p, tuple)
               and p[0] in ("nullmask", "validdocs", "docmask")
               for p in plan.params):
            return None  # per-segment data params need the per-segment path
        if any(not getattr(self.segments[0].columns[c],
                           "single_value", True)
               for c in plan.col_names):
            # MV columns are (bucket, maxValues) matrices; the sharded
            # column stack is 2-D — per-segment path handles them
            return None
        if plan.kernel_plan is not None and any(
                s.kind in ("distinct_count_theta", "percentile_sketch",
                           "raw_theta", "percentile_raw_sketch")
                for s in plan.kernel_plan.aggs):
            # theta hash lists / percentile centroids are NOT
            # positionally combinable across shards (HLL presence is —
            # it rides the 'or' reduce); per-segment path merges them
            return None
        out = self._run(plan)
        return extract_partial(plan, out)

    def _cost_model_cap(self, plan: CompiledPlan) -> Optional[int]:
        """Scale the planner's cost-model compaction capacity to one
        device's LOCAL shard (local segment count x bucket) — the mesh
        kernels must not run at the heuristic default caps (ROADMAP).
        Shares multistage/costs.scaled_compact_cap with the fused batch
        dispatch so the scaling rule cannot fork."""
        if plan.kernel_plan.strategy != "compact":
            return None
        from ..multistage.costs import scaled_compact_cap
        local = self.n_slots // self.n_dev
        return scaled_compact_cap(plan, local * self.bucket,
                                  self.mesh.devices.flat[0].platform)

    def _run(self, plan: CompiledPlan) -> Dict[str, np.ndarray]:
        cols = tuple(self.device_col(n) for n in plan.col_names)
        # replicated placement on THIS mesh's devices — never the default
        # backend (the driver's dryrun runs a CPU mesh under a TPU default)
        params = resolve_params(plan, sharding=self._sharding(P()))
        cap = self._cost_model_cap(plan)
        local = self.n_slots // self.n_dev
        with span("mesh_dispatch", devices=self.n_dev,
                  local_segments=local, bucket=self.bucket,
                  strategy=plan.kernel_plan.strategy, slots_cap=cap,
                  est_sel=plan.est_selectivity):
            fn = _distributed_kernel(plan.kernel_plan, self.bucket,
                                     self.mesh, len(cols), len(params),
                                     slots_cap=cap)
            with span("device_execute"):
                dev = fn(cols, self._n_docs, params)
                device_fence(dev)
            with span("device_transfer"):
                host = jax.device_get(dev)
            if int(host.pop("overflow", 0)):
                # compact capacity exceeded on some device: rerun at the
                # cannot-overflow capacity of a full local shard
                from ..ops.compact import full_slots_cap
                full = full_slots_cap(local * self.bucket)
                with span("overflow_retry", slots_cap=full):
                    fn = _distributed_kernel(
                        plan.kernel_plan, self.bucket, self.mesh,
                        len(cols), len(params), slots_cap=full)
                    host = jax.device_get(fn(cols, self._n_docs, params))
                host.pop("overflow", None)
                annotate(overflow_retry=True, slots_cap=full)
            if "matched" in host:
                matched = int(np.asarray(host["matched"]).sum())
                annotate(matched=matched,
                         meas_sel=matched / max(
                             sum(s.n_docs for s in self.segments), 1))
            return host


def _distributed_kernel(kernel_plan, bucket: int, mesh: Mesh,
                        n_cols: int, n_params: int,
                        slots_cap: int = None):
    from ..ops.kernels import (_ladder_min_elems, _two_pass_mode,
                               cpu_scatter_default)

    platform = mesh.devices.flat[0].platform
    # the compact-path env knobs resolve HERE so they are part of the
    # cache key (the jitted_kernel convention) — flipping them between
    # calls must never hit a stale cached mesh program
    return _distributed_kernel_cached(kernel_plan, bucket, mesh, n_cols,
                                      n_params, slots_cap,
                                      cpu_scatter_default(platform),
                                      _two_pass_mode(),
                                      _ladder_min_elems())


@functools.lru_cache(maxsize=512)
def _distributed_kernel_cached(kernel_plan, bucket: int, mesh: Mesh,
                               n_cols: int, n_params: int,
                               slots_cap: int, scatter: bool,
                               two_pass_mode: str = "auto",
                               ladder_min: int = 1 << 22):
    """jit(shard_map(kernel + collectives)) cached per plan/mesh."""
    # dense (space,) outputs only: psum/pmin/pmax combine positionally
    # across shards, which device-side transfer compaction would break.
    # platform pins the kernel lowering to the mesh's backend (the
    # driver's dryrun runs a CPU mesh under a TPU process default).
    platform = mesh.devices.flat[0].platform
    compact_gb = (kernel_plan.is_group_by
                  and kernel_plan.strategy == "compact")

    def per_device(cols, n_docs, params):
        # cols: tuple of (L, bucket) local shards; n_docs: (L,)
        local_segs = n_docs.shape[0]
        if compact_gb:
            # flatten local segments into one row axis: shared table
            # dictionaries make params segment-agnostic, so one Pallas
            # compaction + group pass serves the whole local shard
            kern = build_kernel(kernel_plan, bucket, slots_cap, platform,
                                xfer_compact=False,
                                local_segments=local_segs,
                                scatter=scatter,
                                two_pass_mode=two_pass_mode,
                                ladder_min=ladder_min)
            flat = tuple(c.reshape(local_segs * bucket) for c in cols)
            local = kern(flat, n_docs, params)
        else:
            kern = build_kernel(kernel_plan, bucket, slots_cap, platform,
                                xfer_compact=False, scatter=scatter,
                                two_pass_mode=two_pass_mode,
                                ladder_min=ladder_min)
            out = jax.vmap(lambda c, n: kern(c, n, params))(cols, n_docs)
            local = {}
            for k, v in out.items():
                op = _reduce_op(k)
                if op == "sum":
                    local[k] = v.sum(axis=0)
                elif op == "min":
                    local[k] = v.min(axis=0)
                elif op == "max":
                    local[k] = v.max(axis=0)
                else:
                    local[k] = v.max(axis=0)
        red = {}
        for k, v in local.items():
            op = _reduce_op(k)
            if k == "overflow" or op == "sum":
                red[k] = jax.lax.psum(v, SEG_AXIS)
            elif op == "min":
                red[k] = jax.lax.pmin(v, SEG_AXIS)
            elif op == "max":
                red[k] = jax.lax.pmax(v, SEG_AXIS)
            else:  # 'or' on bool presence
                red[k] = jax.lax.pmax(
                    v.astype(jnp.int32), SEG_AXIS).astype(bool)
        return red

    in_specs = (tuple(P(SEG_AXIS, None) for _ in range(n_cols)),
                P(SEG_AXIS),
                tuple(P() for _ in range(n_params)))
    mapped = _shard_map(per_device, mesh=mesh, in_specs=in_specs,
                           out_specs=P(), check_vma=False)
    return jax.jit(mapped)
