"""Wire contracts of the gRPC data plane.

server_pb2.py is VENDORED protoc output (protoc 3.21 gencode, verified
against the installed protobuf runtime by tests/test_grpc_contract.py's
regeneration check) — regenerate with:

    protoc --python_out=pinot_tpu/protos -I pinot_tpu/protos \
        pinot_tpu/protos/server.proto
"""
from . import server_pb2  # noqa: F401
