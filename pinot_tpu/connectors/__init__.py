"""Ecosystem connectors: query results and raw segments as pandas /
numpy / torch structures.

Reference parity: pinot-connectors/ (pinot-spark-connector,
pinot-spark-3-connector, pinot-flink-connector) — their read path plans
a table scan, splits it per segment/server, and hands each split to the
compute framework as that framework's native rows. The Python data
ecosystem's "Spark" is pandas/torch, so the connector surface here is:

- ``read_sql``       broker SQL -> pandas.DataFrame
- ``read_table``     whole-table (or column-projected) scan over the
                     segments a data manager holds -> DataFrame, one
                     per-segment split at a time like the Spark
                     connector's PinotInputPartition
- ``to_torch``       DataFrame/ResultTable -> dict of torch tensors
                     (the feature-ingest handoff)

Writes go the other way through the batch ingestion job spec
(ingestion/batch.py), which is the reference's write-connector shape.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np


def _pandas():
    import pandas as pd
    return pd


def _to_frame(res: Any):
    """ResultTable -> DataFrame (shared by read_sql and to_torch)."""
    pd = _pandas()
    return pd.DataFrame([tuple(r) for r in res.rows], columns=res.columns)


def read_sql(conn: Any, sql: str):
    """Execute SQL through any connection-ish object (in-process
    ``connect()`` callable, Broker, or HttpConnection) -> DataFrame."""
    if callable(conn) and not hasattr(conn, "query") \
            and not hasattr(conn, "execute"):
        res = conn(sql)
    elif hasattr(conn, "execute"):
        res = conn.execute(sql)
    else:
        res = conn.query(sql)
    return _to_frame(res)


def iter_segment_frames(dm: Any, columns: Optional[Sequence[str]] = None
                        ) -> Iterator[Any]:
    """One DataFrame per segment split (PinotInputPartition analog):
    callers stream the table without materializing it whole."""
    pd = _pandas()
    for seg in dm.acquire_segments():
        cols = list(columns) if columns else list(seg.columns)
        data = {}
        for c in cols:
            vals = np.asarray(seg.raw_values(c))
            if not getattr(seg.columns[c], "single_value", True):
                vals = list(vals)  # ragged MV rows stay python lists
            nm = seg.null_mask(c)
            if nm is not None and np.any(nm):
                # surface NULLs as None/NaN, not stored default values
                # (training on default-0 "nulls" silently corrupts).
                # Build the object vector explicitly: np.asarray over
                # equal-length row lists would go 2-D and break pandas
                obj = np.empty(len(vals), dtype=object)
                for i, x in enumerate(vals):
                    obj[i] = x
                obj[np.asarray(nm)] = None
                vals = obj
            data[c] = vals
        frame = pd.DataFrame(data)
        if seg.valid_docs is not None:
            frame = frame[np.asarray(seg.valid_docs)].reset_index(
                drop=True)
        yield frame


def read_table(dm: Any, columns: Optional[Sequence[str]] = None):
    """Whole table -> one DataFrame (concat of the per-segment splits)."""
    pd = _pandas()
    frames = list(iter_segment_frames(dm, columns))
    if not frames:
        return pd.DataFrame(columns=list(columns or []))
    return pd.concat(frames, ignore_index=True)


def to_torch(frame_or_result: Any) -> Dict[str, Any]:
    """Numeric columns -> torch tensors (strings stay out; the caller
    encodes those through the table dictionaries if needed)."""
    import torch
    if hasattr(frame_or_result, "rows"):  # ResultTable
        frame_or_result = _to_frame(frame_or_result)
    out: Dict[str, Any] = {}
    for name in frame_or_result.columns:
        col = frame_or_result[name].to_numpy()
        if col.dtype == object or col.dtype.kind in "US":
            continue
        # copy: segment memmaps are read-only and torch tensors must be
        # writable (training code mutates feature buffers in place)
        out[name] = torch.from_numpy(
            np.array(col, copy=True, order="C"))
    return out
