from .builder import SegmentBuilder  # noqa: F401
from .immutable import ImmutableSegment  # noqa: F401
from .dictionary import Dictionary  # noqa: F401
