"""Mutable (consuming) segment: row-append buffers, queryable snapshots.

Reference parity: pinot-segment-local/.../indexsegment/mutable/
MutableSegmentImpl.java:119 (1364 lines — concurrently-readable in-memory
segment built row-by-row; index(GenericRow) at :488). TPU-native stance:
the consuming segment is a HOST structure (growing numpy buffers with
capacity doubling) queried through the vectorized host path — fresh rows
are few relative to sealed data, so chasing device residency for them
buys nothing; on seal the rows flow through SegmentBuilder into the same
immutable format every other segment uses (sorted dictionaries, minimal
widths) and become device-resident like any offline segment. That mirrors
Pinot's CONSUMING -> ONLINE conversion exactly.

Readers never lock writers: index() appends under a lock; snapshot()
captures (buffers, count) pairs — numpy buffers only grow, so rows
[0, count) are immutable once visible.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Mapping, Optional, Tuple

import numpy as np

from ..spi.config import TableConfig
from ..spi.schema import DataType, FieldSpec, Schema
from .builder import SegmentBuilder
from .dictionary import Dictionary

_INITIAL_CAPACITY = 4096


class _MutableColumn:
    def __init__(self, spec: FieldSpec):
        self.spec = spec
        self.is_mv = not spec.single_value
        self.is_string = (spec.data_type == DataType.STRING
                          or not spec.data_type.is_numeric)
        if self.is_string or self.is_mv:
            # MV columns hold python lists per row (round-4: partial
            # upsert APPEND/UNION need MV on the consuming segment)
            self.values: Any = np.empty(_INITIAL_CAPACITY, dtype=object)
        else:
            self.values = np.zeros(_INITIAL_CAPACITY,
                                   dtype=spec.data_type.np_dtype)
        self.nulls = np.zeros(_INITIAL_CAPACITY, dtype=bool)
        self.any_nulls = False

    def ensure(self, capacity: int) -> None:
        if capacity <= len(self.values):
            return
        new_cap = len(self.values)
        while new_cap < capacity:
            new_cap *= 2
        nv = (np.empty(new_cap, dtype=object) if self.is_string
              else np.zeros(new_cap, dtype=self.values.dtype))
        nv[: len(self.values)] = self.values
        nn = np.zeros(new_cap, dtype=bool)
        nn[: len(self.nulls)] = self.nulls
        self.values, self.nulls = nv, nn

    def append(self, i: int, v: Any) -> None:
        if v is None:
            self.nulls[i] = True
            self.any_nulls = True
            if self.is_mv:
                self.values[i] = []
                return
            v = self.spec.null_value()
        if self.is_mv:
            self.values[i] = list(v) if isinstance(v, (list, tuple)) \
                else [v]
        elif self.is_string:
            self.values[i] = str(v)
        else:
            if self.spec.data_type == DataType.BOOLEAN and isinstance(
                    v, (bool, str)):
                v = 1 if v in (True, "true", "True", 1) else 0
            self.values[i] = v


class MutableSegment:
    def __init__(self, schema: Schema, name: str,
                 table_config: Optional[TableConfig] = None):
        self.schema = schema
        self.name = name
        self.table_config = table_config or TableConfig(schema.name)
        self._cols: Dict[str, _MutableColumn] = {
            f.name: _MutableColumn(f) for f in schema.fields}
        self._count = 0
        self._lock = threading.Lock()
        self.start_offset: Optional[int] = None
        self.created_at = None
        self.sealed_docs = 0  # set by seal(); authoritative for offsets
        # upsert validDocIds over consuming rows (all-true when not upsert)
        self._valid = np.ones(_INITIAL_CAPACITY, dtype=bool)

    @property
    def n_docs(self) -> int:
        return self._count

    # -- write path --------------------------------------------------------
    def index(self, row: Mapping[str, Any]) -> int:
        """Append one row; returns its doc id (MutableSegmentImpl.index)."""
        with self._lock:
            i = self._count
            for name, col in self._cols.items():
                col.ensure(i + 1)
                col.append(i, row.get(name))
            if i >= len(self._valid):
                nv = np.ones(len(self._valid) * 2, dtype=bool)
                nv[: len(self._valid)] = self._valid
                self._valid = nv
            self._valid[i] = True
            self._count = i + 1  # publish after the row is fully written
            return i

    def invalidate_doc(self, doc_id: int) -> None:
        """Upsert: an earlier row for this PK was superseded. Takes the
        segment lock: a concurrent index_row may be swapping _valid for
        the doubled array, and an unlocked store to the old buffer would
        silently resurrect the superseded row (found by analysis/jaxlint
        unlocked-mutation)."""
        with self._lock:
            self._valid[doc_id] = False

    def get_row(self, doc_id: int) -> Dict[str, Any]:
        """One indexed row in value space (None for nulls) — the
        partial-upsert merge reads the previous live row through this
        (GenericRow readback; MutableSegmentImpl.getRecord analog)."""
        row: Dict[str, Any] = {}
        for name, c in self._cols.items():
            if c.nulls[doc_id]:
                row[name] = None
            elif c.is_mv:
                row[name] = list(c.values[doc_id])
            else:
                v = c.values[doc_id]
                row[name] = v.item() if isinstance(v, np.generic) else v
        return row

    def valid_mask(self, n: int) -> np.ndarray:
        return self._valid[:n]

    def index_batch(self, rows) -> int:
        for r in rows:
            self.index(r)
        return self._count

    # -- read path ---------------------------------------------------------
    def snapshot(self) -> "MutableSegmentView":
        with self._lock:
            n = self._count
            cols = {name: (c.values, c.nulls, c.any_nulls)
                    for name, c in self._cols.items()}
            valid = self._valid
        return MutableSegmentView(self, n, cols, valid)

    # -- seal --------------------------------------------------------------
    def seal(self, out_dir: str, segment_name: Optional[str] = None) -> str:
        """Build the immutable segment directory from the current rows
        (CONSUMING -> ONLINE conversion; RealtimeSegmentConverter analog).
        The row count actually sealed is published as self.sealed_docs —
        offset accounting MUST use it, not a later read of n_docs (rows
        indexed concurrently with the build are not in the artifact)."""
        with self._lock:
            n = self._count
        self.sealed_docs = n
        columns: Dict[str, Any] = {}
        for name, c in self._cols.items():
            if c.any_nulls and c.nulls[:n].any():
                arr = np.empty(n, dtype=object)
                arr[:] = c.values[:n]
                arr[c.nulls[:n]] = None
                columns[name] = arr
            else:
                columns[name] = c.values[:n].copy()
        builder = SegmentBuilder(self.schema, self.table_config)
        return builder.build(columns, out_dir, segment_name or self.name)


class _ViewColumnMeta:
    """Planner/host-path column metadata for a consuming snapshot: no
    dictionary, no min/max (no constant folding against moving data)."""

    def __init__(self, spec: FieldSpec, any_nulls: bool):
        self.name = spec.name
        self.data_type = spec.data_type
        self.field_type = spec.field_type.value
        self.encoding = "RAW"
        self.cardinality = 0
        self.is_sorted = False
        self.min = None
        self.max = None
        self.has_nulls = any_nulls
        self.partitions = None
        self.single_value = spec.single_value
        self.max_values = None

    @property
    def has_dict(self) -> bool:
        return False


class MutableSegmentView:
    """Immutable row-range view over a consuming segment; implements the
    host-path segment protocol (raw_values/null_mask/columns/schema).
    is_mutable routes the planner straight to the host path."""

    is_mutable = True

    def __init__(self, parent: MutableSegment, n: int,
                 cols: Dict[str, Tuple[np.ndarray, np.ndarray, bool]],
                 valid: Optional[np.ndarray] = None):
        self.parent = parent
        self.name = parent.name
        self.schema = parent.schema
        self.n_docs = n
        self._cols = cols
        # expose upsert validDocIds only when some doc is invalidated (the
        # all-true case keeps the common path mask-free)
        self.valid_docs = None
        if valid is not None and not valid[:n].all():
            self.valid_docs = valid[:n]
        self.columns: Dict[str, _ViewColumnMeta] = {
            f.name: _ViewColumnMeta(f, cols[f.name][2])
            for f in parent.schema.fields}

    def raw_values(self, col: str) -> np.ndarray:
        vals, _, _ = self._cols[col]
        return vals[: self.n_docs]

    def null_mask(self, col: str) -> Optional[np.ndarray]:
        vals, nulls, any_nulls = self._cols[col]
        if not any_nulls:
            return None
        return nulls[: self.n_docs]

    def dictionary(self, col: str) -> Optional[Dictionary]:
        return None
