"""Segment directory abstraction: v1 (file-per-index) and v3 (single
packed file) formats behind one buffer API.

Reference parity: pinot-segment-spi/.../store/SegmentDirectory.java with
its v1/v2 (per-index files) and v3 (single ``columns.psf`` + index map)
implementations, SegmentVersion lineage, and
SegmentFormatConverterFactory (v1->v3 conversion on load when
tableConfig asks for it). Same trade: v3 keeps ONE mmap per segment —
one file handle, one page-table range, one object to ship to deep store
— while v1 stays trivially inspectable and append-friendly.

All readers access segment bytes through :func:`read_array` /
:func:`read_json` / :func:`exists`; in v1 those hit loose files, in v3
they return zero-copy slices of the packed mmap. Writers (segment build,
index reload) always produce loose files; :func:`fold_new_files` absorbs
them into a v3 segment afterwards (the reference's v3 writer appends to
the single file the same way, leaving dead bytes on removal until the
next repack).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

V3_FILE = "columns.psf"
V3_MAP = "index_map.json"
METADATA_FILE = "metadata.json"
# mutable runtime artifacts (rewritten in place after load) must never be
# absorbed into the immutable packed file — a stale packed copy would
# resurrect after the loose file is deleted (upsert valid-docs snapshots)
RUNTIME_FILES = frozenset({"valid.bin"})
_ALIGN = 64  # slice alignment so device uploads see aligned hosts buffers

# seg_dir -> (packed mmap, {name: [offset, length]}, map mtime).
# Bounded LRU: segment churn (rebalance, minion purge) must not pin
# unlinked columns.psf mmaps for process lifetime; removal paths also
# call invalidate() eagerly.
from collections import OrderedDict
import threading
_CACHE: "OrderedDict[str, Tuple[np.memmap, Dict[str, List[int]], float]]" \
    = OrderedDict()
_CACHE_MAX = 256
_CACHE_LOCK = threading.Lock()  # LRU mutation is not GIL-atomic; broker/
# gRPC thread pools hit _load_map concurrently


def is_v3(seg_dir: str) -> bool:
    return os.path.exists(os.path.join(seg_dir, V3_MAP))


def _load_map(seg_dir: str) -> Tuple[np.memmap, Dict[str, List[int]]]:
    map_path = os.path.join(seg_dir, V3_MAP)
    mtime = os.path.getmtime(map_path)
    with _CACHE_LOCK:
        hit = _CACHE.get(seg_dir)
        if hit is not None and hit[2] == mtime:
            _CACHE.move_to_end(seg_dir)
            return hit[0], hit[1]
    with open(map_path) as fh:
        index_map = json.load(fh)
    packed = np.memmap(os.path.join(seg_dir, V3_FILE), dtype=np.uint8,
                       mode="r")
    from ..utils.leak import track
    track(packed, "segdir_mmap", seg_dir)
    with _CACHE_LOCK:
        _CACHE[seg_dir] = (packed, index_map, mtime)
        _CACHE.move_to_end(seg_dir)
        while len(_CACHE) > _CACHE_MAX:
            _CACHE.popitem(last=False)
    return packed, index_map


def invalidate(seg_dir: str) -> None:
    with _CACHE_LOCK:
        _CACHE.pop(seg_dir, None)


def exists(seg_dir: str, name: str) -> bool:
    # loose files win over packed entries: runtime artifacts (upsert
    # valid-doc snapshots, freshly built indexes awaiting fold) are
    # always the newest copy
    if os.path.exists(os.path.join(seg_dir, name)):
        return True
    if is_v3(seg_dir):
        _, index_map = _load_map(seg_dir)
        return name in index_map
    return False


def _slice(seg_dir: str, name: str) -> Optional[np.ndarray]:
    """The raw uint8 view for ``name`` in a v3 segment, else None."""
    if not is_v3(seg_dir):
        return None
    packed, index_map = _load_map(seg_dir)
    ent = index_map.get(name)
    if ent is None:
        return None
    off, length = ent
    return packed[off:off + length]


def read_array(seg_dir: str, name: str, dtype, count: int = -1,
               shape: Optional[Tuple[int, ...]] = None,
               mmap: bool = True) -> np.ndarray:
    """Typed array for a segment entry. v3: zero-copy slice of the packed
    mmap; v1: np.memmap (mmap=True) or np.fromfile."""
    dt = np.dtype(dtype)
    path = os.path.join(seg_dir, name)
    view = None if os.path.exists(path) else _slice(seg_dir, name)
    if view is not None:
        arr = view.view(dt)
        if count >= 0:
            arr = arr[:count]
        return arr.reshape(shape) if shape is not None else arr
    if os.path.getsize(path) == 0:
        # np.memmap refuses empty files; a 0-byte artifact is legitimate
        # (CSR docs file of an index with no postings)
        arr = np.zeros(0, dtype=dt)
        return arr.reshape(shape) if shape is not None else arr
    if shape is not None and mmap:
        return np.memmap(path, dtype=dt, mode="r", shape=shape)
    if mmap:
        arr = np.memmap(path, dtype=dt, mode="r")
        return arr[:count] if count >= 0 else arr
    arr = np.fromfile(path, dtype=dt, count=count)
    return arr.reshape(shape) if shape is not None else arr


def read_bytes(seg_dir: str, name: str) -> bytes:
    path = os.path.join(seg_dir, name)
    view = None if os.path.exists(path) else _slice(seg_dir, name)
    if view is not None:
        return view.tobytes()
    with open(path, "rb") as fh:
        return fh.read()


def read_json(seg_dir: str, name: str) -> Any:
    path = os.path.join(seg_dir, name)
    view = None if os.path.exists(path) else _slice(seg_dir, name)
    if view is not None:
        return json.loads(view.tobytes().decode("utf-8"))
    with open(path) as fh:
        return json.load(fh)


# ---------------------------------------------------------------------------
# conversion + maintenance
# ---------------------------------------------------------------------------

def _data_files(seg_dir: str) -> List[str]:
    out = []
    for fn in sorted(os.listdir(seg_dir)):
        if fn in (METADATA_FILE, V3_FILE, V3_MAP) or fn in RUNTIME_FILES:
            continue
        if os.path.isfile(os.path.join(seg_dir, fn)):
            out.append(fn)
    return out


def _set_version(seg_dir: str, version: str) -> None:
    meta_path = os.path.join(seg_dir, METADATA_FILE)
    with open(meta_path) as fh:
        meta = json.load(fh)
    meta["formatVersion"] = version
    tmp = meta_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(meta, fh, indent=1)
    os.replace(tmp, meta_path)


def convert_to_v3(seg_dir: str) -> Dict[str, List[int]]:
    """Pack every loose data file into columns.psf (v1 -> v3)."""
    if is_v3(seg_dir):
        _, index_map = _load_map(seg_dir)
        return index_map
    names = _data_files(seg_dir)
    index_map: Dict[str, List[int]] = {}
    tmp = os.path.join(seg_dir, V3_FILE + ".tmp")
    off = 0
    with open(tmp, "wb") as out:
        for name in names:
            pad = (-off) % _ALIGN
            if pad:
                out.write(b"\0" * pad)
                off += pad
            with open(os.path.join(seg_dir, name), "rb") as fh:
                data = fh.read()
            out.write(data)
            index_map[name] = [off, len(data)]
            off += len(data)
    os.replace(tmp, os.path.join(seg_dir, V3_FILE))
    map_tmp = os.path.join(seg_dir, V3_MAP + ".tmp")
    with open(map_tmp, "w") as fh:
        json.dump(index_map, fh)
    os.replace(map_tmp, os.path.join(seg_dir, V3_MAP))
    _set_version(seg_dir, "v3")
    for name in names:
        os.remove(os.path.join(seg_dir, name))
    invalidate(seg_dir)
    return index_map


def convert_to_v1(seg_dir: str) -> None:
    """Unpack columns.psf back into loose files (v3 -> v1)."""
    if not is_v3(seg_dir):
        return
    packed, index_map = _load_map(seg_dir)
    for name, (off, length) in index_map.items():
        with open(os.path.join(seg_dir, name), "wb") as fh:
            fh.write(packed[off:off + length].tobytes())
    invalidate(seg_dir)
    del packed
    os.remove(os.path.join(seg_dir, V3_MAP))
    os.remove(os.path.join(seg_dir, V3_FILE))
    _set_version(seg_dir, "v1")


def fold_new_files(seg_dir: str) -> List[str]:
    """Absorb loose files written next to a v3 segment (index reload)
    into the packed file by appending; returns the folded names."""
    if not is_v3(seg_dir):
        return []
    names = _data_files(seg_dir)
    if not names:
        return []
    packed, index_map = _load_map(seg_dir)
    index_map = dict(index_map)
    del packed
    invalidate(seg_dir)
    with open(os.path.join(seg_dir, V3_FILE), "ab") as out:
        off = out.tell()
        for name in names:
            pad = (-off) % _ALIGN
            if pad:
                out.write(b"\0" * pad)
                off += pad
            with open(os.path.join(seg_dir, name), "rb") as fh:
                data = fh.read()
            out.write(data)
            index_map[name] = [off, len(data)]
            off += len(data)
    map_tmp = os.path.join(seg_dir, V3_MAP + ".tmp")
    with open(map_tmp, "w") as fh:
        json.dump(index_map, fh)
    os.replace(map_tmp, os.path.join(seg_dir, V3_MAP))
    for name in names:
        os.remove(os.path.join(seg_dir, name))
    return names


def remove_entries(seg_dir: str, names: List[str]) -> List[str]:
    """Drop entries from a v3 index map (bytes stay until the next
    repack — the reference's v3 removal works the same way)."""
    if not is_v3(seg_dir):
        return []
    _, index_map = _load_map(seg_dir)
    index_map = dict(index_map)
    dropped = [n for n in names if index_map.pop(n, None) is not None]
    if dropped:
        map_tmp = os.path.join(seg_dir, V3_MAP + ".tmp")
        with open(map_tmp, "w") as fh:
            json.dump(index_map, fh)
        os.replace(map_tmp, os.path.join(seg_dir, V3_MAP))
        invalidate(seg_dir)
    return dropped


def entry_names(seg_dir: str) -> List[str]:
    """All data entry names (v3 map keys + any loose files)."""
    if is_v3(seg_dir):
        _, index_map = _load_map(seg_dir)
        return sorted(set(index_map) | set(_data_files(seg_dir)))
    return _data_files(seg_dir)
