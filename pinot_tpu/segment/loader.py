"""Segment reload: reconcile a built segment's indexes with the current
table config, in place.

Reference parity: pinot-segment-local/.../segment/index/loader/ (the
IndexHandler family run by ImmutableSegmentLoader's preprocessing): when
a TableConfig gains or loses index definitions, servers rebuild the
affected index files on the already-built segment instead of re-ingesting
— the reload path behind the controller's "reload table/segment" REST
operations. The TPU-native segment keeps one metadata.json, so
reconciliation is: build missing index files from the stored forward
index + dictionary, delete stale ones, rewrite column metadata
atomically.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List

import numpy as np

from ..spi.config import TableConfig
from .builder import METADATA_FILE
from . import segdir
from .immutable import ImmutableSegment


def reconcile_indexes(seg_dir: str, table_config: TableConfig
                      ) -> Dict[str, List[str]]:
    """Align the segment's secondary indexes with table_config.

    Returns {"added": ["col:kind", ...], "removed": [...]}. No-ops when
    nothing changed. The forward index and dictionaries are never
    touched — only secondary indexes reconcile (IndexHandler contract).
    """
    from .. import index as index_pkg

    meta_path = os.path.join(seg_dir, METADATA_FILE)
    with open(meta_path) as fh:
        meta = json.load(fh)
    seg = ImmutableSegment.load(seg_dir)
    idx_cfg = table_config.indexing

    # pass 1: plan + validate EVERYTHING before touching any file, so a
    # config error can't strand metadata pointing at deleted indexes
    plan: List[tuple] = []  # (name, cmeta, to_add, to_remove)
    for name, cmeta in meta["columns"].items():
        if cmeta.get("encoding") == "VECTOR":
            continue  # vector storage IS the index; no reload semantics
        have = set(cmeta.get("indexes", {}) or {})
        want = set(idx_cfg.indexes_for(name))
        if have == want:
            continue
        to_add = sorted(want - have)
        if "inverted" in to_add and not seg.columns[name].has_dict:
            raise ValueError(f"inverted index needs a dictionary "
                             f"column: {name!r}")
        plan.append((name, cmeta, to_add, sorted(have - want)))

    # pass 2: build additions (new files; a crash here leaves unreferenced
    # extras, never a dangling metadata entry)
    added: List[str] = []
    removed: List[str] = []
    for name, cmeta, to_add, to_remove in plan:
        m = seg.columns[name]
        if to_add:
            built = index_pkg.build_indexes_for_column(
                name, to_add, seg_dir, values=seg.raw_values(name),
                ids=np.asarray(seg.fwd(name)) if m.has_dict else None,
                cardinality=m.cardinality,
                configs={"geo": idx_cfg.geo_index_columns.get(name) or {}})
            cmeta.setdefault("indexes", {}).update(built)
            added.extend(f"{name}:{k}" for k in to_add)
        for kind in to_remove:
            cmeta["indexes"].pop(kind, None)
            removed.append(f"{name}:{kind}")
        if not cmeta.get("indexes"):
            cmeta.pop("indexes", None)

    if not (added or removed):
        return {"added": [], "removed": []}

    # pass 3: atomic metadata swap, THEN delete files nothing references
    tmp = meta_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(meta, fh, indent=1)
    os.replace(tmp, meta_path)  # readers see old or new, never half
    for name, _cmeta, _a, to_remove in plan:
        for kind in to_remove:
            _remove_index_files(seg_dir, name, kind)
    # v3 segments: absorb freshly built loose index files into the packed
    # file (the reference's v3 SegmentDirectory.Writer appends the same
    # way; removal above only dropped map entries, bytes repack later)
    segdir.fold_new_files(seg_dir)
    return {"added": added, "removed": removed}


def _remove_index_files(seg_dir: str, col: str, kind: str) -> None:
    from ..index.registry import FILE_STEMS  # module-owned suffixes
    for suffix in FILE_STEMS.get(kind, (f".{kind}",)):
        stem = col + suffix
        doomed = [fn for fn in segdir.entry_names(seg_dir)
                  if fn == stem or fn.startswith(stem + ".")]
        segdir.remove_entries(seg_dir, doomed)
        for fn in doomed:
            path = os.path.join(seg_dir, fn)
            if os.path.exists(path):
                os.remove(path)
