"""Segment reload: reconcile a built segment's indexes with the current
table config, in place.

Reference parity: pinot-segment-local/.../segment/index/loader/ (the
IndexHandler family run by ImmutableSegmentLoader's preprocessing): when
a TableConfig gains or loses index definitions, servers rebuild the
affected index files on the already-built segment instead of re-ingesting
— the reload path behind the controller's "reload table/segment" REST
operations. The TPU-native segment keeps one metadata.json, so
reconciliation is: build missing index files from the stored forward
index + dictionary, delete stale ones, rewrite column metadata
atomically.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List

import numpy as np

from ..spi.config import TableConfig
from .builder import METADATA_FILE
from .immutable import ImmutableSegment


def reconcile_indexes(seg_dir: str, table_config: TableConfig
                      ) -> Dict[str, List[str]]:
    """Align the segment's secondary indexes with table_config.

    Returns {"added": ["col:kind", ...], "removed": [...]}. No-ops when
    nothing changed. The forward index and dictionaries are never
    touched — only secondary indexes reconcile (IndexHandler contract).
    """
    from .. import index as index_pkg

    meta_path = os.path.join(seg_dir, METADATA_FILE)
    with open(meta_path) as fh:
        meta = json.load(fh)
    seg = ImmutableSegment.load(seg_dir)

    added: List[str] = []
    removed: List[str] = []
    idx_cfg = table_config.indexing
    for name, cmeta in meta["columns"].items():
        if cmeta.get("encoding") == "VECTOR":
            continue  # vector storage IS the index; no reload semantics
        have = set(cmeta.get("indexes", {}) or {})
        want = set(idx_cfg.indexes_for(name))
        if have == want:
            continue
        m = seg.columns[name]
        for kind in sorted(have - want):
            _remove_index_files(seg_dir, name, kind)
            cmeta["indexes"].pop(kind, None)
            removed.append(f"{name}:{kind}")
        missing = sorted(want - have)
        if missing:
            if "inverted" in missing and not m.has_dict:
                raise ValueError(f"inverted index needs a dictionary "
                                 f"column: {name!r}")
            values = seg.raw_values(name)
            ids = np.asarray(seg.fwd(name)) if m.has_dict else None
            built = index_pkg.build_indexes_for_column(
                name, missing, seg_dir, values=values, ids=ids,
                cardinality=m.cardinality)
            cmeta.setdefault("indexes", {}).update(built)
            added.extend(f"{name}:{k}" for k in missing)
        if not cmeta.get("indexes"):
            cmeta.pop("indexes", None)

    if added or removed:
        tmp = meta_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(meta, fh, indent=1)
        os.replace(tmp, meta_path)  # atomic: readers see old or new
    return {"added": added, "removed": removed}


# on-disk file stems per index kind (each kind's module owns its SUFFIX;
# csr-backed kinds write <stem>.docs.bin/.off.bin sub-files)
_KIND_STEMS = {"inverted": ".inv", "bloom": ".bloom", "range": ".rng",
               "text": ".text", "json": ".json", "vector": ".vec"}


def _remove_index_files(seg_dir: str, col: str, kind: str) -> None:
    stem = col + _KIND_STEMS.get(kind, f".{kind}")
    for fn in os.listdir(seg_dir):
        if fn == stem or fn.startswith(stem + "."):
            os.remove(os.path.join(seg_dir, fn))
