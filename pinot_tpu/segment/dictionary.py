"""Sorted dictionaries: value <-> dict-id maps.

Reference parity: pinot-segment-local/.../segment/index/readers/
{OnHeapStringDictionary, IntDictionary, ...}. Pinot dictionaries are sorted,
which is what makes range predicates resolvable to contiguous id ranges
(RangeIndex-free range filtering) and dictionary-based MIN/MAX fast paths
possible (AggregationPlanNode.java:98-112). We keep exactly that invariant:
ids are ranks in sorted order.

The dictionary lives host-side (numpy); only int ids ship to the TPU.
String group-by results resolve ids back to strings at broker reduce —
mirroring Pinot's dict-id execution end-to-end.
"""
from __future__ import annotations

import bisect
from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..spi.schema import DataType


class Dictionary:
    """Immutable sorted dictionary for one column."""

    def __init__(self, values: Union[np.ndarray, List[str]], data_type: DataType):
        self.data_type = data_type
        if data_type == DataType.STRING or not isinstance(values, np.ndarray):
            self._values: Any = list(values)
            self._is_string = True
        else:
            self._values = values
            self._is_string = False

    def __len__(self) -> int:
        return len(self._values)

    @property
    def cardinality(self) -> int:
        return len(self._values)

    @property
    def values(self) -> Any:
        return self._values

    def value(self, dict_id: int) -> Any:
        return self._values[dict_id]

    def values_for(self, ids: np.ndarray) -> np.ndarray:
        """Vectorized id -> value (used at broker reduce for group keys)."""
        if self._is_string:
            arr = np.asarray(self._values, dtype=object)
            return arr[ids]
        return np.asarray(self._values)[ids]

    # -- lookups -----------------------------------------------------------
    def index_of(self, value: Any) -> int:
        """Exact lookup; -1 when absent (BaseImmutableDictionary semantics:
        insertionIndex < 0 encodes absence)."""
        i = self.insertion_index(value)
        if i < len(self._values) and self._eq(self._values[i], value):
            return i
        return -1

    def insertion_index(self, value: Any) -> int:
        """Leftmost index where value would insert (np.searchsorted 'left')."""
        if self._is_string:
            return bisect.bisect_left(self._values, str(value))
        return int(np.searchsorted(self._values, value, side="left"))

    def _eq(self, a: Any, b: Any) -> bool:
        if self._is_string:
            return a == str(b)
        return bool(a == b)

    def id_range(self, lo: Any, hi: Any, incl_lo: bool, incl_hi: bool
                 ) -> Tuple[int, int]:
        """Map a value range to an inclusive id range [lo_id, hi_id].

        Returns (1, 0) (empty) when no ids fall in range. Open bounds use
        None for +-infinity.
        """
        n = len(self._values)
        if lo is None:
            lo_id = 0
        else:
            i = self.insertion_index(lo)
            if incl_lo:
                lo_id = i
            else:
                # first id strictly greater than lo
                lo_id = i + 1 if i < n and self._eq(self._values[i], lo) else i
        if hi is None:
            hi_id = n - 1
        else:
            i = self.insertion_index(hi)
            if incl_hi:
                hi_id = i if i < n and self._eq(self._values[i], hi) else i - 1
            else:
                hi_id = i - 1
        if lo_id > hi_id:
            return (1, 0)
        return (lo_id, hi_id)

    @property
    def min_value(self) -> Any:
        return self._values[0] if len(self._values) else None

    @property
    def max_value(self) -> Any:
        return self._values[-1] if len(self._values) else None

    # -- encode ------------------------------------------------------------
    @classmethod
    def build(cls, raw: np.ndarray, data_type: DataType
              ) -> Tuple["Dictionary", np.ndarray]:
        """Build sorted dictionary and return (dictionary, dict_ids)."""
        if data_type == DataType.STRING:
            svals = np.asarray([str(v) for v in raw], dtype=object)
            uniq, inv = np.unique(svals, return_inverse=True)
            return cls(list(uniq), data_type), inv.astype(np.int32)
        uniq, inv = np.unique(raw, return_inverse=True)
        return cls(uniq, data_type), inv.astype(np.int32)


def min_id_dtype(cardinality: int) -> np.dtype:
    """Smallest unsigned int dtype that stores ids < cardinality (the
    TPU-native analog of Pinot's ceil(log2(card))-bit packing in
    FixedBitSVForwardIndexReaderV2 — byte-aligned widths load zero-copy
    via memmap and upcast to int32 on device)."""
    if cardinality <= 1 << 8:
        return np.dtype(np.uint8)
    if cardinality <= 1 << 16:
        return np.dtype(np.uint16)
    return np.dtype(np.int32)
