"""Immutable segment: memmap load + padded device residency.

Reference parity: pinot-segment-local/.../indexsegment/immutable/
ImmutableSegmentLoader.java:101 (mmap all index buffers via PinotDataBuffer,
per-column DataSource map). The TPU-native replacement for PinotDataBuffer
(pinot-segment-spi/.../memory/PinotDataBuffer.java:60 — LArray/Unsafe
off-heap mmap) is np.memmap for zero-copy host reads feeding
jax.device_put as pow2-padded device arrays; padding bounds the number of
distinct XLA compilations (bucketed shapes) and validity is re-derived on
device as iota < n_docs (masks replace RoaringBitmap docId sets).
"""
from __future__ import annotations

import itertools
import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..spi.schema import DataType, Schema
from ..utils.devmem import global_device_memory
from ..utils.heat import global_segment_heat
from . import segdir
from .builder import METADATA_FILE
from .dictionary import Dictionary

MIN_BUCKET = 1 << 10

# monotonically unique id per loaded segment (never reused, unlike id())
_SEG_UID = itertools.count(1)


def bucket_for(n_docs: int, min_bucket: int = MIN_BUCKET) -> int:
    b = min_bucket
    while b < n_docs:
        b <<= 1
    return b


class ColumnMeta:
    def __init__(self, name: str, d: Dict[str, Any]):
        self.name = name
        self.data_type = DataType(d["dataType"])
        self.field_type = d["fieldType"]
        self.encoding = d["encoding"]  # DICT | RAW
        self.fwd_dtype = np.dtype(d["fwdDtype"])
        self.fwd_format = d.get("fwdFormat", "PLAIN")  # |BITPACK|COMPRESSED
        self.bits = d.get("bits")
        self.codec = d.get("codec")
        self.raw_size = d.get("rawSize")
        self.cardinality = d.get("cardinality", 0)
        self.is_sorted = d.get("isSorted", False)
        self.min = d.get("min")
        self.max = d.get("max")
        self.has_nulls = d.get("hasNulls", False)
        self.dict_format = d.get("dictFormat")
        self.dict_dtype = d.get("dictDtype")
        self.partitions = d.get("partitions")
        self.single_value = d.get("singleValue", True)
        self.max_values = d.get("maxValues")  # MV: padded row width
        # secondary indexes: kind -> extra metadata (index/registry.py)
        self.indexes: Dict[str, Any] = d.get("indexes", {})

    @property
    def has_dict(self) -> bool:
        return self.encoding == "DICT"


class ImmutableSegment:
    """A loaded immutable segment: host memmaps + lazy device cache."""

    def __init__(self, seg_dir: str, read_mode: str = "mmap"):
        self.dir = seg_dir
        with open(os.path.join(seg_dir, METADATA_FILE)) as fh:
            self.metadata = json.load(fh)
        self.name: str = self.metadata["segmentName"]
        self.n_docs: int = self.metadata["totalDocs"]
        self.schema = Schema.from_dict(self.metadata["schema"])
        self.columns: Dict[str, ColumnMeta] = {
            name: ColumnMeta(name, d)
            for name, d in self.metadata["columns"].items()}
        self._read_mode = read_mode
        # process-unique load identity: caches that outlive the segment
        # object (engine/batch._STACK_CACHE) must key on THIS, not the
        # segment name — names recur across tables/reloads with the same
        # bucket, and a name-keyed device cache silently serves the old
        # table's data (found by the round-9 chaos soak)
        self.uid: int = next(_SEG_UID)
        self._index_readers: Dict[Tuple[str, str], Any] = {}
        self._fwd: Dict[str, np.ndarray] = {}
        self._dicts: Dict[str, Dictionary] = {}
        self._nulls: Dict[str, Optional[np.ndarray]] = {}
        # key: (name, bucket, sharding) — sharding None = default backend
        self._device: Dict[Tuple[str, int, Any], jax.Array] = {}
        # warm tier (engine/tier.py): padded host arrays kept after an
        # HBM demotion so re-promotion is one device_put, no re-pad.
        # Only populated while a tier budget is armed — unbounded runs
        # stay byte-for-byte the pre-tier behavior.
        self._warm: Dict[Tuple[str, int, Any], np.ndarray] = {}
        # residency lock: a cache insert (+ its devmem add) and a tier
        # demotion's drain (+ its devmem removes) must be atomic with
        # respect to each other, or a concurrent demote could drop an
        # array whose bytes were just registered — a permanently
        # orphaned devmem entry. Reads (device_col's dict.get) stay
        # lock-free: a racy miss only re-uploads.
        self._res_lock = threading.Lock()
        # upsert validDocIds (None = all docs valid); versioned so the
        # device-resident copy invalidates on update
        self.valid_docs: Optional[np.ndarray] = None
        self.valid_docs_version = 0
        if segdir.exists(seg_dir, "valid.bin"):
            bits = np.asarray(segdir.read_array(seg_dir, "valid.bin",
                                                np.uint8, mmap=False))
            self.valid_docs = np.unpackbits(bits)[: self.n_docs].astype(bool)
        from ..utils import leak
        leak.track(self, "segment", self.name)

    @classmethod
    def load(cls, seg_dir: str, read_mode: str = "mmap") -> "ImmutableSegment":
        return cls(seg_dir, read_mode)

    @property
    def format_version(self) -> str:
        """SegmentVersion lineage: "v1" (file per index) or "v3" (single
        packed columns.psf); numeric 1 is the pre-versioning spelling."""
        v = self.metadata.get("formatVersion", "v1")
        return "v1" if v in (1, "1", "v1") else str(v)

    # -- host access -------------------------------------------------------
    def fwd(self, col: str) -> np.ndarray:
        """Stored forward index (dict ids or raw values), host-side.

        PLAIN columns memmap zero-copy; BITPACK/COMPRESSED decode once
        through the native runtime (pinot_tpu.native) and cache."""
        if col not in self._fwd:
            m = self.columns[col]
            name = f"{col}.fwd.bin"
            if m.fwd_format == "BITPACK":
                from .. import native
                buf = np.ascontiguousarray(segdir.read_array(
                    self.dir, name, np.uint8))
                arr = native.fixedbit_unpack(buf, self.n_docs, m.bits)
            elif m.fwd_format == "COMPRESSED":
                from .. import native
                comp = np.ascontiguousarray(segdir.read_array(
                    self.dir, name, np.uint8))
                raw = native.decompress(comp, m.raw_size, m.codec)
                arr = raw.view(m.fwd_dtype)[: self.n_docs]
            else:
                shape = ((self.n_docs,) if m.single_value
                         else (self.n_docs, m.max_values))
                arr = segdir.read_array(self.dir, name, m.fwd_dtype,
                                        shape=shape,
                                        mmap=self._read_mode == "mmap")
            self._fwd[col] = arr
        return self._fwd[col]

    def dictionary(self, col: str) -> Optional[Dictionary]:
        m = self.columns[col]
        if not m.has_dict:
            return None
        if col not in self._dicts:
            if m.dict_format == "json":
                vals = segdir.read_json(self.dir, f"{col}.dict.json")
                self._dicts[col] = Dictionary(vals, m.data_type)
            else:
                vals = np.asarray(segdir.read_array(
                    self.dir, f"{col}.dict.bin", np.dtype(m.dict_dtype),
                    mmap=False))
                self._dicts[col] = Dictionary(vals, m.data_type)
        return self._dicts[col]

    def null_mask(self, col: str) -> Optional[np.ndarray]:
        m = self.columns[col]
        if not m.has_nulls:
            return None
        if col not in self._nulls:
            bits = np.asarray(segdir.read_array(
                self.dir, f"{col}.null.bin", np.uint8, mmap=False))
            self._nulls[col] = np.unpackbits(bits)[: self.n_docs].astype(bool)
        return self._nulls[col]

    def index_reader(self, col: str, kind: str):
        """Lazy secondary-index reader (StandardIndexes registry analog);
        None when the column has no such index."""
        m = self.columns.get(col)
        if m is None or kind not in m.indexes:
            return None
        key = (col, kind)
        if key not in self._index_readers:
            from .. import index as index_pkg
            reader = index_pkg.load_index(self.dir, col, kind,
                                          m.indexes[kind])
            if kind == "vector":
                # bind tier/devmem identity: the reader's device
                # residents account as (uid, col) in the `vector` pool
                # and its uploads admit THIS segment to the HBM tier
                reader.attach_owner(self, col)
            self._index_readers[key] = reader
        return self._index_readers[key]

    def raw_values(self, col: str) -> np.ndarray:
        """Decoded values (host-side; for selection results / oracles)."""
        m = self.columns[col]
        if m.encoding == "VECTOR":
            return np.asarray(self.index_reader(col, "vector").matrix)
        stored = self.fwd(col)
        if not m.single_value:
            d = self.dictionary(col)
            out = np.empty(self.n_docs, dtype=object)
            for i, row in enumerate(np.asarray(stored)):
                ids = row[row >= 0]
                out[i] = list(d.values_for(ids))
            return out
        if m.has_dict:
            return self.dictionary(col).values_for(np.asarray(stored))
        return np.asarray(stored)

    # -- device residency --------------------------------------------------
    @property
    def bucket(self) -> int:
        return bucket_for(self.n_docs)

    def _put(self, host: np.ndarray, sharding) -> jax.Array:
        """device_put honoring an explicit placement (mesh sharding or
        device, None = process default); bare placement is wrong when a
        query runs on a CPU mesh under a TPU default."""
        return jax.device_put(host, sharding)

    def _cache_device(self, key, arr: jax.Array,
                      host: Optional[np.ndarray] = None) -> jax.Array:
        """Every _device insert routes through here so the device-memory
        registry's live-byte gauges always reconcile with the cache —
        and so the HBM tier (engine/tier.py) sees every admission: the
        insert promotes this segment hot and enforces the shared budget.
        ``host`` is the uploaded host representation; while a tier
        budget is armed it is stashed warm for cheap re-promotion."""
        from ..engine.tier import global_tier
        with self._res_lock:
            self._device[key] = arr
            global_device_memory.add("segment_cols", (self.uid, key),
                                     int(arr.nbytes))
            if host is not None and global_tier.armed:
                old = self._warm.get(key)
                self._warm[key] = host
                global_tier.note_warm(
                    self.uid, int(getattr(host, "nbytes", 0))
                    - (int(old.nbytes) if old is not None else 0))
        # tier admission OUTSIDE _res_lock: enforcement may demote
        # OTHER segments (their _res_lock) — never nested under ours
        global_tier.admitted(self)
        return arr

    def device_col(self, col: str, bucket: Optional[int] = None,
                   sharding=None) -> jax.Array:
        """Padded device array for a column's stored representation.

        Dict ids upcast to int32 (byte-width storage is a host format detail;
        int32 is the TPU-friendly lane width). Raw columns keep their dtype.
        Pad value 0 — validity masks make padding inert.

        This is also the tier's transparent re-promotion path: a
        demoted segment's read misses the device cache, uploads from
        the warm host array when one is stashed (no re-pad) and lands
        byte-identical regardless of prior tier placement.
        """
        from ..engine.tier import global_tier
        bucket = bucket or self.bucket
        key = (col, bucket, sharding)
        hit = self._device.get(key)
        # observed device-cache hit ratio feeds the segment-heat table
        # (the tier's admission signal)
        global_segment_heat.device_access(self, hit is not None)
        # tier.evict chaos hook: may force-demote THIS segment mid-query
        # (a ref already fetched stays alive; later columns re-promote)
        global_tier.on_access(self)
        if hit is None:
            host = self._warm.get(key)
            if host is None:
                host = self.host_col_padded(col, bucket)
            hit = self._cache_device(key, self._put(host, sharding),
                                     host=host)
        return hit

    def host_col_padded(self, col: str, bucket: Optional[int] = None
                        ) -> np.ndarray:
        """The bucket-padded host representation device_col uploads —
        exposed separately so the streaming scan path (engine/pipeline.py)
        can double-buffer transfers WITHOUT populating the device cache."""
        bucket = bucket or self.bucket
        m = self.columns[col]
        host = np.asarray(self.fwd(col))
        if m.has_dict:
            host = host.astype(np.int32, copy=False)
        if bucket > self.n_docs:
            # MV columns pad rows with -1 (the padded-slot sentinel);
            # SV padding is inert under validity masks either way
            pad = np.full((bucket - self.n_docs,) + host.shape[1:],
                          -1 if not m.single_value else 0,
                          dtype=host.dtype)
            host = np.concatenate([host, pad])
        return host

    def device_cols(self, cols: List[str], bucket: Optional[int] = None,
                    sharding=None) -> Tuple[jax.Array, ...]:
        bucket = bucket or self.bucket
        return tuple(self.device_col(c, bucket, sharding=sharding)
                     for c in cols)

    def device_dict_values(self, col: str, sharding=None) -> jax.Array:
        """Device-resident sorted dictionary values (cached; used for
        id->value gathers inside kernels)."""
        # return the LOCAL ref, never re-read self._device: a
        # concurrent tier demotion may drop the key between insert and
        # return (the device_col discipline — a racy loser only
        # re-uploads next call, never KeyErrors)
        key = (f"__dict__{col}", 0, sharding)
        hit = self._device.get(key)
        if hit is None:
            vals = self._warm.get(key)
            if vals is None:
                m = self.columns[col]
                vals = np.asarray(self.dictionary(col).values,
                                  dtype=m.data_type.np_dtype)
            hit = self._cache_device(key, self._put(vals, sharding),
                                     host=vals)
        return hit

    def device_null_mask(self, col: str, bucket: Optional[int] = None,
                         sharding=None) -> jax.Array:
        bucket = bucket or self.bucket
        key = (f"__null__{col}", bucket, sharding)
        hit = self._device.get(key)
        if hit is None:
            padded = self._warm.get(key)
            if padded is None:
                nm = self.null_mask(col)
                padded = np.zeros(bucket, dtype=bool)
                if nm is not None:
                    padded[: len(nm)] = nm
            hit = self._cache_device(key, self._put(padded, sharding),
                                     host=padded)
        return hit  # local ref: a racy demotion must not KeyError

    def set_valid_docs(self, mask: Optional[np.ndarray]) -> None:
        self.valid_docs = mask
        self.valid_docs_version += 1
        # drop stale device AND warm copies (the warm stash must never
        # re-promote a superseded validity mask)
        with self._res_lock:
            for key in [k for k in self._device
                        if k[0].startswith("__valid__")]:
                del self._device[key]
                global_device_memory.remove("segment_cols",
                                            (self.uid, key))
            for key in [k for k in self._warm
                        if k[0].startswith("__valid__")]:
                old = self._warm.pop(key)
                from ..engine.tier import global_tier
                global_tier.note_warm(self.uid, -int(old.nbytes))

    def persist_valid_docs(self) -> None:
        """Snapshot validDocIds next to the segment (upsert snapshot analog,
        pinot-segment-local/.../upsert/ validDocIds persistence)."""
        path = os.path.join(self.dir, "valid.bin")
        if self.valid_docs is None:
            if os.path.exists(path):
                os.remove(path)
            return
        np.packbits(self.valid_docs).tofile(path)

    def device_valid_mask(self, bucket: Optional[int] = None,
                          sharding=None) -> jax.Array:
        bucket = bucket or self.bucket
        key = (f"__valid__v{self.valid_docs_version}", bucket, sharding)
        hit = self._device.get(key)
        if hit is None:
            padded = self._warm.get(key)
            if padded is None:
                padded = np.zeros(bucket, dtype=bool)
                if self.valid_docs is not None:
                    padded[: self.n_docs] = self.valid_docs
                else:
                    padded[: self.n_docs] = True
            hit = self._cache_device(key, self._put(padded, sharding),
                                     host=padded)
        return hit  # local ref: a racy demotion must not KeyError

    def demote_device(self, drop_warm: bool = False) -> None:
        """Tier demotion (engine/tier.py): drop the device residents
        and every stacked/cube copy containing this segment (the
        round-9 eviction discipline — a demotion that left a stacked
        copy resident would free nothing). The warm padded host arrays
        survive for cheap re-promotion unless ``drop_warm`` (host ->
        disk: the mmap is the only remaining copy). The drain is
        atomic vs concurrent inserts (_res_lock), so devmem can never
        track an array this demotion dropped."""
        with self._res_lock:
            for key in list(self._device):
                global_device_memory.remove("segment_cols",
                                            (self.uid, key))
            self._device.clear()
            if drop_warm:
                self._drop_warm_locked()
        from ..engine.batch import evict_stacks_containing
        evict_stacks_containing(self.name)
        from ..ops.plan_cache import global_cube_cache
        global_cube_cache.evict_containing(self.name)
        # vector-pool residents (index/vector.py) demote with the
        # segment too: the readers re-upload transparently on the next
        # search, byte-identically (their own lock discipline)
        for (c, kind), rd in list(self._index_readers.items()):
            if kind == "vector":
                rd.evict_device()

    def _drop_warm_locked(self) -> bool:  # holds-lock: _res_lock
        if not self._warm:
            return False
        from ..engine.tier import global_tier
        global_tier.note_warm(
            self.uid,
            -sum(int(a.nbytes) for a in self._warm.values()))
        self._warm.clear()  # jaxlint: ok unlocked-mutation
        return True

    def drop_warm(self) -> bool:
        """Release ONLY the warm host stash (engine/tier's warm-budget
        enforcement on segments that stay HOT — their device residents
        are untouched; the next demotion just re-pads from mmap).
        True when there was a stash to drop."""
        with self._res_lock:
            return self._drop_warm_locked()

    def evict_device(self) -> None:
        """Full unload: device + warm copies gone, tier state cold."""
        self.demote_device(drop_warm=True)
        from ..engine.tier import global_tier
        global_tier.on_evicted(self)

    def __repr__(self) -> str:
        return (f"ImmutableSegment({self.name!r}, docs={self.n_docs}, "
                f"cols={list(self.columns)})")
