"""Segment builder: two-pass stats -> encode -> write.

Reference parity: pinot-segment-local/.../segment/creator/impl/
SegmentIndexCreationDriverImpl.java:117 (init: stats pass) and :246 (build:
dictionary + per-column index creation, seal, v3 single-dir layout). The
TPU-native format drops bit-packing in favor of byte-aligned minimal int
widths (uint8/uint16/int32 dict ids) that memmap zero-copy and upcast on
device; raw numeric columns store their native fixed width.

On-disk layout (segment dir):
    metadata.json             — docs, per-column stats/encoding
    <col>.fwd.bin             — forward index, little-endian fixed width
    <col>.dict.bin            — numeric dictionary (sorted values)
    <col>.dict.json           — string dictionary (sorted values)
    <col>.null.bin            — packed null bitmap (np.packbits), if any nulls
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Union

import numpy as np

from ..spi.config import TableConfig
from ..spi.schema import DataType, FieldType, Schema
from .dictionary import Dictionary, min_id_dtype

FORMAT_VERSION = "v1"
METADATA_FILE = "metadata.json"


def _fwd_path(d: str, col: str) -> str:
    return os.path.join(d, f"{col}.fwd.bin")


def _dict_bin_path(d: str, col: str) -> str:
    return os.path.join(d, f"{col}.dict.bin")


def _dict_json_path(d: str, col: str) -> str:
    return os.path.join(d, f"{col}.dict.json")


def _null_path(d: str, col: str) -> str:
    return os.path.join(d, f"{col}.null.bin")


class Categorical:
    """Pre-encoded dictionary column input: `codes` index into `values`.

    Bulk-ingest fast path for low-cardinality string dimensions (the
    reference's dictionary-encoded ingest always materializes per-row
    objects; at 100M+ rows the Python str-per-row loop dominates build
    time). Values need not be sorted — codes are remapped to sorted
    dictionary ids at build, preserving the sorted-id invariant that
    range predicates and dict MIN/MAX fast paths rely on."""

    def __init__(self, codes: np.ndarray, values: Sequence[str]):
        self.codes = np.asarray(codes)
        self.values = [str(v) for v in values]
        if len(set(self.values)) != len(self.values):
            raise ValueError("Categorical values must be unique")

    def __len__(self) -> int:
        return len(self.codes)


class SegmentBuilder:
    """Builds one immutable segment directory from rows or columns."""

    def __init__(self, schema: Schema, table_config: Optional[TableConfig] = None):
        self.schema = schema
        self.table_config = table_config or TableConfig(schema.name)

    # -- input normalization ----------------------------------------------
    def _to_columns(self, data: Union[Sequence[Mapping[str, Any]],
                                      Mapping[str, Any]]
                    ) -> Dict[str, np.ndarray]:
        """Accept list-of-row-dicts or dict-of-columns; apply null defaults;
        return typed numpy columns plus null masks (attached as attr)."""
        cols: Dict[str, np.ndarray] = {}
        nulls: Dict[str, np.ndarray] = {}
        if isinstance(data, Mapping):
            n = None
            for f in self.schema.fields:
                if f.name not in data:
                    raise ValueError(f"missing column {f.name!r}")
                raw = data[f.name]
                if isinstance(raw, Categorical):
                    if n is None:
                        n = len(raw)
                    elif len(raw) != n:
                        raise ValueError(
                            f"column {f.name!r} length {len(raw)} != {n}")
                    cols[f.name] = raw  # type: ignore[assignment]
                    nulls[f.name] = np.zeros(len(raw), dtype=bool)
                    continue
                if not f.single_value:
                    # ragged MV rows refuse np.asarray; keep object cells
                    arr = np.empty(len(raw), dtype=object)
                    for j, r in enumerate(raw):
                        arr[j] = r
                else:
                    arr = np.asarray(raw)
                if n is None:
                    n = len(arr)
                elif len(arr) != n:
                    raise ValueError(f"column {f.name!r} length {len(arr)} != {n}")
                cols[f.name], nulls[f.name] = self._coerce(f, arr)
        else:
            rows = list(data)
            n = len(rows)
            for f in self.schema.fields:
                raw_list = [r.get(f.name) for r in rows]
                cols[f.name], nulls[f.name] = self._coerce(
                    f, np.asarray(raw_list, dtype=object))
        self._nulls = nulls
        return cols

    def _coerce(self, f, arr: np.ndarray):
        null_mask = np.zeros(len(arr), dtype=bool)
        if not f.single_value:
            # multi-value column: rows are sequences (ragged); None -> []
            # (reference: FixedBitMVForwardIndexReader stores offset+values;
            # the TPU-native layout is a padded (n, maxValues) id matrix)
            out = np.empty(len(arr), dtype=object)
            cast = (str if f.data_type == DataType.STRING
                    else f.data_type.np_dtype.type)
            for i, row in enumerate(arr):
                if row is None:
                    null_mask[i] = True
                    out[i] = []
                else:
                    out[i] = [cast(v) for v in row]
            return out, null_mask
        if f.name in self.table_config.indexing.vector_index_columns:
            # vector column: rows are fixed-dim float sequences; stored only
            # through the vector index (index/vector.py), queried only via
            # VECTOR_SIMILARITY
            out = np.empty(len(arr), dtype=object)
            out[:] = [np.asarray(v, dtype=np.float32) for v in arr]
            return out, null_mask
        if arr.dtype == object:
            null_mask = np.array([v is None for v in arr], dtype=bool)
            if null_mask.any():
                arr = arr.copy()
                arr[null_mask] = f.null_value()
        if f.data_type == DataType.STRING or not f.data_type.is_numeric:
            out = np.asarray([str(v) for v in arr], dtype=object)
        else:
            if f.data_type == DataType.BOOLEAN and arr.dtype == object:
                arr = np.asarray(
                    [1 if v in (True, 1, "true", "True") else 0 for v in arr])
            out = arr.astype(f.data_type.np_dtype)
        return out, null_mask

    # -- encoding decision -------------------------------------------------
    def _use_dictionary(self, f, cardinality: int) -> bool:
        idx = self.table_config.indexing
        if f.name in idx.no_dictionary_columns:
            return False
        if f.name in idx.dictionary_columns:
            return True
        if not f.data_type.is_numeric:
            return True  # strings always dict-encoded
        if f.field_type == FieldType.METRIC:
            return False  # raw metrics aggregate without an id->value gather
        return cardinality <= idx.dict_cardinality_threshold

    # -- build -------------------------------------------------------------
    def build(self, data: Union[Sequence[Mapping[str, Any]], Mapping[str, Any]],
              out_dir: str, segment_name: Optional[str] = None,
              shared_dicts: Optional[Dict[str, Dictionary]] = None
              ) -> str:
        """Build a segment; returns the segment directory path.

        shared_dicts: table-level dictionaries (TPU-native extension: when a
        whole table is built at once, all its segments share one dictionary
        per column so group-by partials combine on-device via psum without
        per-segment id remapping — see parallel/distributed.py).
        """
        cols = self._to_columns(data)
        n_docs = len(next(iter(cols.values()))) if cols else 0
        segment_name = segment_name or f"{self.schema.name}_{int(time.time()*1e3)}"
        seg_dir = os.path.join(out_dir, segment_name)
        os.makedirs(seg_dir, exist_ok=True)

        meta: Dict[str, Any] = {
            "formatVersion": FORMAT_VERSION,
            "segmentName": segment_name,
            "tableName": self.schema.name,
            "totalDocs": n_docs,
            "creationTimeMs": int(time.time() * 1e3),
            "columns": {},
            "schema": self.schema.to_dict(),
        }
        if self.table_config.partition_column:
            meta["partitionColumn"] = self.table_config.partition_column

        idx_cfg = self.table_config.indexing
        for f in self.schema.fields:
            arr = cols[f.name]
            if f.name in idx_cfg.vector_index_columns:
                from .. import index as index_pkg
                vcfg = idx_cfg.vector_index_columns[f.name]
                cmeta = {
                    "dataType": f.data_type.value,
                    "fieldType": f.field_type.value,
                    "encoding": "VECTOR",
                    "fwdDtype": "float32",
                    "cardinality": 0,
                }
                extra = index_pkg.build_indexes_for_column(
                    f.name, ["vector"], seg_dir, values=arr, ids=None,
                    cardinality=0, configs={"vector": vcfg})
                cmeta["indexes"] = extra
                meta["columns"][f.name] = cmeta
                continue
            cmeta = self._build_column(
                f, arr, seg_dir,
                shared_dict=(shared_dicts or {}).get(f.name))
            null_mask = self._nulls.get(f.name)
            if null_mask is not None and null_mask.any():
                np.packbits(null_mask).tofile(_null_path(seg_dir, f.name))
                cmeta["hasNulls"] = True
                cmeta["nullCount"] = int(null_mask.sum())
            meta["columns"][f.name] = cmeta

        if self.table_config.partition_column:
            pc = self.table_config.partition_column
            pmeta = meta["columns"][pc]
            # stable partition function (PartitionFunction SPI): modulo for
            # ints, murmur2 for strings — the broker pruner recomputes
            # partitions of query literals, so builtin hash() (per-process
            # salted) can never be used here
            from ..spi.partition import partition_ids
            pids = partition_ids(cols[pc],
                                 self.table_config.num_partitions)
            pmeta["partitions"] = sorted(set(pids))
            meta["numPartitions"] = self.table_config.num_partitions

        with open(os.path.join(seg_dir, METADATA_FILE), "w") as fh:
            json.dump(meta, fh, indent=1, default=_json_default)
        if self.table_config.segments.format_version == "v3":
            from . import segdir
            segdir.convert_to_v3(seg_dir)
        return seg_dir

    def _build_mv_column(self, f, arr: np.ndarray, seg_dir: str,
                         shared_dict: Optional[Dictionary] = None
                         ) -> Dict[str, Any]:
        """Multi-value column: padded (n, maxValues) dict-id matrix, pad
        id -1 (signed min-width storage). -1 is inert under any-over-axis
        predicates and MvReduce aggregations without needing the
        cardinality at eval time."""
        n = len(arr)
        flat = [v for row in arr for v in row]
        if f.data_type == DataType.STRING:
            flat_arr = np.asarray(flat, dtype=object)
        else:
            flat_arr = np.asarray(flat, dtype=f.data_type.np_dtype) \
                if flat else np.asarray([], dtype=f.data_type.np_dtype)
        if shared_dict is not None:
            dictionary = shared_dict
            flat_ids = self._encode_with(shared_dict, flat_arr, f.data_type)
        else:
            dictionary, flat_ids = Dictionary.build(flat_arr, f.data_type)
        max_values = max((len(row) for row in arr), default=1) or 1
        card = dictionary.cardinality
        dt = next(d for d in (np.int8, np.int16, np.int32)
                  if card <= np.iinfo(d).max)
        mat = np.full((n, max_values), -1, dtype=dt)
        pos = 0
        for i, row in enumerate(arr):
            k = len(row)
            if k:
                mat[i, :k] = flat_ids[pos:pos + k]
                pos += k
        mat.tofile(_fwd_path(seg_dir, f.name))
        cmeta: Dict[str, Any] = {
            "dataType": f.data_type.value,
            "fieldType": f.field_type.value,
            "encoding": "DICT",
            "singleValue": False,
            "maxValues": int(max_values),
            "fwdDtype": dt().dtype.name,
            "cardinality": card,
            "isSorted": False,
        }
        if f.data_type == DataType.STRING:
            with open(_dict_json_path(seg_dir, f.name), "w") as fh:
                json.dump(list(dictionary.values), fh)
            cmeta["dictFormat"] = "json"
        else:
            vals = np.asarray(dictionary.values, dtype=f.data_type.np_dtype)
            vals.tofile(_dict_bin_path(seg_dir, f.name))
            cmeta["dictFormat"] = "bin"
            cmeta["dictDtype"] = f.data_type.np_dtype.name
        if card:
            cmeta["min"] = _json_scalar(dictionary.min_value)
            cmeta["max"] = _json_scalar(dictionary.max_value)
        return cmeta

    def _build_column(self, f, arr: np.ndarray, seg_dir: str,
                      shared_dict: Optional[Dictionary] = None) -> Dict[str, Any]:
        if not f.single_value:
            return self._build_mv_column(f, arr, seg_dir, shared_dict)
        n = len(arr)
        cmeta: Dict[str, Any] = {
            "dataType": f.data_type.value,
            "fieldType": f.field_type.value,
        }
        if isinstance(arr, Categorical):
            order = np.argsort(np.asarray(arr.values, dtype=object))
            remap = np.empty(len(arr.values), dtype=np.int32)
            remap[order] = np.arange(len(arr.values), dtype=np.int32)
            dictionary = Dictionary(
                [arr.values[i] for i in order], DataType.STRING)
            ids = remap[arr.codes]
            cardinality = dictionary.cardinality
            use_dict = True
        elif shared_dict is not None:
            dictionary = shared_dict
            ids = self._encode_with(shared_dict, arr, f.data_type)
            cardinality = shared_dict.cardinality
            use_dict = True
        else:
            if f.data_type == DataType.STRING or not f.data_type.is_numeric:
                cardinality = len(set(str(v) for v in arr)) if n else 0
            else:
                cardinality = int(len(np.unique(arr))) if n else 0
            use_dict = self._use_dictionary(f, cardinality)
            dictionary, ids = (Dictionary.build(arr, f.data_type)
                               if use_dict else (None, None))
            if dictionary is not None:
                cardinality = dictionary.cardinality

        cmeta["cardinality"] = cardinality
        is_sorted = bool(n == 0 or (
            use_dict and bool(np.all(ids[1:] >= ids[:-1]))) or (
            not use_dict and f.data_type.is_numeric
            and bool(np.all(arr[1:] >= arr[:-1]))))
        cmeta["isSorted"] = is_sorted

        idx_cfg = self.table_config.indexing
        if use_dict:
            assert dictionary is not None and ids is not None
            cmeta["encoding"] = "DICT"
            if idx_cfg.bit_packed_ids and cardinality > 1:
                from .. import native
                bits = native.bits_for(cardinality)
                buf = native.fixedbit_pack(ids.astype(np.int32), bits)
                buf.tofile(_fwd_path(seg_dir, f.name))
                cmeta["fwdFormat"] = "BITPACK"
                cmeta["bits"] = bits
                cmeta["fwdDtype"] = "int32"
            else:
                id_dtype = min_id_dtype(cardinality)
                ids.astype(id_dtype).tofile(_fwd_path(seg_dir, f.name))
                cmeta["fwdDtype"] = id_dtype.name
            if f.data_type == DataType.STRING or not f.data_type.is_numeric:
                with open(_dict_json_path(seg_dir, f.name), "w") as fh:
                    json.dump(list(dictionary.values), fh)
                cmeta["dictFormat"] = "json"
            else:
                vals = np.asarray(dictionary.values, dtype=f.data_type.np_dtype)
                vals.tofile(_dict_bin_path(seg_dir, f.name))
                cmeta["dictFormat"] = "bin"
                cmeta["dictDtype"] = f.data_type.np_dtype.name
            cmeta["min"] = _json_scalar(dictionary.min_value)
            cmeta["max"] = _json_scalar(dictionary.max_value)
        else:
            cmeta["encoding"] = "RAW"
            cmeta["fwdDtype"] = arr.dtype.name
            if idx_cfg.compression:
                from .. import native
                codec = idx_cfg.compression
                if codec in ("ZSTD", "LZ4", "SNAPPY") \
                        and not native.available():
                    codec = "ZLIB"  # degrade to the pure-python codec; the
                    # metadata must always name the stream actually written
                if codec == "DELTA" and (arr.dtype.kind not in "iu"
                                         or arr.ndim != 1):
                    codec = "ZLIB"  # DELTA is integer-only
                if codec == "DELTA":
                    try:
                        comp = native.compress(arr, codec)
                    except RuntimeError:
                        # data-dependent: deltas wider than 32 bits —
                        # degrade like every other unsupported case
                        codec = "ZLIB"
                        comp = native.compress(arr, codec)
                else:
                    comp = native.compress(arr, codec)
                comp.tofile(_fwd_path(seg_dir, f.name))
                cmeta["fwdFormat"] = "COMPRESSED"
                cmeta["codec"] = codec
                cmeta["rawSize"] = int(arr.nbytes)
            else:
                arr.tofile(_fwd_path(seg_dir, f.name))
            if n:
                cmeta["min"] = _json_scalar(arr.min())
                cmeta["max"] = _json_scalar(arr.max())

        kinds = self.table_config.indexing.indexes_for(f.name)
        if kinds:
            from .. import index as index_pkg
            if "inverted" in kinds and not use_dict:
                raise ValueError(f"inverted index needs a dictionary "
                                 f"column: {f.name!r}")
            if isinstance(arr, Categorical):  # indexes need materialized rows
                arr = np.asarray(arr.values, dtype=object)[arr.codes]
            icfgs = {"geo": self.table_config.indexing
                     .geo_index_columns.get(f.name) or {}}
            cmeta["indexes"] = index_pkg.build_indexes_for_column(
                f.name, kinds, seg_dir, values=arr,
                ids=ids if use_dict else None,
                cardinality=cardinality, configs=icfgs)
        return cmeta

    @staticmethod
    def _encode_with(dictionary: Dictionary, arr: np.ndarray,
                     data_type: DataType) -> np.ndarray:
        if data_type == DataType.STRING or not data_type.is_numeric:
            lookup = {v: i for i, v in enumerate(dictionary.values)}
            return np.asarray([lookup[str(v)] for v in arr], dtype=np.int32)
        vals = np.asarray(dictionary.values)
        ids = np.searchsorted(vals, arr)
        if not np.all(vals[ids] == arr):
            raise ValueError("value missing from shared dictionary")
        return ids.astype(np.int32)


def build_table_dictionaries(schema: Schema, table_config: TableConfig,
                             column_chunks: Iterable[Mapping[str, np.ndarray]]
                             ) -> Dict[str, Dictionary]:
    """Union per-column values across all chunks into table-level sorted
    dictionaries (for the shared-dict multi-segment build path)."""
    builder = SegmentBuilder(schema, table_config)
    accum: Dict[str, List[np.ndarray]] = {f.name: [] for f in schema.fields}
    chunks = list(column_chunks)
    for chunk in chunks:
        cols = builder._to_columns(chunk)
        for name, arr in cols.items():
            accum[name].append(arr)
    dicts: Dict[str, Dictionary] = {}
    for f in schema.fields:
        if not f.single_value:
            # MV columns: union over the flattened values
            flat = [v for a in accum[f.name] for row in a for v in row]
            allv = (np.asarray(flat, dtype=object)
                    if f.data_type == DataType.STRING
                    else np.asarray(flat, dtype=f.data_type.np_dtype))
            dicts[f.name], _ = Dictionary.build(allv, f.data_type)
            continue
        allv = np.concatenate([np.asarray(a, dtype=object)
                               if f.data_type == DataType.STRING else a
                               for a in accum[f.name]])
        card_est = len(np.unique(allv.astype(str))) if allv.dtype == object \
            else len(np.unique(allv))
        if builder._use_dictionary(f, card_est):
            dicts[f.name], _ = Dictionary.build(allv, f.data_type)
    return dicts


def _json_scalar(v: Any) -> Any:
    if isinstance(v, np.generic):
        return v.item()
    return v


def _json_default(v: Any) -> Any:
    if isinstance(v, np.generic):
        return v.item()
    raise TypeError(f"not JSON serializable: {type(v)}")
