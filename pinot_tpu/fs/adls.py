"""Azure Data Lake Storage Gen2 PinotFS (dfs REST API), stdlib-only.

Reference analog: pinot-plugins/pinot-file-system/pinot-adls/.../
AzurePinotFS.java + pinot-environment/pinot-azure (the azure-storage
SDK is replaced by a from-scratch client for the public ADLS Gen2
"dfs" endpoint — the hierarchical-namespace Path REST contract).

Protocol implemented:
- create file: PUT ?resource=file, then PATCH ?action=append (chunked,
  position=N) and PATCH ?action=flush&position=total — the Gen2
  three-step write
- read: GET with Range; properties: HEAD (x-ms-* + Content-Length)
- list: GET /{filesystem}?resource=filesystem&directory=&recursive=
  with continuation tokens
- rename: PUT dst with x-ms-rename-source (atomic on HNS accounts)
- delete: DELETE ?recursive=
- bearer-token auth (OAuth) or anonymous against emulators

Paths are scheme-local `filesystem/path...` (abfss://fs@account/path
maps to fs/path).
"""
from __future__ import annotations

import json
import os
import urllib.parse
from typing import Dict, List, Optional, Tuple

from ..spi.filesystem import PinotFS, register_fs
from .common import (TokenSource, bearer_headers, download_ranged,
                     split_bucket_path, walk_local)
from .rest import RestClient, RestError


class AdlsClient:
    def __init__(self, endpoint_url: str, token: TokenSource = None,
                 timeout: float = 30.0, max_retries: int = 3,
                 backoff: float = 0.2, chunk_size: int = 8 << 20):
        self.rest = RestClient(endpoint_url, timeout=timeout,
                               max_retries=max_retries, backoff=backoff)
        self._token = token
        self.chunk_size = chunk_size

    def _auth(self) -> Dict[str, str]:
        return bearer_headers(self._token)

    @staticmethod
    def _p(fs: str, path: str = "") -> str:
        out = "/" + urllib.parse.quote(fs, safe="")
        if path:
            out += "/" + urllib.parse.quote(path)
        return out

    @staticmethod
    def _check(st: int, body: bytes, ok=(200,)) -> None:
        if st not in ok:
            try:
                err = json.loads(body.decode())["error"]
                msg = f"{err.get('code')}: {err.get('message')}"
            except (ValueError, KeyError, TypeError):
                msg = body.decode(errors="replace")
            raise RestError(st, msg)

    # -- path ops ---------------------------------------------------------

    def create_file(self, fs: str, path: str, data: bytes) -> None:
        import io
        self.create_file_stream(fs, path, io.BytesIO(data))

    def create_file_stream(self, fs: str, path: str, fh) -> None:
        """The Gen2 three-step write (create / chunked append / flush),
        streaming from a file handle — one chunk in memory at a time."""
        st, _h, body = self.rest.request(
            "PUT", self._p(fs, path), query={"resource": "file"},
            headers=self._auth())
        self._check(st, body, ok=(201,))
        pos = 0
        while True:
            chunk = fh.read(self.chunk_size)
            if not chunk:
                break
            # append is NOT idempotent (a blind transport replay after a
            # lost response lands at a stale position and 409s); surface
            # transient failures to the caller instead (rest.py contract:
            # idempotent requests only)
            st, _h, body = self.rest.request(
                "PATCH", self._p(fs, path),
                query={"action": "append", "position": str(pos)},
                headers=self._auth(), body=chunk, retriable=False)
            self._check(st, body, ok=(202,))
            pos += len(chunk)
        st, _h, body = self.rest.request(
            "PATCH", self._p(fs, path),
            query={"action": "flush", "position": str(pos)},
            headers=self._auth())
        self._check(st, body, ok=(200,))

    def mkdirs(self, fs: str, path: str) -> None:
        st, _h, body = self.rest.request(
            "PUT", self._p(fs, path), query={"resource": "directory"},
            headers=self._auth())
        self._check(st, body, ok=(201,))

    def read(self, fs: str, path: str,
             rng: Optional[Tuple[int, int]] = None) -> bytes:
        headers = dict(self._auth())
        if rng is not None:
            headers["Range"] = f"bytes={rng[0]}-{rng[1]}"
        st, _h, body = self.rest.request("GET", self._p(fs, path),
                                         headers=headers)
        self._check(st, body, ok=(200, 206))
        return body

    def properties(self, fs: str, path: str) -> Optional[dict]:
        st, h, _b = self.rest.request("HEAD", self._p(fs, path),
                                      headers=self._auth())
        if st == 404:
            return None
        if st != 200:
            raise RestError(st, "HEAD failed")
        return {"length": int(h.get("content-length", "0")),
                "directory": h.get("x-ms-resource-type") == "directory"}

    def list_paths(self, fs: str, directory: str = "",
                   recursive: bool = False,
                   max_results: Optional[int] = None) -> List[dict]:
        out: List[dict] = []
        token = None
        while True:
            q = {"resource": "filesystem",
                 "recursive": str(recursive).lower()}
            if directory:
                q["directory"] = directory
            if max_results is not None:
                q["maxResults"] = str(max_results)
            if token:
                q["continuation"] = token
            st, h, body = self.rest.request("GET", self._p(fs), query=q,
                                            headers=self._auth())
            self._check(st, body)
            out.extend(json.loads(body.decode()).get("paths", []))
            if max_results is not None and len(out) >= max_results:
                return out
            token = h.get("x-ms-continuation")
            if not token:
                return out

    def rename(self, fs: str, src: str, dst: str) -> None:
        st, _h, body = self.rest.request(
            "PUT", self._p(fs, dst),
            headers={**self._auth(),
                     "x-ms-rename-source": self._p(fs, src)})
        self._check(st, body, ok=(201,))

    def delete(self, fs: str, path: str, recursive: bool = False) -> None:
        st, _h, body = self.rest.request(
            "DELETE", self._p(fs, path),
            query={"recursive": str(recursive).lower()},
            headers=self._auth())
        self._check(st, body, ok=(200, 202))


class AdlsPinotFS(PinotFS):
    """PinotFS over ADLS Gen2 (AzurePinotFS.java analog); paths are
    `filesystem/path...`."""

    DOWNLOAD_CHUNK = 8 << 20

    def __init__(self, client: AdlsClient):
        self.client = client

    @classmethod
    def register(cls, scheme: str = "adl", **kwargs) -> "AdlsPinotFS":
        fs = cls(AdlsClient(**kwargs))
        register_fs(scheme, lambda: fs)
        if scheme == "adl":        # default registration covers all three
            for alias in ("abfs", "abfss"):
                register_fs(alias, lambda: fs)
        return fs

    @staticmethod
    def _split(path: str) -> Tuple[str, str]:
        return split_bucket_path(path, "adls")

    def exists(self, path: str) -> bool:
        fs, p = self._split(path)
        if not p:
            try:
                self.client.list_paths(fs, max_results=1)
                return True
            except RestError as e:
                if e.status == 404:
                    return False
                raise
        return self.client.properties(fs, p) is not None

    def length(self, path: str) -> int:
        fs, p = self._split(path)
        props = self.client.properties(fs, p)
        if props is None:
            raise FileNotFoundError(path)
        return props["length"]

    def mkdir(self, path: str) -> None:
        fs, p = self._split(path)
        if p:
            self.client.mkdirs(fs, p)

    def listdir(self, path: str) -> List[str]:
        fs, p = self._split(path)
        base = p.rstrip("/")
        entries = self.client.list_paths(fs, directory=base)
        out = []
        strip = (base + "/") if base else ""
        for e in entries:
            name = e.get("name", "")
            if strip and name.startswith(strip):
                name = name[len(strip):]
            if name:
                out.append(name.split("/")[0])
        return sorted(set(out))

    def delete(self, path: str, force: bool = False) -> bool:
        fs, p = self._split(path)
        props = self.client.properties(fs, p)
        if props is None:
            return False
        if props["directory"] and not force:
            if self.client.list_paths(fs, directory=p.rstrip("/"),
                                      max_results=1):
                return False
        self.client.delete(fs, p, recursive=True)
        return True

    def move(self, src: str, dst: str) -> None:
        sfs, sp = self._split(src)
        dfs, dp = self._split(dst)
        if sfs != dfs:
            raise ValueError("ADLS rename is filesystem-local; "
                             f"{sfs!r} != {dfs!r}")
        self.client.rename(sfs, sp, dp)

    def copy(self, src: str, dst: str) -> None:
        sfs, sp = self._split(src)
        dfs, dp = self._split(dst)
        props = self.client.properties(sfs, sp)
        if props is None:
            raise FileNotFoundError(src)
        if props["directory"]:
            for e in self.client.list_paths(sfs, directory=sp.rstrip("/"),
                                            recursive=True):
                if e.get("isDirectory") in (True, "true"):
                    continue
                rel = e["name"][len(sp.rstrip("/")) + 1:]
                self.copy(f"{sfs}/{e['name']}",
                          f"{dfs}/{dp.rstrip('/')}/{rel}")
            return
        data = self.client.read(sfs, sp)
        self.client.create_file(dfs, dp, data)

    def copy_from_local(self, local_src: str, dst: str) -> None:
        fs, p = self._split(dst)
        if os.path.isdir(local_src):
            for full, rel in walk_local(local_src):
                self.copy_from_local(full, f"{fs}/{p.rstrip('/')}/{rel}")
            return
        with open(local_src, "rb") as fh:
            self.client.create_file_stream(fs, p, fh)

    def copy_to_local(self, src: str, local_dst: str) -> None:
        fs, p = self._split(src)
        size = self.length(src)
        download_ranged(
            lambda lo, hi: self.client.read(fs, p, (lo, hi)),
            size, local_dst, self.DOWNLOAD_CHUNK)
