"""Shared stdlib HTTP transport for the object-store PinotFS clients.

The S3 client (fs/s3.py) carries its own connection handling because
SigV4 signs per-attempt; the GCS / WebHDFS / ADLS clients share this
one: bounded retries with exponential backoff on 5xx/connection errors
(idempotent requests only), optional redirect capture (WebHDFS's
two-step CREATE/OPEN handshake returns 307s that must NOT be followed
blindly — the data request goes to the redirect target with a body).
"""
from __future__ import annotations

import http.client
import time
import urllib.parse
from typing import Dict, Optional, Tuple


class RestError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message[:300]}")
        self.status = status


class RestClient:
    """One origin; request() takes an absolute path + query."""

    def __init__(self, endpoint_url: str, timeout: float = 30.0,
                 max_retries: int = 3, backoff: float = 0.2,
                 headers: Optional[Dict[str, str]] = None):
        p = urllib.parse.urlparse(endpoint_url)
        if p.scheme not in ("http", "https"):
            raise ValueError(f"endpoint needs http(s): {endpoint_url}")
        self.secure = p.scheme == "https"
        self.host = p.hostname or ""
        self.port = p.port or (443 if self.secure else 80)
        self.timeout = timeout
        self.max_retries = max_retries
        self.backoff = backoff
        self.base_headers = dict(headers or {})

    def request(self, method: str, path: str,
                query: Optional[Dict[str, str]] = None,
                headers: Optional[Dict[str, str]] = None,
                body: bytes = b"", retriable: bool = True
                ) -> Tuple[int, Dict[str, str], bytes]:
        qs = urllib.parse.urlencode(sorted((query or {}).items()))
        full = path + (("?" + qs) if qs else "")
        hdrs = {**self.base_headers, **(headers or {})}
        attempts = self.max_retries if retriable else 0
        conn_cls = (http.client.HTTPSConnection if self.secure
                    else http.client.HTTPConnection)
        for attempt in range(attempts + 1):
            conn = conn_cls(self.host, self.port, timeout=self.timeout)
            try:
                conn.request(method, full, body=body, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
                rh = {k.lower(): v for k, v in resp.getheaders()}
                if resp.status >= 500 and attempt < attempts:
                    time.sleep(self.backoff * (2 ** attempt))
                    continue
                return resp.status, rh, data
            except (ConnectionError, OSError, http.client.HTTPException):
                if attempt == attempts:
                    raise
                time.sleep(self.backoff * (2 ** attempt))
            finally:
                conn.close()
        raise AssertionError("unreachable")
