"""HDFS PinotFS over the WebHDFS REST API, stdlib-only.

Reference analog: pinot-plugins/pinot-file-system/pinot-hdfs/.../
HadoopPinotFS.java (the hadoop-client FileSystem is replaced by
WebHDFS — the REST gateway every namenode ships; a public, stable
contract since Hadoop 1.x).

Protocol notes implemented faithfully:
- CREATE and OPEN are TWO-STEP: the namenode answers 307 with a
  Location pointing at a datanode; the client re-issues the request
  (with the body / for the bytes) against that location. The stub
  test server exercises the same redirect handshake.
- APPEND is not needed (segments upload whole); RENAME, DELETE
  (recursive), MKDIRS, LISTSTATUS, GETFILESTATUS cover the PinotFS
  surface. user.name query auth (simple auth), as Hadoop defaults to.

Paths are plain absolute paths under hdfs:// (scheme-local).
"""
from __future__ import annotations

import json
import os
import urllib.parse
from typing import Dict, List, Optional, Tuple

from ..spi.filesystem import PinotFS, register_fs
from .common import walk_local
from .rest import RestClient, RestError


class WebHdfsClient:
    def __init__(self, endpoint_url: str, user: str = "pinot",
                 timeout: float = 30.0, max_retries: int = 3,
                 backoff: float = 0.2):
        self.rest = RestClient(endpoint_url, timeout=timeout,
                               max_retries=max_retries, backoff=backoff)
        self.user = user

    def _q(self, op: str, **extra: str) -> Dict[str, str]:
        q = {"op": op, "user.name": self.user}
        q.update(extra)
        return q

    @staticmethod
    def _path(path: str) -> str:
        if not path.startswith("/"):
            path = "/" + path
        return "/webhdfs/v1" + urllib.parse.quote(path)

    @staticmethod
    def _check(st: int, body: bytes, ok=(200,)) -> None:
        if st not in ok:
            try:
                exc = json.loads(body.decode())["RemoteException"]
                msg = f"{exc.get('exception')}: {exc.get('message')}"
            except (ValueError, KeyError, TypeError):
                msg = body.decode(errors="replace")
            raise RestError(st, msg)

    def _redirected(self, method: str, path: str, q: Dict[str, str],
                    body: bytes = b"") -> Tuple[int, bytes]:
        """The namenode 307 handshake: re-issue against Location."""
        st, h, resp = self.rest.request(method, path, query=q,
                                        retriable=not body)
        if st == 307:
            loc = urllib.parse.urlparse(h.get("location", ""))
            q2 = dict(urllib.parse.parse_qsl(loc.query))
            st, _h, resp = self.rest.request(
                method, loc.path, query=q2, body=body,
                headers={"Content-Type": "application/octet-stream"},
                retriable=not body)
        return st, resp

    # -- file ops ---------------------------------------------------------

    def create(self, path: str, data: bytes,
               overwrite: bool = True) -> None:
        st, body = self._redirected(
            "PUT", self._path(path),
            self._q("CREATE", overwrite=str(overwrite).lower()), data)
        self._check(st, body, ok=(200, 201))

    def open(self, path: str, offset: Optional[int] = None,
             length: Optional[int] = None) -> bytes:
        extra: Dict[str, str] = {}
        if offset is not None:
            extra["offset"] = str(offset)
        if length is not None:
            extra["length"] = str(length)
        st, body = self._redirected("GET", self._path(path),
                                    self._q("OPEN", **extra))
        self._check(st, body)
        return body

    def status(self, path: str) -> Optional[dict]:
        st, _h, body = self.rest.request(
            "GET", self._path(path), query=self._q("GETFILESTATUS"))
        if st == 404:
            return None
        self._check(st, body)
        return json.loads(body.decode())["FileStatus"]

    def list_status(self, path: str) -> List[dict]:
        st, _h, body = self.rest.request(
            "GET", self._path(path), query=self._q("LISTSTATUS"))
        self._check(st, body)
        return json.loads(body.decode())["FileStatuses"]["FileStatus"]

    def mkdirs(self, path: str) -> None:
        st, _h, body = self.rest.request(
            "PUT", self._path(path), query=self._q("MKDIRS"))
        self._check(st, body)

    def rename(self, src: str, dst: str) -> bool:
        st, _h, body = self.rest.request(
            "PUT", self._path(src),
            query=self._q("RENAME", destination=dst))
        self._check(st, body)
        return bool(json.loads(body.decode()).get("boolean"))

    def delete(self, path: str, recursive: bool = False) -> bool:
        st, _h, body = self.rest.request(
            "DELETE", self._path(path),
            query=self._q("DELETE", recursive=str(recursive).lower()))
        self._check(st, body)
        return bool(json.loads(body.decode()).get("boolean"))


class HdfsPinotFS(PinotFS):
    """PinotFS over WebHDFS (HadoopPinotFS.java analog)."""

    DOWNLOAD_CHUNK = 8 << 20

    def __init__(self, client: WebHdfsClient):
        self.client = client

    @classmethod
    def register(cls, **kwargs) -> "HdfsPinotFS":
        fs = cls(WebHdfsClient(**kwargs))
        register_fs("hdfs", lambda: fs)
        return fs

    def exists(self, path: str) -> bool:
        return self.client.status(path) is not None

    def length(self, path: str) -> int:
        st = self.client.status(path)
        if st is None:
            raise FileNotFoundError(path)
        return int(st.get("length", 0))

    def mkdir(self, path: str) -> None:
        self.client.mkdirs(path)

    def listdir(self, path: str) -> List[str]:
        return sorted(s["pathSuffix"] for s in
                      self.client.list_status(path) if s["pathSuffix"])

    def delete(self, path: str, force: bool = False) -> bool:
        st = self.client.status(path)
        if st is None:
            return False
        if st.get("type") == "DIRECTORY" and not force:
            kids = self.client.list_status(path)
            if kids:
                return False
        return self.client.delete(path, recursive=True)

    def move(self, src: str, dst: str) -> None:
        if not self.client.rename(src, dst):
            raise OSError(f"rename failed: {src} -> {dst}")

    def copy(self, src: str, dst: str) -> None:
        st = self.client.status(src)
        if st is None:
            raise FileNotFoundError(src)
        if st.get("type") == "DIRECTORY":
            self.client.mkdirs(dst)
            for s in self.client.list_status(src):
                self.copy(f"{src.rstrip('/')}/{s['pathSuffix']}",
                          f"{dst.rstrip('/')}/{s['pathSuffix']}")
            return
        self.client.create(dst, self.client.open(src))

    def copy_from_local(self, local_src: str, dst: str) -> None:
        if os.path.isdir(local_src):
            self.client.mkdirs(dst)
            for full, rel in walk_local(local_src):
                self.copy_from_local(full, f"{dst.rstrip('/')}/{rel}")
            return
        with open(local_src, "rb") as fh:
            self.client.create(dst, fh.read())

    def copy_to_local(self, src: str, local_dst: str) -> None:
        from .common import download_ranged
        size = self.length(src)
        download_ranged(
            lambda lo, hi: self.client.open(src, offset=lo,
                                            length=hi - lo + 1),
            size, local_dst, self.DOWNLOAD_CHUNK)
