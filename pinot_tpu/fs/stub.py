"""In-process S3-compatible object store (test fixture).

The MinIO-of-the-test-suite: an HTTP server speaking enough of the S3
REST API for S3PinotFS — PUT/GET(Range)/HEAD/DELETE object, server-side
copy (x-amz-copy-source), ListObjectsV2 (prefix/delimiter/continuation),
multipart upload (initiate/part/complete/abort). Verifies AWS SigV4
signatures when credentials are configured (recomputing the signature
from the raw request — the client and server share only the public
algorithm, not code paths: the server reconstructs the canonical request
from what arrived on the wire). Supports failure injection (`fail_next`)
so client retry/backoff paths are testable.
"""
from __future__ import annotations

import hashlib
import http.server
import threading
import urllib.parse
from typing import Dict, List, Optional, Tuple

from .s3 import sigv4_headers


class _Store:
    def __init__(self):
        self.objects: Dict[Tuple[str, str], bytes] = {}
        self.uploads: Dict[str, Dict[int, bytes]] = {}
        self.lock = threading.Lock()
        self.next_upload = 0


def _xml_escape(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))


class _S3Handler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    # -- plumbing ---------------------------------------------------------

    @property
    def stub(self) -> "FakeS3Server":
        return self.server.stub  # type: ignore[attr-defined]

    def _read_body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    def _respond(self, status: int, body: bytes = b"",
                 headers: Optional[Dict[str, str]] = None) -> None:
        self.send_response(status)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(body)

    def _error(self, status: int, code: str, msg: str = "") -> None:
        body = (f"<Error><Code>{code}</Code>"
                f"<Message>{_xml_escape(msg)}</Message></Error>").encode()
        self._respond(status, body)

    def _parse(self) -> Tuple[str, str, Dict[str, str]]:
        parsed = urllib.parse.urlparse(self.path)
        q = {k: v[0] for k, v in
             urllib.parse.parse_qs(parsed.query,
                                   keep_blank_values=True).items()}
        path = urllib.parse.unquote(parsed.path).lstrip("/")
        bucket, _, key = path.partition("/")
        return bucket, key, q

    def _check_auth(self, body: bytes) -> bool:
        stub = self.stub
        if stub.access_key is None:
            return True
        auth = self.headers.get("Authorization") or ""
        if not auth.startswith("AWS4-HMAC-SHA256 "):
            self._error(403, "AccessDenied", "missing SigV4 authorization")
            return False
        try:
            fields = dict(
                f.strip().split("=", 1)
                for f in auth[len("AWS4-HMAC-SHA256 "):].split(","))
            signed = fields["SignedHeaders"].split(";")
            sent_sig = fields["Signature"]
        except (ValueError, KeyError):
            self._error(403, "AccessDenied", "malformed authorization")
            return False
        parsed = urllib.parse.urlparse(self.path)
        q = {k: v[0] for k, v in
             urllib.parse.parse_qs(parsed.query,
                                   keep_blank_values=True).items()}
        # reconstruct the canonical request from the wire
        hdrs = {k: self.headers[k] for k in signed
                if k not in ("host",) and self.headers.get(k) is not None}
        payload_sha = self.headers.get("x-amz-content-sha256",
                                       hashlib.sha256(body).hexdigest())
        expect = sigv4_headers(
            self.command, self.headers.get("Host", ""),
            urllib.parse.unquote(parsed.path), q, hdrs, payload_sha,
            stub.access_key, stub.secret_key, stub.region,
            self.headers.get("x-amz-date", ""))
        exp_sig = expect["Authorization"].rsplit("Signature=", 1)[1]
        if exp_sig != sent_sig:
            self._error(403, "SignatureDoesNotMatch",
                        "recomputed signature differs")
            return False
        if payload_sha != hashlib.sha256(body).hexdigest():
            self._error(400, "XAmzContentSHA256Mismatch", "payload hash")
            return False
        return True

    def _inject_failure(self) -> bool:
        stub = self.stub
        with stub._lock:
            if stub.fail_next > 0:
                stub.fail_next -= 1
                self._error(500, "InternalError", "injected failure")
                return True
        return False

    # -- verbs ------------------------------------------------------------

    def do_PUT(self) -> None:
        body = self._read_body()
        if self._inject_failure() or not self._check_auth(body):
            return
        bucket, key, q = self._parse()
        store = self.stub.store
        if "partNumber" in q and "uploadId" in q:
            with store.lock:
                up = store.uploads.get(q["uploadId"])
                if up is None:
                    return self._error(404, "NoSuchUpload", q["uploadId"])
                up[int(q["partNumber"])] = body
            etag = hashlib.md5(body).hexdigest()
            return self._respond(200, headers={"ETag": f'"{etag}"'})
        src = self.headers.get("x-amz-copy-source")
        if src is not None:
            sp = urllib.parse.unquote(src).lstrip("/")
            sb, _, sk = sp.partition("/")
            with store.lock:
                data = store.objects.get((sb, sk))
                if data is None:
                    return self._error(404, "NoSuchKey", sp)
                store.objects[(bucket, key)] = data
            return self._respond(
                200, b"<CopyObjectResult><ETag/></CopyObjectResult>")
        with store.lock:
            store.objects[(bucket, key)] = body
        self._respond(200, headers={"ETag": '"etag"'})

    def do_GET(self) -> None:
        if self._inject_failure() or not self._check_auth(b""):
            return
        bucket, key, q = self._parse()
        store = self.stub.store
        if not key and q.get("list-type") == "2":
            return self._list(bucket, q)
        with store.lock:
            data = store.objects.get((bucket, key))
        if data is None:
            return self._error(404, "NoSuchKey", key)
        rng = self.headers.get("Range")
        if rng and rng.startswith("bytes="):
            lo_s, _, hi_s = rng[len("bytes="):].partition("-")
            lo = int(lo_s)
            hi = min(int(hi_s), len(data) - 1) if hi_s else len(data) - 1
            part = data[lo:hi + 1]
            return self._respond(206, part, headers={
                "Content-Range": f"bytes {lo}-{hi}/{len(data)}"})
        self._respond(200, data)

    def _list(self, bucket: str, q: Dict[str, str]) -> None:
        store = self.stub.store
        prefix = q.get("prefix", "")
        delim = q.get("delimiter", "")
        start = q.get("continuation-token", "")
        page = self.stub.list_page_size
        if "max-keys" in q:
            page = min(page, max(int(q["max-keys"]), 1))
        with store.lock:
            keys = sorted(k for b, k in store.objects if b == bucket
                          and k.startswith(prefix))
            sizes = {k: len(store.objects[(bucket, k)]) for k in keys}
        # collapse into ordered units (key or rolled-up common prefix) —
        # prefixes count toward the page and are emitted exactly once
        # across pages (real MaxKeys semantics), so continuation tokens
        # can never re-emit a prefix
        units: List[Tuple[str, bool]] = []
        for k in keys:
            if delim:
                rest = k[len(prefix):]
                if delim in rest:
                    p = prefix + rest.split(delim, 1)[0] + delim
                    if not units or units[-1][0] != p:
                        units.append((p, True))
                    continue
            units.append((k, False))
        contents: List[Tuple[str, int]] = []
        prefixes: List[str] = []
        truncated = False
        next_token = ""
        for name, is_prefix in units:
            if name <= start:
                continue
            if len(contents) + len(prefixes) >= page:
                truncated = True
                break
            next_token = name
            if is_prefix:
                prefixes.append(name)
            else:
                contents.append((name, sizes[name]))
        parts = ["<?xml version='1.0'?><ListBucketResult>"]
        for k, size in contents:
            parts.append(f"<Contents><Key>{_xml_escape(k)}</Key>"
                         f"<Size>{size}</Size></Contents>")
        for p in prefixes:
            parts.append(f"<CommonPrefixes><Prefix>{_xml_escape(p)}"
                         "</Prefix></CommonPrefixes>")
        parts.append(f"<IsTruncated>{'true' if truncated else 'false'}"
                     "</IsTruncated>")
        if next_token:
            parts.append(f"<NextContinuationToken>"
                         f"{_xml_escape(next_token)}"
                         "</NextContinuationToken>")
        parts.append("</ListBucketResult>")
        self._respond(200, "".join(parts).encode())

    def do_HEAD(self) -> None:
        if self._inject_failure() or not self._check_auth(b""):
            return
        bucket, key, _q = self._parse()
        with self.stub.store.lock:
            data = self.stub.store.objects.get((bucket, key))
        if data is None:
            return self._respond(404)
        self._respond(200, data)  # HEAD: length header only, no body

    def do_DELETE(self) -> None:
        if self._inject_failure() or not self._check_auth(b""):
            return
        bucket, key, q = self._parse()
        store = self.stub.store
        if "uploadId" in q:
            with store.lock:
                store.uploads.pop(q["uploadId"], None)
            return self._respond(204)
        with store.lock:
            store.objects.pop((bucket, key), None)
        self._respond(204)

    def do_POST(self) -> None:
        body = self._read_body()
        if self._inject_failure() or not self._check_auth(body):
            return
        bucket, key, q = self._parse()
        store = self.stub.store
        if "uploads" in q:
            with store.lock:
                store.next_upload += 1
                uid = f"up-{store.next_upload}"
                store.uploads[uid] = {}
            xml = (f"<InitiateMultipartUploadResult>"
                   f"<Bucket>{_xml_escape(bucket)}</Bucket>"
                   f"<Key>{_xml_escape(key)}</Key>"
                   f"<UploadId>{uid}</UploadId>"
                   "</InitiateMultipartUploadResult>")
            return self._respond(200, xml.encode())
        if "uploadId" in q:
            with store.lock:
                up = store.uploads.pop(q["uploadId"], None)
                if up is None:
                    return self._error(404, "NoSuchUpload", q["uploadId"])
                store.objects[(bucket, key)] = b"".join(
                    up[n] for n in sorted(up))
            return self._respond(
                200, b"<CompleteMultipartUploadResult/>")
        self._error(400, "InvalidRequest", "unsupported POST")


class FakeS3Server:
    """S3-compatible store on 127.0.0.1 (port 0 = ephemeral)."""

    def __init__(self, port: int = 0, access_key: Optional[str] = None,
                 secret_key: str = "", region: str = "us-east-1",
                 list_page_size: int = 1000):
        self.store = _Store()
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.list_page_size = list_page_size
        self.fail_next = 0
        self._lock = threading.Lock()

        class _Srv(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Srv(("127.0.0.1", port), _S3Handler)
        self._server.stub = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self.endpoint_url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def inject_failures(self, n: int) -> None:
        with self._lock:
            self.fail_next = n

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
