"""Shared helpers for the object-store PinotFS plugins.

The four filesystems (S3/GCS/ADLS/HDFS) share identical local-tree
upload recursion, chunked ranged-download loops, bucket/key path
splitting, and bearer-auth header construction; a fix to any of these
(symlink policy, partial-download cleanup, token refresh) lands once
here instead of four times.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, Iterator, Optional, Tuple, Union

TokenSource = Union[str, Callable[[], str], None]


def bearer_headers(token: TokenSource) -> Dict[str, str]:
    """Authorization header for a static token or refresh callable."""
    if token is None:
        return {}
    tok = token() if callable(token) else token
    return {"Authorization": f"Bearer {tok}"}


def split_bucket_path(path: str, what: str) -> Tuple[str, str]:
    """`bucket/key...` scheme-local split shared by S3/GCS/ADLS."""
    path = path.lstrip("/")
    bucket, _, key = path.partition("/")
    if not bucket:
        raise ValueError(f"{what} path needs a bucket: {path!r}")
    return bucket, key


def walk_local(local_src: str) -> Iterator[Tuple[str, str]]:
    """(absolute file path, posix-relative path) for every file under
    local_src — the shared directory-upload recursion."""
    for root, _dirs, files in os.walk(local_src):
        for f in files:
            full = os.path.join(root, f)
            yield full, os.path.relpath(full, local_src)\
                .replace(os.sep, "/")


def download_ranged(read_range: Callable[[int, int], bytes], size: int,
                    local_dst: str, chunk: int) -> None:
    """Chunked ranged download to a local file. read_range(lo, hi) must
    return the inclusive byte range."""
    os.makedirs(os.path.dirname(local_dst) or ".", exist_ok=True)
    with open(local_dst, "wb") as fh:
        pos = 0
        while pos < size:
            end = min(pos + chunk, size) - 1
            fh.write(read_range(pos, end))
            pos = end + 1


def iter_file_chunks(fh, chunk: int) -> Iterator[bytes]:
    while True:
        data = fh.read(chunk)
        if not data:
            return
        yield data
