"""In-process GCS / WebHDFS / ADLS Gen2 protocol stubs (test fixtures).

Sibling of fs/stub.py (the S3 stub): each server speaks enough of the
real wire protocol for its PinotFS client — the client and stub share
only the public contract, never code paths. All three support failure
injection (`inject_failures(n)` makes the next n requests 503) so the
retry/backoff paths are testable, and verify auth when configured
(bearer token for GCS/ADLS, user.name presence for WebHDFS).
"""
from __future__ import annotations

import http.server
import json
import re
import threading
import urllib.parse
from typing import Dict, List, Optional, Tuple


class _BaseHandler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    @property
    def stub(self):
        return self.server.stub  # type: ignore[attr-defined]

    def _read_body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    def _respond(self, status: int, body: bytes = b"",
                 headers: Optional[Dict[str, str]] = None) -> None:
        self.send_response(status)
        hdrs = dict(headers or {})
        for k, v in hdrs.items():
            self.send_header(k, v)
        if not any(k.lower() == "content-length" for k in hdrs):
            self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _inject_failure(self) -> bool:
        with self.stub._lock:
            if self.stub.fail_next > 0:
                self.stub.fail_next -= 1
                self._respond(503, b"injected failure")
                return True
        return False

    def _parse(self) -> Tuple[str, Dict[str, str]]:
        u = urllib.parse.urlparse(self.path)
        return (urllib.parse.unquote(u.path),
                dict(urllib.parse.parse_qsl(u.query)))


class _BaseServer:
    handler_cls: type

    def __init__(self, port: int = 0, **cfg):
        self.fail_next = 0
        self._lock = threading.Lock()
        self.cfg = cfg

        class _Srv(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Srv(("127.0.0.1", port), self.handler_cls)
        self._server.stub = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self.endpoint_url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def inject_failures(self, n: int) -> None:
        with self._lock:
            self.fail_next = n

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


# ---------------------------------------------------------------------------
# GCS JSON API
# ---------------------------------------------------------------------------

class _GcsHandler(_BaseHandler):
    def _check_auth(self) -> bool:
        tok = self.stub.cfg.get("token")
        if tok is None:
            return True
        if self.headers.get("Authorization") == f"Bearer {tok}":
            return True
        self._respond(401, json.dumps(
            {"error": {"message": "invalid bearer token"}}).encode())
        return False

    def _err(self, status: int, msg: str) -> None:
        self._respond(status, json.dumps(
            {"error": {"message": msg}}).encode())

    def do_GET(self) -> None:
        if self._inject_failure() or not self._check_auth():
            return
        path, q = self._parse()
        m = re.fullmatch(r"/storage/v1/b/([^/]+)/o/(.+)", path)
        if m:
            bucket, obj = m.group(1), m.group(2)
            data = self.stub.objects.get((bucket, obj))
            if data is None:
                return self._err(404, f"object {obj!r} not found")
            if q.get("alt") == "media":
                rng = self.headers.get("Range")
                if rng:
                    lo, hi = map(int, rng.split("=")[1].split("-"))
                    return self._respond(206, data[lo: hi + 1])
                return self._respond(200, data)
            return self._respond(200, json.dumps(
                {"name": obj, "size": str(len(data))}).encode())
        m = re.fullmatch(r"/storage/v1/b/([^/]+)/o", path)
        if m:
            return self._list(m.group(1), q)
        self._err(400, f"bad GET {path}")

    def _list(self, bucket: str, q: Dict[str, str]) -> None:
        prefix = q.get("prefix", "")
        delim = q.get("delimiter", "")
        page = int(q.get("maxResults", self.stub.cfg.get("page", 1000)))
        names = sorted(k for (b, k) in self.stub.objects
                       if b == bucket and k.startswith(prefix))
        items: List[dict] = []
        prefixes: List[str] = []
        for k in names:
            rest = k[len(prefix):]
            if delim and delim in rest:
                p = prefix + rest.split(delim)[0] + delim
                if p not in prefixes:
                    prefixes.append(p)
                continue
            items.append({"name": k,
                          "size": str(len(self.stub.objects[(bucket, k)]))})
        start = int(q.get("pageToken", 0))
        out = {"items": items[start: start + page],
               "prefixes": prefixes if start == 0 else []}
        if start + page < len(items):
            out["nextPageToken"] = str(start + page)
        self._respond(200, json.dumps(out).encode())

    def do_POST(self) -> None:
        body = self._read_body()
        if self._inject_failure() or not self._check_auth():
            return
        path, q = self._parse()
        m = re.fullmatch(r"/upload/storage/v1/b/([^/]+)/o", path)
        if m:
            bucket = m.group(1)
            name = q.get("name", "")
            if q.get("uploadType") == "media":
                self.stub.objects[(bucket, name)] = body
                return self._respond(200, json.dumps(
                    {"name": name, "size": str(len(body))}).encode())
            if q.get("uploadType") == "resumable":
                with self.stub._lock:
                    self.stub.next_session += 1
                    sid = f"sess-{self.stub.next_session}"
                    self.stub.sessions[sid] = (bucket, name, bytearray())
                loc = (f"{self.stub.endpoint_url}{path}?"
                       + urllib.parse.urlencode(
                           {"uploadType": "resumable", "name": name,
                            "upload_id": sid}))
                return self._respond(200, headers={"Location": loc})
        m = re.fullmatch(r"/storage/v1/b/([^/]+)/o/(.+?)"
                         r"/rewriteTo/b/([^/]+)/o/(.+)", path)
        if m:
            sb, so, db, do = (m.group(i) for i in range(1, 5))
            data = self.stub.objects.get((sb, so))
            if data is None:
                return self._err(404, "source not found")
            self.stub.objects[(db, do)] = data
            return self._respond(200, json.dumps(
                {"done": True,
                 "resource": {"name": do,
                              "size": str(len(data))}}).encode())
        self._err(400, f"bad POST {path}")

    def do_PUT(self) -> None:
        body = self._read_body()
        if self._inject_failure() or not self._check_auth():
            return
        path, q = self._parse()
        sid = q.get("upload_id")
        sess = self.stub.sessions.get(sid) if sid else None
        if sess is None:
            return self._err(400, "unknown upload session")
        bucket, name, buf = sess
        cr = self.headers.get("Content-Range", "")
        mq = re.fullmatch(r"bytes \*/(\d+)", cr)
        if mq:
            # status query: finalize when complete, else report Range
            if len(buf) == int(mq.group(1)):
                self.stub.objects[(bucket, name)] = bytes(buf)
                del self.stub.sessions[sid]
                return self._respond(200, json.dumps(
                    {"name": name, "size": str(len(buf))}).encode())
            return self._respond(
                308, headers={"Range": f"bytes=0-{len(buf) - 1}"})
        m = re.fullmatch(r"bytes (\d+)-(\d+)/(\d+)", cr)
        if not m:
            return self._err(400, f"bad Content-Range {cr!r}")
        lo, hi, total = map(int, m.groups())
        if lo != len(buf):
            return self._err(
                409, f"out-of-order chunk at {lo}, have {len(buf)}")
        with self.stub._lock:
            truncate = self.stub.truncate_next > 0
            if truncate:
                self.stub.truncate_next -= 1
            stall = self.stub.stall_finalize_next > 0
            if stall and hi + 1 == total:
                self.stub.stall_finalize_next -= 1
            else:
                stall = False
        if truncate and len(body) > 1:
            # persist only half the chunk: the 308 Range tells the
            # client where to resume (the resumable protocol contract)
            body = body[: len(body) // 2]
            buf.extend(body)
            return self._respond(
                308, headers={"Range": f"bytes=0-{len(buf) - 1}"})
        if stall:
            # persist everything but DON'T finalize: the client must
            # issue a 'bytes */total' status query to complete
            buf.extend(body)
            return self._respond(
                308, headers={"Range": f"bytes=0-{len(buf) - 1}"})
        buf.extend(body)
        if hi + 1 == total:
            self.stub.objects[(bucket, name)] = bytes(buf)
            del self.stub.sessions[sid]
            return self._respond(200, json.dumps(
                {"name": name, "size": str(total)}).encode())
        self._respond(308, headers={"Range": f"bytes=0-{len(buf) - 1}"})

    def do_DELETE(self) -> None:
        if self._inject_failure() or not self._check_auth():
            return
        path, _q = self._parse()
        m = re.fullmatch(r"/storage/v1/b/([^/]+)/o/(.+)", path)
        if m and (m.group(1), m.group(2)) in self.stub.objects:
            del self.stub.objects[(m.group(1), m.group(2))]
            return self._respond(204)
        self._err(404, "not found")


class FakeGcsServer(_BaseServer):
    handler_cls = _GcsHandler

    def __init__(self, port: int = 0, token: Optional[str] = None,
                 page: int = 1000):
        self.objects: Dict[Tuple[str, str], bytes] = {}
        self.sessions: Dict[str, tuple] = {}
        self.next_session = 0
        self.truncate_next = 0     # partial-persist injection (308 Range)
        self.stall_finalize_next = 0
        super().__init__(port, token=token, page=page)

    def truncate_chunks(self, n: int) -> None:
        """Make the next n resumable chunk PUTs persist only half and
        reply 308 with the committed Range — clients must resume from
        the reported offset, not their own bookkeeping."""
        with self._lock:
            self.truncate_next = n

    def stall_finalize(self, n: int) -> None:
        """Make the next n FINAL chunk PUTs persist fully but answer 308
        (full Range) instead of finalizing — clients must complete the
        session with a 'bytes */total' status-query PUT."""
        with self._lock:
            self.stall_finalize_next = n


# ---------------------------------------------------------------------------
# WebHDFS
# ---------------------------------------------------------------------------

class _HdfsHandler(_BaseHandler):
    def _err(self, status: int, exc: str, msg: str) -> None:
        self._respond(status, json.dumps({"RemoteException": {
            "exception": exc, "message": msg}}).encode())

    def _check_auth(self, q: Dict[str, str]) -> bool:
        if self.stub.cfg.get("require_user") and "user.name" not in q:
            self._err(401, "AuthenticationException", "no user.name")
            return False
        return True

    @staticmethod
    def _fs_path(path: str) -> str:
        assert path.startswith("/webhdfs/v1")
        return path[len("/webhdfs/v1"):] or "/"

    def _status_of(self, p: str) -> Optional[dict]:
        st = self.stub
        if p in st.files:
            return {"pathSuffix": p.rsplit("/", 1)[-1], "type": "FILE",
                    "length": len(st.files[p])}
        if p in st.dirs or any(f.startswith(p.rstrip("/") + "/")
                               for f in list(st.files) + list(st.dirs)):
            return {"pathSuffix": p.rstrip("/").rsplit("/", 1)[-1],
                    "type": "DIRECTORY", "length": 0}
        return None

    def do_GET(self) -> None:
        if self._inject_failure():
            return
        path, q = self._parse()
        if not self._check_auth(q):
            return
        p = self._fs_path(path)
        op = q.get("op", "").upper()
        if op == "OPEN":
            if "redirected" not in q:
                loc = (f"{self.stub.endpoint_url}{self.path}"
                       "&redirected=true")
                return self._respond(307, headers={"Location": loc})
            data = self.stub.files.get(p)
            if data is None:
                return self._err(404, "FileNotFoundException", p)
            off = int(q.get("offset", 0))
            ln = int(q.get("length", len(data) - off))
            return self._respond(200, data[off: off + ln])
        if op == "GETFILESTATUS":
            st = self._status_of(p)
            if st is None:
                return self._err(404, "FileNotFoundException", p)
            return self._respond(200, json.dumps(
                {"FileStatus": st}).encode())
        if op == "LISTSTATUS":
            base = p.rstrip("/")
            kids: Dict[str, dict] = {}
            for f, data in self.stub.files.items():
                if f.startswith(base + "/"):
                    rest = f[len(base) + 1:]
                    name = rest.split("/")[0]
                    if "/" in rest:
                        kids[name] = {"pathSuffix": name,
                                      "type": "DIRECTORY", "length": 0}
                    else:
                        kids[name] = {"pathSuffix": name, "type": "FILE",
                                      "length": len(data)}
            for d in self.stub.dirs:
                if d.rstrip("/").startswith(base + "/"):
                    name = d[len(base) + 1:].split("/")[0]
                    kids.setdefault(name, {"pathSuffix": name,
                                           "type": "DIRECTORY",
                                           "length": 0})
            return self._respond(200, json.dumps({"FileStatuses": {
                "FileStatus": [kids[k] for k in sorted(kids)]}}).encode())
        self._err(400, "UnsupportedOperationException", op)

    def do_PUT(self) -> None:
        body = self._read_body()
        if self._inject_failure():
            return
        path, q = self._parse()
        if not self._check_auth(q):
            return
        p = self._fs_path(path)
        op = q.get("op", "").upper()
        if op == "CREATE":
            if "redirected" not in q:
                loc = (f"{self.stub.endpoint_url}{self.path}"
                       "&redirected=true")
                return self._respond(307, headers={"Location": loc})
            if q.get("overwrite", "true") != "true" \
                    and p in self.stub.files:
                return self._err(403, "FileAlreadyExistsException", p)
            self.stub.files[p] = body
            return self._respond(201)
        if op == "MKDIRS":
            self.stub.dirs.add(p.rstrip("/"))
            return self._respond(200, b'{"boolean": true}')
        if op == "RENAME":
            dst = q.get("destination", "")
            ok = False
            if p in self.stub.files:
                self.stub.files[dst] = self.stub.files.pop(p)
                ok = True
            else:
                pre = p.rstrip("/") + "/"
                moves = [f for f in self.stub.files if f.startswith(pre)]
                for f in moves:
                    self.stub.files[dst.rstrip("/") + "/" + f[len(pre):]] \
                        = self.stub.files.pop(f)
                    ok = True
                if p.rstrip("/") in self.stub.dirs:
                    self.stub.dirs.discard(p.rstrip("/"))
                    self.stub.dirs.add(dst.rstrip("/"))
                    ok = True
            return self._respond(
                200, json.dumps({"boolean": ok}).encode())
        self._err(400, "UnsupportedOperationException", op)

    def do_DELETE(self) -> None:
        if self._inject_failure():
            return
        path, q = self._parse()
        if not self._check_auth(q):
            return
        p = self._fs_path(path)
        ok = False
        if p in self.stub.files:
            del self.stub.files[p]
            ok = True
        else:
            pre = p.rstrip("/") + "/"
            if q.get("recursive") == "true":
                for f in [f for f in self.stub.files
                          if f.startswith(pre)]:
                    del self.stub.files[f]
                    ok = True
            if p.rstrip("/") in self.stub.dirs:
                self.stub.dirs.discard(p.rstrip("/"))
                ok = True
        self._respond(200, json.dumps({"boolean": ok}).encode())


class FakeWebHdfsServer(_BaseServer):
    handler_cls = _HdfsHandler

    def __init__(self, port: int = 0, require_user: bool = True):
        self.files: Dict[str, bytes] = {}
        self.dirs: set = set()
        super().__init__(port, require_user=require_user)


# ---------------------------------------------------------------------------
# ADLS Gen2 (dfs endpoint)
# ---------------------------------------------------------------------------

class _AdlsHandler(_BaseHandler):
    def _err(self, status: int, code: str, msg: str) -> None:
        self._respond(status, json.dumps(
            {"error": {"code": code, "message": msg}}).encode())

    def _check_auth(self) -> bool:
        tok = self.stub.cfg.get("token")
        if tok is None:
            return True
        if self.headers.get("Authorization") == f"Bearer {tok}":
            return True
        self._err(401, "InvalidAuthenticationInfo", "bad bearer token")
        return False

    def _split(self, path: str) -> Tuple[str, str]:
        parts = path.lstrip("/").split("/", 1)
        return parts[0], parts[1] if len(parts) > 1 else ""

    def do_PUT(self) -> None:
        self._read_body()
        if self._inject_failure() or not self._check_auth():
            return
        path, q = self._parse()
        fs, p = self._split(path)
        src = self.headers.get("x-ms-rename-source")
        if src:
            sfs, sp = self._split(urllib.parse.unquote(src))
            st = self.stub
            moved = False
            if (sfs, sp) in st.files:
                st.files[(fs, p)] = st.files.pop((sfs, sp))
                moved = True
            pre = sp.rstrip("/") + "/"
            for (f2, k) in [k2 for k2 in st.files
                            if k2[0] == sfs and k2[1].startswith(pre)]:
                st.files[(fs, p.rstrip("/") + "/" + k[len(pre):])] = \
                    st.files.pop((f2, k))
                moved = True
            if (sfs, sp.rstrip("/")) in st.dirs:
                st.dirs.discard((sfs, sp.rstrip("/")))
                st.dirs.add((fs, p.rstrip("/")))
                moved = True
            if not moved:
                return self._err(404, "PathNotFound", sp)
            return self._respond(201)
        if q.get("resource") == "file":
            self.stub.pending[(fs, p)] = bytearray()
            return self._respond(201)
        if q.get("resource") == "directory":
            self.stub.dirs.add((fs, p.rstrip("/")))
            return self._respond(201)
        self._err(400, "InvalidRequest", "unsupported PUT")

    def do_PATCH(self) -> None:
        body = self._read_body()
        if self._inject_failure() or not self._check_auth():
            return
        path, q = self._parse()
        fs, p = self._split(path)
        buf = self.stub.pending.get((fs, p))
        if buf is None:
            return self._err(404, "PathNotFound", p)
        if q.get("action") == "append":
            pos = int(q.get("position", 0))
            if pos != len(buf):
                return self._err(409, "InvalidFlushPosition",
                                 f"{pos} != {len(buf)}")
            buf.extend(body)
            return self._respond(202)
        if q.get("action") == "flush":
            if int(q.get("position", 0)) != len(buf):
                return self._err(409, "InvalidFlushPosition", "short")
            self.stub.files[(fs, p)] = bytes(buf)
            del self.stub.pending[(fs, p)]
            return self._respond(200)
        self._err(400, "InvalidRequest", "unsupported PATCH")

    def do_GET(self) -> None:
        if self._inject_failure() or not self._check_auth():
            return
        path, q = self._parse()
        fs, p = self._split(path)
        if q.get("resource") == "filesystem":
            directory = q.get("directory", "").rstrip("/")
            rec = q.get("recursive") == "true"
            paths = []
            seen_dirs = set()
            for (f2, k) in sorted(self.stub.files):
                if f2 != fs:
                    continue
                if directory and not k.startswith(directory + "/"):
                    continue
                rel = k[len(directory) + 1:] if directory else k
                if not rec and "/" in rel:
                    d = (directory + "/" if directory else "") \
                        + rel.split("/")[0]
                    if d not in seen_dirs:
                        seen_dirs.add(d)
                        paths.append({"name": d, "isDirectory": "true",
                                      "contentLength": "0"})
                    continue
                paths.append({"name": k, "contentLength":
                              str(len(self.stub.files[(f2, k)]))})
            for (f2, d) in sorted(self.stub.dirs):
                if f2 != fs or d in seen_dirs:
                    continue
                if directory and not d.startswith(directory + "/"):
                    continue
                rel = d[len(directory) + 1:] if directory else d
                if not rec and "/" in rel:
                    continue
                paths.append({"name": d, "isDirectory": "true",
                              "contentLength": "0"})
            return self._respond(200, json.dumps(
                {"paths": paths}).encode())
        data = self.stub.files.get((fs, p))
        if data is None:
            return self._err(404, "PathNotFound", p)
        rng = self.headers.get("Range")
        if rng:
            lo, hi = map(int, rng.split("=")[1].split("-"))
            return self._respond(206, data[lo: hi + 1])
        self._respond(200, data)

    def do_HEAD(self) -> None:
        if self._inject_failure() or not self._check_auth():
            return
        path, _q = self._parse()
        fs, p = self._split(path)
        data = self.stub.files.get((fs, p))
        if data is not None:
            return self._respond(200, headers={
                "x-ms-resource-type": "file",
                "Content-Length": str(len(data))})
        if (fs, p.rstrip("/")) in self.stub.dirs or any(
                k2[0] == fs and k2[1].startswith(p.rstrip("/") + "/")
                for k2 in self.stub.files):
            return self._respond(200, headers={
                "x-ms-resource-type": "directory"})
        self._respond(404)

    def do_DELETE(self) -> None:
        if self._inject_failure() or not self._check_auth():
            return
        path, q = self._parse()
        fs, p = self._split(path)
        st = self.stub
        found = False
        if (fs, p) in st.files:
            del st.files[(fs, p)]
            found = True
        if q.get("recursive") == "true":
            pre = p.rstrip("/") + "/"
            for k2 in [k for k in st.files
                       if k[0] == fs and k[1].startswith(pre)]:
                del st.files[k2]
                found = True
        if (fs, p.rstrip("/")) in st.dirs:
            st.dirs.discard((fs, p.rstrip("/")))
            found = True
        if not found:
            return self._err(404, "PathNotFound", p)
        self._respond(200)


class FakeAdlsServer(_BaseServer):
    handler_cls = _AdlsHandler

    def __init__(self, port: int = 0, token: Optional[str] = None):
        self.files: Dict[Tuple[str, str], bytes] = {}
        self.pending: Dict[Tuple[str, str], bytearray] = {}
        self.dirs: set = set()
        super().__init__(port, token=token)
